"""Experiment driver (reference: ddls/launchers/launcher.py:17).

Runs epoch-loop iterations until a stop condition is met (num_epochs /
num_episodes / num_actor_steps), accumulates results, triggers the logger at
its configured frequencies and the checkpointer at its cadence, and keeps
the epoch loop's best-checkpoint tracking fed.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional


class Launcher:
    def __init__(self,
                 epoch_loop,
                 num_epochs: Optional[int] = None,
                 num_episodes: Optional[int] = None,
                 num_actor_steps: Optional[int] = None,
                 num_eval_episodes: Optional[int] = None,
                 eval_freq: Optional[int] = None,
                 epoch_batch_size: int = 1,
                 verbose: bool = True,
                 **kwargs):
        if not any([num_epochs, num_episodes, num_actor_steps]):
            raise ValueError(
                "need at least one stop condition (num_epochs, num_episodes"
                " or num_actor_steps)")
        self.epoch_loop = epoch_loop
        self.num_epochs = num_epochs
        self.num_episodes = num_episodes
        self.num_actor_steps = num_actor_steps
        self.num_eval_episodes = num_eval_episodes
        self.eval_freq = eval_freq
        self.epoch_batch_size = epoch_batch_size
        self.verbose = verbose
        # launcher-level eval settings override the epoch loop's cadence
        # when given (reference launcher surface: launcher.py:17)
        if eval_freq is not None and hasattr(epoch_loop,
                                             "evaluation_interval"):
            epoch_loop.evaluation_interval = eval_freq
        if num_eval_episodes is not None and hasattr(epoch_loop,
                                                     "evaluation_duration"):
            epoch_loop.evaluation_duration = num_eval_episodes

        self.epoch_counter = 0
        self.episode_counter = 0
        self.actor_step_counter = 0

    # -------------------------------------------------------------- control
    def _should_stop(self) -> bool:
        if self.num_epochs is not None and self.epoch_counter >= self.num_epochs:
            return True
        if (self.num_episodes is not None
                and self.episode_counter >= self.num_episodes):
            return True
        if (self.num_actor_steps is not None
                and self.actor_step_counter >= self.num_actor_steps):
            return True
        return False

    def run(self, logger=None, checkpointer=None) -> Dict[str, Any]:
        start = time.time()
        last_results: Dict[str, Any] = {}
        # checkpoint at launch, as the reference does (launcher.py:151)
        if checkpointer is not None:
            path = checkpointer.write(self.epoch_loop, self.epoch_counter)
            if self.verbose:
                print(f"Wrote initial checkpoint to {path}")

        while not self._should_stop():
            for _ in range(self.epoch_batch_size):
                results = self.epoch_loop.run()
                self.epoch_counter += 1
                self.episode_counter += int(
                    results.get("episodes_this_iter", 0))
                self.actor_step_counter += int(
                    results.get("env_steps_this_iter", 0))
                last_results = results

                if logger is not None:
                    # accumulate every epoch; epoch_log_freq gates only the
                    # disk write (reference launcher.py:118 accumulates
                    # unconditionally too)
                    logger.log({"epochs": [self._scalarise(results)]})
                    freq = getattr(logger, "epoch_log_freq", 1) or 1
                    if self.epoch_counter % freq == 0:
                        logger.save()
                self.epoch_loop.log(results)

                if (checkpointer is not None
                        and checkpointer.should_checkpoint(
                            self.epoch_counter)):
                    path = checkpointer.write(self.epoch_loop,
                                              self.epoch_counter)
                    self.epoch_loop.register_checkpoint(path, results)

                if self.verbose:
                    msg = (f"epoch {self.epoch_counter}"
                           f" | env steps {self.actor_step_counter}"
                           f" | episodes {self.episode_counter}")
                    ev = results.get("evaluation", {})
                    if "episode_reward_mean" in ev:
                        msg += (" | eval reward "
                                f"{ev['episode_reward_mean']:.3f}")
                    elif "episode_reward_mean" in results:
                        msg += (" | reward "
                                f"{results['episode_reward_mean']:.3f}")
                    print(msg, flush=True)
                if self._should_stop():
                    break

        if logger is not None:
            logger.save(blocking=True)
        total_time = time.time() - start
        summary = {
            "epochs_run": self.epoch_counter,
            "episodes_run": self.episode_counter,
            "actor_steps_run": self.actor_step_counter,
            "wall_time": total_time,
            "best_checkpoint": getattr(self.epoch_loop,
                                       "best_checkpoint_path", None),
            "best_metric_value": getattr(self.epoch_loop,
                                         "best_metric_value", None),
            "final_results": last_results,
        }
        if self.verbose:
            print(f"Run complete: {self.epoch_counter} epochs in "
                  f"{total_time:.1f}s")
        return summary

    @staticmethod
    def _scalarise(results: Dict[str, Any]) -> Dict[str, Any]:
        """Strip bulky per-episode payloads before logging."""
        out = {k: v for k, v in results.items() if k != "episodes"}
        return out
