"""Background-thread results persistence.

Mirrors the reference Logger (ddls/loggers/logger.py:11): accumulates nested
result dicts in memory and periodically writes them to disk on a background
thread, either as one gzip-pickle per log name or into a SQLite database
(the reference uses ``sqlitedict``, which is not available here; a small
stdlib-``sqlite3`` key/value table provides the same shape). When SQLite is
used, in-memory logs are cleared after each flush so long runs stay bounded
(reference: logger.py:55-97).
"""
from __future__ import annotations

import gzip
import pickle
import threading
from pathlib import Path
from typing import Any, Dict, Optional

from ddls_tpu.utils.common import SqliteDict, merge_logs as _merge_log


class Logger:
    """Accumulate + persist experiment results.

    Args mirror the reference config surface (logger block of
    rllib_config.yaml). ``epoch_log_freq`` is read by the Launcher to gate
    how often epoch results are logged+flushed; the episode/actor-step
    frequencies are carried for config parity and for custom loops that log
    at those granularities.
    """

    def __init__(self,
                 path_to_save: Optional[str] = None,
                 actor_step_log_freq: Optional[int] = None,
                 episode_log_freq: Optional[int] = None,
                 epoch_log_freq: Optional[int] = 1,
                 use_sqlite_database: bool = False,
                 **kwargs):
        self.path_to_save = path_to_save
        self.actor_step_log_freq = actor_step_log_freq
        self.episode_log_freq = episode_log_freq
        self.epoch_log_freq = epoch_log_freq
        self.use_sqlite_database = use_sqlite_database
        self.results: Dict[str, Any] = {}
        self._save_thread: Optional[threading.Thread] = None
        if self.path_to_save is not None:
            Path(self.path_to_save).mkdir(parents=True, exist_ok=True)

    # -------------------------------------------------------------- logging
    def log(self, results: Dict[str, Any]) -> None:
        """Merge one round of results (lists extend, scalars overwrite).

        Results may carry ``LazyMetrics`` futures (pipelined epoch loop):
        the merge keeps them as-is — no device traffic on the logging
        call — and they are materialised on the background save thread
        (``_save_data``), i.e. off the epoch critical path."""
        self.results = _merge_log(self.results, results)

    def save(self, name: str = "results", blocking: bool = False) -> None:
        """Persist accumulated results on a background thread (reference
        spawns a save thread and joins the previous one: logger.py:41-53)."""
        if self.path_to_save is None:
            return
        self.join()
        snapshot = self.results
        if self.use_sqlite_database:
            # bounded memory: what has been handed to the writer is dropped
            # from the in-memory accumulation (reference: logger.py:55-97)
            self.results = {}
        self._save_thread = threading.Thread(
            target=self._save_data, args=(name, snapshot), daemon=True)
        self._save_thread.start()
        if blocking:
            self.join()

    def join(self) -> None:
        if self._save_thread is not None:
            self._save_thread.join()
            self._save_thread = None

    # ------------------------------------------------------------ backends
    def _save_data(self, name: str, results: Dict[str, Any]) -> None:
        # lazy-metric sync boundary: replace device futures with plain
        # float dicts before pickling. This runs on the save thread, so
        # the device_get it implies never blocks the training loop; a
        # LazyMetrics materialised here also materialises the SAME object
        # referenced by any still-held results dict (idempotent fetch).
        from ddls_tpu.train.metrics import materialize_results

        results = materialize_results(results)
        if self.use_sqlite_database:
            db = SqliteDict(str(Path(self.path_to_save) / f"{name}.sqlite"))
            try:
                for key, val in results.items():
                    db[key] = _merge_log(db.get(key), val)
                db.commit()
            finally:
                db.close()
        else:
            path = Path(self.path_to_save) / f"{name}.pkl.gz"
            with gzip.open(path, "wb") as f:
                pickle.dump(results, f)

    @staticmethod
    def load(path: str) -> Dict[str, Any]:
        """Load a saved results file (either backend, by extension)."""
        if str(path).endswith(".sqlite"):
            db = SqliteDict(path)
            try:
                return {k: db[k] for k in db.keys()}
            finally:
                db.close()
        with gzip.open(path, "rb") as f:
            return pickle.load(f)
