"""Lazy training-metric materialisation: device scalars as futures.

The pipelined epoch loop (train/loops.py, docs/perf_round6.md) never
blocks the hot collect→update path on a device→host transfer: learner
metrics stay on device as jax arrays, wrapped in a ``LazyMetrics``
mapping that rides the epoch's results dict unchanged. They are
materialised — ONE batched ``jax.device_get`` for everything pending —
only at a logging/eval boundary (``metrics_sync_interval`` epochs, a
W&B flatten, a Logger disk flush, or first item access), so the per-
update ~116 ms tunnelled-TPU round trip the sequential loop paid under
``train.host_sync`` disappears from steady state (CLAUDE.md invariant:
metrics are futures until a sync boundary).

``LazyMetrics`` is a ``Mapping``: ``results["learner"]["total_loss"]``
still works everywhere (first scalar access materialises the whole
dict), ``"k" in m`` / ``len(m)`` / iteration never touch the device,
and a materialised instance is indistinguishable from the plain float
dict the sequential loop returns — the bit-exactness pin in
tests/test_train_pipeline.py compares them directly.
"""
from __future__ import annotations

import threading
from collections.abc import Mapping
from typing import Any, Dict, Iterable, List, Optional


def as_float(value) -> float:
    """Scalar coercion for metric values that may live on device. Use at
    sync boundaries only — on a device array this blocks on the
    transfer, which is exactly what the hot loop must never do."""
    import numpy as np

    return float(np.asarray(value))


class LazyMetrics(Mapping):
    """Mapping over scalar training metrics with deferred device→host.

    ``device_metrics`` is either one dict of device (or host) scalars,
    or — with ``reduce="mean"`` — a LIST of such dicts (the DQN epoch
    shape: many updates per epoch, logged as their per-key mean) or one
    dict of ``[U]``-STACKED device arrays (the fused epoch shape,
    rl/fused.py: a ``lax.scan`` stacks each update's metrics, and the
    whole epoch's dict is fetched in one transfer then averaged).
    ``extras`` are host-side scalars (counters the loop already owns)
    merged in at materialisation and readable/writable without any
    device traffic.
    """

    __slots__ = ("_device", "_host", "_extras", "_reduce", "_lock")

    def __init__(self, device_metrics=None,
                 extras: Optional[Dict[str, Any]] = None,
                 reduce: Optional[str] = None):
        if reduce not in (None, "mean"):
            raise ValueError(f"unknown reduce {reduce!r}")
        if reduce is None and isinstance(device_metrics, list):
            raise ValueError("a list of metric dicts needs reduce='mean'")
        self._device = device_metrics
        self._host: Optional[Dict[str, float]] = None
        self._extras: Dict[str, Any] = dict(extras or {})
        self._reduce = reduce
        self._lock = threading.Lock()
        if device_metrics is None or (isinstance(device_metrics, list)
                                      and not device_metrics):
            self._host = {}
            self._device = None

    # ------------------------------------------------------------ futures
    @property
    def pending(self) -> bool:
        return self._host is None

    def device_values(self):
        """The unfetched device tree (None once materialised) — what a
        group sync hands to one batched ``jax.device_get``."""
        return self._device if self._host is None else None

    def _finish(self, fetched) -> Dict[str, float]:
        """Install the host values for a tree fetched elsewhere (the
        group-sync path); idempotent under the instance lock."""
        with self._lock:
            if self._host is None:
                self._host = self._reduce_host(fetched)
                self._device = None
            return self._host

    def _reduce_host(self, fetched) -> Dict[str, float]:
        import numpy as np

        if self._reduce == "mean":
            if isinstance(fetched, dict):
                # fused-epoch shape: one dict of [U]-stacked arrays;
                # accumulate in f64 exactly like the list path below
                # (float(v) per update, then a python-float mean)
                return {k: float(np.mean(np.asarray(v, np.float64)))
                        for k, v in fetched.items()}
            dicts = [{k: float(v) for k, v in d.items()} for d in fetched]
            return {k: float(np.mean([d[k] for d in dicts]))
                    for k in (dicts[0] if dicts else {})}
        return {k: float(v) for k, v in fetched.items()}

    def materialize(self) -> Dict[str, float]:
        """Host dict of floats (device + extras); fetches at most once.
        This is the ONLY place a LazyMetrics touches the device."""
        if self._host is None:
            import jax

            with self._lock:
                if self._host is None:
                    self._host = self._reduce_host(
                        jax.device_get(self._device))
                    self._device = None
        return {**self._host, **{k: as_float(v)
                                 for k, v in self._extras.items()}}

    @staticmethod
    def materialize_group(group: Iterable["LazyMetrics"]) -> None:
        """Materialise every pending instance with ONE ``device_get``
        over all their trees — the metrics-ring sync boundary."""
        import jax

        pending = [lm for lm in group if lm.pending]
        if not pending:
            return
        fetched = jax.device_get([lm._device for lm in pending])
        for lm, host in zip(pending, fetched):
            lm._finish(host)

    # ------------------------------------------------------------ mapping
    def _keys(self) -> List[str]:
        if self._host is not None:
            base = list(self._host)
        elif self._reduce == "mean" and not isinstance(self._device,
                                                       dict):
            base = list(self._device[0]) if self._device else []
        else:
            base = list(self._device or {})
        return base + [k for k in self._extras if k not in base]

    def __getitem__(self, key: str):
        if key in self._extras:
            return self._extras[key]
        return self.materialize()[key]

    def __setitem__(self, key: str, value) -> None:
        """Host-side extras only (e.g. ES's eval_fitness_mean, DQN's
        replay_size) — never a fresh device future."""
        self._extras[key] = value

    def __contains__(self, key) -> bool:
        return key in self._keys()

    def __iter__(self):
        return iter(self._keys())

    def __len__(self) -> int:
        return len(self._keys())

    def __repr__(self) -> str:
        state = "pending" if self.pending else "materialized"
        return f"LazyMetrics({state}, keys={self._keys()})"

    def __eq__(self, other) -> bool:
        if isinstance(other, (LazyMetrics, dict)):
            return dict(self.materialize()) == dict(
                other.materialize() if isinstance(other, LazyMetrics)
                else other)
        return NotImplemented

    def __ne__(self, other) -> bool:
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq


def materialize_results(node):
    """Deep-copy a results tree with every ``LazyMetrics`` replaced by
    its materialised float dict (and shared containers copied), so the
    result is plain-picklable. Called by persistence boundaries
    (train/logger.py's background save thread, the W&B flatten) — i.e.
    the sync happens off the epoch critical path."""
    if isinstance(node, LazyMetrics):
        return node.materialize()
    if isinstance(node, dict):
        return {k: materialize_results(v) for k, v in node.items()}
    if isinstance(node, list):
        return [materialize_results(v) for v in node]
    if isinstance(node, tuple):
        return tuple(materialize_results(v) for v in node)
    return node
