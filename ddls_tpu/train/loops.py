"""Training and evaluation loops.

TPU-native replacements for the reference's loop layer (SURVEY.md §2.7):

* ``RLEpochLoop`` — replaces ``RLlibEpochLoop`` (ddls/loops/
  rllib_epoch_loop.py:34). Instead of wrapping an RLlib Trainer (Ray
  process topology), it owns the flax GNN policy, the mesh-sharded
  ``PPOLearner``, and a vectorised rollout collector; ``run()`` is one
  collect+update epoch as two jitted device programs. Accepts the
  reference's RLlib-style ``algo_config``/``model`` dicts so the existing
  config trees drive it unchanged.
* ``EvalLoop`` — heuristic-actor evaluation (ddls/loops/eval_loop.py:14).
* ``RLEvalLoop`` — trained-policy evaluation from a checkpoint
  (ddls/loops/rllib_eval_loop.py:11).
* ``EnvLoop`` / ``EpochLoop`` — generic episode/epoch drivers
  (ddls/loops/env_loop.py:4, epoch_loop.py:5).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ddls_tpu import telemetry
from ddls_tpu.utils.common import (available_cores, get_class_from_path,
                                   seed_everything)

# RLlib PPO keys (algo/ppo.yaml) -> PPOConfig fields
_RLLIB_TO_PPO = {
    "lr": "lr",
    "gamma": "gamma",
    "lambda": "gae_lambda",
    "lambda_": "gae_lambda",
    "kl_coeff": "kl_coeff",
    "kl_target": "kl_target",
    "clip_param": "clip_param",
    "vf_clip_param": "vf_clip_param",
    "vf_loss_coeff": "vf_loss_coeff",
    "entropy_coeff": "entropy_coeff",
    "num_sgd_iter": "num_sgd_iter",
    "sgd_minibatch_size": "sgd_minibatch_size",
    "train_batch_size": "train_batch_size",
    "grad_clip": "grad_clip",
}


# algo_config keys consumed by the epoch loops themselves rather than the
# per-algorithm translators (num_workers sizes the vectorised env pool;
# device_collector flips PPO collection to the jitted in-kernel env,
# device_bank_jobs sizes its per-lane sampled job banks,
# use_jax_lookahead_memo gates the in-kernel lookahead memo:
# "auto" (default) = on at every lane count (the wide-vmap batched
# probe), True/False force it — sim/jax_memo.py)
_LOOP_LEVEL_ALGO_KEYS = {"num_workers", "device_collector",
                         "device_bank_jobs", "use_jax_lookahead_memo"}


def _reject_unknown_algo_keys(algo_name: str, keys, known) -> None:
    """Hard-error on algo_config keys nothing consumes. Silently accepting
    and ignoring a hyperparameter is the failure mode round 1 flagged for
    algo configs (VERDICT r2 weakness 6): a user sweeping such a key would
    sweep a no-op. Ray-only plumbing keys are not grandfathered — the
    shipped yamls omit them, and a config carrying them should say so
    loudly rather than pretend they took effect."""
    unknown = sorted(set(keys) - set(known) - _LOOP_LEVEL_ALGO_KEYS)
    if unknown:
        raise ValueError(
            f"{algo_name} algo_config keys {unknown} are not consumed by "
            f"the TPU stack; remove them (or map them in train/loops.py). "
            f"Known keys: {sorted(set(known) | _LOOP_LEVEL_ALGO_KEYS)}")


def ppo_config_from_rllib(algo_config: Optional[dict]):
    """Translate an RLlib-style PPO config dict into a ``PPOConfig``."""
    from ddls_tpu.rl.ppo import PPOConfig

    _reject_unknown_algo_keys("ppo", (algo_config or {}), _RLLIB_TO_PPO)
    kwargs = {}
    for src, dst in _RLLIB_TO_PPO.items():
        if algo_config and algo_config.get(src) is not None:
            kwargs[dst] = algo_config[src]
    return PPOConfig(**kwargs)


# RLlib Ape-X DQN keys (algo/apex_dqn.yaml) -> DQNConfig fields; nested
# replay_buffer_config / exploration_config keys are flattened first
_RLLIB_TO_DQN = {
    "lr": "lr",
    "gamma": "gamma",
    "n_step": "n_step",
    "train_batch_size": "train_batch_size",
    "target_network_update_freq": "target_network_update_freq",
    "double_q": "double_q",
    "dueling": "dueling",
    "num_atoms": "num_atoms",
    "grad_clip": "grad_clip",
    "training_intensity": "training_intensity",
    "capacity": "buffer_capacity",
    "prioritized_replay_alpha": "prioritized_replay_alpha",
    "prioritized_replay_beta": "prioritized_replay_beta",
    "prioritized_replay_eps": "prioritized_replay_eps",
    "learning_starts": "learning_starts",
    "initial_epsilon": "initial_epsilon",
    "final_epsilon": "final_epsilon",
    "epsilon_timesteps": "epsilon_timesteps",
}


def dqn_config_from_rllib(algo_config: Optional[dict]):
    """Translate an RLlib-style Ape-X DQN config dict into a ``DQNConfig``
    (reference surface: scripts/ramp_job_partitioning_configs/algo/
    apex_dqn.yaml; Ray-plumbing keys are ignored)."""
    from ddls_tpu.rl.dqn import DQNConfig

    flat = dict(algo_config or {})
    for nested in ("replay_buffer_config", "exploration_config"):
        flat.update(flat.pop(nested, None) or {})
    _reject_unknown_algo_keys("apex_dqn", flat, _RLLIB_TO_DQN)
    kwargs = {}
    for src, dst in _RLLIB_TO_DQN.items():
        if flat.get(src) is not None:
            kwargs[dst] = flat[src]
    return DQNConfig(**kwargs)


def build_policy_from_model_config(n_actions: int,
                                   model_config: Optional[dict]):
    """Build a ``GNNPolicy`` from the reference's model/gnn.yaml surface."""
    from ddls_tpu.models.policy import GNNPolicy

    model_config = model_config or {}
    cmc = model_config.get("custom_model_config", {})
    fcnet_hiddens = model_config.get("fcnet_hiddens") or (256, 256)
    return GNNPolicy(
        n_actions=n_actions,
        out_features_msg=cmc.get("out_features_msg", 32),
        out_features_hidden=cmc.get("out_features_hidden", 64),
        out_features_node=cmc.get("out_features_node", 16),
        out_features_graph=cmc.get("out_features_graph", 8),
        num_rounds=cmc.get("num_rounds", 2),
        module_depth=cmc.get("module_depth", 1),
        activation=cmc.get("aggregator_activation", "relu"),
        fcnet_hiddens=tuple(fcnet_hiddens),
        fcnet_activation=model_config.get("fcnet_activation", "relu"),
        apply_action_mask=cmc.get("apply_action_mask", True))


def _episode_summary(episodes: List[dict]) -> Dict[str, float]:
    # scalar coercions go through the lazy-materialisation helper's
    # as_float: episode records are host state by contract (never device
    # fetches on the per-update path), and routing the coercion through
    # one place keeps it that way if a collector ever slips a device
    # scalar into a record
    from ddls_tpu.train.metrics import as_float

    if not episodes:
        return {}
    out: Dict[str, float] = {
        "episode_reward_mean": as_float(np.mean(
            [e["episode_return"] for e in episodes])),
        "episode_reward_min": as_float(np.min(
            [e["episode_return"] for e in episodes])),
        "episode_reward_max": as_float(np.max(
            [e["episode_return"] for e in episodes])),
        "episode_len_mean": as_float(np.mean(
            [e["episode_length"] for e in episodes])),
        "episodes_this_iter": len(episodes),
    }
    # cluster custom metrics, averaged over episodes (what the reference's
    # RLlib callback surfaces as custom_metrics: ramp_cluster/utils.py:25-73)
    for key in ("num_jobs_completed", "num_jobs_blocked", "blocking_rate",
                "acceptance_rate", "mean_job_completion_time",
                "mean_job_completion_time_speedup"):
        vals = [e[key] for e in episodes if key in e]
        if vals:
            out[f"custom_metrics/{key}_mean"] = as_float(np.mean(vals))
    return out


class RLEpochLoop:
    """One PPO epoch per ``run()`` call, with periodic greedy evaluation.

    ``env_config`` / ``model`` / ``algo_config`` follow the reference's
    config surfaces; mesh/rollout sizing is TPU-specific:

    * ``num_envs`` — parallel env instances (reference: PPO num_workers);
    * ``rollout_length`` — steps per env per epoch (derived from
      train_batch_size when omitted);
    * ``n_devices`` — mesh size for the dp axis (defaults to all devices).

    Pipelining (docs/perf_round6.md):

    * ``loop_mode="pipelined"`` (default) keeps the hot collect→update
      path free of blocking device→host transfers: learner metrics stay
      on device as futures (``LazyMetrics``) and are drained in ONE
      batched fetch at a sync boundary (every ``metrics_sync_interval``
      epochs, an eval epoch, or first scalar access); collection uses
      the deferred-fetch collector (one fused dispatch per step, actions
      the only per-step fetch). ``"sequential"`` reproduces the pre-
      pipelining loop exactly: per-update ``float(device_get(metrics))``
      under ``train.host_sync``. The two modes are bit-identical in
      params/metrics/episodes (pinned in tests/test_train_pipeline.py);
      only the dispatch/sync schedule differs.
    * ``pipeline_depth >= 1`` (opt-in, off-policy-tolerant learners only
      — IMPALA, whose V-trace correction exists precisely for this lag)
      additionally keeps up to ``pipeline_depth`` collected batches in
      flight on a background thread against pre-update params, so host
      env stepping overlaps the device update. Each batch's params
      snapshot is taken at submission; the behavior logp travelling in
      the traj lets V-trace absorb however many updates land before the
      batch is consumed (the per-batch ``params_age_updates`` metric
      reports exactly that lag). On the shm backend the batches ride a
      ``pipeline_depth + 2``-segment trajectory ring (rl/ring.py) whose
      lease→publish→release ownership replaces the per-segment bulk
      copy. Learners whose update assumes fresh data (ppo/pg/dqn/es)
      reject ``pipeline_depth > 0`` loudly, as does any
      ``loop_mode != "pipelined"``.

    Fused mode (rl/fused.py, docs/perf_round8.md):

    * ``loop_mode="fused"`` runs the whole epoch as ONE jitted program —
      a ``lax.scan`` over ``updates_per_epoch`` collect→update rounds on
      the in-kernel environment (the Podracer/Anakin shape; implies
      device collection, single-process only). Learner metrics come back
      as a [U]-stacked device dict (one ``LazyMetrics`` per epoch) and
      episode counters as compact [U, B, T] device traces; BOTH are
      drained per ``metrics_sync_interval`` epochs in one batched fetch
      — never per update — so the steady-state epoch is transfer-free
      (pinned under ``jax.transfer_guard`` in tests/test_fused.py).
      ``fused_config`` tunes the lane/segment autotuner: ``lanes`` +
      ``segment_len`` pin the config explicitly (skipping the
      probe-compile), ``probe_dir``/``probe_timeout_s`` steer the
      probing; when no candidate compiles the loop falls back to
      ``loop_mode="pipelined"`` LOUDLY (a warning naming every probed
      config). Learners without the scan-based in-kernel contract
      (DQN: host replay insertion; ES: population fitness on host envs)
      reject fused before any env construction.
    """

    # pipeline_depth > 0 staleness is only sound for learners with an
    # explicit off-policy correction; subclasses opt in (ImpalaEpochLoop)
    SUPPORTS_STALE_COLLECTION = False
    # fused epochs need the shared [T, B] traj contract AND an update
    # that traces as one pure function (state, traj, last_values, rng)
    # -> (state, metrics); DQN/ES opt out (host replay / host fitness)
    SUPPORTS_FUSED = True
    # sharded param layouts (parallel/partition.py fsdp/tp) ride the
    # device-collection trajectory contract; DQN/ES opt out (their
    # host replay / population paths never consume the spec table)
    SUPPORTS_PARAM_SHARDING = True
    # socket collection (rl/fragments.py) ships whole [T, B] trajectory
    # segments from actor-host processes over the shared traj contract;
    # DQN's replay insertion and ES's population fitness step the host
    # envs directly and opt out
    SUPPORTS_SOCKET_COLLECTION = True

    def __init__(self,
                 path_to_env_cls: str,
                 env_config: dict,
                 model: Optional[dict] = None,
                 algo_config: Optional[dict] = None,
                 num_envs: Optional[int] = None,
                 rollout_length: Optional[int] = None,
                 n_devices: Optional[int] = None,
                 use_parallel_envs="auto",
                 metric: str = "evaluation/episode_reward_mean",
                 metric_goal: str = "maximise",
                 evaluation_interval: Optional[int] = 1,
                 evaluation_duration: int = 3,
                 evaluation_config: Optional[dict] = None,
                 seed: Optional[int] = 0,
                 test_seed: Optional[int] = None,
                 wandb=None,
                 loop_mode: str = "pipelined",
                 metrics_sync_interval: int = 10,
                 pipeline_depth: int = 0,
                 vec_env_backend: str = "auto",
                 updates_per_epoch: int = 4,
                 fused_config: Optional[dict] = None,
                 sebulba_config: Optional[dict] = None,
                 param_sharding: str = "replicated",
                 tp_size: Optional[int] = None,
                 path_to_model_cls: Optional[str] = None,  # config parity
                 collect_transport: str = "inprocess",
                 socket_config: Optional[dict] = None,
                 scenario=None,
                 run_ledger=None,
                 **kwargs):
        import jax

        from ddls_tpu.rl.rollout import ParallelVectorEnv, VectorEnv

        # scenario plumbing (ddls_tpu/scenarios, ROADMAP item 5): one
        # ScenarioSpec (name, path, or instance) supplies the env
        # construction kwargs and (for failure specs) the runtime; an
        # explicit env_config entry overrides the spec's TOP-LEVEL key
        # wholesale (never a deep merge — a merged jobs_config would
        # silently union synthesis knobs). The canonical spec resolves
        # runtime=None, so its env path is byte-identical to passing the
        # same env_config by hand.
        self.scenario_fingerprint: Optional[str] = None
        if scenario is not None:
            from ddls_tpu.hardware.topologies import build_topology
            from ddls_tpu.scenarios.spec import (build_runtime,
                                                 env_kwargs as
                                                 _scenario_env_kwargs,
                                                 get_spec,
                                                 spec_fingerprint)

            spec = get_spec(scenario) if isinstance(scenario, str) \
                else scenario
            merged = dict(_scenario_env_kwargs(spec))
            merged.update(env_config or {})
            runtime = build_runtime(spec, build_topology(spec.topology))
            if runtime is not None:
                merged["scenario_runtime"] = runtime
            env_config = merged
            self.scenario_fingerprint = spec_fingerprint(spec)

        self._env_cls_path = path_to_env_cls
        self.env_cls = get_class_from_path(path_to_env_cls)
        self.env_config = dict(env_config)
        self.metric = metric
        self.metric_goal = metric_goal
        self.evaluation_interval = evaluation_interval
        self.evaluation_duration = evaluation_duration
        self.evaluation_config = evaluation_config or {}
        self.wandb = wandb
        self.seed = 0 if seed is None else int(seed)
        self.test_seed = test_seed

        if loop_mode not in ("sequential", "pipelined", "fused",
                             "sebulba"):
            raise ValueError(
                f"loop_mode must be 'sequential', 'pipelined', 'fused' "
                f"or 'sebulba', got {loop_mode!r}")
        if (loop_mode in ("fused", "sebulba")
                and not self.SUPPORTS_FUSED):
            # SUPPORTS_FUSED gates BOTH in-kernel-collection drivers:
            # fused (one traced collect→update program) and sebulba
            # (in-kernel collection on an actor sub-mesh) need the
            # shared traj contract plus a standalone jitted update
            raise ValueError(
                f"{type(self).__name__} does not support loop_mode="
                f"{loop_mode!r}: the fused/sebulba drivers need "
                "in-kernel collection plus a jitted scan-based update "
                "— DQN's replay insertion and ES's population fitness "
                "step the host envs by contract (use ppo/impala/pg, or "
                "rl/es_device.py for on-device ES)")
        if loop_mode == "fused" and jax.process_count() > 1:
            raise ValueError(
                "loop_mode='fused' is single-process: collection lanes "
                "and the sharded update live in ONE program, which "
                "would need globally-assembled bank/sim-state arrays "
                "under multi-host (use loop_mode='pipelined' with "
                "device_collector there)")
        if loop_mode == "sebulba" and jax.process_count() > 1:
            raise ValueError(
                "loop_mode='sebulba' is single-process: the actor/"
                "learner split partitions the LOCAL devices and hands "
                "batches over a process-local device ring (use "
                "loop_mode='pipelined' with device_collector under "
                "multi-host)")
        # param layout knob (parallel/partition.py): validated BEFORE any
        # env construction, the fused/sebulba loud-rejection convention
        from ddls_tpu.parallel import partition as _partition

        _partition.validate_layout(param_sharding)
        self.param_sharding = param_sharding
        self.tp_size = tp_size
        if param_sharding != "replicated":
            if not self.SUPPORTS_PARAM_SHARDING:
                raise ValueError(
                    f"{type(self).__name__} does not support "
                    f"param_sharding={param_sharding!r}: the sharded "
                    "layouts ride the device-collection trajectory "
                    "contract — DQN's replay insertion and ES's "
                    "population fitness never consume the spec table "
                    "(use ppo/impala/pg, or param_sharding='replicated')")
            if jax.process_count() > 1:
                raise ValueError(
                    f"param_sharding={param_sharding!r} is single-"
                    "process: the sharded state lives on one process's "
                    "mesh — the multi-host identical-state placement "
                    "contract (parallel/mesh.py:place_state_tree) only "
                    "covers replicated layouts today (use "
                    "param_sharding='replicated' under multi-host)")
            if loop_mode == "sebulba" and param_sharding == "tp":
                raise ValueError(
                    "param_sharding='tp' cannot combine with "
                    "loop_mode='sebulba': the actor/learner sub-meshes "
                    "are 1-axis dp meshes (rl/sebulba.py:split_meshes) "
                    "and have no 'mp' axis to shard over — use "
                    "param_sharding='fsdp' or a non-split loop_mode")
            # fail fast on an infeasible mesh for the layout (e.g. a tp
            # factorisation that does not divide the device count)
            _partition.mesh_for_layout(n_devices, param_sharding,
                                       tp_size)
        self.loop_mode = loop_mode
        self.updates_per_epoch = max(int(updates_per_epoch or 1), 1)
        self.fused_config = dict(fused_config or {})
        # sebulba runtime state: the sub-mesh split (self.mesh becomes
        # the LEARNER sub-mesh after _build_sebulba so the update/
        # checkpoints/eval keep using it) — keys: actor_devices (count,
        # default half the local devices), ring_segments (default
        # pipeline_depth + 2)
        self.sebulba_config = dict(sebulba_config or {})
        self.actor_mesh = None
        # fused runtime state: the driver, its autotune decision, the
        # undrained compact episode-counter traces, and the chip lock
        # held for the run on accelerator backends
        self.fused = None
        self.autotune_result = None
        self._fused_episode_ring: List[Any] = []
        self._chip_lock = None
        self.metrics_sync_interval = max(int(metrics_sync_interval or 1), 1)
        self.pipeline_depth = int(pipeline_depth or 0)
        if self.pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be >= 0, got {pipeline_depth}")
        if self.pipeline_depth and not self.SUPPORTS_STALE_COLLECTION:
            raise ValueError(
                f"{type(self).__name__} does not support pipeline_depth > "
                "0: collecting against stale params needs an explicit "
                "off-policy correction (IMPALA's V-trace); ppo/pg/dqn/es "
                "must collect with the current params (pipeline_depth=0)")
        if (self.pipeline_depth
                and self.loop_mode not in ("pipelined", "sebulba")):
            raise ValueError(
                "pipeline_depth > 0 requires loop_mode='pipelined' or "
                "'sebulba'")
        if vec_env_backend not in ("auto", "pipe", "shm"):
            raise ValueError(
                f"vec_env_backend must be 'auto', 'pipe' or 'shm', got "
                f"{vec_env_backend!r}")
        # subprocess obs transport (rl/rollout.py): 'auto' = zero-copy
        # shared-memory slabs where POSIX shm is usable, pipe otherwise;
        # bit-exact either way (tests/test_shm.py pins pipe==shm params/
        # episodes), so the default favours the cheaper transport
        self.vec_env_backend = vec_env_backend
        # pipelining runtime state: the queue of prefetched
        # (out, straj, slv) futures (depth entries deep, each tagged
        # with the update-counter version its params snapshot was taken
        # at), the unsynced-metrics ring, and the lazily-created
        # executors (collection thread / device-update watcher)
        self._collect_futures: List[Any] = []
        self._collect_executor = None
        self._watch_executor = None
        self._metrics_ring: List[Any] = []
        self._updates_dispatched = 0

        self._configure_algo(algo_config, num_envs, rollout_length)
        # collection backend: host vectorised envs (default) or the
        # fully-jitted in-kernel env (rl/ppo_device.py) — one device
        # dispatch per [T, B] segment instead of T round-trips. Parsed
        # here (not in _configure_algo, which subclasses replace) so every
        # algo sees the key; loops whose collection cannot run in-kernel
        # (DQN, ES) reject it loudly in their _build_learner.
        self.device_collector = bool(
            (algo_config or {}).get("device_collector", False))
        if self.loop_mode in ("fused", "sebulba"):
            # fused/sebulba collection runs the in-kernel env by
            # construction: the same template-env/bank setup as
            # device_collector
            self.device_collector = True
        self.device_bank_jobs = (algo_config or {}).get("device_bank_jobs")
        # the in-kernel lookahead memo knob (ISSUE 13/17,
        # sim/jax_memo.py): "auto" resolves to ON at every lane count —
        # the batched probe masks hit lanes out of the lookahead
        # while_loop, so multi-lane vmap collection hits the cache too
        self.use_jax_lookahead_memo = (algo_config or {}).get(
            "use_jax_lookahead_memo", "auto")
        if (self.use_jax_lookahead_memo != "auto"
                and not self.device_collector):
            # loud-rejection convention (as pipeline_depth /
            # device_collector on DQN/ES): a forced value on a path that
            # never consults it would silently no-op while the user
            # believes the memo is active in their comparison runs
            raise ValueError(
                "use_jax_lookahead_memo is an in-kernel-collection knob "
                "(sim/jax_memo.py): it needs algo_config."
                "device_collector=true or loop_mode='fused' — remove it "
                "or leave it 'auto' for host collection")

        # socket collection knob (rl/fragments.py, ROADMAP item 4):
        # trajectory ring segments arrive framed from actor-host
        # processes; validated BEFORE env construction, the loud-
        # rejection convention
        if collect_transport not in ("inprocess", "socket"):
            raise ValueError(
                f"collect_transport must be 'inprocess' or 'socket', "
                f"got {collect_transport!r}")
        if socket_config and collect_transport != "socket":
            raise ValueError(
                "socket_config is a collect_transport='socket' knob: a "
                "forced config on the in-process path would silently "
                "no-op — remove it or set collect_transport='socket'")
        self.collect_transport = collect_transport
        self.socket_config = dict(socket_config or {})
        if collect_transport == "socket":
            if not self.SUPPORTS_SOCKET_COLLECTION:
                raise ValueError(
                    f"{type(self).__name__} does not support "
                    "collect_transport='socket': fragments ship whole "
                    "[T, B] trajectory segments over the shared traj "
                    "contract — DQN's replay insertion and ES's "
                    "population fitness step the host envs directly "
                    "(use ppo/impala/pg)")
            if self.loop_mode != "pipelined":
                raise ValueError(
                    "collect_transport='socket' requires loop_mode="
                    "'pipelined': the fragment consumer is the deferred-"
                    "fetch collector contract (fused/sebulba collect "
                    "in-kernel; sequential would serialise the only "
                    "overlap the second process buys)")
            if self.device_collector:
                raise ValueError(
                    "collect_transport='socket' is host collection on "
                    "the actor hosts — it cannot combine with "
                    "algo_config.device_collector (the in-kernel env "
                    "has no vec env to ship)")
            if jax.process_count() > 1:
                raise ValueError(
                    "collect_transport='socket' is single-LEARNER-"
                    "process: actor hosts are its own spawned "
                    "subprocesses (multi-host jax runtimes coordinate "
                    "collectives, not fragment sockets)")

        # Multi-host: each process must collect DIFFERENT rollouts (its
        # shard of the global batch), so env seeds and the action-sampling
        # rng are offset by the process index; parameter init and the rng
        # fed into the jitted sharded update must stay IDENTICAL on every
        # process, or the nominally replicated state silently diverges.
        self._collect_seed = self.seed + jax.process_index() * 100_003

        seed_everything(self.seed)
        host_pool_size = self.num_envs
        # the actor hosts inherit the caller's env-parallelism intent
        # even though the learner itself only keeps a template env
        self._actor_use_parallel_envs = (
            use_parallel_envs if use_parallel_envs != "auto"
            else available_cores() > 1)
        if self.device_collector or self.collect_transport == "socket":
            # collection runs in-kernel (device_collector) or on the
            # actor hosts (socket fragments); the learner side only
            # needs ONE in-process env as the obs/param template
            # (evaluation builds its own envs via make_eval_env)
            use_parallel_envs = False
            host_pool_size = 1
        elif use_parallel_envs == "auto":
            # subprocess env workers only pay off with real cores to run on
            use_parallel_envs = available_cores() > 1
        if use_parallel_envs:
            self.vec_env = ParallelVectorEnv(
                self.env_cls, self.env_config, self.num_envs,
                seeds=[self._collect_seed + i
                       for i in range(self.num_envs)],
                backend=self.vec_env_backend)
        else:
            self.vec_env = VectorEnv(
                [lambda: self.env_cls(**self.env_config)
                 for _ in range(host_pool_size)],
                seeds=[self._collect_seed + i
                       for i in range(host_pool_size)])
        self.vec_env.reset()

        template_env = getattr(self.vec_env, "envs", [None])[0]
        if template_env is not None:
            n_actions = template_env.action_space.n
        else:
            n_actions = int(np.asarray(
                self.vec_env.obs[0]["action_mask"]).shape[0])
        self.n_actions = n_actions
        # raw model config rides the fragment CONFIG frame so actor
        # hosts build the identical policy (frozen param-tree paths)
        self._model_config = model
        self.model = self._build_model(n_actions, model)

        obs0 = jax.tree_util.tree_map(np.asarray, self.vec_env.obs[0])
        self.params = self.model.init(jax.random.PRNGKey(self.seed), obs0)

        from ddls_tpu.models.policy import batched_policy_apply
        # replicated/fsdp build the exact 1-D dp mesh make_mesh always
        # built; tp builds the ("dp", "mp") mesh its layout shards over
        self.mesh = _partition.mesh_for_layout(n_devices,
                                               self.param_sharding,
                                               self.tp_size)
        self.apply_fn = lambda p, o: batched_policy_apply(self.model, p, o)
        self._build_learner()
        # warm-start / mid-training resume (the reference has no Launcher
        # resume — SURVEY §5.4; here any saved train state can seed a new
        # run, e.g. fine-tuning the best checkpoint at a lower lr)
        if kwargs.get("initial_checkpoint_path"):
            self.load_agent_checkpoint(kwargs["initial_checkpoint_path"])
            print(f"Warm-started train state from "
                  f"{kwargs['initial_checkpoint_path']}")

        self._rng = jax.random.PRNGKey(self.seed + 1)
        # offset keeps the collect stream distinct from the update stream
        # even on process 0, where _collect_seed == seed
        self._collect_rng = jax.random.PRNGKey(self._collect_seed + 7919)
        self.epoch_counter = 0
        self.total_env_steps = 0
        self.best_metric_value: Optional[float] = None
        self.best_checkpoint_path: Optional[str] = None
        self.checkpoint_history: List[dict] = []
        self.run_time = 0.0

        # opt-in run ledger (telemetry/runlog.py, ISSUE 18): the
        # manifest records the RESOLVED loop config; close() finalizes
        # it with the ring/memo counter blocks and final results
        self.run_ledger = run_ledger
        if self.run_ledger is not None:
            self.run_ledger.update_config({
                "algo": next((k for k, v in EPOCH_LOOPS.items()
                              if v is type(self)), type(self).__name__),
                "loop_mode": self.loop_mode,
                "num_envs": self.num_envs,
                "rollout_length": self.rollout_length,
                "updates_per_epoch": self.updates_per_epoch,
                "pipeline_depth": self.pipeline_depth,
                "metrics_sync_interval": self.metrics_sync_interval,
                "device_collector": self.device_collector,
                "param_sharding": self.param_sharding,
                "vec_env_backend": self.vec_env_backend,
                "collect_transport": self.collect_transport,
                "n_devices": getattr(self.mesh, "size", None),
                "seed": self.seed,
            })
            if (self.scenario_fingerprint is not None
                    and self.run_ledger.scenario_fingerprint is None):
                # scenario-built runs are fingerprint-reproducible: the
                # manifest carries the spec hash unless the caller
                # already pinned one
                self.run_ledger.scenario_fingerprint = \
                    self.scenario_fingerprint
            self.run_ledger.open()

    # ------------------------------------------------------------ algo hooks
    def _size_rollouts(self, algo_config, num_envs, rollout_length,
                       train_batch_size: int) -> None:
        """num_envs from config (reference: num_workers), rollout length
        sized so one epoch collects about one train batch."""
        self.num_envs = int(num_envs
                            or (algo_config or {}).get("num_workers") or 8)
        self.rollout_length = int(
            rollout_length or max(train_batch_size // self.num_envs, 1))

    def _configure_algo(self, algo_config, num_envs, rollout_length) -> None:
        """Translate the RLlib-style algo_config; PPO by default."""
        self.ppo_cfg = ppo_config_from_rllib(algo_config)
        self._size_rollouts(algo_config, num_envs, rollout_length,
                            self.ppo_cfg.train_batch_size)

    def _build_model(self, n_actions: int, model_config):
        return build_policy_from_model_config(n_actions, model_config)

    def _make_learner(self):
        from ddls_tpu.rl.ppo import PPOLearner

        return PPOLearner(self.apply_fn, self.ppo_cfg, self.mesh,
                          param_sharding=self.param_sharding)

    def _build_learner(self) -> None:
        from ddls_tpu.rl.rollout import RolloutCollector

        if self.loop_mode == "sebulba":
            # split BEFORE the learner builds: self.mesh becomes the
            # LEARNER sub-mesh (may fall back to pipelined, loudly)
            self._split_sebulba_mesh()
        self.learner = self._make_learner()
        self.state = self.learner.init_state(self.params)
        if self.loop_mode == "fused":
            self._build_fused()
            if self.loop_mode == "fused":  # may have fallen back
                return
        if self.loop_mode == "sebulba":
            self.collector = self._make_sebulba_collector()
            return
        if getattr(self, "device_collector", False):
            self.collector = self._make_device_collector()
            return
        if self.collect_transport == "socket":
            self.collector = self._make_fragment_collector()
            return
        self.collector = RolloutCollector(
            self.vec_env, self.learner, self.rollout_length,
            deferred_fetch=(self.loop_mode == "pipelined"),
            # ring capacity: depth prefetched batches + the one being
            # consumed + one of slack, so a healthy steady state never
            # stalls a lease (rl/ring.py counts the stalls if it does)
            ring_segments=(self.pipeline_depth + 2
                           if self.loop_mode == "pipelined" else None))
        self.collector._needs_reset = False  # env already reset in __init__

    def _make_fragment_collector(self):
        """Socket fragment consumer (rl/fragments.py): actor-host
        subprocesses run the deferred-fetch collector against THEIR
        envs and ship trajectory ring segments as framed messages; the
        returned LearnerFragment duck-types the collector contract —
        its segments live in the learner's OWN TrajRing, so run()'s
        canonical two-phase release (note_staged/note_update) applies
        unchanged."""
        from ddls_tpu.rl.fragments import LearnerFragment

        cfg = dict(self.socket_config)
        return LearnerFragment(
            env_cls_path=self._env_cls_path,
            env_config=self.env_config,
            model_config=self._model_config,
            n_actions=self.n_actions,
            num_envs=self.num_envs,
            rollout_length=self.rollout_length,
            collect_seed=self._collect_seed,
            global_seed=self.seed,
            # same sizing as the in-process pipelined ring: depth
            # prefetched batches + the one consumed + one of slack
            ring_segments=self.pipeline_depth + 2,
            num_actor_hosts=int(cfg.pop("num_actor_hosts", 1)),
            use_parallel_envs=self._actor_use_parallel_envs,
            vec_env_backend=self.vec_env_backend,
            **cfg)

    def _fused_step_fn(self):
        """The learner's UNJITTED update for in-scan tracing inside the
        fused epoch program, normalised to the PPO signature
        ``(state, traj, last_values, rng) -> (state, metrics)``.
        Learners whose update takes no rng override this to drop it
        (the rng stream is still split per round so the update-key
        bookkeeping matches the sequential loop exactly)."""
        return self.learner._train_step

    def _build_fused(self) -> None:
        """Autotune a (lanes, segment_len) config and build the fused
        epoch driver; on total probe failure fall back to
        ``loop_mode='pipelined'`` with device collection, LOUDLY."""
        import warnings

        import jax

        from ddls_tpu.rl import fused as fused_mod

        env0, et, ot = self._device_tables()
        dp = int(self.mesh.shape["dp"])
        total = self.rollout_length * self.num_envs
        cfg = self.fused_config
        step_fn = self._fused_step_fn()
        sh_fn = getattr(self.learner, "_state_shardings", None)
        state_shardings = (sh_fn(self.state) if sh_fn is not None
                           else getattr(self.learner, "_replicated",
                                        None))

        def build_driver(lanes, segment_len):
            return fused_mod.FusedEpochDriver(
                et, ot, self.model,
                self._stacked_banks(et, env0, lanes), segment_len,
                self.updates_per_epoch, train_step_fn=step_fn,
                state_shardings=state_shardings, mesh=self.mesh,
                memo_cfg=self._memo_knob())

        # own the chip for the probing AND the whole training run (the
        # documented wedge gotcha: a probe loop opening a second axon
        # client against an owned chip). CPU has no chip to own, and
        # tests must not contend on the shared lock file. Released on
        # ANY exit that doesn't end in a fused driver — a leaked lock
        # file would divert every later run's probes to CPU.
        if jax.default_backend() != "cpu":
            self._chip_lock = fused_mod.chip_lock(
                cfg.get("probe_dir")).__enter__()
            if not self._chip_lock.owned:
                # a LIVE foreign owner has the chip (and no wrapper
                # above us delegated ownership via DDLS_TPU_LOCK_OWNER):
                # probe-compiling anyway would open the second axon
                # client the lock exists to prevent (the multi-hour
                # wedge). Fall back loudly instead of contending.
                warnings.warn(
                    "fused: chip held by another owner "
                    "(.probe/tpu.lock); not probe-compiling against an "
                    "owned chip — falling back to loop_mode='pipelined'"
                    " with device collection")
                self._chip_lock = None
                self.loop_mode = "pipelined"
                return
        try:
            driver, result = fused_mod.autotune_fused(
                build_driver, self.state, et, total,
                self.updates_per_epoch, dp, max_lanes=self.num_envs,
                probe_dir=cfg.get("probe_dir"),
                probe_timeout_s=float(cfg.get("probe_timeout_s",
                                              240.0)),
                signature_extra=(f"{type(self.learner).__name__}|"
                                 f"{self.model!r}"),
                lanes=cfg.get("lanes"),
                segment_len=cfg.get("segment_len"),
                memo_cfg=self._memo_knob())
        except BaseException:
            if self._chip_lock is not None:
                self._chip_lock.__exit__()
                self._chip_lock = None
            raise
        self.autotune_result = result
        if driver is None:
            warnings.warn(
                "fused autotune: no (lanes, segment_len) config "
                f"compiled within the probe budget — probed "
                f"{[(l, s, e) for l, s, _, e in result.probed]}; "
                "falling back to loop_mode='pipelined' with device "
                "collection")
            if self._chip_lock is not None:
                self._chip_lock.__exit__()
                self._chip_lock = None
            # flipping the mode makes _build_learner's fused guard fall
            # through to the device-collector build — no collector is
            # constructed here (device_collector is already True)
            self.loop_mode = "pipelined"
            return
        self.fused = driver

    def _split_sebulba_mesh(self) -> None:
        """Partition the configured training mesh into the actor
        sub-mesh and the learner complement (rl/sebulba.py) BEFORE the
        learner builds: ``self.mesh`` becomes the LEARNER sub-mesh, so
        the update, checkpoints and eval keep their one mesh handle.
        An infeasible AUTO split (one device, or lanes that do not
        divide a sub-mesh) falls back LOUDLY to ``loop_mode=
        'pipelined'`` with device collection (the fused-fallback
        convention); an EXPLICIT ``sebulba_config`` that cannot split
        is a config error and raises."""
        import warnings

        from ddls_tpu.rl.sebulba import split_meshes

        devs = list(self.mesh.devices.flat)
        explicit = self.sebulba_config.get("actor_devices")
        try:
            actor_mesh, learner_mesh = split_meshes(explicit,
                                                    devices=devs)
        except ValueError as err:
            if explicit is not None:
                raise
            warnings.warn(
                f"sebulba: {err} — falling back to "
                "loop_mode='pipelined' with device collection")
            self.loop_mode = "pipelined"
            return
        bad = [f"num_envs={self.num_envs} does not divide the {name} "
               f"sub-mesh dp axis ({int(m.shape['dp'])})"
               for name, m in (("actor", actor_mesh),
                               ("learner", learner_mesh))
               if self.num_envs % int(m.shape["dp"])]
        if bad:
            if explicit is not None:
                raise ValueError("sebulba: " + "; ".join(bad))
            warnings.warn(
                "sebulba: " + "; ".join(bad) + " — falling back to "
                "loop_mode='pipelined' with device collection")
            self.loop_mode = "pipelined"
            return
        self.actor_mesh = actor_mesh
        self.mesh = learner_mesh

    def _make_sebulba_collector(self):
        """The actor half of the Sebulba split (rl/sebulba.py): the
        fused-style in-kernel collection jitted over the actor
        sub-mesh, handing device trajectories to the learner sub-mesh
        through a device-mode trajectory ring."""
        from ddls_tpu.rl.sebulba import SebulbaCollector

        env0, et, ot = self._device_tables()
        stacked = self._stacked_banks(et, env0, self.num_envs)
        return SebulbaCollector(
            et, ot, self.model, stacked, self.rollout_length,
            actor_mesh=self.actor_mesh,
            # ring capacity: the depth-K sizing of the shm ring
            # (depth in-flight batches + the consumed one + slack)
            ring_segments=int(self.sebulba_config.get("ring_segments")
                              or self.pipeline_depth + 2),
            memo_cfg=self._memo_knob(),
            param_layout=self.param_sharding)

    def _memo_knob(self):
        """The ``use_jax_lookahead_memo`` algo key as the value the
        collectors' ``resolve_memo_cfg`` consumes: "auto" passes
        through (per-build lane-count resolution), True/False force a
        MemoConfig/None."""
        from ddls_tpu.sim.jax_memo import MemoConfig

        knob = self.use_jax_lookahead_memo
        if knob == "auto":
            return "auto"
        return MemoConfig() if knob else None

    def _device_tables(self):
        """Static jitted-env tables from the template env (shared by the
        device collector and the fused epoch driver)."""
        from ddls_tpu.sim.jax_env import (build_episode_tables,
                                          build_obs_tables)

        env0 = self.vec_env.envs[0]
        et = build_episode_tables(env0)
        ot = build_obs_tables(env0, et)
        return env0, et, ot

    def _device_bank_size(self, env0) -> int:
        """Jobs per lane bank via the ONE sizing home
        (rl/fused.py:horizon_bank_jobs): explicit config, else the sim
        horizon with CLT margin."""
        from ddls_tpu.rl.fused import horizon_bank_jobs

        return horizon_bank_jobs(env0, self.seed + 31,
                                 explicit=self.device_bank_jobs)

    def _stacked_banks(self, et, env0, n_lanes: int):
        """Per-lane job banks via the ONE seed-formula home
        (rl/fused.py:stacked_job_banks — lane i keeps the seed the
        device collector always gave env i, so fused lanes == num_envs
        reproduce the collector's banks bit-for-bit)."""
        from ddls_tpu.rl.fused import stacked_job_banks

        return stacked_job_banks(et, env0, n_lanes,
                                 self._device_bank_size(env0),
                                 seed_base=self._collect_seed)

    def _collection_mesh(self, n_lanes: int):
        """The mesh lanes shard over, or None for single-device
        collection: shard lanes over LOCAL devices when they divide
        evenly (the pod collection shape: each chip runs its own lanes;
        without this a multi-chip slice collects on one chip and
        updates on all). Multi-process: a per-process LOCAL mesh keeps
        each process's banks/rngs its own (the global mesh would demand
        cross-process arrays) while still using every local chip."""
        import jax

        local = jax.local_devices()
        if len(local) <= 1:
            return None
        # the candidate mesh is what the collector would actually
        # shard over: the configured training mesh in single-process
        # mode (possibly FEWER devices than the host exposes), a
        # per-process local mesh otherwise
        if jax.process_count() == 1:
            candidate = self.mesh
        else:
            from ddls_tpu.parallel.mesh import make_mesh
            candidate = make_mesh(len(local), devices=local)
        # gate on the value DevicePPOCollector validates (ppo_device
        # .py: num_envs % mesh.shape['dp']), not the local device
        # count — e.g. n_devices=3 on an 8-device host with
        # num_envs=8 divides the host but not the mesh, and must
        # fall back to single-device collection instead of raising
        # (ADVICE r5 item 1)
        dp = int(candidate.shape["dp"])
        if n_lanes % dp == 0:
            return candidate
        import warnings
        warnings.warn(
            f"device_collector: num_envs={n_lanes} not "
            f"divisible by the mesh dp axis ({dp}); lanes "
            "will collect on ONE device (set num_envs to a "
            "multiple for sharded collection)")
        return None

    def _make_device_collector(self):
        """The jitted-env collection path (algo_config
        ``device_collector: true``): per-lane job banks sampled from the
        env's own workload distributions, episodes stepped entirely
        in-kernel. Serves every loop that consumes the shared traj dict
        (ppo, impala, pg). Requires the canonical-RAMP jitted env
        (sim/jax_env.py) and a priceless observation."""
        from ddls_tpu.rl.ppo_device import DevicePPOCollector

        env0, et, ot = self._device_tables()
        stacked = self._stacked_banks(et, env0, self.num_envs)
        mesh = self._collection_mesh(self.num_envs)
        params_shardings = None
        if self.param_sharding != "replicated":
            if mesh is None:
                raise ValueError(
                    f"param_sharding={self.param_sharding!r} needs the "
                    "device collector's lanes sharded over the training "
                    f"mesh, but num_envs={self.num_envs} does not "
                    "divide its dp axis — size num_envs to a multiple "
                    "of the dp width (single-device collection would "
                    "implicitly gather the sharded params every "
                    "collect)")
            from ddls_tpu.parallel.partition import params_shardings_of
            params_shardings = params_shardings_of(
                self.learner._state_shardings(self.state))
        return DevicePPOCollector(et, ot, self.model, stacked,
                                  self.rollout_length,
                                  mesh=mesh,
                                  memo_cfg=self._memo_knob(),
                                  params_shardings=params_shardings)

    # ----------------------------------------------------------------- epoch
    def _split_rng(self):
        """Update rng: the same sequence on every process (fed into the
        jitted sharded train step)."""
        import jax

        self._rng, sub = jax.random.split(self._rng)
        if (self.loop_mode in ("pipelined", "sebulba")
                and jax.process_count() == 1):
            # explicit placement beside the replicated params: the jitted
            # update would otherwise reshard the key implicitly onto the
            # mesh every epoch (the transfer-guard pin catches exactly
            # this class of hidden per-update transfer). Single-process
            # only: under multi-host the key must ride into the jit as a
            # host-local value on every process (a device_put onto the
            # global mesh would fabricate a global array per process)
            replicated = getattr(getattr(self, "learner", None),
                                 "_replicated", None)
            if replicated is not None:
                sub = jax.device_put(sub, replicated)
        return sub

    def _split_collect_rng(self):
        """Collection rng: process-distinct, so hosts sample different
        actions and contribute genuinely different batch shards."""
        import jax

        self._collect_rng, sub = jax.random.split(self._collect_rng)
        return sub

    # ------------------------------------------------- pipelining plumbing
    def _collect_and_stage(self, params, rng):
        """Collect one batch and stage it on the mesh (double-buffered
        under ``pipeline_depth >= 1``: staging the next batches runs on
        the collection thread while the update consumes the previous
        one, whose donated buffers free as it runs).

        Ring handoff (rl/ring.py): when the collector leased a
        trajectory-ring segment, the alias verdict is probed here on
        the segment's FIRST staging (does ``shard_traj``'s device_put
        share the segment's host memory? — the np.shares_memory
        question, answered pointer-wise so it runs under the transfer
        guard). Alias-free segments get the staged tree itself as
        their release token (free the moment the copies land); aliased
        segments wait for an update-output token attached in ``run``."""
        with telemetry.span("train.collect"):
            out = self.collector.collect(params, rng)
        # the staging hop is also a transfer-ledger record (ISSUE 18):
        # host→device for host collection, actor→learner mesh for
        # sebulba — bytes from .nbytes metadata only
        direction = "a2l" if self.loop_mode == "sebulba" else "h2d"
        with telemetry.span("train.device_transfer"):
            with telemetry.transfer("stage.traj", direction) as tr:
                straj, slv = self.learner.shard_traj(out["traj"],
                                                     out["last_values"])
                tr.add(straj)
                tr.add(slv)
        segment = out.get("ring_segment")
        if segment is not None:
            # phase 1 of the ring token protocol (rl/ring.py
            # note_staged): alias verdict + copy-case token
            out["ring"].note_staged(segment, straj["obs"],
                                    generation=out.get("ring_generation"))
        return out, straj, slv

    def _next_batch(self):
        """The epoch's staged batch; under ``pipeline_depth >= 1`` also
        tops the background-collection queue back up to ``depth``
        batches, each submitted against the CURRENT (pre-update) params
        — once the caller dispatches updates, a queued batch is as many
        updates stale as landed before its consumption (its
        ``params_age``), which V-trace corrects. The rng stream is
        split on the main thread in submission order, so collection n
        consumes the same key in every mode (bit-exactness across
        depths of what each batch is collected WITH is not promised —
        staleness is the point — but the rng bookkeeping stays
        deterministic and process-local, preserving the multi-host
        rules). The queue-top-up gate is a pure function of the queue
        length and the configured depth — deterministic, multi-host
        safe."""
        import jax
        import jax.numpy as jnp

        if self._collect_futures:
            future, version = self._collect_futures.pop(0)
            out, straj, slv = future.result()
            out["params_age"] = self._updates_dispatched - version
        else:
            out, straj, slv = self._collect_and_stage(
                self.state.params, self._split_collect_rng())
            out["params_age"] = 0
        if self.pipeline_depth:
            if self._collect_executor is None:
                from concurrent.futures import ThreadPoolExecutor

                self._collect_executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="collect-pipeline")
            while len(self._collect_futures) < self.pipeline_depth:
                # jnp.copy: the live state is about to be DONATED into
                # the update, which deletes its param buffers out from
                # under a concurrent reader; the stale collector needs
                # its own copy
                params = jax.tree_util.tree_map(jnp.copy,
                                                self.state.params)
                rng = self._split_collect_rng()
                self._collect_futures.append((
                    self._collect_executor.submit(
                        self._collect_and_stage, params, rng),
                    self._updates_dispatched))
        return out, straj, slv

    def _watch_update(self, metrics, t0: float) -> None:
        """Record the in-flight update's device wall as a
        ``train.update_device`` span from a monitor thread, so the span
        overlap view (telemetry.overlap_summary) can MEASURE how much of
        it ran concurrently with collection instead of asserting it.
        Only active while telemetry is enabled — the monitor blocks on
        the device off the critical path."""
        if not telemetry.enabled():
            return
        if self._watch_executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._watch_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="update-watch")

        def _block():
            import jax

            try:
                jax.block_until_ready(metrics)
                telemetry.record_span("train.update_device", t0)
            except Exception:
                pass  # observability must never break training

        self._watch_executor.submit(_block)

    def _harvest_metrics(self, metrics, extras: Optional[dict] = None
                         ) -> Any:
        """Sequential mode: the pre-pipelining per-update blocking fetch
        (one ``train.host_sync`` span per epoch). Pipelined mode: wrap
        the device dict as a LazyMetrics future on the unsynced ring;
        ``_maybe_sync_metrics`` drains the ring at sync boundaries.
        ``extras`` are host-side scalars (e.g. the depth-K loop's
        ``params_age_updates``) that ride the mapping without touching
        the device."""
        import jax

        if self.loop_mode == "sequential":
            with telemetry.span("train.host_sync"):
                fetched = {k: float(v)
                           for k, v in jax.device_get(metrics).items()}
            fetched.update(extras or {})
            return fetched
        from ddls_tpu.train.metrics import LazyMetrics

        lazy = LazyMetrics(metrics, extras=extras)
        self._metrics_ring.append(lazy)
        return lazy

    def _maybe_sync_metrics(self, force: bool = False) -> None:
        """Drain the unsynced-metrics ring in ONE batched device fetch
        when a sync boundary is reached (every ``metrics_sync_interval``
        epochs, an eval epoch, or ``force``). The gate is deterministic
        (epoch counter only) — multi-host safe."""
        if not self._metrics_ring:
            return
        if not (force
                or self.epoch_counter % self.metrics_sync_interval == 0):
            return
        from ddls_tpu.train.metrics import LazyMetrics

        ring, self._metrics_ring = self._metrics_ring, []
        with telemetry.span("train.host_sync"):
            with telemetry.transfer("drain.metrics", "d2h") as tr:
                if telemetry.enabled():
                    for lm in ring:
                        tr.add(lm.device_values())
                LazyMetrics.materialize_group(ring)
        self._record_memo_drain()

    def _record_memo_drain(self) -> None:
        """Telemetry-only memo-counter event at a sync boundary (the
        timeline's memo hit-rate counter track): a drain is already a
        sanctioned device-fetch boundary, and the fetch only happens
        while telemetry is enabled (local arrays — no collective, so a
        per-process telemetry toggle stays multi-host safe)."""
        if not telemetry.enabled():
            return
        source = self.fused if self.fused is not None else getattr(
            self, "collector", None)
        fn = getattr(source, "memo_counters", None)
        if fn is None:
            return
        try:
            counters = fn()
        except Exception:
            return
        if counters:
            telemetry.record_event("memo_counters", **counters)

    def sync_metrics(self) -> None:
        """Force-drain any unsynced metrics (checkpoint/shutdown/test
        boundary)."""
        self._maybe_sync_metrics(force=True)

    def ring_stats(self) -> Optional[Dict[str, Any]]:
        """The trajectory ring's ledger counters (rl/ring.py stats:
        segments/leases/stalls/occupancy/mean params-age), or None when
        no ring is installed. Host ints only — safe to fetch at a
        reporting boundary (the bench JSON line's ``ring`` block)."""
        ring = getattr(self.vec_env, "traj_ring", None)
        if ring is None:
            # the sebulba device-mode ring lives on the collector, not
            # the vec env (rl/sebulba.py)
            ring = getattr(getattr(self, "collector", None), "ring",
                           None)
        return ring.stats() if ring is not None else None

    # ------------------------------------------------------- fused epoch
    def _maybe_drain_fused_episodes(self, force: bool = False
                                    ) -> List[dict]:
        """Drain the fused/sebulba epochs' compact episode-counter
        traces in ONE batched fetch and harvest episode records, at the
        SAME sync boundaries as the metrics ring (every
        ``metrics_sync_interval`` epochs, an eval epoch, or ``force``)
        — never per update. The gate is deterministic (epoch counter +
        config only — multi-host rules). The harvester is the owning
        driver: ``self.fused`` ([U, B, T] traces) or the sebulba
        collector ([B, T] traces) — both keep host-side episode
        lengths, so drains must stay in collection order."""
        if not self._fused_episode_ring:
            return []
        is_eval = bool(self.evaluation_interval
                       and self.epoch_counter
                       % self.evaluation_interval == 0)
        if not (force or is_eval
                or self.epoch_counter % self.metrics_sync_interval == 0):
            return []
        import jax

        harvester = (self.fused if self.fused is not None
                     else self.collector)
        ring, self._fused_episode_ring = self._fused_episode_ring, []
        with telemetry.span("train.host_sync"):
            with telemetry.transfer("drain.episodes", "d2h") as tr:
                tr.add(ring)
                fetched = jax.device_get(ring)
        episodes: List[dict] = []
        for ep in fetched:
            episodes.extend(harvester.harvest_episodes(ep))
        return episodes

    def _run_fused(self) -> Dict[str, Any]:
        """One fused epoch: ONE device dispatch runs
        ``updates_per_epoch`` collect→update rounds (`rl/fused.py`).
        Metrics ride the epoch as a [U]-stacked LazyMetrics future and
        episode counters as a pending device trace; both drain per
        ``metrics_sync_interval`` under ``train.host_sync`` — the
        steady-state epoch performs NO device→host transfer. Episode
        summaries therefore appear on drain epochs (covering every
        epoch since the last drain), not per epoch."""
        from ddls_tpu.train.metrics import LazyMetrics

        start = time.time()
        with telemetry.span("train.fused_epoch"):
            (self.state, (self._collect_rng, self._rng), metrics,
             ep) = self.fused.fused_epoch(
                self.state, (self._collect_rng, self._rng))
        self.epoch_counter += 1
        env_steps = self.fused.env_steps_per_epoch
        self.total_env_steps += env_steps
        lazy = LazyMetrics(
            metrics, reduce="mean",
            extras={"num_updates": self.fused.updates_per_epoch})
        self._metrics_ring.append(lazy)
        self._fused_episode_ring.append(ep)
        self._maybe_sync_metrics()
        episodes = self._maybe_drain_fused_episodes()
        results: Dict[str, Any] = {
            "epoch_counter": self.epoch_counter,
            "env_steps_this_iter": env_steps,
            "total_env_steps": self.total_env_steps,
            "learner": lazy,
        }
        return self._finalize_results(results, episodes, start)

    def run(self) -> Dict[str, Any]:
        """Collect one trajectory batch and apply one PPO update.

        Per-update phase spans (no-ops while telemetry is disabled): note
        jax dispatch is async, so ``train.train_step`` measures trace/
        dispatch and ``train.host_sync`` absorbs the device wait — in
        sequential mode once per update, in pipelined mode once per sync
        boundary, with ``train.update_device`` (monitor thread) carrying
        the true device wall of the update (the attribution
        Podracer/MSRL instrument for)."""
        if self.loop_mode == "fused":
            return self._run_fused()
        start = time.time()
        out, straj, slv = self._next_batch()
        update_t0 = telemetry.clock_now() if telemetry.enabled() else 0.0
        with telemetry.span("train.train_step"):
            self.state, metrics = self.learner.train_step(
                self.state, straj, slv, self._split_rng())
        del straj, slv  # donated on accelerator backends: moved-from
        self._updates_dispatched += 1
        segment = out.get("ring_segment")
        if segment is not None:
            # phase 2 of the ring token protocol: alias-case segments
            # may only be rewritten once the update that read their
            # bytes is done — an update output is exactly that marker
            out["ring"].note_update(segment, metrics["total_loss"],
                                    generation=out.get("ring_generation"))
        if self.loop_mode in ("pipelined", "sebulba"):
            self._watch_update(metrics, update_t0)

        self.epoch_counter += 1
        self.total_env_steps += out["env_steps"]
        extras = None
        if self.pipeline_depth:
            # per-batch staleness in updates (the lag V-trace absorbs);
            # host ints — never a device fetch
            age = int(out.get("params_age", 0))
            extras = {"params_age_updates": age}
            ring = out.get("ring")
            if ring is not None:
                ring.observe_params_age(age)
        transit = out.get("segment_transit_s")
        if transit is not None:
            # params_age_updates' sibling (rl/fragments.py): wire +
            # framing lag per segment, net of the actor's own collect
            # wall — says what the network costs, in seconds, next to
            # what staleness costs, in updates. Already a host float
            # (single-clock durations), never a device fetch.
            extras = dict(extras or {})
            extras["segment_transit_s"] = transit
        learner_metrics = self._harvest_metrics(metrics, extras=extras)
        self._maybe_sync_metrics()
        episodes = out["episodes"]
        if self.loop_mode == "sebulba":
            # episode counters stay device-resident until the drain
            # boundary (fused discipline: the steady-state epoch stays
            # transfer-free)
            self._fused_episode_ring.append(out["ep_pending"])
            episodes = self._maybe_drain_fused_episodes()
        results: Dict[str, Any] = {
            "epoch_counter": self.epoch_counter,
            "env_steps_this_iter": out["env_steps"],
            "total_env_steps": self.total_env_steps,
            "learner": learner_metrics,
        }
        return self._finalize_results(results, episodes, start)

    def _finalize_results(self, results: Dict[str, Any],
                          episodes: List[dict], start: float) -> Dict[str, Any]:
        """Shared epoch epilogue: episode summary, periodic evaluation,
        timing bookkeeping."""
        results.update(_episode_summary(episodes))
        results["episodes"] = episodes

        if (self.evaluation_interval
                and self.epoch_counter % self.evaluation_interval == 0):
            # eval is a logging boundary: drain any unsynced metric
            # futures first (the deterministic eval gate itself already
            # syncs the host with the device). Any pipeline_depth >= 1
            # background collections must also settle first — their env
            # stepping draws from the process-global numpy/random state
            # that evaluate() snapshots and reseeds, and racing those
            # would corrupt both streams.
            self._maybe_sync_metrics(force=True)
            for future, _ in self._collect_futures:
                future.result()
            with telemetry.span("train.eval"):
                results["evaluation"] = self.evaluate(
                    self.evaluation_duration)
        self.run_time += time.time() - start
        results["epoch_time"] = time.time() - start
        results["run_time"] = self.run_time
        return results

    # ------------------------------------------------------------ evaluation
    def make_eval_env(self):
        """Build the evaluation env: training env_config with the
        evaluation_config env overrides applied (eval_default.yaml
        evaluation_config.env_config surface)."""
        import copy

        from ddls_tpu.utils.common import recursive_update

        env_config = copy.deepcopy(self.env_config)
        eval_env_overrides = (self.evaluation_config or {}).get(
            "env_config") or {}
        env_config = recursive_update(env_config, eval_env_overrides)
        return self.env_cls(**env_config)

    def evaluate(self, num_episodes: int,
                 seed: Optional[int] = None) -> Dict[str, Any]:
        """Greedy-policy evaluation episodes on a fresh env (the reference
        evaluates with explore=False on eval workers: eval_default.yaml).

        The process-global RNG state is snapshotted around evaluation:
        env.reset(seed) seeds numpy/random globally, and letting the fixed
        test seed leak into the training envs' workload sampling would both
        corrupt training stochasticity and contaminate the held-out test
        stream."""
        import random as _random

        np_state = np.random.get_state()
        py_state = _random.getstate()
        try:
            base_seed = (seed if seed is not None
                         else (self.test_seed
                               if self.test_seed is not None
                               else self.seed + 10_000))
            episodes = self._run_greedy_episodes_batched(num_episodes,
                                                         base_seed)
            return _episode_summary(episodes)
        finally:
            np.random.set_state(np_state)
            _random.setstate(py_state)

    def _run_greedy_episodes_batched(self, num_episodes: int,
                                     base_seed: int) -> List[dict]:
        """One episode per parallel eval env, all driven by a single
        jitted greedy call per step (the TPU-native replacement for the
        reference's parallel eval workers, eval_default.yaml). Finished
        envs keep contributing their last obs to the (static-shape) batch
        but are no longer stepped.

        Env stochasticity is drawn lazily from the process-global
        numpy/random state that ``env.reset(seed)`` seeds, so each env's
        global-RNG state is swapped in around its reset and every step —
        episode i consumes exactly the stream seeded by ``base_seed + i``,
        bit-identical to running the episodes sequentially (and therefore
        invariant to ``num_episodes``)."""
        import random as _random

        from ddls_tpu.rl.rollout import harvest_episode_record, stack_obs

        def rng_state():
            return (np.random.get_state(), _random.getstate())

        def set_rng_state(state) -> None:
            np.random.set_state(state[0])
            _random.setstate(state[1])

        # env construction is expensive (full cluster/topology build);
        # reuse across evaluate() calls — env.reset(seed) makes reuse
        # bit-identical to fresh envs (asserted in tests)
        cache = getattr(self, "_eval_envs", [])
        while len(cache) < num_episodes:
            cache.append(self.make_eval_env())
        self._eval_envs = cache
        envs = cache[:num_episodes]
        obs, rng_states = [], []
        for i, env in enumerate(envs):
            obs.append(env.reset(seed=base_seed + i))
            rng_states.append(rng_state())
        done = np.zeros(num_episodes, dtype=bool)
        totals = np.zeros(num_episodes)
        lengths = np.zeros(num_episodes, dtype=np.int64)
        records: List[Optional[dict]] = [None] * num_episodes
        while not done.all():
            actions = self._greedy_actions(stack_obs(obs))
            for i in np.flatnonzero(~done):
                set_rng_state(rng_states[i])
                obs[i], reward, d, _ = envs[i].step(int(actions[i]))
                rng_states[i] = rng_state()
                totals[i] += reward
                lengths[i] += 1
                if d:
                    done[i] = True
                    records[i] = harvest_episode_record(
                        envs[i], i, totals[i], lengths[i])
        return [r for r in records if r is not None]

    def _run_greedy_episode(self, env, seed: int) -> Dict[str, Any]:
        """Single-episode evaluation on a caller-provided env (RLEvalLoop
        surface); same greedy policy as the batched path."""
        from ddls_tpu.rl.rollout import harvest_episode_record, stack_obs

        obs = env.reset(seed=seed)
        done = False
        total, steps = 0.0, 0
        while not done:
            action = int(self._greedy_actions(stack_obs([obs]))[0])
            obs, reward, done, _ = env.step(action)
            total += reward
            steps += 1
        return harvest_episode_record(env, 0, total, steps)

    def _greedy_actions(self, batched_obs) -> np.ndarray:
        """Greedy actions for a [B, ...] obs batch via one jitted device
        call; PPO-family: argmax of the (mask-adjusted) policy logits."""
        import jax

        if not hasattr(self, "_jit_greedy"):
            self._jit_greedy = jax.jit(
                lambda p, o: self.learner.apply_fn(p, o)[0].argmax(axis=-1))
        return np.asarray(jax.device_get(
            self._jit_greedy(self.state.params, batched_obs)))


    # ----------------------------------------------------------- checkpoints
    def save_agent_checkpoint(self, path: str) -> str:
        from ddls_tpu.train.checkpointer import save_train_state

        save_train_state(self.state, path)
        return path

    def load_agent_checkpoint(self, path: str) -> None:
        from ddls_tpu.train.checkpointer import restore_train_state

        self.state = restore_train_state(path, target=self.state)

    @staticmethod
    def _lookup_metric(results: Dict[str, Any], metric: str):
        """Resolve a '/'-separated metric path, allowing keys that contain
        literal '/' (e.g. 'evaluation/custom_metrics/blocking_rate_mean'
        where 'custom_metrics/blocking_rate_mean' is one key): at each dict
        level the longest matching '/'-joined key wins."""
        from collections.abc import Mapping

        def walk(node, segments):
            if not segments:
                return node
            if not isinstance(node, Mapping):  # dicts AND LazyMetrics
                return None
            for cut in range(len(segments), 0, -1):
                key = "/".join(segments[:cut])
                if key in node:
                    found = walk(node[key], segments[cut:])
                    if found is not None:
                        return found
            return None

        return walk(results, metric.split("/"))

    def register_checkpoint(self, path: str,
                            results: Dict[str, Any]) -> None:
        """Track the best checkpoint by the configured metric (reference:
        rllib_epoch_loop.py:184-227)."""
        value = self._lookup_metric(results, self.metric)
        record = {"epoch": self.epoch_counter, "path": path,
                  "metric": self.metric, "value": value}
        self.checkpoint_history.append(record)
        if value is None:
            return
        better = (self.best_metric_value is None
                  or (value > self.best_metric_value
                      if self.metric_goal == "maximise"
                      else value < self.best_metric_value))
        if better:
            self.best_metric_value = value
            self.best_checkpoint_path = path

    # ---------------------------------------------------------------- misc
    def log(self, results: Dict[str, Any]) -> None:
        """Flatten scalars to W&B if configured (reference:
        rllib_epoch_loop.py:144)."""
        if self.wandb is None:
            return
        from collections.abc import Mapping

        flat = {}

        def walk(node, prefix=""):
            if isinstance(node, Mapping):  # dicts AND LazyMetrics (the
                # W&B flatten IS a logging boundary: iterating a pending
                # LazyMetrics materialises it — one batched fetch)
                for k, v in node.items():
                    walk(v, f"{prefix}{k}/")
            elif isinstance(node, (int, float, np.floating, np.integer)):
                flat[prefix[:-1]] = float(node)

        walk(results)
        # telemetry phase spans ride the same flatten (one vocabulary for
        # per-update timing whether read from W&B or a snapshot)
        if telemetry.enabled():
            for name, summ in telemetry.span_summaries().items():
                for key, value in summ.items():
                    flat[f"telemetry/span/{name}/{key}"] = float(value)
        self.wandb.log(flat)

    def close(self) -> None:
        for future, _ in self._collect_futures:
            try:  # leave the env workers in a consistent state
                future.result(timeout=60)
            except Exception:
                pass
        self._collect_futures = []
        for executor in (self._collect_executor, self._watch_executor):
            if executor is not None:
                executor.shutdown(wait=True)
        self._collect_executor = self._watch_executor = None
        self.sync_metrics()
        # the final undrained interval's fused episode records are
        # harvested (completed episodes must not vanish with the loop);
        # no run() remains to return them, so they land on
        # ``undrained_episodes`` for callers that aggregate records
        self.undrained_episodes = self._maybe_drain_fused_episodes(
            force=True)
        if self.run_ledger is not None:
            # run-boundary counter blocks for snapshot.json (host ints /
            # already-fetched values only — one memo fetch, no per-epoch
            # cost)
            source = (self.fused if self.fused is not None
                      else getattr(self, "collector", None))
            memo_fn = getattr(source, "memo_counters", None)
            memo = None
            if memo_fn is not None:
                try:
                    memo = memo_fn()
                except Exception:
                    memo = None
            if memo and telemetry.enabled():
                telemetry.record_event("memo_counters", **memo)
            self.run_ledger.finalize(blocks={
                "ring": self.ring_stats(),
                "memo": memo,
                "train": {"epochs": self.epoch_counter,
                          "total_env_steps": self.total_env_steps,
                          "run_time_s": self.run_time},
            })
        if self._chip_lock is not None:
            self._chip_lock.__exit__()
            self._chip_lock = None
        collector = getattr(self, "collector", None)
        if collector is not None and hasattr(collector, "close"):
            collector.close()  # the sebulba device ring's ledger
        self.vec_env.close()


class ApexDQNEpochLoop(RLEpochLoop):
    """Ape-X DQN epoch loop: vectorised epsilon-greedy collection into a
    prioritised replay buffer + jitted double/dueling DQN updates on the
    mesh (reference trains the same env through RLlib's ApexTrainer,
    algo/apex_dqn.yaml; see ddls_tpu.rl.dqn for the TPU-native redesign)."""

    # replay insertion + epsilon schedules step the HOST envs; a fused
    # in-kernel epoch cannot express them (rejected loudly in __init__)
    SUPPORTS_FUSED = False
    SUPPORTS_PARAM_SHARDING = False  # host replay insertion path
    SUPPORTS_SOCKET_COLLECTION = False  # replay needs per-step control

    def _configure_algo(self, algo_config, num_envs, rollout_length) -> None:
        self.dqn_cfg = dqn_config_from_rllib(algo_config)
        self._size_rollouts(algo_config, num_envs, rollout_length,
                            self.dqn_cfg.train_batch_size)

    def _build_model(self, n_actions: int, model_config):
        import copy

        # Q-net logits must stay finite for the dueling mean; invalid
        # actions are masked at selection instead (dqn.py module docstring)
        model_config = copy.deepcopy(model_config or {})
        model_config.setdefault("custom_model_config", {})[
            "apply_action_mask"] = False
        return build_policy_from_model_config(n_actions, model_config)

    def _build_learner(self) -> None:
        from ddls_tpu.rl.dqn import ApexDQNLearner, PrioritizedReplayBuffer

        if self.device_collector:
            raise ValueError(
                "device_collector is not supported for apex_dqn: replay "
                "insertion + epsilon schedules step the host envs (use "
                "ppo/impala/pg, or rl/es_device.py for on-device ES)")
        cfg = self.dqn_cfg
        self.learner = ApexDQNLearner(self.apply_fn, cfg, self.mesh)
        self.state = self.learner.init_state(self.params)
        self.replay = PrioritizedReplayBuffer(
            cfg.buffer_capacity, cfg.prioritized_replay_alpha,
            cfg.prioritized_replay_beta, cfg.prioritized_replay_eps,
            seed=self.seed)
        self._nstep_queues: List[List[dict]] = [
            [] for _ in range(self.num_envs)]
        if (self.loop_mode == "pipelined"
                and getattr(self.vec_env, "prefetch_stacked", None)
                is False):
            self.vec_env.prefetch_stacked = True

    def run(self) -> Dict[str, Any]:
        """Collect rollout_length epsilon-greedy steps per env into replay,
        then apply ``training_intensity``-matched DQN updates.

        Replay insertion and epsilon schedules keep collection on the
        host, so only the metric-sync schedule changes between loop
        modes: sequential fetches each update's metrics under its own
        ``train.host_sync``; pipelined keeps the per-update dicts on
        device and logs their mean as one LazyMetrics future (the
        per-update ``td`` fetch stays — priorities feed the next
        sample). ``pipeline_depth > 0`` is rejected in __init__."""
        import jax

        from ddls_tpu.rl.dqn import nstep_transitions, per_worker_epsilons
        from ddls_tpu.rl.rollout import OBS_KEYS

        def slim(obs):
            # keep only network-consumed keys (drops e.g. the constant
            # action_set) so replay storage matches the acting pytree
            return {k: obs[k] for k in OBS_KEYS}

        cfg = self.dqn_cfg
        start = time.time()
        T, B = self.rollout_length, self.num_envs

        with telemetry.span("train.collect"):
            for _ in range(T):
                # stacked_obs: with the prefetching vec env this batch
                # was assembled while the previous step's workers ran
                batched = self.vec_env.stacked_obs()
                eps = per_worker_epsilons(B, self.total_env_steps, cfg)
                actions = np.asarray(self.learner.sample_actions(
                    self.state.params, batched, self._split_collect_rng(),
                    eps))
                prev_obs = list(self.vec_env.obs)
                _, rewards, dones = self.vec_env.step(actions)
                for i in range(B):
                    queue = self._nstep_queues[i]
                    queue.append({
                        "obs": slim(prev_obs[i]), "action": int(actions[i]),
                        "reward": float(rewards[i]), "done": bool(dones[i]),
                        # at episode end this is the auto-reset obs, but
                        # then discount == 0 so the target never reads it
                        "next_obs": slim(self.vec_env.obs[i])})
                    for tr in nstep_transitions(queue, cfg.n_step,
                                                cfg.gamma,
                                                flush=bool(dones[i])):
                        self.replay.add(tr)
                self.total_env_steps += B

        env_steps = T * B
        metrics_acc: List[Dict[str, float]] = []
        # learning_starts counts cumulative sampled transitions (as RLlib
        # does), NOT current buffer occupancy — a capacity smaller than
        # learning_starts must still start training once enough steps were
        # sampled. The buffer-warm gate is a *deterministic lower bound* on
        # replay size (sampled steps minus the worst-case n-step queue
        # residue) rather than the actual per-host size: under multi-host
        # training the jitted update is a cross-process collective, so
        # every process must take this branch on the same epoch.
        replay_lower_bound = self.total_env_steps - B * (cfg.n_step - 1)
        if (self.total_env_steps >= cfg.learning_starts
                and replay_lower_bound >= cfg.train_batch_size
                and self.replay.size >= cfg.train_batch_size):
            num_updates = max(1, int(round(
                env_steps * cfg.training_intensity / cfg.train_batch_size)))
            for _ in range(num_updates):
                batch, idx, weights = self.replay.sample(
                    cfg.train_batch_size)
                tbatch = {"obs": batch["obs"],
                          "actions": batch["action"],
                          "rewards": batch["reward"],
                          "next_obs": batch["next_obs"],
                          "discounts": batch["discount"],
                          "weights": weights}
                with telemetry.span("train.train_step"):
                    self.state, metrics, td = self.learner.train_step(
                        self.state, tbatch)
                # host-side replay work gets its own span: train.host_sync
                # must attribute DEVICE wait only (run() docstring), not
                # priority-update CPU time
                with telemetry.span("train.replay_update"):
                    self.replay.update_priorities(idx, td)
                if self.loop_mode == "sequential":
                    with telemetry.span("train.host_sync"):
                        metrics_acc.append({k: float(v) for k, v in
                                            jax.device_get(metrics).items()})
                else:
                    metrics_acc.append(metrics)  # device futures

        self.epoch_counter += 1
        extras = {"num_updates": len(metrics_acc),
                  "replay_size": self.replay.size}
        if self.loop_mode == "sequential":
            learner_metrics = (
                {k: float(np.mean([m[k] for m in metrics_acc]))
                 for k in metrics_acc[0]} if metrics_acc else {})
            learner_metrics.update(extras)
        else:
            from ddls_tpu.train.metrics import LazyMetrics

            learner_metrics = LazyMetrics(metrics_acc, reduce="mean",
                                          extras=extras)
            self._metrics_ring.append(learner_metrics)
            self._maybe_sync_metrics()
        results: Dict[str, Any] = {
            "epoch_counter": self.epoch_counter,
            "env_steps_this_iter": env_steps,
            "total_env_steps": self.total_env_steps,
            "learner": learner_metrics,
        }
        return self._finalize_results(
            results, self.vec_env.drain_completed_episodes(), start)

    def _greedy_actions(self, batched_obs) -> np.ndarray:
        # epsilon-0 through the learner's sampler so invalid actions stay
        # masked at selection (Q-logits themselves are unmasked, dqn.py)
        import jax

        B = int(np.asarray(batched_obs["action_mask"]).shape[0])
        actions = self.learner.sample_actions(
            self.state.params, batched_obs, jax.random.PRNGKey(0),
            np.zeros(B, np.float32))
        return np.asarray(actions)


# RLlib IMPALA keys (algo/impala.yaml) -> ImpalaConfig fields; Ray queue /
# aggregation plumbing keys are ignored
_RLLIB_TO_IMPALA = {
    "lr": "lr",
    "gamma": "gamma",
    "vtrace_clip_rho_threshold": "vtrace_clip_rho_threshold",
    "vtrace_clip_pg_rho_threshold": "vtrace_clip_pg_rho_threshold",
    "vtrace_drop_last_ts": "vtrace_drop_last_ts",
    "vf_loss_coeff": "vf_loss_coeff",
    "entropy_coeff": "entropy_coeff",
    "grad_clip": "grad_clip",
    "opt_type": "opt_type",
    "decay": "decay",
    "momentum": "momentum",
    "epsilon": "epsilon",
    "train_batch_size": "train_batch_size",
}


def impala_config_from_rllib(algo_config: Optional[dict]):
    from ddls_tpu.rl.impala import ImpalaConfig

    _reject_unknown_algo_keys("impala", (algo_config or {}),
                              _RLLIB_TO_IMPALA)
    kwargs = {}
    for src, dst in _RLLIB_TO_IMPALA.items():
        if algo_config and algo_config.get(src) is not None:
            kwargs[dst] = algo_config[src]
    return ImpalaConfig(**kwargs)


def pg_config_from_rllib(algo_config: Optional[dict]):
    from ddls_tpu.rl.pg import PGConfig

    known = (("lr", "lr"), ("gamma", "gamma"), ("grad_clip", "grad_clip"),
             ("train_batch_size", "train_batch_size"))
    _reject_unknown_algo_keys("pg", (algo_config or {}),
                              [src for src, _ in known])
    kwargs = {}
    for src, dst in known:
        if algo_config and algo_config.get(src) is not None:
            kwargs[dst] = algo_config[src]
    return PGConfig(**kwargs)


def es_config_from_rllib(algo_config: Optional[dict]):
    from ddls_tpu.rl.es import ESConfig

    known = ("stepsize", "noise_stdev", "l2_coeff", "episodes_per_batch",
             "report_length", "eval_prob", "action_noise_std",
             "train_batch_size")
    _reject_unknown_algo_keys("es", (algo_config or {}), known)
    kwargs = {}
    for key in known:
        if algo_config and algo_config.get(key) is not None:
            kwargs[key] = algo_config[key]
    return ESConfig(**kwargs)


class ImpalaEpochLoop(RLEpochLoop):
    """IMPALA epoch loop: the same vectorised collector as PPO (its one-
    epoch policy lag is exactly what V-trace corrects) with a single jitted
    V-trace update per batch (reference: algo/impala.yaml through
    rllib_epoch_loop.py:34).

    The one loop where ``pipeline_depth >= 1`` is sound: up to ``depth``
    collections run ahead on the background thread against pre-update
    params while the device applies updates — V-trace's importance
    weighting corrects exactly that policy lag (reported per batch as
    ``params_age_updates``), in the actor/learner-decoupled shape of
    the Podracer/MSRL/SEED-RL pipelines. On the shm backend the
    in-flight batches live in a ``depth + 2``-segment trajectory ring
    (rl/ring.py) whose ownership ledger stands in for the per-segment
    bulk copy; other backends fall back to fresh per-collect buffers,
    correct either way."""

    SUPPORTS_STALE_COLLECTION = True

    def _configure_algo(self, algo_config, num_envs, rollout_length) -> None:
        self.impala_cfg = impala_config_from_rllib(algo_config)
        self._size_rollouts(algo_config, num_envs, rollout_length,
                            self.impala_cfg.train_batch_size)

    def _make_learner(self):
        from ddls_tpu.rl.impala import ImpalaLearner

        return ImpalaLearner(self.apply_fn, self.impala_cfg, self.mesh,
                             param_sharding=self.param_sharding)

    def _fused_step_fn(self):
        # V-trace update takes no rng; the per-round key split still
        # happens in-kernel so the stream bookkeeping matches the
        # sequential loop (which also splits then ignores the key)
        step = self.learner._train_step
        return lambda state, traj, last_values, rng: step(
            state, traj, last_values)


class PGEpochLoop(RLEpochLoop):
    """Vanilla policy-gradient epoch loop (reference: algo/pg.yaml)."""

    def _configure_algo(self, algo_config, num_envs, rollout_length) -> None:
        self.pg_cfg = pg_config_from_rllib(algo_config)
        self._size_rollouts(algo_config, num_envs, rollout_length,
                            self.pg_cfg.train_batch_size)

    def _make_learner(self):
        from ddls_tpu.rl.pg import PGLearner

        return PGLearner(self.apply_fn, self.pg_cfg, self.mesh,
                         param_sharding=self.param_sharding)

    def _fused_step_fn(self):
        step = self.learner._train_step  # REINFORCE update takes no rng
        return lambda state, traj, last_values, rng: step(
            state, traj, last_values)


class ESEpochLoop(RLEpochLoop):
    """Evolution-strategies epoch loop (reference: algo/es.yaml).

    Each epoch: draw an antithetic population (one member per vectorised
    env), evaluate every member's fitness over a fixed interaction window
    with a single vmapped population forward per step, then apply the
    rank-shaped ES update on device. ``num_envs`` is the population size
    and must be even.
    """

    # population fitness steps the HOST envs (the fully on-device ES
    # path is rl/es_device.py); fused epochs are rejected loudly
    SUPPORTS_FUSED = False
    SUPPORTS_PARAM_SHARDING = False  # host population-fitness path
    SUPPORTS_SOCKET_COLLECTION = False  # fitness steps envs directly

    def _configure_algo(self, algo_config, num_envs, rollout_length) -> None:
        self.es_cfg = es_config_from_rllib(algo_config)
        self.num_envs = int(num_envs
                            or (algo_config or {}).get("num_workers") or 10)
        if self.num_envs % 2:
            self.num_envs += 1  # antithetic pairs
        self.rollout_length = int(
            rollout_length
            or max(self.es_cfg.train_batch_size // self.num_envs, 1))

    def _build_learner(self) -> None:
        from ddls_tpu.rl.es import ESLearner

        self.learner = ESLearner(self.apply_fn, self.es_cfg, self.mesh,
                                 population=self.num_envs)
        if self.device_collector:
            raise ValueError(
                "device_collector is not supported for es (population "
                "fitness steps the host envs; the fully on-device ES "
                "path is rl/es_device.py:train_es_on_device)")
        self.state = self.learner.init_state(self.params)
        self.collector = None

    def run(self) -> Dict[str, Any]:
        import jax

        start = time.time()
        # the perturbation rng feeds a state update, so it must be the
        # SHARED stream: every host draws the identical population. Hosts
        # then evaluate it on their own (differently seeded) envs and the
        # per-member fitness is averaged across hosts — multi-host ES is
        # fitness variance reduction, not population scale-out.
        epoch_rng = self._split_rng()
        perturb_rng, eval_gate_rng = jax.random.split(epoch_rng)
        # action-noise rng is COLLECT randomness (per-process, like env
        # seeds): hosts must explore independently for the cross-host
        # fitness average to reduce variance. Only perturb/gate draws come
        # from the shared stream (they feed the update / guard a branch)
        noise_rng = self._split_collect_rng()
        with telemetry.span("train.collect"):
            stacked, eps = self.learner.perturb(self.state.params,
                                                perturb_rng)
            fitness = self.learner.evaluate_population(
                stacked, self.vec_env, window=self.rollout_length,
                rng=noise_rng)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            fitness = np.mean(
                multihost_utils.process_allgather(
                    np.asarray(fitness, np.float32)), axis=0)
        with telemetry.span("train.train_step"):
            self.state, metrics = self.learner.update(self.state, eps,
                                                      fitness)
        metrics = self._harvest_metrics(metrics)
        # training episodes are drained BEFORE any eval window so the eval
        # policy's episodes can never leak into the training stats
        completed_episodes = self.vec_env.drain_completed_episodes()
        # eval_prob: occasionally measure the unperturbed mean params
        # (noise-free, excluded from the gradient). The gate draws from the
        # SHARED rng stream, so every host takes the same branch and the
        # fitness allgather above can never desync (CLAUDE.md multi-host
        # rule: deterministic gates only). The window runs on the training
        # vec env — window fitness already carries state across epochs (the
        # next population inherits the last one's env states by design), so
        # the mean policy advancing them is the same regime; its episodes
        # are drained and dropped, and its steps are reported separately
        eval_env_steps = 0
        if (self.es_cfg.eval_prob > 0
                and float(jax.random.uniform(eval_gate_rng))
                < self.es_cfg.eval_prob):
            metrics["eval_fitness_mean"] = self.learner.evaluate_mean_params(
                self.state.params, self.vec_env,
                window=self.rollout_length)
            eval_env_steps = self.rollout_length * self.num_envs
            # drop the eval window's own episodes AND the part-eval partial
            # episodes still in flight: a fresh restart is the only way
            # mean-policy steps can't straddle into next epoch's stats
            self.vec_env.drain_completed_episodes()
            self.vec_env.restart_episodes()

        self.epoch_counter += 1
        # sync gate AFTER the increment, so the drain cadence matches the
        # base/DQN loops (epochs interval, 2*interval, ...) exactly
        self._maybe_sync_metrics()
        env_steps = self.rollout_length * self.num_envs
        self.total_env_steps += env_steps
        results: Dict[str, Any] = {
            "epoch_counter": self.epoch_counter,
            "env_steps_this_iter": env_steps,
            "total_env_steps": self.total_env_steps,
            "learner": metrics,
        }
        if eval_env_steps:
            results["eval_env_steps_this_iter"] = eval_env_steps
        return self._finalize_results(results, completed_episodes, start)


# algo_name (our algo/*.yaml) -> epoch-loop class; train_from_config
# dispatches through this and hard-errors on unknown names so a mistyped
# algo can never silently train PPO-with-defaults
EPOCH_LOOPS = {
    "ppo": RLEpochLoop,
    "apex_dqn": ApexDQNEpochLoop,
    "impala": ImpalaEpochLoop,
    "pg": PGEpochLoop,
    "es": ESEpochLoop,
}


def make_epoch_loop(algo_name: Optional[str], **kwargs):
    name = (algo_name or "ppo").lower()
    if name not in EPOCH_LOOPS:
        raise ValueError(
            f"unknown algo_name {algo_name!r}; available: "
            f"{sorted(EPOCH_LOOPS)}")
    return EPOCH_LOOPS[name](**kwargs)


class EvalLoop:
    """Heuristic-actor evaluation (reference: ddls/loops/eval_loop.py:14).

    ``actor`` implements ``compute_action(obs, job_to_place=...)``; results
    harvest the cluster's steps_log and episode_stats.
    """

    def __init__(self, env, actor, wandb=None, verbose: bool = False,
                 **kwargs):
        self.env = env
        self.actor = actor
        self.wandb = wandb
        self.verbose = verbose

    def run(self, seed: Optional[int] = None,
            max_steps: Optional[int] = None) -> Dict[str, Any]:
        obs = self.env.reset(seed=seed)
        # episode boundary for stateful actors (e.g. AdaptiveDegreePacking's
        # legacy load estimate): explicit reset beats heuristic detection
        reset = getattr(self.actor, "reset", None)
        if callable(reset):
            reset()
        done, steps, total_reward = False, 0, 0.0
        start = time.time()
        while not done and (max_steps is None or steps < max_steps):
            job = None
            queue = getattr(self.env.cluster, "job_queue", None)
            if queue is not None and len(queue.jobs):
                job = list(queue.jobs.values())[0]
            action = self.actor.compute_action(obs, job_to_place=job,
                                               env=self.env)
            obs, reward, done, _ = self.env.step(action)
            total_reward += reward
            steps += 1
            if self.verbose:
                print(f"step {steps}: action={action} reward={reward:.4f}")
        results = {
            "episode_return": total_reward,
            "episode_length": steps,
            "wall_time": time.time() - start,
            "episode_stats": dict(self.env.cluster.episode_stats),
            "steps_log": {k: list(v)
                          for k, v in self.env.cluster.steps_log.items()},
        }
        if self.wandb is not None:
            self.wandb.log({"eval/episode_return": total_reward,
                            "eval/episode_length": steps})
        return results


class RLEvalLoop:
    """Checkpoint-restoring policy evaluation (reference:
    ddls/loops/rllib_eval_loop.py:11)."""

    def __init__(self, epoch_loop: RLEpochLoop, **kwargs):
        self.epoch_loop = epoch_loop

    def run(self, checkpoint_path: Optional[str] = None,
            seed: Optional[int] = None) -> Dict[str, Any]:
        if checkpoint_path:
            self.epoch_loop.load_agent_checkpoint(checkpoint_path)
        env = self.epoch_loop.make_eval_env()
        record = self.epoch_loop._run_greedy_episode(
            env, seed if seed is not None
            else (self.epoch_loop.test_seed or 0))
        return {
            "episode": record,
            "episode_stats": dict(env.cluster.episode_stats),
            "steps_log": {k: list(v)
                          for k, v in env.cluster.steps_log.items()},
        }


class EnvLoop:
    """Generic single-episode driver (reference: ddls/loops/env_loop.py:4)."""

    def __init__(self, env, actor):
        self.env = env
        self.actor = actor

    def run(self, seed: Optional[int] = None) -> Dict[str, Any]:
        obs = self.env.reset(seed=seed)
        done, steps, total = False, 0, 0.0
        while not done:
            action = self.actor.compute_action(obs)
            obs, reward, done, _ = self.env.step(action)
            total += reward
            steps += 1
        return {"episode_return": total, "episode_length": steps}


class EpochLoop:
    """Generic batch-of-episodes driver (reference:
    ddls/loops/epoch_loop.py:5)."""

    def __init__(self, env_loop: EnvLoop, episodes_per_epoch: int = 1):
        self.env_loop = env_loop
        self.episodes_per_epoch = episodes_per_epoch

    def run(self) -> Dict[str, Any]:
        episodes = [self.env_loop.run()
                    for _ in range(self.episodes_per_epoch)]
        return {"episodes": episodes}
