"""Agent checkpointing via orbax.

The reference delegates to RLlib ``trainer.save`` through a thin
``Checkpointer`` (ddls/checkpointers/checkpointer.py:3,
ddls/loops/rllib_epoch_loop.py:251); here the epoch loop exposes
``save_agent_checkpoint(path)`` (orbax PyTree checkpoint of the learner
``TrainState``) and this class owns the directory layout + cadence.
"""
from __future__ import annotations

from pathlib import Path
from typing import Optional


class Checkpointer:
    def __init__(self, path_to_save: str,
                 epoch_checkpoint_freq: Optional[int] = 1, **kwargs):
        self.checkpoints_dir = Path(path_to_save) / "checkpoints"
        self.checkpoints_dir.mkdir(parents=True, exist_ok=True)
        self.epoch_checkpoint_freq = epoch_checkpoint_freq

    def should_checkpoint(self, epoch_counter: int) -> bool:
        freq = self.epoch_checkpoint_freq
        # 0/None uniformly mean "never checkpoint"
        return bool(freq) and freq > 0 and epoch_counter % freq == 0

    def write(self, epoch_loop, epoch_counter: int) -> str:
        path = self.checkpoints_dir / f"checkpoint_{epoch_counter:06d}"
        epoch_loop.save_agent_checkpoint(str(path))
        return str(path)


def save_train_state(state, path: str) -> None:
    """Save a learner TrainState (params/opt_state/counters).

    Single-process: orbax PyTree checkpoint. Multi-process: only the
    primary calls this, and orbax synchronises *all* processes on save
    (even for host arrays), which would deadlock -- so the fully
    replicated state is fetched to host numpy and written by this process
    alone as a gzip pickle.
    """
    import jax
    if jax.process_count() > 1:
        import gzip
        import pickle

        state_np = jax.tree_util.tree_map(
            lambda x: jax.device_get(x) if isinstance(x, jax.Array) else x,
            state)
        out = Path(path).absolute()
        out.mkdir(parents=True, exist_ok=True)
        with gzip.open(out / "state.pkl.gz", "wb") as f:
            pickle.dump(state_np, f)
        return
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(str(Path(path).absolute()), state, force=True)


def restore_train_state(path: str, target=None):
    """Restore a TrainState saved by :func:`save_train_state`.

    ``target`` (a template state with matching structure) restores typed
    arrays; without it, the raw pytree is returned. Handles both backends
    (orbax dir or the multi-process single-writer pickle).

    When ``target`` leaves are committed ``jax.Array``s, the restored
    state is re-placed onto the target's SHARDINGS leaf-for-leaf — a
    sharded-layout state (``parallel/partition.py`` fsdp/tp) restores
    sharded, never silently de-sharded to host/default placement; the
    replicated default round-trips through the same path bit-identically
    (a ``device_put`` onto the sharding it was saved from).
    """
    pickled = Path(path).absolute() / "state.pkl.gz"
    if pickled.exists():
        import gzip
        import pickle

        import jax

        with gzip.open(pickled, "rb") as f:
            loaded = pickle.load(f)
        if target is not None:
            return _reapply_shardings(jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(target),
                jax.tree_util.tree_leaves(loaded)), target)
        return loaded
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    if target is not None:
        return _reapply_shardings(
            ckptr.restore(str(Path(path).absolute()), item=target),
            target)
    return ckptr.restore(str(Path(path).absolute()))


def _reapply_shardings(restored, target):
    """Re-place restored leaves onto the target's shardings (single-
    process ``device_put``; multi-process states are replicated-only —
    train/loops.py rejects sharded layouts there — and ride the
    collective-free ``place_state_tree`` contract at init instead)."""
    import jax

    if jax.process_count() > 1:
        return restored

    def put(r, t):
        if isinstance(t, jax.Array) and t.committed:
            return jax.device_put(r, t.sharding)
        return r

    return jax.tree_util.tree_map(put, restored, target)
