"""Verbatim reference-config compatibility.

The reference's shipped config trees
(`/root/reference/scripts/ramp_job_partitioning_configs`,
`ramp_job_placement_shaping_configs`) name Ray/RLlib trainer classes,
`ddls.*` module paths, and Ray process-plumbing hyperparameters. This
module translates that surface onto the TPU stack so the reference trees
load and run unchanged (BASELINE "the existing configs run unchanged"),
while keeping the strict unknown-key rejection for anything NOT on the
known reference surface (train/loops.py:_reject_unknown_algo_keys).

Policy: *known* reference classes are mapped; *known* Ray plumbing keys
are dropped with one loud warning naming them; anything unknown still
hard-errors downstream.
"""
from __future__ import annotations

import warnings
from typing import Any, Dict

# reference trainer-class path suffix -> TPU algo name
TRAINER_TO_ALGO = {
    "PPOTrainer": "ppo",
    "ApexTrainer": "apex_dqn",
    "ImpalaTrainer": "impala",
    "PGTrainer": "pg",
    "ESTrainer": "es",
}

# reference `ddls.` class paths -> TPU classes (curated, not guessed:
# an unmapped ddls.* path raises so silent misconfiguration is impossible)
REF_CLASS_MAP = {
    "ddls.environments.ramp_job_partitioning."
    "ramp_job_partitioning_environment.RampJobPartitioningEnvironment":
        "ddls_tpu.envs.partitioning_env.RampJobPartitioningEnvironment",
    "ddls.environments.ramp_job_placement_shaping."
    "ramp_job_placement_shaping_environment."
    "RampJobPlacementShapingEnvironment":
        "ddls_tpu.envs.placement_shaping_env."
        "RampJobPlacementShapingEnvironment",
    "ddls.environments.job_placing.job_placing_all_nodes_environment."
    "JobPlacingAllNodesEnvironment":
        "ddls_tpu.envs.job_placing_env.JobPlacingAllNodesEnvironment",
    "ddls.loops.rllib_epoch_loop.RLlibEpochLoop":
        "ddls_tpu.train.loops.RLEpochLoop",
    "ddls.loops.rllib_eval_loop.RLlibEvalLoop":
        "ddls_tpu.train.loops.RLEvalLoop",
    "ddls.loops.eval_loop.EvalLoop":
        "ddls_tpu.train.loops.EvalLoop",
    "ddls.loops.env_loop.EnvLoop":
        "ddls_tpu.train.loops.EnvLoop",
    "ddls.loops.epoch_loop.EpochLoop":
        "ddls_tpu.train.loops.EpochLoop",
    "ddls.ml_models.policies.gnn_policy.GNNPolicy":
        "ddls_tpu.models.policy.GNNPolicy",
    "ddls.ml_models.policies.GNNPolicy":
        "ddls_tpu.models.policy.GNNPolicy",
    "ddls.distributions.fixed.Fixed":
        "ddls_tpu.demands.distributions.Fixed",
    "ddls.distributions.uniform.Uniform":
        "ddls_tpu.demands.distributions.Uniform",
    "ddls.distributions.custom_skew_norm.CustomSkewNorm":
        "ddls_tpu.demands.distributions.CustomSkewNorm",
    "ddls.distributions.probability_mass_function."
    "ProbabilityMassFunction":
        "ddls_tpu.demands.distributions.ProbabilityMassFunction",
    "ddls.distributions.list_of_distributions.ListOfDistributions":
        "ddls_tpu.demands.distributions.ListOfDistributions",
    "ddls.devices.processors.gpus.A100.A100": "A100",
    "ddls.devices.processors.gpus.gpu.GPU": "GPU",
    "ddls.environments.ramp_job_placement_shaping.agents.first_fit."
    "FirstFit": "ddls_tpu.envs.baselines.FirstFitShaper",
    "ddls.environments.ramp_job_placement_shaping.agents.last_fit."
    "LastFit": "ddls_tpu.envs.baselines.LastFitShaper",
    "ddls.environments.ramp_job_placement_shaping.agents.random."
    "Random": "ddls_tpu.envs.baselines.RandomShaper",
    "ddls.environments.ramp_job_partitioning.agents.random.Random":
        "ddls_tpu.envs.baselines.RandomActor",
    "ddls.environments.ramp_job_partitioning.agents.no_parallelism."
    "NoParallelism": "ddls_tpu.envs.baselines.NoParallelism",
    "ddls.environments.ramp_job_partitioning.agents.max_parallelism."
    "MaxParallelism": "ddls_tpu.envs.baselines.MaxParallelism",
    "ddls.environments.ramp_job_partitioning.agents.min_parallelism."
    "MinParallelism": "ddls_tpu.envs.baselines.MinParallelism",
    "ddls.environments.ramp_job_partitioning.agents.sip_ml.SiPML":
        "ddls_tpu.envs.baselines.SiPML",
    "ddls.environments.ramp_job_partitioning.agents.acceptable_jct."
    "AcceptableJCT": "ddls_tpu.envs.baselines.AcceptableJCT",
    # Ray-wiring callables: stats/eval harvesting is native in the TPU
    # stack (rl/rollout.py harvest_episode_record), so these translate to
    # None and the consuming keys are dropped upstream
    "ddls.environments.ramp_cluster.utils."
    "RLlibRampClusterEnvironmentCallback": None,
    "ddls.environments.ramp_cluster.utils.custom_eval_function": None,
}

# Ray process/scheduler plumbing with no TPU-stack counterpart: dropped
# from algo_config (and its known nested dicts) with one warning.
# Everything here appears in the reference's shipped algo yamls.
RAY_ALGO_PLUMBING = {
    # sampling / worker orchestration
    "batch_mode", "rollout_fragment_length", "shuffle_sequences",
    "min_sample_timesteps_per_iteration", "min_time_s_per_iteration",
    "timeout_s_replay_manager", "timeout_s_sampler_manager",
    "max_requests_in_flight_per_replay_worker",
    "max_requests_in_flight_per_sampler_worker",
    "max_requests_in_flight_per_aggregator_worker",
    "num_aggregation_workers", "num_multi_gpu_tower_stacks",
    "learner_queue_size", "learner_queue_timeout",
    "minibatch_buffer_size", "broadcast_interval", "after_train_step",
    "timeout_s_aggregator_manager", "replay_buffer_num_slots",
    "replay_proportion",
    # schedule variants the TPU learners fix. NOTE: keys the TPU
    # translators DO consume (opt_type, decay, momentum, epsilon,
    # vtrace_clip_*, vtrace_drop_last_ts, report_length, eval_prob —
    # train/loops.py _RLLIB_TO_IMPALA / es known tuple) must NOT appear
    # here: this shim runs on native trees too, and stripping a consumed
    # key would silently sweep a no-op
    "lr_schedule", "entropy_coeff_schedule", "use_critic", "use_gae",
    "_lr_vf", "_separate_vf_optimizer", "_disable_preprocessor_api",
    # the vtrace on/off toggle itself (the TPU IMPALA is always vtrace)
    "vtrace",
    # DQN head variants the TPU learner fixes
    "hiddens", "noisy", "sigma0", "v_max", "v_min",
    # ES evaluation/noise-table plumbing (Ray's shared noise buffer; the
    # TPU ES samples noise on device)
    "observation_filter", "noise_size",
    # nested replay/exploration plumbing
    "type", "no_local_replay_buffer", "prioritized_replay",
    "replay_buffer_shards_colocated_with_driver",
    "worker_side_prioritization", "warmup_timesteps",
}

# epoch_loop keys that configure the reference's Ray wiring; the TPU
# epoch loops accept-and-ignore **kwargs, but rllib_config duplicates
# whole groups and must not leak into env/model kwargs
EPOCH_LOOP_DROP = {"rllib_config", "path_to_rllib_trainer_cls"}


def _map_class_strings(node: Any, warn: set) -> Any:
    if isinstance(node, dict):
        return {k: _map_class_strings(v, warn) for k, v in node.items()}
    if isinstance(node, list):
        return [_map_class_strings(v, warn) for v in node]
    if isinstance(node, str) and node.startswith("ddls."):
        if node in REF_CLASS_MAP:
            warn.add(node)
            return REF_CLASS_MAP[node]
        raise ValueError(
            f"reference class path {node!r} has no TPU-stack mapping; "
            "add it to ddls_tpu.train.compat.REF_CLASS_MAP")
    return node


def _strip_plumbing(algo_config: Dict[str, Any]) -> list:
    dropped = []
    for key in sorted(set(algo_config) & RAY_ALGO_PLUMBING):
        algo_config.pop(key)
        dropped.append(key)
    for nested in ("replay_buffer_config", "exploration_config"):
        sub = algo_config.get(nested)
        if isinstance(sub, dict):
            for key in sorted(set(sub) & RAY_ALGO_PLUMBING):
                sub.pop(key)
                dropped.append(f"{nested}.{key}")
    return dropped


def apply_reference_compat(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Translate a composed reference config in place (no-op for native
    TPU-stack trees). Returns ``cfg``."""
    notes = []

    algo = cfg.get("algo")
    if isinstance(algo, dict):
        trainer = algo.pop("path_to_rllib_trainer_cls", None)
        if trainer is not None and "algo_name" not in algo:
            suffix = str(trainer).rsplit(".", 1)[-1]
            if suffix not in TRAINER_TO_ALGO:
                raise ValueError(
                    f"unknown RLlib trainer class {trainer!r}; known: "
                    f"{sorted(TRAINER_TO_ALGO)}")
            algo["algo_name"] = TRAINER_TO_ALGO[suffix]
            notes.append(f"path_to_rllib_trainer_cls={trainer} -> "
                         f"algo_name={algo['algo_name']}")
        if isinstance(algo.get("algo_config"), dict):
            dropped = _strip_plumbing(algo["algo_config"])
            if dropped:
                notes.append(
                    f"dropped Ray plumbing algo_config keys: {dropped}")

    loop = cfg.get("epoch_loop")
    if isinstance(loop, dict):
        # the shaping tree's pre-group rllib_config.yaml keeps the trainer
        # class inside epoch_loop (no algo group exists); hoist it so the
        # algorithm selection survives the Ray-wiring drop below
        trainer = loop.get("path_to_rllib_trainer_cls")
        if (isinstance(trainer, str)
                and "algo_name" not in (cfg.get("algo") or {})):
            suffix = trainer.rsplit(".", 1)[-1]
            if suffix not in TRAINER_TO_ALGO:
                raise ValueError(
                    f"unknown RLlib trainer class {trainer!r}; known: "
                    f"{sorted(TRAINER_TO_ALGO)}")
            cfg.setdefault("algo", {})["algo_name"] = \
                TRAINER_TO_ALGO[suffix]
            notes.append(f"epoch_loop.path_to_rllib_trainer_cls={trainer}"
                         f" -> algo_name={cfg['algo']['algo_name']}")
        for key in sorted(set(loop) & EPOCH_LOOP_DROP):
            loop.pop(key)
            notes.append(
                f"dropped epoch_loop.{key} (Ray wiring; inline "
                "rllib_config values are NOT translated — that legacy "
                "pre-group surface is stale upstream: its env keys "
                "crash the reference's own RampTopology)"
                if key == "rllib_config"
                else f"dropped epoch_loop.{key} (Ray wiring)")

    eval_cfg = cfg.get("eval_config")
    if isinstance(eval_cfg, dict):
        inner = eval_cfg.get("evaluation_config")
        if isinstance(inner, dict) and "callbacks" in inner:
            inner.pop("callbacks")
            notes.append("dropped eval_config.evaluation_config.callbacks "
                         "(RLlib callback; stats are harvested natively)")

    mapped: set = set()
    cfg2 = _map_class_strings(cfg, mapped)
    cfg.clear()
    cfg.update(cfg2)
    if mapped:
        notes.append(f"mapped {len(mapped)} ddls.* class paths onto the "
                     "TPU stack")
    if notes:
        warnings.warn("reference-config compat: " + "; ".join(notes),
                      stacklevel=2)
    return cfg
