from ddls_tpu.train.checkpointer import (Checkpointer, restore_train_state,
                                         save_train_state)
from ddls_tpu.train.launcher import Launcher
from ddls_tpu.train.logger import Logger, SqliteDict
from ddls_tpu.train.loops import (ApexDQNEpochLoop, EnvLoop, EpochLoop,
                                  EvalLoop, RLEpochLoop, RLEvalLoop,
                                  build_policy_from_model_config,
                                  dqn_config_from_rllib, make_epoch_loop,
                                  ppo_config_from_rllib)

__all__ = ["Checkpointer", "restore_train_state", "save_train_state",
           "Launcher", "Logger", "SqliteDict", "ApexDQNEpochLoop", "EnvLoop",
           "EpochLoop", "EvalLoop", "RLEpochLoop", "RLEvalLoop",
           "build_policy_from_model_config", "dqn_config_from_rllib",
           "make_epoch_loop", "ppo_config_from_rllib"]
