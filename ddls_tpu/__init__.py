"""ddls_tpu: a TPU-native framework with the capabilities of cwfparsonson/ddls.

Two halves, mirroring the reference (see SURVEY.md):

1. A discrete-event simulator of a distributed deep-learning cluster (the RAMP
   all-optical architecture): jobs are DNN computation graphs, actions are
   resource-management decisions (op partitioning / placement, flow routing and
   scheduling), and the simulator computes job completion times, blocking rates
   and throughputs.

2. A reinforcement-learning stack (PAC-ML) that learns how many times to
   partition each job's ops: an environment wrapping the simulator, a
   message-passing GNN policy written in flax with XLA-native segment ops, and a
   pure-JAX PPO learner that shards its update over a ``jax.sharding.Mesh``
   (gradient all-reduce = ``psum`` over the ICI mesh) with vectorised rollouts.

Where the reference (PyTorch/DGL/RLlib/Ray) delegates compute to CUDA, this
package is JAX/XLA-first and designed for TPU pod slices.
"""

__version__ = "0.1.0"
