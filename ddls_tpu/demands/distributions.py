"""Stochastic workload parameter distributions.

Counterpart of the reference's ``ddls/distributions/`` package. Each
distribution exposes ``sample(size=None)`` returning a scalar (size=None) or an
ndarray. (Reference: ddls/distributions/{fixed,uniform,probability_mass_function,
custom_skew_norm,list_of_distributions}.py.)

Note the reference's Uniform references an undefined name in its
negative-decimals branch (SURVEY.md §7.5); here negative ``decimals`` rounds to
tens/hundreds/... as presumably intended.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np


class Distribution:
    def sample(self, size: Optional[int] = None):
        raise NotImplementedError


class Fixed(Distribution):
    def __init__(self, val: Union[int, float], **kwargs):
        self.val = val

    def sample(self, size: Optional[int] = None):
        if size is None:
            return self.val
        return np.full(size, self.val)


class Uniform(Distribution):
    def __init__(self,
                 min_val: Union[int, float],
                 max_val: Union[int, float],
                 decimals: Optional[int] = None,
                 **kwargs):
        self.min_val = min_val
        self.max_val = max_val
        self.decimals = decimals

    def sample(self, size: Optional[int] = None):
        val = np.random.uniform(self.min_val, self.max_val, size=size)
        if self.decimals is not None:
            val = np.round(val, self.decimals)
        if size is None:
            return float(val)
        return val


class ProbabilityMassFunction(Distribution):
    def __init__(self, probability_mass_function: dict, **kwargs):
        self.values = np.array(list(probability_mass_function.keys()), dtype=float)
        probs = np.array(list(probability_mass_function.values()), dtype=float)
        self.probs = probs / probs.sum()

    def sample(self, size: Optional[int] = None):
        val = np.random.choice(self.values, size=size, p=self.probs)
        if size is None:
            return float(val)
        return val


class CustomSkewNorm(Distribution):
    """Skew-normal samples rescaled into [min_val, max_val]."""

    def __init__(self,
                 skewness: float,
                 min_val: Union[int, float],
                 max_val: Union[int, float],
                 decimals: Optional[int] = None,
                 num_cached_samples: int = 10000,
                 **kwargs):
        from scipy.stats import skewnorm

        self.min_val = min_val
        self.max_val = max_val
        self.decimals = decimals
        raw = skewnorm.rvs(a=skewness, size=num_cached_samples)
        raw = raw - raw.min()
        raw = raw / raw.max()
        self._pool = raw * (max_val - min_val) + min_val

    def sample(self, size: Optional[int] = None):
        val = np.random.choice(self._pool, size=size)
        if self.decimals is not None:
            val = np.round(val, self.decimals)
        if size is None:
            return float(val)
        return val


class LoadgenInterarrival(Distribution):
    """Replay the serving stack's fingerprinted arrival process
    (``serve/loadgen.py generate_trace``: diurnal + burst + heavy-tail
    tenancy) as the SIMULATOR's job interarrival distribution, so
    training and serving share one workload vocabulary (scenario
    subsystem, docs/scenarios.md).

    The trace is built ONCE at construction — a pure function of the
    knobs — and its cumulative ``arrival_s`` (scaled by ``time_scale``
    into simulator seconds) is replayed as successive gaps, cycling
    when exhausted. Deterministic across resets: the cluster rebuilds
    its JobsGenerator (and therefore this distribution) from the same
    config dict each reset, re-zeroing the replay pointer.
    """

    def __init__(self, n_requests: int = 256, base_rps: float = 1.0,
                 seed: int = 0, time_scale: float = 1.0, **knobs):
        from ddls_tpu.serve.loadgen import generate_trace, trace_fingerprint

        trace = generate_trace(n_requests=int(n_requests),
                               base_rps=float(base_rps), seed=int(seed),
                               **knobs)
        self.trace_fingerprint = trace_fingerprint(trace)
        arrivals = np.asarray(trace["arrival_s"],
                              dtype=np.float64) * float(time_scale)
        self._gaps = np.diff(arrivals, prepend=0.0)
        self._ptr = 0

    def sample(self, size: Optional[int] = None):
        if size is not None:
            return np.array([self.sample() for _ in range(size)])
        gap = self._gaps[self._ptr % len(self._gaps)]
        self._ptr += 1
        return float(gap)


class ListOfDistributions(Distribution):
    """Uniformly sample one of several distributions; ``sample()`` returns the
    chosen Distribution object (used to vary the max-JCT-frac dist between
    episodes, reference: ddls/distributions/list_of_distributions.py)."""

    def __init__(self, name_to_cls_to_kwargs: dict, **kwargs):
        from ddls_tpu.utils import get_class_from_path

        self.distributions = []
        for cls_to_kwargs in name_to_cls_to_kwargs.values():
            for cls_path, cls_kwargs in cls_to_kwargs.items():
                self.distributions.append(get_class_from_path(cls_path)(**cls_kwargs))

    def sample(self, size: Optional[int] = None):
        idx = np.random.randint(len(self.distributions))
        return self.distributions[idx]


def make_distribution(spec) -> Distribution:
    """Instantiate a Distribution from a ``{'_target_': path, **kwargs}`` dict
    (the reference's hand-rolled hydra instantiation,
    ddls/demands/jobs/jobs_generator.py:125-130) or pass through an object."""
    if isinstance(spec, Distribution):
        return spec
    if isinstance(spec, dict):
        if "_target_" not in spec:
            raise ValueError("distribution dict spec requires a '_target_' key")
        from ddls_tpu.utils import get_class_from_path

        kwargs = {k: v for k, v in spec.items() if k != "_target_"}
        return get_class_from_path(spec["_target_"])(**kwargs)
    raise TypeError(f"cannot build a Distribution from {spec!r}")
