"""DNN training jobs and their execution state.

A :class:`Job` wraps an :class:`~ddls_tpu.graphs.op_graph.OpGraph` (one
forward+backward training step) to be executed ``num_training_steps`` times,
plus the job's SLA (max acceptable completion time as a fraction of its
sequential completion time). Mirrors the reference's
``ddls/demands/jobs/job.py:42`` but splits cleanly into:

* immutable per-model details (sequential JCT, totals, max-cost ops, depths)
  that are memoised across jobs of the same model;
* an :class:`ExecState` of flat numpy arrays (remaining run times, readiness
  masks, parent-dep counters) driven by the simulator's tick engine -- the
  array-native replacement for the reference's per-node attribute mutation
  (job.py:432-563).

Readiness semantics (identical to the reference):

* an op is ready when its count of completed incoming deps equals its number
  of *non-mutual* parents (mutual sync-edge pairs are children of both
  endpoints -- job.py:508-533);
* when an op completes, all its out-edges become ready deps (job.py:492-498);
* a training step is complete when every op *and* every dep has completed
  (job.py:549-551).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ddls_tpu.graphs.op_graph import EdgeId, OpGraph


def compute_immutable_details(graph: OpGraph, num_training_steps: int) -> dict:
    """Per-model statistics that never change over a job's lifetime
    (reference: job.py:192-325 _init_job_immutable_details)."""
    arrays = graph.finalize()
    compute, memory = arrays["compute"], arrays["memory"]
    sizes, depth = arrays["edge_size"], arrays["depth"]
    op_ids, edge_ids = arrays["op_ids"], arrays["edge_ids"]

    if len(compute):
        throughput = np.divide(memory, compute, out=np.zeros_like(memory),
                               where=compute > 0)
        max_op_compute_throughput = float(throughput.max())
    else:
        max_op_compute_throughput = 0.0

    i_max_compute = int(np.argmax(compute)) if len(compute) else 0
    i_max_memory = int(np.argmax(memory)) if len(memory) else 0
    i_max_depth = int(np.argmax(depth)) if len(depth) else 0
    e_max_size = int(np.argmax(sizes)) if len(sizes) else 0

    return {
        "job_sequential_completion_time": float(compute.sum()) * num_training_steps,
        "job_total_op_memory_cost": float(memory.sum()),
        "job_total_dep_size": float(sizes.sum()),
        "max_compute_node": op_ids[i_max_compute] if op_ids else None,
        "max_compute_cost": float(compute[i_max_compute]) if len(compute) else 0.0,
        "max_memory_node": op_ids[i_max_memory] if op_ids else None,
        "max_memory_cost": float(memory[i_max_memory]) if len(memory) else 0.0,
        "max_depth_node": op_ids[i_max_depth] if op_ids else None,
        "max_depth": int(depth[i_max_depth]) if len(depth) else 0,
        "max_dep_size_dep": edge_ids[e_max_size] if edge_ids else None,
        "max_dep_size": float(sizes[e_max_size]) if len(sizes) else 0.0,
        # per-op compute throughput = memory / compute (reference:
        # job.py:214-222); used to normalise throughput rewards
        "max_op_compute_throughput": max_op_compute_throughput,
    }


class ExecState:
    """Flat-array execution state of one training step."""

    def __init__(self, graph: OpGraph,
                 dep_init_run_times: Optional[Dict[EdgeId, float]] = None):
        arrays = graph.finalize()
        self.graph = graph
        self.op_index: Dict[str, int] = arrays["op_index"]
        self.edge_index: Dict[EdgeId, int] = arrays["edge_index"]
        self.op_ids: List[str] = arrays["op_ids"]
        self.edge_ids: List[EdgeId] = arrays["edge_ids"]
        self.out_edges: List[List[int]] = arrays["out_edges"]
        self.edge_dst: np.ndarray = arrays["edge_dst"]
        self.num_parents: np.ndarray = arrays["num_parents"]
        self.edge_mutual: np.ndarray = arrays["edge_mutual"]

        n, m = graph.n_ops, graph.n_deps
        self.remaining_op = arrays["compute"].copy()
        self.init_dep_run_time = np.zeros(m, dtype=np.float64)
        self.remaining_dep = np.zeros(m, dtype=np.float64)
        self.parent_deps_done = np.zeros(n, dtype=np.int64)
        self.op_completed = np.zeros(n, dtype=bool)
        self.dep_completed = np.zeros(m, dtype=bool)
        # ops with zero non-mutual parents are ready at the start of a step
        # (covers both true sources and ops whose only in-edges are mutual
        # sync edges)
        self.ops_ready: Set[int] = {
            i for i in range(n) if self.num_parents[i] == 0}
        self.deps_ready: Set[int] = set()
        self.n_ops_completed = 0
        self.n_deps_completed = 0
        if dep_init_run_times:
            for edge, t in dep_init_run_times.items():
                self.set_dep_init_run_time(edge, t)

    # ------------------------------------------------------------------ events
    def set_dep_init_run_time(self, edge: EdgeId, run_time: float) -> None:
        ei = self.edge_index[edge]
        self.init_dep_run_time[ei] = run_time
        self.remaining_dep[ei] = run_time

    def tick_op(self, op_i: int, tick: float) -> bool:
        """Advance one op; returns True if it completed this tick."""
        rem = self.remaining_op[op_i]
        self.remaining_op[op_i] = rem - min(tick, rem)
        if self.remaining_op[op_i] == 0 and not self.op_completed[op_i]:
            self._complete_op(op_i)
            return True
        return False

    def tick_dep(self, dep_i: int, tick: float) -> bool:
        rem = self.remaining_dep[dep_i]
        self.remaining_dep[dep_i] = rem - min(tick, rem)
        if self.remaining_dep[dep_i] == 0 and not self.dep_completed[dep_i]:
            self._complete_dep(dep_i)
            return True
        return False

    def _complete_op(self, op_i: int) -> None:
        self.op_completed[op_i] = True
        self.n_ops_completed += 1
        self.ops_ready.discard(op_i)
        for ei in self.out_edges[op_i]:
            if not self.dep_completed[ei]:
                self.deps_ready.add(ei)

    def _complete_dep(self, dep_i: int) -> None:
        self.dep_completed[dep_i] = True
        self.n_deps_completed += 1
        self.deps_ready.discard(dep_i)
        if self.edge_mutual[dep_i]:
            # sync edges never gate readiness of their destination op.
            # (The reference counts them into its completed-parent-deps set,
            # which can fire an op early when a sync dep beats a real parent
            # dep -- job.py:525-533; counting only non-mutual deps here
            # removes that race without changing well-ordered schedules.)
            return
        child = int(self.edge_dst[dep_i])
        self.parent_deps_done[child] += 1
        if self.parent_deps_done[child] == self.num_parents[child]:
            if not self.op_completed[child]:
                self.ops_ready.add(child)

    # ------------------------------------------------------------------ queries
    def is_training_step_complete(self) -> bool:
        return (self.n_ops_completed == len(self.op_ids)
                and self.n_deps_completed == len(self.edge_ids))


class Job:
    """A training job: graph + SLA + bookkeeping + (optional) exec state.

    ``original_job`` points at the unpartitioned job when this Job was built
    by a partitioning transform (reference: job.py:77-79,109-118).
    """

    _id_counter = 0

    def __init__(self,
                 graph: OpGraph,
                 num_training_steps: int,
                 max_acceptable_jct_frac: float,
                 job_id: Optional[int] = None,
                 details: Optional[dict] = None,
                 immutable_details: Optional[dict] = None,
                 original_job: Optional["Job"] = None):
        if not (0 < max_acceptable_jct_frac <= 1):
            raise ValueError(
                "max_acceptable_jct_frac must satisfy 0 < frac <= 1, got "
                f"{max_acceptable_jct_frac}")
        self.graph = graph
        self.num_training_steps = num_training_steps
        self.max_acceptable_jct_frac = max_acceptable_jct_frac
        if job_id is None:
            Job._id_counter += 1
            job_id = Job._id_counter
        self.job_id = job_id
        self.details: dict = dict(details or {})
        self.details.setdefault("model", graph.meta.get("model", "unknown"))

        if immutable_details is None:
            immutable_details = compute_immutable_details(graph, num_training_steps)
        self.immutable = immutable_details
        self.details.update(immutable_details)

        self.details["max_acceptable_job_completion_time"] = (
            self.max_acceptable_jct_frac
            * self.immutable["job_sequential_completion_time"])

        self.reset_mutable_details()
        self.state: Optional[ExecState] = None
        # per-edge placed communication times, set by the comm model after op
        # placement; survives training-step resets (the reference keeps
        # these as edge 'init_run_time' attributes, job.py:461-464). The
        # canonical store on the hot path is the aligned array
        # (graph.edge_ids order); the dict view is materialised lazily for
        # the fallback/host-engine readers
        self._dep_init_run_time: Optional[Dict[EdgeId, float]] = {}
        self.dep_init_run_time_arr = None
        self.training_step_counter = 0
        self.original_job = original_job if original_job is not None else self

    # ------------------------------------------------------------------ lifecycle
    def reset_mutable_details(self) -> None:
        """(reference: job.py:160-175 _init_job_mutable_details)"""
        self.details["communication_overhead_time"] = 0.0
        self.details["computation_overhead_time"] = 0.0
        self.details["mounted_workers"] = set()
        self.details["mounted_channels"] = set()

    def reset_training_step(self) -> ExecState:
        self.state = ExecState(self.graph, self.dep_init_run_time)
        return self.state

    @property
    def dep_init_run_time(self) -> Dict[EdgeId, float]:
        """Dict view of the placed per-dep times (lazy: the hot path keeps
        only the aligned array; fallback readers materialise this once)."""
        if self._dep_init_run_time is None:
            arr = self.dep_init_run_time_arr
            self._dep_init_run_time = (
                dict(zip(self.graph.edge_ids, arr.tolist()))
                if arr is not None else {})
        return self._dep_init_run_time

    def set_dep_init_run_time(self, edge: EdgeId, run_time: float) -> None:
        self.dep_init_run_time[edge] = float(run_time)
        self.dep_init_run_time_arr = None  # single-edge write: mirror stale
        if self.state is not None:
            self.state.set_dep_init_run_time(edge, run_time)

    def set_dep_init_run_times_bulk(self, times) -> None:
        """Set every dep's initial run time from an array aligned with
        ``graph.edge_ids`` order (the hot path prices all deps at once)."""
        self.dep_init_run_time_arr = np.asarray(times, np.float64).copy()
        self._dep_init_run_time = None  # dict view rebuilt on demand
        if self.state is not None:
            arr = self.dep_init_run_time_arr
            self.state.init_dep_run_time[:] = arr
            self.state.remaining_dep[:] = arr

    def register_arrived(self, time_arrived: float, job_idx: int) -> None:
        self.details["time_arrived"] = time_arrived
        self.details["time_started"] = None
        self.details["time_completed"] = None
        self.details["job_idx"] = job_idx
        if self.original_job is not self:
            self.original_job.details["job_idx"] = job_idx

    def register_running(self, time_started: float) -> None:
        self.details["time_started"] = time_started

    def register_completed(self, time_completed: float) -> None:
        self.details["time_completed"] = time_completed

    # ------------------------------------------------------------------ queries
    @property
    def seq_completion_time(self) -> float:
        return self.immutable["job_sequential_completion_time"]

    @property
    def max_acceptable_jct(self) -> float:
        return self.details["max_acceptable_job_completion_time"]

    def is_job_complete(self) -> bool:
        return self.training_step_counter == self.num_training_steps

    def clone_fresh(self, job_id: Optional[int] = None) -> "Job":
        """A fresh (unstarted) copy of this job sharing immutable details."""
        return Job(graph=self.graph,
                   num_training_steps=self.num_training_steps,
                   max_acceptable_jct_frac=self.max_acceptable_jct_frac,
                   job_id=job_id,
                   details={"model": self.details["model"]},
                   immutable_details=self.immutable)

    def __repr__(self) -> str:
        return (f"Job(id={self.job_id}, model={self.details.get('model')!r}, "
                f"n_ops={self.graph.n_ops}, n_deps={self.graph.n_deps}, "
                f"steps={self.num_training_steps}, "
                f"seq_jct={self.seq_completion_time:.3f}, "
                f"max_frac={self.max_acceptable_jct_frac})")
