"""Capacity-bounded job queue (reference: ddls/environments/cluster/job_queue.py:8)."""
from __future__ import annotations

from collections import OrderedDict

from ddls_tpu.demands.job import Job


class JobQueue:
    def __init__(self, queue_capacity: int = 10):
        self.queue_capacity = queue_capacity
        self.jobs: "OrderedDict[int, Job]" = OrderedDict()

    def can_fit(self, job: Job) -> bool:
        return len(self.jobs) < self.queue_capacity

    def add(self, job: Job) -> None:
        if not self.can_fit(job):
            raise RuntimeError(
                f"job queue at capacity ({self.queue_capacity}); cannot add "
                f"job {job.job_id}")
        self.jobs[job.job_id] = job

    def remove(self, job: Job) -> None:
        del self.jobs[job.job_id]

    def __len__(self) -> int:
        return len(self.jobs)

    def __contains__(self, job_id) -> bool:
        return job_id in self.jobs
