from ddls_tpu.demands.job import Job
from ddls_tpu.demands.jobs_generator import JobsGenerator
from ddls_tpu.demands.job_queue import JobQueue

__all__ = ["Job", "JobsGenerator", "JobQueue"]
