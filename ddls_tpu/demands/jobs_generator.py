"""Workload generator: loads/synthesises job graphs and samples arrivals.

Counterpart of the reference's ``ddls/demands/jobs/jobs_generator.py:64``:
loads graph profile files (PipeDream ``.txt`` / CostGraphDef ``.pbtxt``) from a
directory, replicates them ``replication_factor`` times, wraps each in a
:class:`~ddls_tpu.demands.job.Job` with a sampled max-acceptable-JCT fraction,
then serves jobs (``replace`` / ``remove`` / ``remove_and_repeat``) and
interarrival times. Per-model immutable details are computed once and shared
across replicas (reference memo: jobs_generator.py:140-183).

Additions over the reference:

* ``synthetic`` config generates PipeDream-format profiles on the fly (the
  reference's datasets are not distributed with it);
* dataset-wide min/max stats for observation normalisation are identical in
  structure (reference: jobs_generator.py:276-333), including the
  fully-connected worst-case bound on partitioned dep totals.
"""
from __future__ import annotations

import glob
import hashlib
import os
import random
import tempfile
from typing import List, Optional, Union

import numpy as np

from ddls_tpu.demands.distributions import Distribution, make_distribution
from ddls_tpu.demands.job import Job, compute_immutable_details
from ddls_tpu.graphs.readers import read_graph_file
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files


class JobSampler:
    """Sample jobs from a pool (reference Sampler: ddls/utils.py:50).

    On pool exhaustion under ``remove_and_repeat``, the pool is rebuilt with
    fresh job ids so ids stay unique across refills.
    """

    def __init__(self, prototypes: List[Job], mode: str, shuffle: bool):
        if mode not in ("replace", "remove", "remove_and_repeat"):
            raise ValueError(f"unknown job_sampling_mode {mode}")
        self.prototypes = prototypes
        self.mode = mode
        self.shuffle = shuffle
        self.refill_counter = 0
        self._next_id = 0
        self._pool: List[Job] = []
        self._refill()

    def _refill(self) -> None:
        self._pool = []
        for proto in self.prototypes:
            self._pool.append(proto.clone_fresh(job_id=self._next_id))
            self._next_id += 1
        if self.shuffle:
            random.shuffle(self._pool)
        self.refill_counter += 1

    def __len__(self) -> int:
        return len(self._pool)

    def sample(self) -> Job:
        if not self._pool:
            raise RuntimeError(
                "job pool exhausted (job_sampling_mode='remove'); no more "
                "jobs to sample")
        idx = np.random.randint(len(self._pool))
        job = self._pool[idx]
        if self.mode == "replace":
            # hand out a fresh clone so exec state never aliases
            clone = job.clone_fresh(job_id=self._next_id)
            self._next_id += 1
            return clone
        self._pool.pop(idx)
        if self.mode == "remove_and_repeat" and not self._pool:
            self._refill()
        return job


def discover_profile_files(path_to_files: str) -> list:
    """Sorted graph-profile files under a directory — the single discovery
    rule shared by the generator and by the cluster's workload signature
    (cache validity must see exactly the files the generator loads)."""
    return sorted(
        p for p in glob.glob(path_to_files.rstrip("/") + "/*")
        if p.endswith(".txt") or p.endswith(".pbtxt"))


class JobsGenerator:
    def __init__(self,
                 path_to_files: Optional[str] = None,
                 job_interarrival_time_dist: Union[Distribution, dict] = None,
                 max_acceptable_job_completion_time_frac_dist:
                     Union[Distribution, dict, None] = None,
                 max_files: Optional[int] = None,
                 replication_factor: int = 1,
                 job_sampling_mode: str = "remove_and_repeat",
                 shuffle_files: bool = False,
                 num_training_steps: int = 1,
                 max_partitions_per_op_in_observation: int = 1,
                 synthetic: Optional[dict] = None,
                 device_type: str = "A100",
                 **kwargs):
        if path_to_files is None and synthetic is None:
            raise ValueError("need path_to_files or a synthetic config")
        if job_interarrival_time_dist is None:
            raise ValueError(
                "job_interarrival_time_dist is required (pass a Distribution "
                "or a {'_target_': ..., **kwargs} dict)")
        self.num_training_steps = num_training_steps
        self.device_type = device_type
        self.max_files = max_files
        generated_paths = None
        if synthetic is not None:
            out_dir = synthetic.get("out_dir") or tempfile.mkdtemp(
                prefix="ddls_tpu_jobs_")
            kw = {k: v for k, v in synthetic.items() if k != "out_dir"}
            # use exactly the files generated this run (a reused out_dir may
            # hold stale profiles from a previous, differently-sized config)
            generated_paths = generate_pipedream_txt_files(out_dir, **kw)
            path_to_files = out_dir
        self.path_to_files = path_to_files

        file_paths = (sorted(generated_paths) if generated_paths is not None
                      else discover_profile_files(path_to_files))
        if not file_paths:
            raise FileNotFoundError(
                f"no .txt/.pbtxt graph profiles under {path_to_files}")
        if max_files is not None:
            file_paths = file_paths[:max_files]
        # workload fingerprint for the cluster's memo-cache validity check:
        # synthetic datasets are deterministic per config (seeded), so the
        # config content identifies them regardless of the tmpdir they were
        # written to; on-disk datasets fingerprint exactly the files loaded
        # (post-max_files truncation), statted+digested at load time (not at
        # reset time — the files could change on disk after this generator
        # read them)
        if synthetic is not None:
            dataset_id = ("synthetic", repr(sorted(synthetic.items())))
        else:
            stats = []
            for f in file_paths:
                st = os.stat(f)
                # content digest of head+tail bytes makes the check
                # content-true: an in-place edit that preserves mtime and
                # size (some sync tools, archive extraction) still changes
                # the fingerprint and invalidates stale memo caches
                with open(f, "rb") as fh:
                    head = fh.read(4096)
                    if st.st_size > 8192:
                        fh.seek(-4096, os.SEEK_END)
                    tail = fh.read(4096)
                digest = hashlib.sha1(head + tail).hexdigest()
                stats.append((os.path.basename(f), st.st_mtime_ns,
                              st.st_size, digest))
            dataset_id = ("files", path_to_files, tuple(stats))
        self.workload_fingerprint = (dataset_id, num_training_steps,
                                     device_type, max_files)

        self.interarrival_dist = make_distribution(job_interarrival_time_dist)
        frac_dist = make_distribution(
            max_acceptable_job_completion_time_frac_dist
            if max_acceptable_job_completion_time_frac_dist is not None
            else {"_target_": "ddls_tpu.demands.distributions.Fixed", "val": 1.0})
        sampled = frac_dist.sample()
        if isinstance(sampled, Distribution):
            # ListOfDistributions: one dist chosen per generator instance
            frac_dist = sampled
        self.frac_dist = frac_dist

        graphs = [read_graph_file(p, device_type=device_type) for p in file_paths]
        model_to_immutable = {}
        prototypes: List[Job] = []
        for _ in range(replication_factor):
            for g in graphs:
                model = g.meta["model"]
                if model not in model_to_immutable:
                    model_to_immutable[model] = compute_immutable_details(
                        g, num_training_steps)
                prototypes.append(Job(
                    graph=g,
                    num_training_steps=num_training_steps,
                    max_acceptable_jct_frac=float(self.frac_dist.sample()),
                    job_id=0,  # assigned by the sampler
                    details={"model": model},
                    immutable_details=model_to_immutable[model]))

        self.sampler = JobSampler(prototypes, job_sampling_mode, shuffle_files)
        self.max_partitions_per_op_in_observation = (
            max_partitions_per_op_in_observation)
        self.jobs_params = self._init_jobs_params(
            prototypes, max_partitions_per_op_in_observation)

    def __len__(self) -> int:
        return len(self.sampler)

    def sample_job(self) -> Job:
        return self.sampler.sample()

    def sample_interarrival_time(self) -> float:
        if len(self.sampler) == 0:
            return float("inf")
        return float(self.interarrival_dist.sample())

    def _init_jobs_params(self, jobs: List[Job], max_parts: int) -> dict:
        """Dataset-wide normalisation stats (reference:
        jobs_generator.py:276-333). The ``max_job_total_num_*`` bounds account
        for partitioning blowing up the graph: each op can split up to
        ``max_parts`` ways; the dep-size bound assumes a fully connected
        worst case (reference: jobs_generator.py:320-324)."""
        raw = {
            "job_sequential_completion_times":
                [j.seq_completion_time for j in jobs],
            "max_acceptable_job_completion_times":
                [j.max_acceptable_jct for j in jobs],
            "max_acceptable_job_completion_time_fracs":
                [j.max_acceptable_jct_frac for j in jobs],
            "job_total_op_memory_costs":
                [j.immutable["job_total_op_memory_cost"] for j in jobs],
            "job_total_dep_sizes":
                [j.immutable["job_total_dep_size"] for j in jobs],
            "job_total_num_ops": [j.graph.n_ops for j in jobs],
            "job_total_num_deps": [j.graph.n_deps for j in jobs],
            "job_num_training_steps": [j.num_training_steps for j in jobs],
            "job_max_dep_size": [j.immutable["max_dep_size"] for j in jobs],
            "job_max_op_compute_throughputs": [
                j.immutable["max_op_compute_throughput"] for j in jobs],
        }
        params = {}
        for key, vals in raw.items():
            vals = np.asarray(vals, dtype=np.float64)
            params[f"min_{key}"] = float(vals.min())
            if key == "job_total_num_ops":
                params[f"max_{key}"] = float(vals.max() * max_parts)
            elif key == "job_total_num_deps":
                max_fwd = int((vals.max() / 2) * max_parts * 2)
                params[f"max_{key}"] = float(max_fwd + 2 * max_fwd)
            elif key == "job_total_dep_sizes":
                max_nodes = max(raw["job_total_num_ops"]) * max_parts
                fully_connected = int(max_nodes * (max_nodes - 1) / 2)
                params[f"max_{key}"] = float(vals.max() * fully_connected)
            else:
                params[f"max_{key}"] = float(vals.max())
        return params
