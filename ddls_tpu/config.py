"""YAML experiment-config system (Hydra-compatible subset).

The reference composes experiments with Hydra + OmegaConf (SURVEY.md §5.6):
a root config with a ``defaults:`` list of config groups
(``- env_config: env_dev`` loads ``env_config/env_dev.yaml`` under the key
``env_config``), ``_target_`` class-path instantiation, and dotted-path CLI
overrides. Hydra is not available in this environment, so this module
implements the same composition semantics on plain PyYAML:

* ``load_config(config_path, config_name, overrides)`` — load + merge the
  ``defaults`` groups under their group names, then apply the root config's
  own keys, then CLI overrides (``a.b.c=value`` for values,
  ``group=name`` to re-select a config group).
* ``instantiate(cfg)`` — recursive ``_target_`` instantiation
  (hydra.utils.instantiate equivalent). Reference (``ddls.*``) class paths
  in configs are mapped to their ddls_tpu equivalents by
  ``get_class_from_path``, so the reference's own config trees load
  unchanged.
* ``save_config(cfg, path)`` — snapshot the composed config to the run dir
  (reference: train_rllib_from_config.py:96).
"""
from __future__ import annotations

import copy
import os
from typing import Any, Dict, List, Optional, Sequence

import re

import yaml

from ddls_tpu.utils.common import get_class_from_path, recursive_update


class _ConfigLoader(yaml.SafeLoader):
    """SafeLoader that also accepts scientific notation without a signed
    exponent (``1.6e12``), which YAML 1.1 would otherwise read as a string
    (OmegaConf handles this for the reference's configs)."""


_ConfigLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(r"""^(?:
        [-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
       |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
       |\.[0-9_]+(?:[eE][-+]?[0-9]+)?
       |[-+]?\.(?:inf|Inf|INF)
       |\.(?:nan|NaN|NAN))$""", re.X),
    list("-+0123456789."))


def _yaml_load(stream):
    return yaml.load(stream, Loader=_ConfigLoader)


def _load_yaml(path: str) -> dict:
    with open(path) as f:
        data = _yaml_load(f)
    return data or {}


def _find_config_file(config_path: str, name: str) -> str:
    if not name.endswith((".yaml", ".yml")):
        name = name + ".yaml"
    full = os.path.join(config_path, name)
    if not os.path.exists(full):
        raise FileNotFoundError(f"config file not found: {full}")
    return full


def _parse_override_value(raw: str) -> Any:
    try:
        return _yaml_load(raw)
    except yaml.YAMLError:
        return raw


def set_by_dotted_path(cfg: dict, dotted: str, value: Any) -> None:
    keys = dotted.split(".")
    node = cfg
    for key in keys[:-1]:
        if not isinstance(node.get(key), dict):
            node[key] = {}
        node = node[key]
    node[keys[-1]] = value


def get_by_dotted_path(cfg: dict, dotted: str, default: Any = None) -> Any:
    node = cfg
    for key in dotted.split("."):
        if not isinstance(node, dict) or key not in node:
            return default
        node = node[key]
    return node


def load_config(config_path: str, config_name: str,
                overrides: Optional[Sequence[str]] = None) -> Dict[str, Any]:
    """Compose a config exactly as the reference's Hydra setup does.

    Group entries in the root config's ``defaults:`` list are loaded from
    ``{config_path}/{group}/{name}.yaml`` and placed under ``cfg[group]``;
    the root config's own keys are merged on top; overrides apply last.
    An override ``group=name`` re-selects a config group if
    ``{config_path}/{group}/`` exists, otherwise it sets a plain value.
    """
    overrides = list(overrides or [])
    root = _load_yaml(_find_config_file(config_path, config_name))
    defaults = root.pop("defaults", [])

    # group re-selection overrides must apply before group loading
    group_selext: Dict[str, str] = {}
    value_overrides: List[str] = []
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"override must be key=value, got: {ov}")
        key, _, raw = ov.partition("=")
        if ("." not in key
                and os.path.isdir(os.path.join(config_path, key))):
            group_selext[key] = str(raw)
        else:
            value_overrides.append(ov)

    cfg: Dict[str, Any] = {}
    for entry in defaults:
        if isinstance(entry, str):  # e.g. "_self_"
            continue
        (group, name), = entry.items()
        name = group_selext.pop(group, name)
        group_cfg = _load_yaml(
            _find_config_file(os.path.join(config_path, group), str(name)))
        cfg.setdefault(group, {})
        recursive_update(cfg[group], group_cfg)
    for group, name in group_selext.items():  # overrides of unlisted groups
        group_cfg = _load_yaml(
            _find_config_file(os.path.join(config_path, group), str(name)))
        cfg.setdefault(group, {})
        recursive_update(cfg[group], group_cfg)

    recursive_update(cfg, root)

    for ov in value_overrides:
        key, _, raw = ov.partition("=")
        set_by_dotted_path(cfg, key, _parse_override_value(raw))
    return cfg


def instantiate(node: Any, **extra_kwargs) -> Any:
    """Recursively build objects from ``_target_`` dicts.

    Non-``_target_`` dicts/lists are traversed; leaves pass through.
    ``extra_kwargs`` are merged into the top-level target's kwargs only
    (matching hydra.utils.instantiate(cfg, **kwargs)).
    """
    if isinstance(node, dict) and "_target_" in node:
        node = dict(node)
        target = node.pop("_target_")
        kwargs = {k: instantiate(v) for k, v in node.items()}
        kwargs.update(extra_kwargs)
        cls = get_class_from_path(target)
        return cls(**kwargs)
    if isinstance(node, dict):
        return {k: instantiate(v) for k, v in node.items()}
    if isinstance(node, list):
        return [instantiate(v) for v in node]
    return node


def save_config(cfg: dict, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(_to_plain(cfg), f, default_flow_style=False,
                       sort_keys=False)


def _to_plain(node: Any) -> Any:
    if isinstance(node, dict):
        return {k: _to_plain(v) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return [_to_plain(v) for v in node]
    if hasattr(node, "item") and getattr(node, "ndim", None) == 0:
        return node.item()
    return node


def deep_copy_config(cfg: dict) -> dict:
    return copy.deepcopy(cfg)
