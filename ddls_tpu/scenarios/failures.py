"""Failure-schedule vocabulary + the ONE completion-time inflation.

The scenario subsystem models heterogeneous device speeds and
deterministic failure windows (worker preemption, straggler channels)
WITHOUT touching any of the three lookahead engines: every engine keeps
serving the NOMINAL lookahead (so host/C++/jax lookahead stay bit-exact
with each other for free, and the memo caches stay valid), and the
scenario is applied as a pure completion-time inflation at lookahead
REGISTRATION time — once on the host tick path
(``cluster._register_completed_lookahead``) and once in the jitted
decision kernel (``sim/jax_env.py``). Both call the shared formula in
this module with the SAME f64 op order, so host-vs-jitted stays at the
existing 1e-9 decision parity and a nominal scenario (unit speeds, no
windows) is a bitwise no-op.

Model (docs/scenarios.md):

- device speeds: a job progresses at ``r0 = min(speed of mounted
  servers)`` — whole-job gating, matching the lookahead's synchronous
  training-step semantics. ``jct_run = nominal / r0``.
- failure windows: half-open intervals ``[t0, t1)`` on one resource
  (server or channel) during which an AFFECTED job progresses at
  ``rate`` (0.0 = full preemption, ``1/slowdown`` = straggler). Windows
  are globally pairwise non-overlapping (validated by the spec layer),
  which makes the single forward pass below EXACT.

SLA admission stays failure-blind by design: the accept/block gate is
judged on the NOMINAL jct (the price the candidate-pricing memo knows),
so scenario injection never changes WHICH jobs are admitted, only when
they finish.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

# window kinds — int codes shared verbatim by the host inflation and the
# jitted kernel's static unroll (the lint engine's backend-surface-parity
# rule pins this table against the flight vocabulary)
FAILURE_WORKER_PREEMPT = 0
FAILURE_CHANNEL_STRAGGLE = 1

# kind code -> flight event kind emitted when the simulated clock first
# crosses the window's t0 (cluster.step). Bijective; every value must be
# a member of telemetry/flight.py EVENT_KINDS AND a literal at the
# cluster.py emission site (lint: backend-surface-parity check 5).
FAILURE_KIND_TO_EVENT = {
    FAILURE_WORKER_PREEMPT: "worker_preempted",
    FAILURE_CHANNEL_STRAGGLE: "channel_degraded",
}

# spec-file spelling of the kind codes
FAILURE_KIND_NAMES = {
    "worker_preempt": FAILURE_WORKER_PREEMPT,
    "channel_straggle": FAILURE_CHANNEL_STRAGGLE,
}


class ScenarioRuntime:
    """A built scenario: dense per-server speeds + the normalized,
    t0-sorted failure windows, in the topology's dense index space
    (``hardware/topologies.py dense_tables``: ``server_index`` order for
    servers, ``channel_index`` order for channels).

    Constructed by ``spec.build_runtime``; attached to
    ``RampClusterEnvironment(scenario_runtime=...)``. ``is_nominal``
    runtimes are never built (build_runtime returns None), so any
    attached runtime implies real inflation work.
    """

    __slots__ = ("name", "fingerprint", "speeds", "windows",
                 "win_t0", "win_t1", "win_rate", "win_kind", "win_res")

    def __init__(self, name: str, fingerprint: str,
                 speeds: Sequence[float],
                 windows: Sequence[Dict[str, object]]):
        self.name = str(name)
        self.fingerprint = str(fingerprint)
        self.speeds = np.asarray(speeds, dtype=np.float64)
        self.windows: List[Dict[str, object]] = [dict(w) for w in windows]
        self.windows.sort(key=lambda w: float(w["t0"]))
        self.win_t0 = np.asarray([w["t0"] for w in self.windows], np.float64)
        self.win_t1 = np.asarray([w["t1"] for w in self.windows], np.float64)
        self.win_rate = np.asarray([w["rate"] for w in self.windows],
                                   np.float64)
        self.win_kind = [int(w["kind"]) for w in self.windows]
        self.win_res = [int(w["resource"]) for w in self.windows]

    @property
    def is_nominal(self) -> bool:
        return (not self.windows
                and bool(np.all(self.speeds == 1.0)))

    def __repr__(self) -> str:
        return (f"ScenarioRuntime({self.name!r}, fp={self.fingerprint}, "
                f"servers={len(self.speeds)}, windows={len(self.windows)})")


def inflate_duration(t_start: float, nominal: float, r0: float,
                     win_t0, win_t1, win_rate,
                     affects: Sequence[bool]) -> float:
    """Adjusted run duration for a job of NOMINAL duration starting at
    ``t_start`` on resources with min speed ``r0``, walking the sorted,
    non-overlapping failure windows once.

    Per affected window overlapping the remaining run: the time spent
    inside the window advances work at ``rate``; ``rate == 0`` (full
    preemption) pushes completion past the window end. The closed-form
    per-window update is exact because windows never overlap, so each is
    visited at most once with the final ``t_done`` already accounting
    for every earlier window.

    The jitted mirror (``inflate_duration_jax``) computes the SAME f64
    expressions in the same order — keep the two in lockstep.
    """
    t_done = t_start + nominal / r0
    for i in range(len(win_t0)):
        if not affects[i]:
            continue
        w0 = float(win_t0[i])
        w1 = float(win_t1[i])
        r = float(win_rate[i])
        lo = w0 if w0 > t_start else t_start
        if not (lo < w1 and t_done > lo):
            continue
        remaining = t_done - lo          # run time still needed at lo
        span = w1 - lo                   # window time available
        cap = r * span                   # work the window can host
        if r > 0.0 and remaining <= cap:
            t_done = lo + remaining / r  # finishes inside the window
        else:
            t_done = w1 + (remaining - cap)
    return t_done - t_start


def inflate_duration_jax(t_start, nominal, r0, win_t0, win_t1, win_rate,
                         affects):
    """Traced mirror of ``inflate_duration`` — same f64 expressions,
    same order, unrolled over the (static) window count. ``affects`` is
    a list of traced booleans; window times/rates are device arrays.
    The ``jnp.where`` divisor guard keeps the untaken branch NaN-free
    without perturbing the taken branch's bits.
    """
    import jax.numpy as jnp

    t_done = t_start + nominal / r0
    for i in range(len(affects)):
        w0, w1, r = win_t0[i], win_t1[i], win_rate[i]
        lo = jnp.maximum(w0, t_start)
        overlap = affects[i] & (lo < w1) & (t_done > lo)
        remaining = t_done - lo
        span = w1 - lo
        cap = r * span
        fits = (r > 0.0) & (remaining <= cap)
        t_new = jnp.where(fits,
                          lo + remaining / jnp.where(r > 0.0, r, 1.0),
                          w1 + (remaining - cap))
        t_done = jnp.where(overlap, t_new, t_done)
    return t_done - t_start
