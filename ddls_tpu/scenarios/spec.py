"""Declarative, seeded, fingerprinted scenario specs.

One ``ScenarioSpec`` names everything the four simulator backends need
to agree on: the fabric (any ``hardware/topologies.py`` builder config,
incl. multi-channel RAMP and torus), the workload (synthetic graph
knobs + arrival process + SLA distribution), per-server device-speed
multipliers and a deterministic failure schedule. Everything derived
from a spec is a pure function of ``(spec.seed, fingerprint(spec))`` —
the failure-window generator is seeded with exactly that pair, so
schedules are bit-reproducible and any spec edit re-keys them.

The arrival process can be the serving stack's own fingerprinted
diurnal/burst/heavy-tail generator (``serve/loadgen.py``) via
``arrival={"kind": "loadgen", ...}`` — training and serving share one
workload vocabulary (ISSUE 16). ``scenarios/conformance.py`` drives a
spec through host vs C++ vs jax lookahead vs the jitted episode
kernels; ``docs/scenarios.md`` has the schema and the
adding-a-scenario recipe.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional

import numpy as np

from ddls_tpu.scenarios.failures import (FAILURE_KIND_NAMES,
                                         FAILURE_KIND_TO_EVENT,
                                         ScenarioRuntime)


class ScenarioError(ValueError):
    """A spec failed validation (bad field, overlapping windows, unknown
    resource, scenario features on an unsupported topology)."""


def _canonical_topology() -> dict:
    # the golden-stats shape (tests/test_stats_parity.py): 8 servers,
    # single-channel complete RAMP
    return {"type": "ramp", "kwargs": {
        "num_communication_groups": 2,
        "num_racks_per_communication_group": 2,
        "num_servers_per_rack": 2,
        "num_channels": 1,
        "total_node_bandwidth": 1.6e12,
        "intra_gpu_propagation_latency": 50e-9,
        "worker_io_latency": 100e-9}}


def _canonical_nodes() -> dict:
    return {"type_1": {"num_nodes": 8, "workers_config": [
        {"num_workers": 1, "worker": "A100"}]}}


@dataclasses.dataclass
class ScenarioSpec:
    """The declarative scenario contract. All fields are plain JSON-able
    values; the fingerprint hashes the canonical JSON form, so field
    ORDER never matters but every VALUE does."""

    name: str = "canonical"
    seed: int = 0
    # fabric: any hardware/topologies.py build_topology config
    topology: dict = dataclasses.field(default_factory=_canonical_topology)
    node_config: dict = dataclasses.field(default_factory=_canonical_nodes)
    # workload: graphs/synthetic.py generate_pipedream_txt_files knobs
    jobs: dict = dataclasses.field(default_factory=lambda: {
        "n_cnn": 2, "n_translation": 1, "seed": 0,
        "min_ops": 4, "max_ops": 6})
    # arrival process: {"kind": "fixed", "interarrival": s} or
    # {"kind": "loadgen", <generate_trace knobs>, "time_scale": s}
    arrival: dict = dataclasses.field(default_factory=lambda: {
        "kind": "fixed", "interarrival": 1000.0})
    # SLA (max acceptable JCT frac) distribution
    sla: dict = dataclasses.field(default_factory=lambda: {
        "kind": "uniform", "min": 0.1, "max": 1.0, "decimals": 2})
    replication_factor: int = 10
    num_training_steps: int = 50
    job_sampling_mode: str = "remove_and_repeat"
    # server id -> speed multiplier (1.0 = nominal; <1 slower)
    device_speeds: Dict[str, float] = dataclasses.field(default_factory=dict)
    # either {"windows": [explicit window dicts]} or generator knobs —
    # see resolve_failure_windows
    failures: dict = dataclasses.field(default_factory=dict)
    max_partitions_per_op: int = 8
    min_op_run_time_quantum: float = 0.01
    sim_seconds: float = 2e4
    pad_obs: dict = dataclasses.field(default_factory=lambda: {
        "max_nodes": 64, "max_edges": 256})

    # ------------------------------------------------------------- json
    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True,
                          indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ScenarioSpec":
        data = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(data) - known)
        if unknown:
            raise ScenarioError(f"unknown ScenarioSpec fields: {unknown}")
        return cls(**data)


def spec_fingerprint(spec: ScenarioSpec) -> str:
    """16-hex content fingerprint over the canonical JSON form (same
    convention as serve/loadgen.py trace_fingerprint)."""
    payload = json.dumps(dataclasses.asdict(spec), sort_keys=True,
                         separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


# ---------------------------------------------------------------- validate
_ARRIVAL_KINDS = ("fixed", "loadgen")
_SLA_KINDS = ("uniform", "fixed")
_SAMPLING_MODES = ("replace", "remove", "remove_and_repeat")


def validate_spec(spec: ScenarioSpec) -> None:
    """Static (topology-free) validation; raises ScenarioError. The
    topology-dependent checks (resource ranges, dense-path gating,
    window overlap after generation) live in build_runtime."""
    if not spec.name:
        raise ScenarioError("spec.name must be non-empty")
    if spec.arrival.get("kind") not in _ARRIVAL_KINDS:
        raise ScenarioError(
            f"arrival.kind must be one of {_ARRIVAL_KINDS}, got "
            f"{spec.arrival.get('kind')!r}")
    if spec.sla.get("kind") not in _SLA_KINDS:
        raise ScenarioError(
            f"sla.kind must be one of {_SLA_KINDS}, got "
            f"{spec.sla.get('kind')!r}")
    if spec.job_sampling_mode not in _SAMPLING_MODES:
        raise ScenarioError(
            f"job_sampling_mode must be one of {_SAMPLING_MODES}")
    for sid, mult in spec.device_speeds.items():
        if not (float(mult) > 0.0):
            raise ScenarioError(
                f"device_speeds[{sid!r}] must be > 0, got {mult}")
    if spec.failures:
        known = {"windows", "n_preempt", "n_straggle", "horizon",
                 "preempt_duration", "straggle_duration",
                 "straggle_slowdown"}
        unknown = sorted(set(spec.failures) - known)
        if unknown:
            raise ScenarioError(f"unknown failures keys: {unknown}")
        for w in spec.failures.get("windows", ()):
            if w.get("kind") not in FAILURE_KIND_NAMES:
                raise ScenarioError(
                    f"window kind must be one of "
                    f"{sorted(FAILURE_KIND_NAMES)}, got {w.get('kind')!r}")
            if not (0.0 <= float(w["t0"]) < float(w["t1"])):
                raise ScenarioError(
                    f"window needs 0 <= t0 < t1, got {w}")


# --------------------------------------------------------- failure windows
def resolve_failure_windows(spec: ScenarioSpec, n_servers: int,
                            n_channels: int) -> List[dict]:
    """The deterministic failure schedule: normalized, t0-sorted,
    globally non-overlapping windows ``{"kind": int, "resource": int,
    "t0": f, "t1": f, "rate": f, "event": str}``.

    Explicit form (``failures["windows"]``) is normalized and checked
    for overlap. Generated form partitions ``horizon`` into one slot
    per window and jitters start/duration/resource INSIDE each slot, so
    non-overlap holds by construction; the rng seed is exactly
    ``(spec.seed, fingerprint(spec))`` — bit-reproducible, re-keyed by
    any spec edit.
    """
    f = spec.failures
    if not f:
        return []
    fp = spec_fingerprint(spec)
    out: List[dict] = []
    if "windows" in f:
        for w in f["windows"]:
            kind = FAILURE_KIND_NAMES[w["kind"]]
            if kind == 0:  # worker_preempt
                rate = float(w.get("rate", 0.0))
            else:
                rate = float(w.get("rate", 1.0 / float(w["slowdown"])))
            out.append({"kind": kind, "resource": int(w["resource"]),
                        "t0": float(w["t0"]), "t1": float(w["t1"]),
                        "rate": rate,
                        "event": FAILURE_KIND_TO_EVENT[kind]})
    else:
        n_pre = int(f.get("n_preempt", 0))
        n_str = int(f.get("n_straggle", 0))
        n = n_pre + n_str
        if n == 0:
            return []
        t_lo, t_hi = (float(t) for t in f.get("horizon", (0.0, 1e4)))
        if not (0.0 <= t_lo < t_hi):
            raise ScenarioError(f"failures.horizon needs 0 <= lo < hi, "
                                f"got {(t_lo, t_hi)}")
        rng = np.random.default_rng([int(spec.seed), int(fp[:8], 16)])
        kinds = ([0] * n_pre) + ([1] * n_str)
        kinds = [kinds[i] for i in rng.permutation(n)]
        slot = (t_hi - t_lo) / n
        for i, kind in enumerate(kinds):
            dur_lo, dur_hi = (f.get("preempt_duration", (30.0, 90.0))
                              if kind == 0
                              else f.get("straggle_duration", (60.0, 240.0)))
            dur = min(float(rng.uniform(dur_lo, dur_hi)), 0.9 * slot)
            t0 = t_lo + i * slot + float(rng.uniform(0.0, slot - dur))
            if kind == 0:
                res, rate = int(rng.integers(n_servers)), 0.0
            else:
                s_lo, s_hi = f.get("straggle_slowdown", (2.0, 6.0))
                res = int(rng.integers(n_channels)) if n_channels else 0
                rate = 1.0 / float(rng.uniform(s_lo, s_hi))
            out.append({"kind": kind, "resource": res, "t0": t0,
                        "t1": t0 + dur, "rate": rate,
                        "event": FAILURE_KIND_TO_EVENT[kind]})
    out.sort(key=lambda w: w["t0"])
    for a, b in zip(out, out[1:]):
        if b["t0"] < a["t1"]:
            raise ScenarioError(
                "failure windows must be globally non-overlapping (the "
                f"inflation walk is exact only then): {a} vs {b}")
    return out


# ------------------------------------------------------------ env plumbing
def arrival_dist_config(spec: ScenarioSpec) -> dict:
    a = spec.arrival
    if a["kind"] == "fixed":
        return {"_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": float(a["interarrival"])}
    knobs = {k: v for k, v in a.items() if k != "kind"}
    knobs["_target_"] = ("ddls_tpu.demands.distributions."
                         "LoadgenInterarrival")
    return knobs


def sla_dist_config(spec: ScenarioSpec) -> dict:
    s = spec.sla
    if s["kind"] == "fixed":
        return {"_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": float(s["frac"])}
    return {"_target_": "ddls_tpu.demands.distributions.Uniform",
            "min_val": float(s["min"]), "max_val": float(s["max"]),
            "decimals": s.get("decimals")}


def jobs_config(spec: ScenarioSpec, dataset_dir: Optional[str] = None) -> dict:
    """JobsGenerator config for the spec. Default: the deterministic
    ``synthetic`` path (JobsGenerator generates the graph files itself
    and fingerprints the knobs); ``dataset_dir`` overrides with a
    pre-generated directory (trace_diff --dataset)."""
    cfg: dict = {
        "job_interarrival_time_dist": arrival_dist_config(spec),
        "max_acceptable_job_completion_time_frac_dist":
            sla_dist_config(spec),
        "replication_factor": int(spec.replication_factor),
        "job_sampling_mode": spec.job_sampling_mode,
        "num_training_steps": int(spec.num_training_steps),
    }
    if dataset_dir is not None:
        cfg["path_to_files"] = dataset_dir
    else:
        cfg["synthetic"] = dict(spec.jobs)
    return cfg


def env_kwargs(spec: ScenarioSpec, dataset_dir: Optional[str] = None,
               sim_seconds: Optional[float] = None) -> dict:
    """RampJobPartitioningEnvironment kwargs for the spec (backend
    selection flags and the scenario runtime are layered on top by
    conformance.build_env)."""
    validate_spec(spec)
    return dict(
        topology_config=spec.topology,
        node_config=spec.node_config,
        jobs_config=jobs_config(spec, dataset_dir=dataset_dir),
        max_partitions_per_op=int(spec.max_partitions_per_op),
        min_op_run_time_quantum=float(spec.min_op_run_time_quantum),
        reward_function="job_acceptance",
        reward_function_kwargs={"fail_reward": -1, "success_reward": 1},
        max_simulation_run_time=(float(sim_seconds) if sim_seconds
                                 is not None else float(spec.sim_seconds)),
        pad_obs_kwargs=dict(spec.pad_obs))


def build_runtime(spec: ScenarioSpec, topology) -> Optional[ScenarioRuntime]:
    """Build the ScenarioRuntime for an instantiated topology — dense
    per-server speeds + resolved windows — or None when the spec is
    nominal (no failure windows, unit speeds), keeping the default hot
    path byte-identical.

    Failure schedules and non-unit speeds are gated to the dense
    single-channel complete topologies (``dense_tables()['pair_channel']
    is not None``): that is where the jitted backend exists and where
    mounted channels are dense ints, so all four backends can agree on
    resource indexing.
    """
    validate_spec(spec)
    dense = topology.dense_tables()
    server_index = dense["server_index"]
    n_srv = len(server_index)
    n_chan = len(dense["channel_ids"])
    speeds = np.ones(n_srv, dtype=np.float64)
    for sid, mult in spec.device_speeds.items():
        if sid not in server_index:
            raise ScenarioError(
                f"device_speeds names unknown server {sid!r} "
                f"(topology has {sorted(server_index)[:4]}...)")
        speeds[server_index[sid]] = float(mult)
    windows = resolve_failure_windows(spec, n_srv, n_chan)
    if not windows and bool(np.all(speeds == 1.0)):
        return None
    if dense["pair_channel"] is None:
        raise ScenarioError(
            "failure windows / device speeds require the dense single-"
            "channel complete topology (scenario inflation indexes "
            "dense server/channel ids; see docs/scenarios.md)")
    for w in windows:
        bound = n_srv if w["kind"] == 0 else n_chan
        if not (0 <= w["resource"] < bound):
            raise ScenarioError(
                f"window resource out of range for this topology: {w} "
                f"(bound {bound})")
    return ScenarioRuntime(spec.name, spec_fingerprint(spec), speeds,
                           windows)


# ----------------------------------------------------------------- registry
def canonical_spec() -> ScenarioSpec:
    """The single-channel complete-topology RAMP setup every existing
    parity/golden test pins — byte-for-byte the trace_diff defaults."""
    return ScenarioSpec(name="canonical")


def multi_channel_spec() -> ScenarioSpec:
    """Canonical fabric with num_channels=2: exercises the dict-mirror
    dep path (host + C++ + jax lookahead); the jitted episode backend
    does not exist off the dense path, so conformance excludes that
    leg with a reason."""
    spec = ScenarioSpec(name="multi_channel")
    spec.topology["kwargs"]["num_channels"] = 2
    return spec


def failures_spec() -> ScenarioSpec:
    """Canonical fabric + heterogeneous speeds + a generated preempt/
    straggler schedule + the serving loadgen arrival process."""
    return ScenarioSpec(
        name="failures",
        seed=1,
        arrival={"kind": "loadgen", "n_requests": 64, "base_rps": 1.0,
                 "seed": 7, "time_scale": 600.0},
        device_speeds={"0-0-0": 0.8, "1-1-1": 1.25},
        failures={"n_preempt": 2, "n_straggle": 2,
                  "horizon": [1500.0, 15000.0],
                  "preempt_duration": [40.0, 120.0],
                  "straggle_duration": [80.0, 300.0],
                  "straggle_slowdown": [2.0, 6.0]})


REGISTRY = {
    "canonical": canonical_spec,
    "multi_channel": multi_channel_spec,
    "failures": failures_spec,
}


def get_spec(name_or_path: str) -> ScenarioSpec:
    """Resolve a registry name or a spec-JSON file path."""
    if name_or_path in REGISTRY:
        return REGISTRY[name_or_path]()
    import os

    if os.path.exists(name_or_path):
        with open(name_or_path) as fh:
            return ScenarioSpec.from_json(fh.read())
    raise ScenarioError(
        f"unknown scenario {name_or_path!r} — not a registry name "
        f"({sorted(REGISTRY)}) and not a spec-JSON path")
