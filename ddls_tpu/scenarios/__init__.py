"""Scenario subsystem: one declarative spec, four conforming backends.

``spec.ScenarioSpec`` names a fabric + workload + device speeds + a
deterministic failure schedule; ``conformance.run_conformance`` proves
host / C++ / jax lookahead / jitted-episode agreement on it (CLI:
``python scripts/conformance.py --json``). See docs/scenarios.md.
"""
from ddls_tpu.scenarios.failures import (FAILURE_CHANNEL_STRAGGLE,
                                         FAILURE_KIND_TO_EVENT,
                                         FAILURE_WORKER_PREEMPT,
                                         ScenarioRuntime, inflate_duration,
                                         inflate_duration_jax)
from ddls_tpu.scenarios.spec import (REGISTRY, ScenarioError, ScenarioSpec,
                                     build_runtime, canonical_spec,
                                     env_kwargs, failures_spec, get_spec,
                                     multi_channel_spec,
                                     resolve_failure_windows,
                                     spec_fingerprint, validate_spec)

__all__ = [
    "FAILURE_CHANNEL_STRAGGLE", "FAILURE_KIND_TO_EVENT",
    "FAILURE_WORKER_PREEMPT", "ScenarioRuntime", "inflate_duration",
    "inflate_duration_jax", "REGISTRY", "ScenarioError", "ScenarioSpec",
    "build_runtime", "canonical_spec", "env_kwargs", "failures_spec",
    "get_spec", "multi_channel_spec", "resolve_failure_windows",
    "spec_fingerprint", "validate_spec",
]
