"""Backend-conformance harness: drive ONE ScenarioSpec through every
simulator backend and assert the pinned parity contracts.

Legs (per spec):

- ``host_native``: seeded host episode vs the C++ lookahead engine
  replaying the same actions — flight traces BIT-exact (rtol 0).
- ``host_jax``: host vs the jitted jax lookahead kernel — rtol 1e-4
  (the array engine packs f32 by construction, x64 or not; this is the
  tolerance tests/test_jax_lookahead.py pins).
- ``host_jitted``: host decisions vs the fully-jitted episode kernel
  (``sim/jax_env.py make_episode_fn``) replaying the host action
  sequence — decision-level diff at 1e-9 (x64). Excluded (with reason)
  off the dense single-channel complete topology, where the jitted
  backend does not exist.
- ``golden``: the spec's fabric reproduces the hand-computed golden
  stats (tests/test_stats_parity.py) EXACTLY on a single-op job.
- ``lint``: the lint engine's backend-surface-parity rule is clean —
  cause tables, episode fields, memo surface and the failure-event
  vocabulary all in sync.

``scripts/conformance.py --json`` is the CLI; ``scripts/trace_diff.py``
wraps the same episode machinery (run_recorded_episode /
decision_events / jitted_decision_events live HERE) for two-backend
interactive diffing.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

from ddls_tpu.scenarios.spec import (ScenarioSpec, build_runtime,
                                     env_kwargs, spec_fingerprint)

HOST_BACKENDS = ("host", "native", "jax")
DEFAULT_LEGS = ("host_native", "host_jax", "host_jitted", "golden",
                "lint")


def build_env(spec: ScenarioSpec, backend: str = "host",
              dataset_dir: Optional[str] = None,
              sim_seconds: Optional[float] = None):
    """A RampJobPartitioningEnvironment for the spec with the requested
    lookahead backend and the spec's ScenarioRuntime attached (None when
    the spec is nominal)."""
    from ddls_tpu.envs import RampJobPartitioningEnvironment
    from ddls_tpu.hardware.topologies import build_topology

    if backend not in HOST_BACKENDS:
        raise ValueError(f"backend must be one of {HOST_BACKENDS}")
    runtime = build_runtime(spec, build_topology(spec.topology))
    return RampJobPartitioningEnvironment(
        **env_kwargs(spec, dataset_dir=dataset_dir,
                     sim_seconds=sim_seconds),
        use_jax_lookahead=(backend == "jax"),
        use_native_lookahead=(backend == "native"),
        scenario_runtime=runtime)


def run_recorded_episode(env, seed: int, actions=None,
                         max_decisions: int = 500, detail: bool = False):
    """One seeded episode under a fresh flight recorder; returns
    (events, actions_taken). With ``actions`` given, replays that
    sequence (truncating when the episode ends early or a replayed
    action goes mask-invalid — both only happen past a divergence, which
    the diff will already have found)."""
    import numpy as np

    from ddls_tpu.telemetry import flight

    prev = (flight.recorder().enabled, flight.recorder().detail)
    flight.reset()
    flight.enable(detail=detail)
    try:
        obs = env.reset(seed=seed)
        rng = np.random.RandomState(seed)
        taken = []
        done = False
        while not done and len(taken) < max_decisions:
            if actions is not None:
                if len(taken) >= len(actions):
                    break
                action = int(actions[len(taken)])
            else:
                valid = np.flatnonzero(np.asarray(obs["action_mask"]))
                action = int(rng.choice(valid))
            try:
                obs, _, done, _ = env.step(action)
            except ValueError:
                break  # replayed action invalid here: post-divergence
            taken.append(action)
        events = flight.drain()
    finally:
        flight.reset()
        flight.recorder().enabled, flight.recorder().detail = prev
    return events, taken


def decision_events(events):
    """The decision-level view of a host trace: `action_decided` events
    with the observation-mask context dropped (the jitted replay kernel
    sees no observation, so the mask is host-only context here) and the
    blocked cause CANONICALISED through the trace-code maps — several
    host sub-action causes collapse onto one code (e.g. 'op_partition'
    -> op_placement), and the jitted side can only ever name the
    canonical string."""
    from ddls_tpu.sim.jax_env import CAUSE_CODE_TO_STR, CAUSE_STR_TO_CODE
    from ddls_tpu.telemetry import flight

    out = []
    for e in flight.comparable_events(events, kinds=("action_decided",)):
        e = {k: v for k, v in e.items() if k != "mask"}
        code = CAUSE_STR_TO_CODE.get(e.get("cause"))
        if code is not None:
            e["cause"] = CAUSE_CODE_TO_STR[code]
        out.append(e)
    return out


def jitted_decision_events(env, host_events, actions):
    """Replay the host action sequence through the fully-jitted episode
    kernel and express its per-decision trace as `action_decided`
    events (the job bank is rebuilt from the host trace's own
    job_arrived events)."""
    import jax.numpy as jnp
    import numpy as np

    from ddls_tpu.sim.jax_env import (CAUSE_CODE_TO_STR,
                                      build_episode_tables,
                                      build_job_bank, make_episode_fn)

    arrivals = [{"model": e["model"],
                 "num_training_steps": e["num_training_steps"],
                 "sla_frac": e["sla_frac"],
                 "time_arrived": e["t"]}
                for e in host_events if e["kind"] == "job_arrived"]
    et = build_episode_tables(env)
    bank = build_job_bank(et, arrivals)
    out = make_episode_fn(et)(
        {k: jnp.asarray(v) for k, v in bank.items()},
        jnp.asarray(actions, jnp.int32))
    reward, accept, cause, jct, t, has_job = (np.asarray(x)
                                              for x in out["trace"])
    events = []
    for i, action in enumerate(actions):
        if not has_job[i]:
            break  # kernel ran out of queued jobs (post-divergence)
        accepted = bool(accept[i])
        events.append({
            "kind": "action_decided", "t": float(t[i]), "job_idx": i,
            "degree": int(action), "accepted": accepted,
            "cause": CAUSE_CODE_TO_STR[int(cause[i])],
            "jct": float(jct[i]) if accepted else 0.0})
    return events


# ----------------------------------------------------------------- legs
def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


def _jitted_supported(spec: ScenarioSpec):
    from ddls_tpu.hardware.topologies import build_topology

    dense = build_topology(spec.topology).dense_tables()
    if dense["pair_channel"] is None:
        return False, ("jitted episode backend exists only on the dense "
                       "single-channel complete topology")
    return True, None


def golden_stats_leg(spec: ScenarioSpec) -> dict:
    """The spec's fabric must reproduce the hand-computed golden stats
    (tests/test_stats_parity.py) EXACTLY: one single-op job (fwd=2,
    bwd=4, activation=100, parameter=10) x 5 steps on one worker. The
    scenario runtime is deliberately NOT attached — this leg pins the
    FABRIC; the inflation no-op is pinned by the full tier-1 suite
    running with scenario_runtime=None everywhere."""
    import tempfile

    from ddls_tpu.agents import (FirstFitDepPlacer, RampFirstFitOpPlacer,
                                 SRPTDepScheduler, SRPTOpScheduler)
    from ddls_tpu.agents.partitioners import build_partition_action
    from ddls_tpu.sim import Action, OpPartition, RampClusterEnvironment

    with tempfile.TemporaryDirectory() as td:
        with open(os.path.join(td, "tiny.txt"), "w") as fh:
            fh.write("node1 -- Linear(id=1) -- forward_compute_time=2.0, "
                     "backward_compute_time=4.0, activation_size=100.0, "
                     "parameter_size=10.0\n")
        cluster = RampClusterEnvironment(topology_config=spec.topology,
                                         node_config=spec.node_config)
        cluster.reset({
            "path_to_files": td,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 1e6},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 1.0},
            "replication_factor": 1,
            "num_training_steps": 5,
            "job_sampling_mode": "remove",
        }, max_simulation_run_time=None, seed=0)

        action_map = {}
        for job_id, job in cluster.job_queue.jobs.items():
            action_map[job_id] = build_partition_action(
                job.graph, min_op_run_time_quantum=0.01,
                max_partitions_per_op=1)
        op_partition = OpPartition(action_map, cluster=cluster)
        op_placement = RampFirstFitOpPlacer().get(op_partition, cluster)
        op_schedule = SRPTOpScheduler().get(op_partition, op_placement,
                                            cluster)
        dep_placement = FirstFitDepPlacer().get(op_partition, op_placement,
                                                cluster)
        dep_schedule = SRPTDepScheduler().get(op_partition, dep_placement,
                                              cluster)
        cluster.step(Action(op_partition=op_partition,
                            op_placement=op_placement,
                            op_schedule=op_schedule,
                            dep_placement=dep_placement,
                            dep_schedule=dep_schedule))

        e = cluster.episode_stats
        n_workers = len(cluster.topology.worker_to_server)
        expect = {
            "num_jobs_completed": 1,
            "job_completion_time": [30.0],
            "jobs_completed_total_operation_memory_cost": [220.0],
            "jobs_completed_total_dependency_size": [110.0],
            "jobs_completed_mean_mounted_worker_utilisation_frac": [1.0],
            "episode_time": 30.0,
            "cluster_info_processed": 330.0,
            "demand_total_info_processed": 320.0,
            "mean_cluster_worker_utilisation_frac": 1.0 / n_workers,
        }
        mismatches = {k: {"got": e[k], "want": v}
                      for k, v in expect.items() if e[k] != v}
    leg = {"leg": "golden", "status": "ok" if not mismatches
           else "divergence"}
    if mismatches:
        leg["mismatches"] = mismatches
    return leg


def lint_leg() -> dict:
    """The lint engine's backend-surface-parity rule over the live tree:
    cause tables bijective, episode fields in sync, memo surface intact,
    failure-event codes present in every backend vocabulary."""
    from ddls_tpu.lint.engine import run_lint
    from ddls_tpu.lint.rules.backend_parity import BackendSurfaceParityRule

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    res = run_lint(roots=(), repo_root=repo_root,
                   rules=[BackendSurfaceParityRule()])
    bad = [f for f in res.findings
           if not getattr(f, "suppressed", False)]
    leg = {"leg": "lint", "status": "ok" if not bad else "divergence"}
    if bad:
        leg["findings"] = [f"{f.rel}:{f.line}: {f.message}" for f in bad]
    return leg


def run_conformance(spec: ScenarioSpec, seed: int = 0,
                    max_decisions: int = 500,
                    sim_seconds: Optional[float] = None,
                    legs: Optional[Sequence[str]] = None) -> dict:
    """Run the requested conformance legs for one spec; returns a
    JSON-able report. ``ok`` is True iff NO leg diverged or errored
    (skipped/unavailable legs are reported but do not fail)."""
    from ddls_tpu.telemetry import flight

    legs = tuple(legs) if legs else DEFAULT_LEGS
    unknown = sorted(set(legs) - set(DEFAULT_LEGS))
    if unknown:
        raise ValueError(f"unknown conformance legs {unknown} "
                         f"(choose from {DEFAULT_LEGS})")
    report: dict = {
        "spec": {"name": spec.name,
                 "fingerprint": spec_fingerprint(spec)},
        "seed": seed,
        "legs": [],
    }

    host_events = actions = host_env = None
    if any(l in legs for l in ("host_native", "host_jax", "host_jitted")):
        host_env = build_env(spec, "host", sim_seconds=sim_seconds)
        host_events, actions = run_recorded_episode(
            host_env, seed, max_decisions=max_decisions)

    def trace_leg(name: str, backend: str, rtol: float) -> dict:
        env_b = build_env(spec, backend, sim_seconds=sim_seconds)
        events_b, _ = run_recorded_episode(env_b, seed, actions=actions,
                                           max_decisions=max_decisions)
        a = flight.comparable_events(host_events)
        b = flight.comparable_events(events_b)
        div = flight.first_divergence(a, b, rtol=rtol)
        leg = {"leg": name, "status": "ok" if div is None
               else "divergence", "rtol": rtol,
               "events_a": len(a), "events_b": len(b),
               "decisions": len(actions)}
        if div is not None:
            leg["divergence"] = flight.format_divergence(
                div, label_a="host", label_b=backend)
        return leg

    for leg_name in legs:
        if leg_name == "host_native":
            from ddls_tpu.native import native_available

            if not native_available():
                report["legs"].append({
                    "leg": leg_name, "status": "unavailable",
                    "reason": "C++ lookahead engine did not build/load"})
            else:
                report["legs"].append(
                    trace_leg(leg_name, "native", rtol=0.0))
        elif leg_name == "host_jax":
            # the array engine packs f32 by construction (x64 changes
            # nothing): compare at the tolerance the repo pins for it
            report["legs"].append(
                trace_leg(leg_name, "jax", rtol=1e-4))
        elif leg_name == "host_jitted":
            supported, reason = _jitted_supported(spec)
            if not supported:
                report["legs"].append({"leg": leg_name,
                                       "status": "skipped",
                                       "reason": reason})
            elif not _x64_enabled():
                report["legs"].append({
                    "leg": leg_name, "status": "skipped",
                    "reason": "jitted decision parity is pinned at 1e-9 "
                              "under x64 only — set JAX_ENABLE_X64=1"})
            else:
                a = decision_events(host_events)
                b = jitted_decision_events(host_env, host_events,
                                           actions)
                div = flight.first_divergence(a, b, rtol=1e-9)
                leg = {"leg": leg_name,
                       "status": "ok" if div is None else "divergence",
                       "rtol": 1e-9, "events_a": len(a),
                       "events_b": len(b), "decisions": len(actions)}
                if div is not None:
                    leg["divergence"] = flight.format_divergence(
                        div, label_a="host", label_b="jitted")
                report["legs"].append(leg)
        elif leg_name == "golden":
            report["legs"].append(golden_stats_leg(spec))
        elif leg_name == "lint":
            report["legs"].append(lint_leg())

    report["ok"] = all(l["status"] in ("ok", "skipped", "unavailable")
                       for l in report["legs"])
    return report
