"""Heuristic baseline actors for the partitioning MDP
(reference: ddls/environments/ramp_job_partitioning/agents/).

All actors implement ``compute_action(obs, job_to_place=None)`` returning an
int from the env's action set. These are the paper's comparison points:
Random, NoParallelism (1), MinParallelism (2), MaxParallelism (largest
valid), SiPML (fixed max), AcceptableJCT (approximately the partition degree
needed to meet the job's SLA).
"""
from __future__ import annotations

import math

import numpy as np


def _valid_actions(obs) -> np.ndarray:
    action_set = np.asarray(obs["action_set"])
    mask = np.asarray(obs["action_mask"]).astype(bool)
    return action_set[mask]


class BaselineActor:
    name = "baseline"

    def __init__(self, name: str = None, **kwargs):
        if name is not None:
            self.name = name

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        raise NotImplementedError


class RandomActor(BaselineActor):
    name = "random"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        return int(np.random.choice(_valid_actions(obs)))


class NoParallelism(BaselineActor):
    """Always run sequentially on one worker (action 1 when valid)."""

    name = "no_parallelism"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        return 1 if 1 in valid else int(valid[0])


class MinParallelism(BaselineActor):
    """Smallest parallel degree (2) when valid."""

    name = "min_parallelism"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        for a in valid:
            if a >= 2:
                return int(a)
        return int(valid[-1])


class MaxParallelism(BaselineActor):
    """Largest valid partition degree."""

    name = "max_parallelism"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        return int(_valid_actions(obs)[-1])


class SiPML(BaselineActor):
    """Fixed maximum partition degree (the SiP-ML policy: always partition as
    much as allowed, reference: agents/sip_ml.py)."""

    name = "sip_ml"

    def __init__(self, max_partitions_per_op: int = 16, **kwargs):
        super().__init__(**kwargs)
        self.max_partitions_per_op = max_partitions_per_op

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        candidates = valid[valid <= self.max_partitions_per_op]
        return int(candidates[-1]) if len(candidates) else int(valid[-1])


class AcceptableJCT(BaselineActor):
    """Partition just enough to (approximately) meet the job's maximum
    acceptable completion time: target = ceil(sequential / max acceptable),
    rounded up to the nearest valid action
    (reference: agents/acceptable_jct.py:21-40). Ignores communication
    overhead, so it is an approximation the learned policy can beat."""

    name = "acceptable_jct"

    def __init__(self, max_partitions_per_op: int = None, **kwargs):
        super().__init__(**kwargs)
        self.max_partitions_per_op = max_partitions_per_op

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        if len(valid) <= 1 or job_to_place is None:
            return int(valid[0])
        target = math.ceil(job_to_place.seq_completion_time
                           / job_to_place.max_acceptable_jct)
        action = valid[-1]
        for a in valid:
            if a == 0:
                continue
            if a >= target:
                action = a
                break
        return int(action)


class OracleJCT(AcceptableJCT):
    """AcceptableJCT upgraded with TRUE lookahead prices: pick the smallest
    partition degree whose priced lookahead JCT (communication included)
    meets the job's max-acceptable JCT, freeing the most workers for later
    arrivals. Falls back to the AcceptableJCT approximation when the env
    doesn't carry candidate prices (candidate_pricing off).

    Consumes the batched candidate pricing the jax-lookahead go/no-go
    scoped (docs/jax_lookahead_gonogo.md point 2): all candidate degrees
    priced per decision, one vmapped dispatch on an accelerator. No
    reference counterpart — the reference's heuristics never see real
    lookahead outcomes."""

    name = "oracle_jct"

    def compute_action(self, obs, job_to_place=None, env=None,
                       **kwargs) -> int:
        prices = getattr(env, "candidate_prices", None) if env else None
        if not prices:
            return super().compute_action(obs, job_to_place=job_to_place,
                                          **kwargs)
        valid = [a for a in _valid_actions(obs) if a != 0]
        if not valid or job_to_place is None:
            return super().compute_action(obs, job_to_place=job_to_place,
                                          **kwargs)
        limit = job_to_place.max_acceptable_jct
        acceptable = [a for a in valid
                      if prices.get(a) is not None and prices[a][0] <= limit]
        if acceptable:
            return int(min(acceptable))
        # no candidate meets the SLA: the job blocks regardless, so take
        # the smallest-JCT placeable candidate (max throughput salvage)
        placeable = [a for a in valid if prices.get(a) is not None]
        if placeable:
            return int(min(placeable, key=lambda a: prices[a][0]))
        return int(valid[0])


class FixedDegreePacking(BaselineActor):
    """The decision rule the round-4 RL policies converged to, extracted
    and named (VERDICT r4 item 1; scripts/experiments/extract_rule.py):
    partition EVERY job to one fixed degree ``d`` when a ``d``-server
    block is free, otherwise decline (action 0).

    Every trained policy in the repo is exactly this rule. The three
    32-server policies (price-feature mixed-load PPO, obs-only
    host-collected PPO, obs-only device-collected PPO) all implement
    d=8 — a depth-2 decision tree reproduces 12,672 held-out policy
    decisions at 100% accuracy; the 128-server fine-tune implements d=4
    (6,400/6,400 decisions) and the 8-server fine-tune d=4 at 97%
    (docs/results_round5/rule_extraction.md has the full data and the
    headline-number reproductions: 123.70 +/- 3.63 on the 20-seed table
    and 0.569 on the load sweep, identical to the shipped checkpoint).

    Why a FIXED degree beats the per-decision-optimal
    smallest-degree-meeting-SLA rule (OracleJCT) on episode return:
    homogeneous blocks keep the cluster perfectly tileable — since every
    accepted job holds exactly ``d`` servers and partial placements are
    declined, free capacity is always a multiple of ``d`` and no
    arrival ever faces a fragmented cluster (the dumps confirm
    free-worker counts only ever hit multiples of ``d``). Mixed-degree
    rules fragment RAMP's symmetric-block geometry, and a job held on
    few servers for long starves future arrivals. The reference's six
    heuristics (ddls/environments/ramp_job_partitioning/agents/) do not
    include this rule; SiPML (always-max) is its degenerate cousin and
    loses badly (88.0 vs 123.7 at d=16 vs 8 on the 20-seed protocol).

    The best degree is scale/load-dependent: measured means on the
    held-out protocols (n>=8): 32 servers/ia-50 — d=8: 123.7, d=4:
    119.7, d=16: 88.0, d=2: 30.5; 8 servers — d=4: 11.5 (beats
    OracleJCT 9.2); 72 servers — d=4: 320.2 (ties OracleJCT), d=8:
    312.0; 128 servers — d=4: 617.5 (ties OracleJCT 625.8). NOT the
    communication-group size (12 at 72 / 16 at 128 servers score far
    worse) — that hypothesis is falsified in the extraction doc.
    """

    name = "fixed_degree_packing"

    def __init__(self, degree: int = 8, **kwargs):
        super().__init__(**kwargs)
        self.degree = degree

    def compute_action(self, obs, job_to_place=None, env=None,
                       **kwargs) -> int:
        return self.degree if self.degree in _valid_actions(obs) else 0


BASELINE_ACTORS = {
    cls.name: cls for cls in (RandomActor, NoParallelism, MinParallelism,
                              MaxParallelism, SiPML, AcceptableJCT,
                              OracleJCT, FixedDegreePacking)
}


# ---------------------------------------------------------------------------
# Placement-shaping baseline actors (reference:
# ddls/environments/ramp_job_placement_shaping/agents/*.py): choose among
# valid meta-block shape actions; action 0 (don't place) is only taken when
# it is the sole valid action.

class FirstFitShaper(BaselineActor):
    """First valid non-zero shape action."""

    name = "first_fit"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        return int(valid[1] if len(valid) > 1 else valid[0])


class LastFitShaper(BaselineActor):
    """Last valid non-zero shape action."""

    name = "last_fit"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        return int(_valid_actions(obs)[-1])


class RandomShaper(BaselineActor):
    """Uniform-random valid non-zero shape action."""

    name = "random_shaper"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        if len(valid) > 1:
            return int(np.random.choice(valid[1:]))
        return int(valid[0])


SHAPER_ACTORS = {
    cls.name: cls for cls in (FirstFitShaper, LastFitShaper, RandomShaper)
}
