"""Heuristic baseline actors for the partitioning MDP
(reference: ddls/environments/ramp_job_partitioning/agents/).

All actors implement ``compute_action(obs, job_to_place=None)`` returning an
int from the env's action set. These are the paper's comparison points:
Random, NoParallelism (1), MinParallelism (2), MaxParallelism (largest
valid), SiPML (fixed max), AcceptableJCT (approximately the partition degree
needed to meet the job's SLA).
"""
from __future__ import annotations

import math

import numpy as np


def _valid_actions(obs) -> np.ndarray:
    action_set = np.asarray(obs["action_set"])
    mask = np.asarray(obs["action_mask"]).astype(bool)
    return action_set[mask]


class BaselineActor:
    name = "baseline"

    def __init__(self, name: str = None, **kwargs):
        if name is not None:
            self.name = name

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        raise NotImplementedError

    def reset(self) -> None:
        """Episode boundary: stateful actors clear cross-decision state
        here. EvalLoop calls this after every ``env.reset`` (train/
        loops.py) so stale state can never leak across episodes."""


class RandomActor(BaselineActor):
    name = "random"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        return int(np.random.choice(_valid_actions(obs)))


class NoParallelism(BaselineActor):
    """Always run sequentially on one worker (action 1 when valid)."""

    name = "no_parallelism"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        return 1 if 1 in valid else int(valid[0])


class MinParallelism(BaselineActor):
    """Smallest parallel degree (2) when valid."""

    name = "min_parallelism"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        for a in valid:
            if a >= 2:
                return int(a)
        return int(valid[-1])


class MaxParallelism(BaselineActor):
    """Largest valid partition degree."""

    name = "max_parallelism"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        return int(_valid_actions(obs)[-1])


class SiPML(BaselineActor):
    """Fixed maximum partition degree (the SiP-ML policy: always partition as
    much as allowed, reference: agents/sip_ml.py)."""

    name = "sip_ml"

    def __init__(self, max_partitions_per_op: int = 16, **kwargs):
        super().__init__(**kwargs)
        self.max_partitions_per_op = max_partitions_per_op

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        candidates = valid[valid <= self.max_partitions_per_op]
        return int(candidates[-1]) if len(candidates) else int(valid[-1])


class AcceptableJCT(BaselineActor):
    """Partition just enough to (approximately) meet the job's maximum
    acceptable completion time: target = ceil(sequential / max acceptable),
    rounded up to the nearest valid action
    (reference: agents/acceptable_jct.py:21-40). Ignores communication
    overhead, so it is an approximation the learned policy can beat."""

    name = "acceptable_jct"

    def __init__(self, max_partitions_per_op: int = None, **kwargs):
        super().__init__(**kwargs)
        self.max_partitions_per_op = max_partitions_per_op

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        if len(valid) <= 1 or job_to_place is None:
            return int(valid[0])
        target = math.ceil(job_to_place.seq_completion_time
                           / job_to_place.max_acceptable_jct)
        action = valid[-1]
        for a in valid:
            if a == 0:
                continue
            if a >= target:
                action = a
                break
        return int(action)


class OracleJCT(AcceptableJCT):
    """AcceptableJCT upgraded with TRUE lookahead prices: pick the smallest
    partition degree whose priced lookahead JCT (communication included)
    meets the job's max-acceptable JCT, freeing the most workers for later
    arrivals. Falls back to the AcceptableJCT approximation when the env
    doesn't carry candidate prices (candidate_pricing off).

    Consumes the batched candidate pricing the jax-lookahead go/no-go
    scoped (docs/jax_lookahead_gonogo.md point 2): all candidate degrees
    priced per decision, one vmapped dispatch on an accelerator. No
    reference counterpart — the reference's heuristics never see real
    lookahead outcomes."""

    name = "oracle_jct"

    def compute_action(self, obs, job_to_place=None, env=None,
                       **kwargs) -> int:
        prices = getattr(env, "candidate_prices", None) if env else None
        if not prices:
            return super().compute_action(obs, job_to_place=job_to_place,
                                          **kwargs)
        valid = [a for a in _valid_actions(obs) if a != 0]
        if not valid or job_to_place is None:
            return super().compute_action(obs, job_to_place=job_to_place,
                                          **kwargs)
        limit = job_to_place.max_acceptable_jct
        acceptable = [a for a in valid
                      if prices.get(a) is not None and prices[a][0] <= limit]
        if acceptable:
            return int(min(acceptable))
        # no candidate meets the SLA: the job blocks regardless, so take
        # the smallest-JCT placeable candidate (max throughput salvage)
        placeable = [a for a in valid if prices.get(a) is not None]
        if placeable:
            return int(min(placeable, key=lambda a: prices[a][0]))
        return int(valid[0])


class FixedDegreePacking(BaselineActor):
    """The decision rule the round-4 RL policies converged to, extracted
    and named (VERDICT r4 item 1; scripts/experiments/extract_rule.py):
    partition EVERY job to one fixed degree ``d`` when a ``d``-server
    block is free, otherwise decline (action 0).

    Every trained policy in the repo is exactly this rule. The three
    32-server policies (price-feature mixed-load PPO, obs-only
    host-collected PPO, obs-only device-collected PPO) all implement
    d=8 — a depth-2 decision tree reproduces 12,672 held-out policy
    decisions at 100% accuracy; the 128-server fine-tune implements d=4
    (6,400/6,400 decisions) and the 8-server fine-tune d=4 at 97%
    (docs/results_round5/rule_extraction.md has the full data and the
    headline-number reproductions: 123.70 +/- 3.63 on the 20-seed table
    and 0.569 on the load sweep, identical to the shipped checkpoint).

    Why a FIXED degree beats the per-decision-optimal
    smallest-degree-meeting-SLA rule (OracleJCT) on episode return:
    homogeneous blocks keep the cluster perfectly tileable — since every
    accepted job holds exactly ``d`` servers and partial placements are
    declined, free capacity is always a multiple of ``d`` and no
    arrival ever faces a fragmented cluster (the dumps confirm
    free-worker counts only ever hit multiples of ``d``). Mixed-degree
    rules fragment RAMP's symmetric-block geometry, and a job held on
    few servers for long starves future arrivals. The reference's six
    heuristics (ddls/environments/ramp_job_partitioning/agents/) do not
    include this rule; SiPML (always-max) is its degenerate cousin and
    loses badly (88.0 vs 123.7 at d=16 vs 8 on the 20-seed protocol).

    The best degree is scale/load-dependent: measured means on the
    held-out protocols (n>=8): 32 servers/ia-50 — d=8: 123.7, d=4:
    119.7, d=16: 88.0, d=2: 30.5; 8 servers — d=4: 11.5 (beats
    OracleJCT 9.2); 72 servers — d=4: 320.2 (ties OracleJCT), d=8:
    312.0; 128 servers — d=4: 617.5 (ties OracleJCT 625.8). NOT the
    communication-group size (12 at 72 / 16 at 128 servers score far
    worse) — that hypothesis is falsified in the extraction doc.
    """

    name = "fixed_degree_packing"

    def __init__(self, degree: int = 8, **kwargs):
        super().__init__(**kwargs)
        self.degree = degree

    def compute_action(self, obs, job_to_place=None, env=None,
                       **kwargs) -> int:
        return self.degree if self.degree in _valid_actions(obs) else 0


class AdaptiveDegreePacking(BaselineActor):
    """Fixed-Degree Packing with the degree chosen by the measured
    d*(scale, load) law instead of a constant
    (docs/results_round5/rule_extraction.md; the degree x load x size
    map in docs/results_round5/degree_map.md):

    * estimate per-server offered load online,
      rho = (sum of arrived jobs' sequential JCTs) / elapsed / n_servers
      — worker-seconds of demand per wall-second per server, all
      observable at decision time;
    * pick the target degree by load: heavy (rho >= 1.2) -> 4 (an
      intra-group fraction: more concurrent slots absorb the overload),
      moderate (0.6 <= rho < 1.2) -> ONE communication group, light
      (rho < 0.6) -> two groups (capped at the action-space max).
      Under ``objective="jct"`` the heavy target defaults to 8 instead
      of 4 — the measured JCT-objective map shifts every
      acceptance-heavy cell one tier up while the geometry stays
      objective-independent (an explicit ``heavy_degree`` overrides);
    * degrees must tile the group structure (d <= group_size or
      d % group_size == 0) — the measured constraint behind degree 16's
      collapse on the 6x6x2 topology (16 = 1 1/3 groups of 12) while
      the same degree excels where it tiles exactly (2x8 at 32 servers,
      1x16 at 128). The law made an out-of-sample prediction — d=12
      (one whole group) at 72 servers, moderate load — that measurement
      confirmed as the best known result at that cell (0.996
      per-decision, 449.2 +/- 0.7, vs always-8's 428).

    Declines (action 0) when the chosen degree has no free block, like
    FixedDegreePacking — uniform-degree tiling is what keeps the
    cluster fragmentation-free. One heuristic, zero training, zero
    pricing: best-or-within-noise at every measured (size, load) cell,
    where the RL path needed one fine-tune per size.
    """

    name = "adaptive_degree_packing"

    def __init__(self, heavy_degree: int = None,
                 heavy_threshold: float = 1.2,
                 light_threshold: float = 0.6,
                 objective: str = "acceptance", **kwargs):
        super().__init__(**kwargs)
        # the geometry half of the law is objective-independent; the
        # load half shifts one tier toward larger degrees under the
        # JCT-blocking reward family (measured map:
        # docs/results_round5/degree_map.md "Scope limit") — every
        # acceptance-heavy d=4 cell becomes d=8 because accepted jobs'
        # JCT ratios enter the return directly. An explicit
        # heavy_degree always wins (ablations must stay expressible)
        if objective not in ("acceptance", "jct"):
            raise ValueError(
                f"unknown objective {objective!r}: expected "
                "'acceptance' or 'jct'")
        if heavy_degree is None:
            heavy_degree = 8 if objective == "jct" else 4
        self.objective = objective
        self.heavy_degree = heavy_degree
        self.heavy_threshold = heavy_threshold
        self.light_threshold = light_threshold
        self.reset()

    def reset(self) -> None:
        # state for the legacy per-decision fallback estimate only (used
        # when the cluster carries no arrival-demand counter); the primary
        # path is stateless across decisions
        self._seq_sum = 0.0
        self._last_time = -1.0
        self._last_arrived = 0

    def _rho(self, env, job_to_place) -> float:
        cluster = env.cluster
        now = float(cluster.stopwatch.time())
        arrived = int(cluster.num_jobs_arrived)
        seq_sum = getattr(cluster, "sum_arrived_seq_completion_time", None)
        if seq_sum is None:
            # duck-typed cluster without the arrival counter: fall back to
            # accumulating per decision. This undercounts demand in
            # overload (queue-capacity-blocked arrivals never reach a
            # decision step — ADVICE r5 item 2) and needs heuristic
            # episode-reset detection; the cluster-counter path above has
            # neither problem (the counter is reset with the cluster and
            # counts every arrival, blocked or not).
            if now < self._last_time or arrived < self._last_arrived:
                self._seq_sum = 0.0
            self._last_time = now
            self._last_arrived = arrived
            self._seq_sum += float(job_to_place.seq_completion_time)
            seq_sum = self._seq_sum
        n = cluster.topology.num_workers
        if now <= 0.0 or arrived < 3:
            return float("nan")  # not enough signal yet
        return seq_sum / now / n

    def _static_target(self, target: int, group: int, max_action: int,
                       ramp_shape) -> int:
        """Snap the load-indicated target down to the largest degree that
        is even (or 1), within the action space, group-tiling, and
        geometrically placeable on an EMPTY cluster — static facts only.
        Whether a block is free right now is deliberately not consulted:
        a busy cluster means decline, not a smaller degree, or the
        uniform tiling (the rule's whole advantage) is lost."""
        from ddls_tpu.envs.obs import _block_shape_exists

        d = min(target, max_action)
        d -= d % 2  # odd starts would otherwise never pass the even test
        while d >= 2:
            if ((d <= group or d % group == 0)
                    and _block_shape_exists(d, tuple(ramp_shape))):
                return d
            d -= 2
        return 1

    def compute_action(self, obs, job_to_place=None, env=None,
                       **kwargs) -> int:
        valid = set(int(a) for a in _valid_actions(obs))
        if env is None or job_to_place is None:
            # silently degrading to some fixed degree would mislabel
            # results as "adaptive"; drivers must pass both (EvalLoop
            # does — loops.py:1002)
            raise ValueError(
                "AdaptiveDegreePacking needs env and job_to_place at "
                "decision time (its load estimate reads the cluster "
                "clock and the queued job's sequential JCT)")
        shape = env.cluster.topology.shape
        group = int(shape[1]) * int(shape[2])
        rho = self._rho(env, job_to_place)
        if rho != rho or rho >= self.heavy_threshold:  # nan -> heavy-safe
            target = self.heavy_degree
        elif rho >= self.light_threshold:
            target = group
        else:
            target = 2 * group
        max_action = int(np.asarray(obs["action_set"]).max())
        d = self._static_target(target, group, max_action, shape)
        return d if d in valid else 0


BASELINE_ACTORS = {
    cls.name: cls for cls in (RandomActor, NoParallelism, MinParallelism,
                              MaxParallelism, SiPML, AcceptableJCT,
                              OracleJCT, FixedDegreePacking,
                              AdaptiveDegreePacking)
}


# ---------------------------------------------------------------------------
# Placement-shaping baseline actors (reference:
# ddls/environments/ramp_job_placement_shaping/agents/*.py): choose among
# valid meta-block shape actions; action 0 (don't place) is only taken when
# it is the sole valid action.

class FirstFitShaper(BaselineActor):
    """First valid non-zero shape action."""

    name = "first_fit"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        return int(valid[1] if len(valid) > 1 else valid[0])


class LastFitShaper(BaselineActor):
    """Last valid non-zero shape action."""

    name = "last_fit"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        return int(_valid_actions(obs)[-1])


class RandomShaper(BaselineActor):
    """Uniform-random valid non-zero shape action."""

    name = "random_shaper"

    def compute_action(self, obs, job_to_place=None, **kwargs) -> int:
        valid = _valid_actions(obs)
        if len(valid) > 1:
            return int(np.random.choice(valid[1:]))
        return int(valid[0])


SHAPER_ACTORS = {
    cls.name: cls for cls in (FirstFitShaper, LastFitShaper, RandomShaper)
}
