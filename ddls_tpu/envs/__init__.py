from ddls_tpu.envs.partitioning_env import RampJobPartitioningEnvironment
from ddls_tpu.envs import baselines, rewards, spaces

__all__ = ["RampJobPartitioningEnvironment", "baselines", "rewards", "spaces"]
