from ddls_tpu.envs.partitioning_env import RampJobPartitioningEnvironment
from ddls_tpu.envs.placement_shaping_env import (
    RampJobPlacementShapingEnvironment)
from ddls_tpu.envs.job_placing_env import JobPlacingAllNodesEnvironment
from ddls_tpu.envs import baselines, rewards, spaces

__all__ = ["RampJobPartitioningEnvironment",
           "RampJobPlacementShapingEnvironment",
           "JobPlacingAllNodesEnvironment", "baselines", "rewards",
           "spaces"]
