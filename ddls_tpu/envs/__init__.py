from ddls_tpu.envs.partitioning_env import RampJobPartitioningEnvironment
from ddls_tpu.envs.placement_shaping_env import (
    RampJobPlacementShapingEnvironment)
from ddls_tpu.envs.job_placing_env import JobPlacingAllNodesEnvironment
from ddls_tpu.envs.job_scheduling_env import JobSchedulingEnvironment
from ddls_tpu.envs.interfaces import (DDLSInformationFunction,
                                      DDLSObservationFunction,
                                      DDLSRewardFunction)
from ddls_tpu.envs import baselines, rewards, spaces

__all__ = ["RampJobPartitioningEnvironment",
           "RampJobPlacementShapingEnvironment",
           "JobPlacingAllNodesEnvironment", "JobSchedulingEnvironment",
           "DDLSObservationFunction", "DDLSRewardFunction",
           "DDLSInformationFunction", "baselines", "rewards",
           "spaces"]
