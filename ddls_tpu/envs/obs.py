"""Observation encoding for the PAC-ML job-partitioning MDP.

Encodes the queued job's computation graph + cluster state into fixed-size
padded arrays ready to batch onto TPU (reference:
ddls/environments/ramp_job_partitioning/observations/
ramp_job_partitioning_observation.py:15):

* ``node_features`` [max_nodes, 5]: compute cost (normalised by the job's max
  op cost), is-max-compute flag, memory cost (normalised), is-max-memory
  flag, depth (normalised by max depth);
* ``edge_features`` [max_edges, 2]: dep size (normalised by the job's max dep
  size), is-max-size flag;
* ``graph_features``: 17 normalised job+cluster scalars (counts, sequential
  JCT, SLA, totals, op-cost moments, dep-size moments, mounted-worker and
  running-job fractions) concatenated with the action mask;
* ``edges_src``/``edges_dst`` [max_edges]: integer endpoints (insertion
  order), zero-padded; ``node_split``/``edge_split``: true counts.

``max_edges`` is the fully connected bound ``max_nodes*(max_nodes-1)/2``
(reference: :52). Action-mask validity per the reference (:80-131): action a
(= max partitions per op; 0 = do not place) is valid iff it is 1 or even, at
most max_partitions_per_op, at most the number of free workers, and (a > 1)
some symmetric block shape of a servers exists in the topology.

One deliberate fix vs the reference: its is-max-compute flag compares an op
id against a per-device dict and is constantly False
(ramp_job_partitioning_observation.py:533); here the flag is real.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Dict, List, Optional

import numpy as np

from ddls_tpu.agents.block_search import block_shapes_for, factor_pairs
from ddls_tpu.envs import spaces

NODE_FEATURE_DIM = 5
EDGE_FEATURE_DIM = 2
GRAPH_FEATURE_DIM = 17


def graph_feature_width(n_actions: int,
                        include_candidate_prices: bool = False) -> int:
    """The encoded ``graph_features`` vector width: base graph features +
    the action mask + candidate prices when enabled. Single owner of the
    formula — the observation space below and serving's
    ``build_model_from_config`` (serve/server.py) both derive from it, so
    a layout change here cannot silently desynchronise them."""
    return GRAPH_FEATURE_DIM + n_actions * (
        2 if include_candidate_prices else 1)


@lru_cache(maxsize=None)
def _block_shape_exists(action: int, ramp_shape: tuple) -> bool:
    """Static per-(action, topology) half of the validity test, memoised:
    this runs per action per decision on the hot path (both the mask
    encoder and candidate pricing call it)."""
    return bool(block_shapes_for(factor_pairs(action), ramp_shape))


def action_is_valid(action: int, env) -> bool:
    if action == 0:
        return True
    if action != 1 and action % 2 != 0:
        return False
    if action > env.max_partitions_per_op:
        return False
    free_workers = (env.cluster.topology.num_workers
                    - len(env.cluster.mounted_workers))
    if action > free_workers:
        return False
    if action == 1:
        return True
    # valid iff some symmetric block shape of `action` servers fits the
    # topology; block_shapes_for already filters to fitting shapes
    return _block_shape_exists(action, env.cluster.topology.shape)


class RampJobPartitioningObservation:
    def __init__(self,
                 max_partitions_per_op: int,
                 pad_obs_kwargs: Optional[dict] = None,
                 machine_epsilon: float = 1e-7,
                 include_candidate_prices: bool = False):
        self.max_partitions_per_op = max_partitions_per_op
        self.pad_obs_kwargs = pad_obs_kwargs or {}
        self.machine_epsilon = machine_epsilon
        # opt-in decision-time candidate-price features: one entry per
        # action, min(priced lookahead JCT / max-acceptable JCT, 2)/2 —
        # 0.5 is exactly the SLA boundary, 1.0 = unpriceable/unplaceable.
        # This is the information OracleJCT acts on; exposing it makes
        # the oracle's policy linearly representable from the observation
        # (docs/results_round4/RESULTS.md §3). Requires the env's
        # candidate_pricing to be enabled.
        self.include_candidate_prices = include_candidate_prices
        self.max_nodes = int(self.pad_obs_kwargs.get("max_nodes", 0))
        # the reference pads edges to the fully-connected worst-case bound
        # (jobs_generator.py:320-324); that is hugely wasteful on TPU (the
        # real graphs are sparse DAGs), so a tighter cap can be configured
        self.max_edges = int(self.pad_obs_kwargs.get(
            "max_edges", (self.max_nodes * (self.max_nodes - 1)) // 2))
        self.observation_space: Optional[spaces.Dict] = None

    def reset(self, env) -> None:
        n_actions = self.max_partitions_per_op + 1
        if self.max_nodes:
            max_n, max_e = self.max_nodes, self.max_edges
        else:
            # unpadded mode: shapes follow the queued job's true size
            job = list(env.cluster.job_queue.jobs.values())[0]
            max_n, max_e = job.graph.n_ops, job.graph.n_deps
        self.observation_space = spaces.Dict({
            "action_set": spaces.Box(0, self.max_partitions_per_op,
                                     (n_actions,), np.int32),
            "action_mask": spaces.Box(0, 1, (n_actions,), np.int32),
            "node_features": spaces.Box(
                0.0, 1.0, (max_n, NODE_FEATURE_DIM), np.float32),
            "edge_features": spaces.Box(
                0.0, 1.0, (max_e, EDGE_FEATURE_DIM), np.float32),
            "graph_features": spaces.Box(
                0.0, 1.0,
                (graph_feature_width(n_actions,
                                     self.include_candidate_prices),),
                np.float32),
            "edges_src": spaces.Box(0, max_n - 1, (max_e,), np.int32),
            "edges_dst": spaces.Box(0, max_n - 1, (max_e,), np.int32),
            "node_split": spaces.Box(0, max_n, (1,), np.int32),
            "edge_split": spaces.Box(0, max_e, (1,), np.int32),
        })

    # ------------------------------------------------------------------ encode
    def extract(self, env, done: bool) -> Dict[str, np.ndarray]:
        job = list(env.cluster.job_queue.jobs.values())[0]
        return self.encode(job, env)

    def get_action_set_and_mask(self, env):
        action_set = np.arange(self.max_partitions_per_op + 1, dtype=np.int32)
        mask = np.array([action_is_valid(a, env) for a in action_set],
                        dtype=np.int32)
        return action_set, mask

    def encode(self, job, env) -> Dict[str, np.ndarray]:
        graph = job.graph
        n, m = graph.n_ops, graph.n_deps
        if self.max_nodes and n > self.max_nodes:
            raise ValueError(
                f"job has {n} ops but pad_obs max_nodes={self.max_nodes}; "
                "increase max_nodes or use smaller graphs")
        if self.max_nodes and m > self.max_edges:
            raise ValueError(
                f"job has {m} deps but max_edges={self.max_edges}")

        arrays = graph.finalize()
        node_feats = self._node_features(job, arrays)
        edge_feats = self._edge_features(job, arrays)
        graph_feats = self._graph_features(job, env)
        action_set, action_mask = self.get_action_set_and_mask(env)
        graph_feats = np.concatenate(
            [graph_feats, action_mask.astype(np.float32)])
        if self.include_candidate_prices:
            graph_feats = np.concatenate(
                [graph_feats, self._price_features(job, env)])

        srcs = arrays["edge_src"].astype(np.int32)
        dsts = arrays["edge_dst"].astype(np.int32)

        max_n = self.max_nodes or n
        max_e = self.max_edges or m
        obs = {
            "action_set": action_set,
            "action_mask": action_mask,
            "node_features": _pad2(node_feats, max_n),
            "edge_features": _pad2(edge_feats, max_e),
            "graph_features": graph_feats.astype(np.float32),
            "edges_src": _pad1(srcs, max_e),
            "edges_dst": _pad1(dsts, max_e),
            "node_split": np.array([n], dtype=np.int32),
            "edge_split": np.array([m], dtype=np.int32),
        }
        for key, val in obs.items():
            if not np.all(np.isfinite(val)):
                raise ValueError(f"observation field {key} contains NaN/inf")
        return obs

    def _price_features(self, job, env) -> np.ndarray:
        """Per-action priced-JCT/SLA ratios (candidate_pricing must be on;
        see __init__). Encoded so 0.5 is the acceptance boundary."""
        if not getattr(env, "candidate_pricing", None):
            raise ValueError(
                "include_candidate_prices needs the env's "
                "candidate_pricing enabled")
        prices = getattr(env, "candidate_prices", None) or {}
        limit = max(job.max_acceptable_jct, 1e-30)
        feats = np.ones(self.max_partitions_per_op + 1, np.float32)
        for a, priced in prices.items():
            if priced is not None:
                feats[a] = min(priced[0] / limit, 2.0) / 2.0
        return feats

    def _node_features(self, job, arrays) -> np.ndarray:
        compute, memory, depth = (arrays["compute"], arrays["memory"],
                                  arrays["depth"])
        max_c = max(job.immutable["max_compute_cost"], 1e-30)
        max_m = max(job.immutable["max_memory_cost"], 1e-30)
        max_d = max(job.immutable["max_depth"], 1)
        feats = np.stack([
            compute / max_c,
            (compute == job.immutable["max_compute_cost"]).astype(np.float64),
            memory / max_m,
            (memory == job.immutable["max_memory_cost"]).astype(np.float64),
            depth / max_d,
        ], axis=1)
        return np.clip(feats, 0.0, 1.0)

    def _edge_features(self, job, arrays) -> np.ndarray:
        sizes = arrays["edge_size"]
        max_s = max(job.immutable["max_dep_size"], 1e-30)
        feats = np.stack([
            sizes / max_s,
            (sizes == job.immutable["max_dep_size"]).astype(np.float64),
        ], axis=1)
        return np.clip(feats, 0.0, 1.0)

    def _graph_features(self, job, env) -> np.ndarray:
        params = env.cluster.jobs_generator.jobs_params
        arrays = job.graph.finalize()

        def norm(val, key) -> float:
            lo, hi = params[f"min_{key}"], params[f"max_{key}"]
            if hi - lo == 0:
                return 1.0
            return float((val - lo) / (hi - lo))

        max_c = max(job.immutable["max_compute_cost"], 1e-30)
        max_m = max(job.immutable["max_memory_cost"], 1e-30)
        max_s = max(job.immutable["max_dep_size"], 1e-30)
        compute_norm = arrays["compute"] / max_c
        memory_norm = arrays["memory"] / max_m
        sizes = arrays["edge_size"]

        topo = env.cluster.topology
        feats = [
            norm(job.graph.n_ops, "job_total_num_ops"),
            norm(job.graph.n_deps, "job_total_num_deps"),
            norm(job.seq_completion_time, "job_sequential_completion_times"),
            norm(job.max_acceptable_jct,
                 "max_acceptable_job_completion_times"),
            norm(job.max_acceptable_jct_frac,
                 "max_acceptable_job_completion_time_fracs"),
            job.max_acceptable_jct_frac,
            norm(job.immutable["job_total_op_memory_cost"],
                 "job_total_op_memory_costs"),
            norm(job.immutable["job_total_dep_size"], "job_total_dep_sizes"),
            norm(job.num_training_steps, "job_num_training_steps"),
            float(np.mean(compute_norm)),
            float(np.median(compute_norm)),
            float(np.mean(memory_norm)),
            float(np.median(memory_norm)),
            float(np.mean(sizes) / max_s) if len(sizes) else 0.0,
            float(np.median(sizes) / max_s) if len(sizes) else 0.0,
            len(env.cluster.mounted_workers) / topo.num_workers,
            len(env.cluster.jobs_running) / topo.num_workers,
        ]
        assert len(feats) == GRAPH_FEATURE_DIM
        return np.clip(np.array(feats, dtype=np.float32), 0.0, 1.0)


def _pad2(x: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((n, x.shape[1]), dtype=np.float32)
    out[:len(x)] = x
    return out


def _pad1(x: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((n,), dtype=x.dtype)
    out[:len(x)] = x
    return out


def _pad_into(x: np.ndarray, dst: np.ndarray, rows: int,
              key: str) -> None:
    """Write ``x`` into the first ``len(x)`` rows of ``dst`` and zero the
    rest — the in-place twin of ``_pad2``/``_pad1`` (the destination may
    hold stale bytes from a previous occupant, so the dead region must be
    re-zeroed, exactly the masked-pad policy)."""
    if dst.shape[0] != rows:
        raise ValueError(f"out[{key!r}] has {dst.shape[0]} rows, pad "
                         f"target is {rows}")
    k = len(x)
    dst[:k] = x
    dst[k:] = 0


# fields pad_obs_to re-pads; everything else passes through unchanged
_REPADDED_KEYS = ("node_features", "edge_features", "edges_src",
                  "edges_dst", "node_split", "edge_split")


def pad_obs_to(obs: Dict[str, np.ndarray], max_nodes: int,
               max_edges: int,
               out: Optional[Dict[str, np.ndarray]] = None
               ) -> Dict[str, np.ndarray]:
    """Re-pad an encoded observation to a different (max_nodes, max_edges)
    pad target, keeping exactly the true rows (``node_split``/``edge_split``)
    and zero-filling the rest — the same masked-pad policy ``encode`` uses,
    so the repad changes which rows are dead padding but never a real row.
    The serving bucketer (serve/bucketing.py) uses this to snap incoming
    observations, whatever bound the client padded to, onto its fixed
    bucket shapes.

    ``out`` (encode-into-destination): a dict of caller-owned destination
    arrays — shared-memory slab slices (rl/shm.py), serve arenas
    (serve/bucketing.py) — written in place instead of allocated. Padded
    fields land under the same policy (real rows copied, dead region
    zeroed — bit-for-bit with the allocating path); any other field
    present in ``out`` (graph_features, action_mask, ...) is copied into
    its destination; obs fields absent from ``out`` pass through by
    reference. The returned dict maps each written field to its ``out``
    array."""
    n = int(np.asarray(obs["node_split"]).reshape(-1)[0])
    m = int(np.asarray(obs["edge_split"]).reshape(-1)[0])
    if n > max_nodes:
        raise ValueError(f"obs has {n} ops but pad target "
                         f"max_nodes={max_nodes}")
    if m > max_edges:
        raise ValueError(f"obs has {m} deps but pad target "
                         f"max_edges={max_edges}")
    node = np.asarray(obs["node_features"], dtype=np.float32)[:n]
    edge = np.asarray(obs["edge_features"], dtype=np.float32)[:m]
    if out is None:
        res = dict(obs)
        res["node_features"] = _pad2(node, max_nodes)
        res["edge_features"] = _pad2(edge, max_edges)
        for key in ("edges_src", "edges_dst"):
            res[key] = _pad1(np.asarray(obs[key], dtype=np.int32)[:m],
                             max_edges)
        res["node_split"] = np.array([n], dtype=np.int32)
        res["edge_split"] = np.array([m], dtype=np.int32)
        return res
    res = dict(obs)
    _pad_into(node, out["node_features"], max_nodes, "node_features")
    _pad_into(edge, out["edge_features"], max_edges, "edge_features")
    for key in ("edges_src", "edges_dst"):
        _pad_into(np.asarray(obs[key], dtype=np.int32)[:m], out[key],
                  max_edges, key)
    out["node_split"][...] = n
    out["edge_split"][...] = m
    for key, dst in out.items():
        if key not in _REPADDED_KEYS:
            np.copyto(dst, np.asarray(obs[key]))
    res.update(out)
    return res


def write_obs_into(obs: Dict[str, np.ndarray],
                   out: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Write an encoded observation into caller-owned destination arrays
    (a shared-memory slab slice, a serve arena) under the masked-pad
    policy, inferring the pad target from the destination's own row
    counts — the worker-side write primitive of the zero-copy rollout
    backend (rl/shm.py)."""
    return pad_obs_to(obs, int(out["node_features"].shape[0]),
                      int(out["edge_features"].shape[0]), out=out)


class ObsWriter:
    """Encode-into-destination helper bound to one (max_nodes, max_edges)
    pad target: ``write(obs, out)`` re-pads ``obs`` into the caller's
    arrays, bit-for-bit with the allocating ``pad_obs_to``. The shm env
    worker (rl/rollout.py) builds one per slab attachment so the per-step
    write carries the pad target instead of re-deriving it from the
    destination's shape each call (which is what ``write_obs_into`` does
    for one-off writes)."""

    def __init__(self, max_nodes: int, max_edges: int):
        self.max_nodes = int(max_nodes)
        self.max_edges = int(max_edges)

    def write(self, obs: Dict[str, np.ndarray],
              out: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return pad_obs_to(obs, self.max_nodes, self.max_edges, out=out)
