"""Legacy job-placing MDP on the dynamic Torus cluster.

Counterpart of the reference's ``JobPlacingAllNodesEnvironment``
(ddls/environments/job_placing/job_placing_all_nodes_environment.py:19):
the agent chooses HOW MANY cluster workers to use for the queued job
(Discrete(num_workers), action ``a`` -> ``a + 1`` workers; or a float
fraction in continuous mode); workers are then selected at random and the
job's ops are allocated sequentially (round-robin) or randomly across
them. The cluster is the legacy dynamic-tick simulator, so many jobs share
workers and communication is free.

Rewards (reference: environments/job_placing/rewards/):

* ``worker_compute_utilisation``  -- the step's mean active-worker frac;
* ``mean_job_completion_time``    -- -log(mean JCT completed this step);
* ``total_job_completion_time``   -- -sum of JCTs completed this step.
"""
from __future__ import annotations

import itertools
import math
from typing import Dict, Optional

import numpy as np

from ddls_tpu.envs.spaces import Box, Dict as DictSpace, Discrete
from ddls_tpu.sim.legacy_cluster import ClusterEnvironment


from ddls_tpu.envs.rewards import _log_transform as _transform_with_log


class WorkerComputeUtilisation:
    # per-step fraction: averaging across auto-steps keeps it in [0, 1]
    aggregate = "mean"

    def reset(self, cluster) -> None:
        pass

    def extract(self, cluster, done: bool) -> float:
        return float(cluster.step_stats["mean_worker_compute_utilisation"])


class MeanJobCompletionTime:
    def __init__(self, sign: int = -1, transform_with_log: bool = True):
        self.sign = sign
        self.transform_with_log = transform_with_log

    def reset(self, cluster) -> None:
        pass

    def extract(self, cluster, done: bool) -> float:
        n = int(cluster.step_stats["num_jobs_completed"])
        if n == 0:
            return 0.0
        reward = float(np.mean(cluster.sim_log["job_completion_time"][-n:]))
        if self.transform_with_log:
            reward = _transform_with_log(reward)
        return self.sign * reward


class TotalJobCompletionTime:
    def __init__(self, sign: int = -1):
        self.sign = sign

    def reset(self, cluster) -> None:
        pass

    def extract(self, cluster, done: bool) -> float:
        n = int(cluster.step_stats["num_jobs_completed"])
        if n == 0:
            return 0.0
        return self.sign * float(
            np.sum(cluster.sim_log["job_completion_time"][-n:]))


REWARD_FUNCTIONS = {
    "worker_compute_utilisation": WorkerComputeUtilisation,
    "mean_job_completion_time": MeanJobCompletionTime,
    "total_job_completion_time": TotalJobCompletionTime,
}


class JobPlacingAllNodesObservation:
    """Padded array encoding of the job waiting to be placed plus cluster
    occupancy (reference: observations/
    job_placing_all_nodes_observation.py:13, simplified to the features the
    GNN policy consumes: per-op costs, edge sizes, job+cluster scalars)."""

    def __init__(self, pad_obs_kwargs: Optional[dict] = None):
        self.pad_obs_kwargs = pad_obs_kwargs or {}

    def reset(self, env) -> None:
        self.max_nodes = int(self.pad_obs_kwargs.get("max_nodes", 64))
        self.max_edges = int(self.pad_obs_kwargs.get(
            "max_edges", self.max_nodes * (self.max_nodes - 1)))
        n_actions = env.action_space.n
        self.observation_space = DictSpace({
            "node_features": Box(0.0, np.inf, (self.max_nodes, 2)),
            "edge_features": Box(0.0, np.inf, (self.max_edges, 1)),
            "graph_features": Box(-np.inf, np.inf, (4,)),
            "edges_src": Box(0, self.max_nodes, (self.max_edges,),
                             dtype=np.int32),
            "edges_dst": Box(0, self.max_nodes, (self.max_edges,),
                             dtype=np.int32),
            "node_split": Box(0, self.max_nodes, (1,), dtype=np.int32),
            "edge_split": Box(0, self.max_edges, (1,), dtype=np.int32),
            "action_set": Box(0, n_actions, (n_actions,), dtype=np.int32),
            "action_mask": Box(0, 1, (n_actions,), dtype=np.int32),
        })

    def extract(self, env, done: bool) -> Dict[str, np.ndarray]:
        job = env._job_to_place()
        cluster = env.cluster
        n_actions = env.action_space.n

        nodes = np.zeros((self.max_nodes, 2), np.float32)
        edges = np.zeros((self.max_edges, 1), np.float32)
        src = np.zeros(self.max_edges, np.int32)
        dst = np.zeros(self.max_edges, np.int32)
        n_ops = n_deps = 0
        if job is not None:
            arrays = job.graph.finalize()
            n_ops = min(job.graph.n_ops, self.max_nodes)
            n_deps = min(job.graph.n_deps, self.max_edges)
            compute = arrays["compute"][:n_ops]
            memory = arrays["memory"][:n_ops]
            nodes[:n_ops, 0] = compute / max(compute.max(), 1e-9)
            nodes[:n_ops, 1] = memory / max(memory.max(), 1e-9)
            sizes = arrays["edge_size"][:n_deps]
            edges[:n_deps, 0] = sizes / max(sizes.max(), 1e-9)
            src[:n_deps] = arrays["edge_src"][:n_deps]
            dst[:n_deps] = arrays["edge_dst"][:n_deps]

        free = [w.memory_free / max(w.memory_capacity, 1)
                for w in cluster.topology.workers.values()]
        graph = np.asarray([
            n_ops / self.max_nodes,
            (math.log10(job.immutable["job_sequential_completion_time"] + 1)
             if job is not None else 0.0),
            float(np.mean(free)),
            len(cluster.jobs_running) / max(cluster.topology.num_workers, 1),
        ], np.float32)

        return {
            "node_features": nodes,
            "edge_features": edges,
            "graph_features": graph,
            "edges_src": src,
            "edges_dst": dst,
            "node_split": np.asarray([n_ops], np.int32),
            "edge_split": np.asarray([n_deps], np.int32),
            "action_set": np.arange(n_actions, dtype=np.int32),
            "action_mask": env._action_mask(job),
        }


class JobPlacingAllNodesEnvironment:
    """reset/step protocol env (same shape as the RAMP envs)."""

    def __init__(self,
                 topology_config: dict,
                 node_config: dict,
                 jobs_config: dict,
                 continuous_action_mode: bool = False,
                 worker_selection: str = "random",
                 op_allocation: str = "sequential",
                 job_scheduler: str = "srpt_job_scheduler",
                 pad_obs_kwargs: Optional[dict] = None,
                 observation_function: str = "default",
                 information_function: str = "default",
                 reward_function: str = "mean_job_completion_time",
                 reward_function_kwargs: Optional[dict] = None,
                 max_cluster_simulation_run_time: float = float("inf"),
                 job_queue_capacity: int = 10,
                 name: str = "job_placing_all_nodes",
                 path_to_save: Optional[str] = None,
                 save_cluster_data: bool = False,
                 save_freq: int = 1,
                 use_sqlite_database: bool = False,
                 **kwargs):
        self.jobs_config = jobs_config
        self.continuous_action_mode = continuous_action_mode
        if worker_selection != "random":
            raise ValueError(
                f"unrecognised worker_selection {worker_selection!r}")
        if op_allocation not in ("sequential", "random"):
            raise ValueError(f"unrecognised op_allocation {op_allocation!r}")
        self.op_allocation = op_allocation
        self.max_cluster_simulation_run_time = max_cluster_simulation_run_time
        self.job_queue_capacity = job_queue_capacity

        self.cluster = ClusterEnvironment(
            topology_config=topology_config,
            node_config=node_config,
            path_to_save=path_to_save if save_cluster_data else None,
            save_freq=save_freq,
            use_sqlite_database=use_sqlite_database)

        if continuous_action_mode:
            # fraction of cluster workers to use
            self.action_space = Box(0.0, 1.0, (1,), dtype=np.float32)
            self.action_space.n = self.cluster.topology.num_workers
        else:
            self.action_space = Discrete(self.cluster.topology.num_workers)

        if observation_function != "default":
            raise ValueError(
                f"unrecognised observation_function {observation_function!r}")
        self.observation_function = JobPlacingAllNodesObservation(
            pad_obs_kwargs=pad_obs_kwargs)

        if reward_function not in REWARD_FUNCTIONS:
            raise ValueError(
                f"unrecognised reward_function {reward_function!r}; "
                f"available: {sorted(REWARD_FUNCTIONS)}")
        self.reward_function = REWARD_FUNCTIONS[reward_function](
            **(reward_function_kwargs or {}))

        if job_scheduler == "srpt_job_scheduler":
            from ddls_tpu.agents.managers import SRPTJobScheduler

            self.job_scheduler = SRPTJobScheduler()
        elif job_scheduler == "fifo_job_scheduler":
            from ddls_tpu.agents.managers import FIFOJobScheduler

            self.job_scheduler = FIFOJobScheduler()
        else:
            raise ValueError(f"unrecognised job_scheduler {job_scheduler!r}")

        from ddls_tpu.envs.interfaces import make_information_function

        self.information_function = make_information_function(
            information_function)

    # ------------------------------------------------------------- protocol
    def reset(self, seed: Optional[int] = None):
        self.cluster.reset(self.jobs_config,
                           max_simulation_run_time=(
                               self.max_cluster_simulation_run_time),
                           job_queue_capacity=self.job_queue_capacity,
                           seed=seed)
        self.observation_function.reset(self)
        self.observation_space = self.observation_function.observation_space
        self.reward_function.reset(self.cluster)
        self.information_function.reset(self)
        self.obs = self.observation_function.extract(self, done=False)
        return self.obs

    def _job_to_place(self):
        jobs = list(self.cluster.job_queue.jobs.values())
        return jobs[0] if jobs else None

    def _action_mask(self, job) -> np.ndarray:
        """Action a (-> a+1 workers) is valid if the a+1 highest-free-memory
        workers can hold the whole job (reference: _get_action_mask,
        job_placing_all_nodes_environment.py:260-281)."""
        n = self.action_space.n
        mask = np.zeros(n, np.int32)
        if job is None:
            return mask
        free = sorted((w.memory_free
                       for w in self.cluster.topology.workers.values()),
                      reverse=True)
        total = job.immutable["job_total_op_memory_cost"]
        cum = np.cumsum(free)
        mask[:] = cum >= total
        return mask

    def _num_workers_from_action(self, action) -> int:
        if self.continuous_action_mode:
            frac = float(action)
            if not 0.0 <= frac <= 1.0:
                raise ValueError(
                    f"continuous action must be in [0, 1], got {action}")
            return round(frac * self.cluster.topology.num_workers)
        return int(action) + 1

    def _placement_fits(self, job, op_to_worker: Dict[str, str]) -> bool:
        need: Dict[str, float] = {}
        for op_id, worker_id in op_to_worker.items():
            need[worker_id] = (need.get(worker_id, 0.0)
                               + job.graph.memory_cost(op_id))
        return all(self.cluster.topology.workers[w].memory_free >= mem
                   for w, mem in need.items())

    def _op_to_worker(self, job, workers) -> Dict[str, str]:
        if self.op_allocation == "sequential":
            cycle = itertools.cycle(workers)
            return {op: next(cycle) for op in job.graph.op_ids}
        return {op: str(np.random.choice(workers))
                for op in job.graph.op_ids}

    def step(self, action):
        num_workers = self._num_workers_from_action(action)
        control_plane = {"job_placement": {}, "job_schedule": {}}
        job = self._job_to_place()
        if num_workers > 0 and job is not None:
            workers = list(np.random.choice(
                list(self.cluster.topology.workers), size=num_workers,
                replace=False))
            op_to_worker = self._op_to_worker(job, workers)
            if self._placement_fits(job, op_to_worker):
                placement = {job.job_id: op_to_worker}
                control_plane["job_placement"] = placement
                control_plane["job_schedule"] = (
                    self.job_scheduler.get_schedule(
                        new_placements=placement, cluster=self.cluster))
            # else: randomly drawn workers lack memory; job stays queued
            # (the agent acts on it again next step)

        self.cluster.step(control_plane)
        step_rewards = [self.reward_function.extract(
            self.cluster, done=self.cluster.is_done())]

        # auto-step until there is a job to act on (reference :226-232),
        # folding each auto-step's reward in so completions that land
        # between agent decisions are not silently dropped from the signal
        while len(self.cluster.job_queue) == 0 and not self.cluster.is_done():
            self.cluster.step({"job_placement": {}, "job_schedule": {}})
            step_rewards.append(self.reward_function.extract(
                self.cluster, done=self.cluster.is_done()))
        # how step rewards combine is a property of the reward function:
        # "mean" for per-step rates, "sum" (default) for rewards scoring
        # disjoint sets of completions
        if getattr(self.reward_function, "aggregate", "sum") == "mean":
            reward = float(np.mean(step_rewards))
        else:
            reward = float(np.sum(step_rewards))

        done = self.cluster.is_done()
        if not done:
            self.obs = self.observation_function.extract(self, done=done)
        info = self.information_function.extract(self, done=done)
        return self.obs, reward, done, info
