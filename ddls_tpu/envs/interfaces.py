"""Formal environment plug-in interfaces.

Counterparts of the reference's abstract bases
(ddls/environments/ddls_observation_function.py:5,
ddls_reward_function.py:5, and the ``information_function`` hook every env
constructor accepts): observation functions encode cluster state into
padded arrays, reward functions score a step, information functions build
the ``info`` dict returned by ``step``. The concrete observation/reward
classes (envs/obs.py, envs/rewards.py, envs/shaping_obs.py) follow these
protocols; the ABCs exist so user-supplied plug-ins have a documented
contract to implement.
"""
from __future__ import annotations

from typing import Any, Dict


class DDLSObservationFunction:
    """Encodes an environment's state into the model-facing observation."""

    def reset(self, env) -> None:
        """(Re)build padding/normalisation state for a fresh episode; must
        set ``self.observation_space``."""
        raise NotImplementedError

    def extract(self, env, done: bool) -> Dict[str, Any]:
        """Encode the current state as a dict of padded arrays."""
        raise NotImplementedError


class DDLSRewardFunction:
    """Scores one environment step (same protocol as
    :class:`ddls_tpu.envs.rewards.RewardFunction`)."""

    def reset(self, env=None, **kwargs) -> None:
        pass

    def extract(self, env, done: bool) -> float:
        raise NotImplementedError


class DDLSInformationFunction:
    """Builds the ``info`` dict returned by ``env.step``."""

    def reset(self, env) -> None:
        pass

    def extract(self, env, done: bool) -> Dict[str, Any]:
        raise NotImplementedError


class DefaultInformation(DDLSInformationFunction):
    """The reference's default information function is a no-op
    (job_placing_all_nodes_environment.py:117-121); this returns an empty
    info dict."""

    def extract(self, env, done: bool) -> Dict[str, Any]:
        return {}


class EpisodeStatsInformation(DDLSInformationFunction):
    """Surfaces headline cluster counters into ``info`` each step —
    useful for RL-framework callbacks that only see (obs, reward, done,
    info) tuples. Reads the live lifecycle tables both cluster simulators
    maintain (the legacy ClusterEnvironment has no episode_stats dict)."""

    def extract(self, env, done: bool) -> Dict[str, Any]:
        cluster = env.cluster
        return {
            "num_jobs_arrived": int(cluster.num_jobs_arrived),
            "num_jobs_completed": len(cluster.jobs_completed),
            "num_jobs_blocked": len(cluster.jobs_blocked),
        }


INFORMATION_FUNCTIONS = {
    "default": DefaultInformation,
    "episode_stats": EpisodeStatsInformation,
}


def make_information_function(name: str) -> DDLSInformationFunction:
    if name not in INFORMATION_FUNCTIONS:
        raise ValueError(
            f"unrecognised information_function {name!r}; available: "
            f"{sorted(INFORMATION_FUNCTIONS)}")
    return INFORMATION_FUNCTIONS[name]()
