"""The placement-shaping MDP: the agent chooses a job's meta-block shape.

Reference: ddls/environments/ramp_job_placement_shaping/
ramp_job_placement_shaping_environment.py:29. The second PAC-ML MDP framing:
a heuristic op partitioner (SiP-ML by default) decides per-op partition
counts before the agent acts; the agent's Discrete(C*R*S + 1) action selects
the (c, r, s) meta-block shape the placer must fit the job into (0 = do not
place). The rest of the pipeline (first-fit placer constrained to the chosen
shape -> SRPT op scheduler -> first-fit dep placer -> SRPT dep scheduler ->
cluster step -> reward -> auto-step to the next decision point) matches the
partitioning env.
"""
from __future__ import annotations

from typing import Optional

from ddls_tpu.agents.partitioners import (RandomOpPartitioner,
                                          SipMlOpPartitioner)
from ddls_tpu.agents.placers import (FirstFitDepPlacer, RampFirstFitOpPlacer,
                                     RandomOpPlacer)
from ddls_tpu.agents.schedulers import SRPTDepScheduler, SRPTOpScheduler
from ddls_tpu.envs import spaces
from ddls_tpu.envs.rewards import make_reward_function
from ddls_tpu.envs.shaping_obs import (RampJobPlacementShapingObservation,
                                       shape_action_table)
from ddls_tpu.sim.actions import Action, JobPlacementShape, OpPartition
from ddls_tpu.sim.cluster import RampClusterEnvironment

OP_PARTITIONERS = {
    "sip_ml_op_partitioner": SipMlOpPartitioner,
    "random_op_partitioner": RandomOpPartitioner,
}
OP_PLACERS = {
    "ramp_first_fit_op_placer": RampFirstFitOpPlacer,
    "random_op_placer": RandomOpPlacer,
}
OP_SCHEDULERS = {"srpt_op_scheduler": SRPTOpScheduler}
DEP_PLACERS = {"first_fit_dep_placer": FirstFitDepPlacer}
DEP_SCHEDULERS = {"srpt_dep_scheduler": SRPTDepScheduler}


class RampJobPlacementShapingEnvironment:
    def __init__(self,
                 topology_config: dict,
                 node_config: dict,
                 jobs_config: dict,
                 op_partitioner: str = "sip_ml_op_partitioner",
                 op_partitioner_kwargs: Optional[dict] = None,
                 op_placer: str = "ramp_first_fit_op_placer",
                 op_placer_kwargs: Optional[dict] = None,
                 op_scheduler: str = "srpt_op_scheduler",
                 op_scheduler_kwargs: Optional[dict] = None,
                 dep_placer: str = "first_fit_dep_placer",
                 dep_placer_kwargs: Optional[dict] = None,
                 dep_scheduler: str = "srpt_dep_scheduler",
                 dep_scheduler_kwargs: Optional[dict] = None,
                 observation_function: str = (
                     "ramp_job_placement_shaping_observation"),
                 pad_obs_kwargs: Optional[dict] = None,
                 information_function: str = "default",
                 reward_function: str = "lookahead_job_completion_time",
                 reward_function_kwargs: Optional[dict] = None,
                 max_simulation_run_time: Optional[float] = None,
                 job_queue_capacity: int = 10,
                 suppress_warnings: bool = True,
                 name: str = "ramp_job_placement_shaping",
                 path_to_save: Optional[str] = None,
                 save_cluster_data: bool = False,
                 save_freq: int = 1,
                 use_sqlite_database: bool = False,
                 use_jax_lookahead: bool = False,
                 use_native_lookahead: str | bool = "auto",
                 apply_action_mask: bool = True,
                 **kwargs):
        self.topology_config = topology_config
        self.node_config = node_config
        self.jobs_config = jobs_config
        self.max_simulation_run_time = (
            float("inf") if max_simulation_run_time is None
            else float(max_simulation_run_time))
        self.job_queue_capacity = job_queue_capacity
        self.apply_action_mask = apply_action_mask
        self.name = name

        self.cluster = RampClusterEnvironment(
            topology_config=topology_config,
            node_config=node_config,
            path_to_save=path_to_save if save_cluster_data else None,
            save_freq=save_freq,
            use_sqlite_database=use_sqlite_database,
            use_jax_lookahead=use_jax_lookahead,
            use_native_lookahead=use_native_lookahead)

        if observation_function != "ramp_job_placement_shaping_observation":
            raise ValueError(
                f"unrecognised observation_function {observation_function}")
        self.observation_function = RampJobPlacementShapingObservation(
            pad_obs_kwargs=pad_obs_kwargs)

        self.action_to_shape = shape_action_table(self.cluster.topology)
        self.action_set = list(self.action_to_shape)
        self.action_space = spaces.Discrete(len(self.action_set))
        self.observation_space: Optional[spaces.Dict] = None

        self.reward_function = make_reward_function(
            reward_function, reward_function_kwargs)

        from ddls_tpu.envs.interfaces import make_information_function
        self.information_function = make_information_function(
            information_function)

        self.op_partitioner = OP_PARTITIONERS[op_partitioner](
            **(op_partitioner_kwargs or {}))
        self.op_placer = OP_PLACERS[op_placer](**(op_placer_kwargs or {}))
        self.op_scheduler = OP_SCHEDULERS[op_scheduler](
            **(op_scheduler_kwargs or {}))
        self.dep_placer = DEP_PLACERS[dep_placer](**(dep_placer_kwargs or {}))
        self.dep_scheduler = DEP_SCHEDULERS[dep_scheduler](
            **(dep_scheduler_kwargs or {}))

    # ------------------------------------------------------------------- api
    def reset(self, seed: Optional[int] = None, verbose: bool = False):
        self.step_counter = 1
        self.op_partition = None
        self.cluster.reset(jobs_config=self.jobs_config,
                           max_simulation_run_time=self.max_simulation_run_time,
                           job_queue_capacity=self.job_queue_capacity,
                           seed=seed)
        self._update_op_partition()
        self.observation_function.reset(self)
        self.observation_space = self.observation_function.observation_space
        self.reward_function.reset(env=self)
        self.information_function.reset(self)
        self.obs = self._get_observation()
        return self.obs

    def _update_op_partition(self) -> None:
        """Run the heuristic partitioner on the queued job (reference:
        :196-198,294-296); degree cap comes from
        jobs_config.max_partitions_per_op_in_observation."""
        if len(self.cluster.job_queue) == 0:
            self.op_partition = None
            return
        max_parts = self.cluster.jobs_generator\
            .max_partitions_per_op_in_observation
        self.op_partition = self.op_partitioner.get(
            cluster=self.cluster, max_partitions_per_op=max_parts)

    def _is_done(self) -> bool:
        return self.cluster.is_done()

    def _get_observation(self):
        return self.observation_function.extract(env=self,
                                                 done=self._is_done())

    def _step_cluster(self, action: Action) -> None:
        self.cluster.step(action)
        self.cluster_step_stats[self.cluster.step_counter] = (
            self.cluster.step_stats)

    def step(self, action: int, verbose: bool = False):
        self.cluster_step_stats = {}

        action = int(action)
        if action not in self.action_to_shape:
            raise ValueError(
                f"action {action} not in action set {self.action_set}")
        if not self.obs["action_mask"][action]:
            if self.apply_action_mask:
                raise ValueError(
                    f"action {action} is invalid under the current action "
                    f"mask {self.obs['action_mask']}; set "
                    "apply_action_mask=False to silently fall back to 0")
            action = 0

        shape = self.action_to_shape[action]
        if shape is not None and self.op_partition is not None:
            op_partition = self.op_partition
            job_id = next(iter(op_partition.partitioned_jobs))
            job_placement_shape = JobPlacementShape({job_id: shape})
            meta_block_shapes = {job_id: shape}
        else:
            op_partition = OpPartition({}, cluster=self.cluster)
            job_placement_shape = JobPlacementShape({})
            meta_block_shapes = None
        self.op_placement = self.op_placer.get(
            op_partition=op_partition, cluster=self.cluster,
            meta_block_shapes=meta_block_shapes)
        self.op_schedule = self.op_scheduler.get(
            op_partition=op_partition, op_placement=self.op_placement,
            cluster=self.cluster)
        self.dep_placement = self.dep_placer.get(
            op_partition=op_partition, op_placement=self.op_placement,
            cluster=self.cluster)
        self.dep_schedule = self.dep_scheduler.get(
            op_partition=op_partition, dep_placement=self.dep_placement,
            cluster=self.cluster)
        self.action = Action(op_partition=op_partition,
                             op_placement=self.op_placement,
                             op_schedule=self.op_schedule,
                             dep_placement=self.dep_placement,
                             dep_schedule=self.dep_schedule,
                             job_placement_shape=job_placement_shape)

        self.last_job_arrived_job_idx = self.cluster.last_job_arrived_job_idx
        self._step_cluster(self.action)

        self.placed_job_idxs = set(self.action.job_idxs)
        for job_idx in list(self.placed_job_idxs):
            if job_idx in self.cluster.jobs_blocked:
                self.placed_job_idxs.discard(job_idx)
        # stash before auto-stepping: episode finalisation can sweep the
        # placed job out of jobs_running (see partitioning_env.step)
        self.last_placed_job = (
            self.cluster.jobs_running.get(self.last_job_arrived_job_idx)
            if self.last_job_arrived_job_idx in self.placed_job_idxs
            else None)

        # auto-step to the next decision point, then extract the reward
        # (same ordering as the partitioning env)
        while len(self.cluster.job_queue) == 0 and not self.cluster.is_done():
            self._step_cluster(Action())

        self.reward = self.reward_function.extract(env=self,
                                                   done=self._is_done())

        self.done = self._is_done()
        if not self.done:
            self._update_op_partition()
            self.obs = self._get_observation()
        self.info = self.information_function.extract(env=self,
                                                      done=self.done)
        self.step_counter += 1
        return self.obs, self.reward, self.done, self.info
