"""Reward functions for the partitioning MDP
(reference: ddls/environments/ramp_job_partitioning/rewards/)."""
from __future__ import annotations

import math
from typing import Union

import numpy as np


def _log_transform(reward: float) -> float:
    return math.copysign(1, reward) * math.log(1 + abs(reward), 10)


def _find_placed_job(env, cluster, job_idx):
    """The placed partitioned job carrying the lookahead details.

    Normally in jobs_running (or jobs_completed if it finished during the
    auto-steps); when the EPISODE ends during the auto-steps, episode
    finalisation sweeps still-running jobs into jobs_blocked and out of
    every dict (cluster.py:1009-1014), so the env stashes the object as
    ``last_placed_job`` before auto-stepping."""
    job = (cluster.jobs_running.get(job_idx)
           or cluster.jobs_completed.get(job_idx))
    if job is None:
        stashed = getattr(env, "last_placed_job", None)
        if stashed is not None and stashed.details["job_idx"] == job_idx:
            job = stashed
    if job is None:
        raise RuntimeError(
            f"placed job idx {job_idx} is neither running, completed, "
            "nor stashed")
    return job


class RewardFunction:
    def reset(self, env=None, **kwargs) -> None:
        pass

    def extract(self, env, done: bool) -> float:
        raise NotImplementedError


class JobAcceptance(RewardFunction):
    """+success_reward if the arriving job was placed, else fail_reward
    (reference: rewards/job_acceptance.py:9)."""

    def __init__(self, fail_reward: float = -1, success_reward: float = 1,
                 **kwargs):
        self.fail_reward = fail_reward
        self.success_reward = success_reward

    def extract(self, env, done: bool) -> float:
        job_idx = env.last_job_arrived_job_idx
        return (self.success_reward if job_idx in env.placed_job_idxs
                else self.fail_reward)


class LookaheadJobCompletionTime(RewardFunction):
    """(signed/inverted/log/normalised) lookahead JCT; blocked jobs get a
    fail reward (optionally sequential JCT x factor)
    (reference: rewards/lookahead_job_completion_time.py:9)."""

    def __init__(self,
                 fail_reward: Union[int, float, str] = "job_sequential_completion_time",
                 fail_reward_factor: float = 1,
                 sign: int = -1,
                 inverse: bool = False,
                 transform_with_log: bool = False,
                 normaliser: Union[str, None] = None,
                 **kwargs):
        self.fail_reward = fail_reward
        self.fail_reward_factor = fail_reward_factor
        self.sign = sign
        self.inverse = inverse
        self.transform_with_log = transform_with_log
        self.normaliser = normaliser

    def _normalise(self, reward: float, job) -> float:
        if self.normaliser == "job_sequential_completion_time":
            return reward / job.seq_completion_time
        if self.normaliser == "job_sequential_completion_time_times_fail_reward_factor":
            return reward / (job.seq_completion_time * self.fail_reward_factor)
        raise ValueError(f"unrecognised normaliser {self.normaliser}")

    def extract(self, env, done: bool) -> float:
        job_idx = env.last_job_arrived_job_idx
        cluster = env.cluster
        if job_idx in env.placed_job_idxs:
            job = _find_placed_job(env, cluster, job_idx)
            reward = job.details["lookahead_job_completion_time"]
            if self.normaliser is not None and reward != 0:
                reward = self._normalise(reward, job)
        else:
            job = cluster.jobs_blocked[job_idx]
            if isinstance(self.fail_reward, str):
                if self.fail_reward != "job_sequential_completion_time":
                    raise ValueError(
                        f"unrecognised fail_reward {self.fail_reward}")
                reward = job.seq_completion_time * self.fail_reward_factor
            else:
                reward = self.fail_reward * self.fail_reward_factor
            if self.normaliser is not None and reward != 0:
                reward = self._normalise(reward, job)

        if self.inverse and reward != 0:
            reward = 1 / reward
        reward *= self.sign
        if self.transform_with_log:
            reward = _log_transform(reward)
        return reward


class _ThroughputReward(RewardFunction):
    """Mean of a cluster step-stats throughput metric over the cluster steps
    elapsed this env step (reference: rewards/mean_compute_throughput.py:9)."""

    metric = "mean_compute_throughput"

    def __init__(self, sign: int = 1, transform_with_log: bool = False,
                 normalise: bool = False, **kwargs):
        self.sign = sign
        self.transform_with_log = transform_with_log
        self.normalise = normalise
        self._max = None

    def reset(self, env=None, **kwargs) -> None:
        if env is None:
            return
        max_tp = env.cluster.jobs_generator.jobs_params[
            "max_job_max_op_compute_throughputs"]
        self._max = max_tp * env.cluster.topology.num_workers

    def extract(self, env, done: bool) -> float:
        throughputs = [stats[self.metric]
                       for stats in env.cluster_step_stats.values()]
        reward = float(np.mean(throughputs)) if throughputs else 0.0
        if self.normalise and self._max:
            reward = reward / self._max
        if reward != 0:
            reward *= self.sign
            if self.transform_with_log:
                reward = _log_transform(reward)
        return reward


class MeanComputeThroughput(_ThroughputReward):
    metric = "mean_compute_throughput"


class MeanClusterThroughput(_ThroughputReward):
    metric = "mean_cluster_throughput"


class MeanDemandTotalThroughput(_ThroughputReward):
    metric = "mean_demand_total_throughput"


class MultiObjectiveJCTBlocking(RewardFunction):
    """Accepted job: lookahead/sequential JCT ratio; blocked job:
    blocking_weight x (normalised sequential JCT + 1)
    (reference: rewards/multi_objective_jct_blocking.py:9)."""

    def __init__(self, blocking_weight: float = 1, sign: int = -1,
                 inverse: bool = False, transform_with_log: bool = False,
                 **kwargs):
        self.blocking_weight = blocking_weight
        self.sign = sign
        self.inverse = inverse
        self.transform_with_log = transform_with_log

    def extract(self, env, done: bool) -> float:
        job_idx = env.last_job_arrived_job_idx
        cluster = env.cluster
        if job_idx in env.placed_job_idxs:
            job = _find_placed_job(env, cluster, job_idx)
            reward = (job.details["lookahead_job_completion_time"]
                      / job.seq_completion_time)
        else:
            job = cluster.jobs_blocked[job_idx]
            params = cluster.jobs_generator.jobs_params
            lo = params["min_job_sequential_completion_times"]
            hi = params["max_job_sequential_completion_times"]
            norm = ((job.seq_completion_time - lo) / (hi - lo)
                    if hi - lo != 0 else 1.0)
            reward = self.blocking_weight * (norm + 1)

        if self.inverse and reward != 0:
            reward = 1 / reward
        reward *= self.sign
        if self.transform_with_log:
            reward = _log_transform(reward)
        return reward


REWARD_FUNCTIONS = {
    "job_acceptance": JobAcceptance,
    "lookahead_job_completion_time": LookaheadJobCompletionTime,
    "mean_compute_throughput": MeanComputeThroughput,
    "mean_cluster_throughput": MeanClusterThroughput,
    "mean_demand_total_throughput": MeanDemandTotalThroughput,
    "multi_objective_jct_blocking": MultiObjectiveJCTBlocking,
}


def make_reward_function(name: str, kwargs: dict = None) -> RewardFunction:
    if name not in REWARD_FUNCTIONS:
        raise ValueError(
            f"unrecognised reward_function {name!r}; known: "
            f"{sorted(REWARD_FUNCTIONS)}")
    return REWARD_FUNCTIONS[name](**(kwargs or {}))
