"""The PAC-ML job-partitioning environment.

MDP framing (reference: ddls/environments/ramp_job_partitioning/
ramp_job_partitioning_environment.py:42): each decision point is a job at the
head of the queue; the discrete action a in {0..max_partitions_per_op} is the
*maximum partition degree* for that job (0 = do not place). The env converts
the action to per-op partition counts with the SiP-ML quantum formula, runs
the heuristic control plane (first-fit op placer -> SRPT op scheduler ->
first-fit dep placer -> SRPT dep scheduler), steps the cluster, computes the
reward, then auto-steps the cluster with empty actions until another job is
queued (so every agent step sees exactly one job to decide on).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Optional, Union

import numpy as np

from ddls_tpu.agents.partitioners import build_partition_action
from ddls_tpu.agents.placers import (FirstFitDepPlacer, RampFirstFitOpPlacer,
                                     RandomOpPlacer)
from ddls_tpu.agents.schedulers import SRPTDepScheduler, SRPTOpScheduler
from ddls_tpu.envs import spaces
from ddls_tpu.envs.obs import RampJobPartitioningObservation
from ddls_tpu.envs.rewards import make_reward_function
from ddls_tpu.sim.actions import Action, OpPartition
from ddls_tpu.sim.cluster import RampClusterEnvironment
from ddls_tpu.telemetry import flight as _flight

OP_PLACERS = {
    "ramp_first_fit_op_placer": RampFirstFitOpPlacer,
    "random_op_placer": RandomOpPlacer,
}
OP_SCHEDULERS = {"srpt_op_scheduler": SRPTOpScheduler}
DEP_PLACERS = {"first_fit_dep_placer": FirstFitDepPlacer}
DEP_SCHEDULERS = {"srpt_dep_scheduler": SRPTDepScheduler}


class RampJobPartitioningEnvironment:
    def __init__(self,
                 topology_config: dict,
                 node_config: dict,
                 jobs_config: dict,
                 max_partitions_per_op: Optional[int] = None,
                 min_op_run_time_quantum: float = 0.01,
                 op_placer: str = "ramp_first_fit_op_placer",
                 op_placer_kwargs: Optional[dict] = None,
                 op_scheduler: str = "srpt_op_scheduler",
                 op_scheduler_kwargs: Optional[dict] = None,
                 dep_placer: str = "first_fit_dep_placer",
                 dep_placer_kwargs: Optional[dict] = None,
                 dep_scheduler: str = "srpt_dep_scheduler",
                 dep_scheduler_kwargs: Optional[dict] = None,
                 observation_function: str = "ramp_job_partitioning_observation",
                 pad_obs_kwargs: Optional[dict] = None,
                 information_function: str = "default",
                 reward_function: str = "lookahead_job_completion_time",
                 reward_function_kwargs: Optional[dict] = None,
                 max_simulation_run_time: Optional[float] = None,
                 job_queue_capacity: int = 10,
                 suppress_warnings: bool = True,
                 name: str = "ramp_job_partitioning",
                 path_to_save: Optional[str] = None,
                 save_cluster_data: bool = False,
                 save_freq: int = 1,
                 use_sqlite_database: bool = False,
                 use_jax_lookahead: bool = False,
                 use_native_lookahead: str | bool = "auto",
                 apply_action_mask: bool = True,
                 candidate_pricing: Optional[str] = None,
                 obs_include_candidate_prices: bool = False,
                 scenario_runtime=None,
                 **kwargs):
        self.topology_config = topology_config
        self.node_config = node_config
        self.jobs_config = jobs_config
        self.max_simulation_run_time = (
            float("inf") if max_simulation_run_time is None
            else float(max_simulation_run_time))
        self.job_queue_capacity = job_queue_capacity
        self.apply_action_mask = apply_action_mask
        # opt-in all-candidate lookahead pricing at each decision point
        # (None | "native" | "jax" | "auto"): prices every valid partition
        # degree of the queued job, exposes them as env.candidate_prices /
        # info["candidate_prices"], and prefetches the lookahead memo so
        # the chosen action's cluster.step lookahead is a cache hit. The
        # jax backend batches all candidates into ONE vmapped dispatch
        # (f32 — results carry f32 rounding into the memo cache, same
        # trade as use_jax_lookahead); "auto" is the bit-exact C++ engine
        # wherever it exists — measured 50x faster than the tunnelled-TPU
        # jax path (docs/perf_round4.md) — with jax as the toolchain-less
        # fallback.
        self.candidate_pricing = candidate_pricing
        self.candidate_prices: dict = {}
        self.name = name

        self.cluster = RampClusterEnvironment(
            topology_config=topology_config,
            node_config=node_config,
            name=name,
            path_to_save=path_to_save if save_cluster_data else None,
            save_freq=save_freq,
            use_sqlite_database=use_sqlite_database,
            use_jax_lookahead=use_jax_lookahead,
            use_native_lookahead=use_native_lookahead,
            suppress_warnings=suppress_warnings,
            scenario_runtime=scenario_runtime)

        self.max_partitions_per_op = (
            max_partitions_per_op if max_partitions_per_op is not None
            else self.cluster.topology.num_workers)
        self.min_op_run_time_quantum = min_op_run_time_quantum

        if observation_function != "ramp_job_partitioning_observation":
            raise ValueError(
                f"unrecognised observation_function {observation_function!r}")
        if obs_include_candidate_prices and not candidate_pricing:
            raise ValueError(
                "obs_include_candidate_prices requires candidate_pricing")
        self.observation_function = RampJobPartitioningObservation(
            self.max_partitions_per_op, pad_obs_kwargs=pad_obs_kwargs,
            include_candidate_prices=obs_include_candidate_prices)

        self.action_set = list(range(self.max_partitions_per_op + 1))
        self.action_space = spaces.Discrete(len(self.action_set))
        self.observation_space: Optional[spaces.Dict] = None

        self.reward_function = make_reward_function(
            reward_function, reward_function_kwargs)

        from ddls_tpu.envs.interfaces import make_information_function
        self.information_function = make_information_function(
            information_function)

        self.op_placer = OP_PLACERS[op_placer](**(op_placer_kwargs or {}))
        self.op_scheduler = OP_SCHEDULERS[op_scheduler](
            **(op_scheduler_kwargs or {}))
        self.dep_placer = DEP_PLACERS[dep_placer](**(dep_placer_kwargs or {}))
        self.dep_scheduler = DEP_SCHEDULERS[dep_scheduler](
            **(dep_scheduler_kwargs or {}))

    # ------------------------------------------------------------------- api
    def reset(self, seed: Optional[int] = None, verbose: bool = False):
        self.step_counter = 1
        self.cluster.reset(jobs_config=self.jobs_config,
                           max_simulation_run_time=self.max_simulation_run_time,
                           job_queue_capacity=self.job_queue_capacity,
                           seed=seed)
        self.observation_function.reset(self)
        self.observation_space = self.observation_function.observation_space
        self.reward_function.reset(env=self)
        self.information_function.reset(self)
        # prices BEFORE the observation: price features (opt-in) describe
        # the job the observation is about, not the previous decision's
        self._price_candidates()
        self.obs = self._get_observation()
        return self.obs

    def _is_done(self) -> bool:
        return self.cluster.is_done()

    def _get_observation(self):
        return self.observation_function.extract(env=self, done=self._is_done())

    def _step_cluster(self, action: Action) -> None:
        self.cluster.step(action)
        self.cluster_step_stats[self.cluster.step_counter] = (
            self.cluster.step_stats)

    def _partition_action_for(self, job, max_partitions: int):
        """Action int -> per-op partition counts via the SiP-ML quantum
        formula (reference: :331-343)."""
        return build_partition_action(job.graph, self.min_op_run_time_quantum,
                                      max_partitions)

    def _price_candidates(self) -> None:
        self.candidate_prices = {}
        if self.candidate_pricing:
            from ddls_tpu.sim.candidate_pricing import price_candidate_degrees

            self.candidate_prices = price_candidate_degrees(
                self, backend=self.candidate_pricing)

    def price_candidate_degrees(self, degrees=None, backend="auto"):
        """Lookahead prices for candidate partition degrees of the queued
        job (see ddls_tpu.sim.candidate_pricing)."""
        from ddls_tpu.sim.candidate_pricing import price_candidate_degrees

        return price_candidate_degrees(self, degrees=degrees,
                                       backend=backend)

    def step(self, action: int, verbose: bool = False):
        self.cluster_step_stats = {}

        action = int(action)
        if action not in self.action_set:
            raise ValueError(
                f"action {action} not in action set {self.action_set}")
        if not self.obs["action_mask"][action]:
            if self.apply_action_mask:
                raise ValueError(
                    f"action {action} is invalid under the current action "
                    f"mask {self.obs['action_mask']}; set "
                    "apply_action_mask=False to silently fall back to 0")
            action = 0

        # flight-recorder decision context, captured BEFORE the cluster
        # step: the decided job (queue head), decision-time clock, mask
        flight_ctx = None
        if _flight.enabled():
            head_job_id = next(iter(self.cluster.job_queue.jobs))
            flight_ctx = (
                self.cluster.job_id_to_job_idx[head_job_id],
                self.cluster.stopwatch.time(),
                [int(v) for v in np.asarray(self.obs["action_mask"])])

        if action != 0:
            job_id, job = next(iter(self.cluster.job_queue.jobs.items()))
            partition_map = {job_id: self._partition_action_for(job, action)}
            self.op_partition = OpPartition(partition_map,
                                            cluster=self.cluster)
        else:
            self.op_partition = OpPartition({}, cluster=self.cluster)

        self.op_placement = self.op_placer.get(
            op_partition=self.op_partition, cluster=self.cluster)
        self.op_schedule = self.op_scheduler.get(
            op_partition=self.op_partition, op_placement=self.op_placement,
            cluster=self.cluster)
        self.dep_placement = self.dep_placer.get(
            op_partition=self.op_partition, op_placement=self.op_placement,
            cluster=self.cluster)
        self.dep_schedule = self.dep_scheduler.get(
            op_partition=self.op_partition, dep_placement=self.dep_placement,
            cluster=self.cluster)
        self.action = Action(op_partition=self.op_partition,
                             op_placement=self.op_placement,
                             op_schedule=self.op_schedule,
                             dep_placement=self.dep_placement,
                             dep_schedule=self.dep_schedule)

        self.last_job_arrived_job_idx = self.cluster.last_job_arrived_job_idx
        self._step_cluster(self.action)

        # jobs the action handled that also survived SLA lookahead
        self.placed_job_idxs = set(self.action.job_idxs)
        for job_idx in list(self.placed_job_idxs):
            if job_idx in self.cluster.jobs_blocked:
                self.placed_job_idxs.discard(job_idx)
        # stash the placed partitioned job BEFORE auto-stepping: if the
        # episode ends during the auto-steps, episode finalisation sweeps
        # still-running jobs into jobs_blocked (cluster.py:1009-1014) and
        # JCT rewards could no longer find the placed job's lookahead
        # details in any lifecycle dict
        self.last_placed_job = (
            self.cluster.jobs_running.get(self.last_job_arrived_job_idx)
            if self.last_job_arrived_job_idx in self.placed_job_idxs
            else None)

        # one decision-level flight event: the exact tuple the jitted
        # episode kernels trace per decision, so scripts/trace_diff.py
        # can diff host decisions against make_episode_fn's replay
        # trace. `accepted` is acceptance AT DECISION TIME (the kernels'
        # semantics): a job placed by this action and then swept by
        # episode finalisation inside the same cluster step
        # (simulation_ended) counts as accepted here — the sweep is its
        # own job_blocked event in the same trace.
        if flight_ctx is not None and _flight.enabled():
            ji, t_dec, mask = flight_ctx
            cluster = self.cluster
            pj = (cluster.jobs_running.get(ji)
                  or cluster.jobs_completed.get(ji))
            if pj is not None:
                accepted, cause = True, None
                jct = float(pj.details["lookahead_job_completion_time"])
            else:
                # blocked-cause ledger rides in jobs_blocked insertion
                # order (register_blocked_job dedups, so positions align)
                cause = cluster.episode_stats[
                    "jobs_blocked_cause_of_unsuccessful_handling"][
                    list(cluster.jobs_blocked).index(ji)]
                accepted, jct = False, 0.0
                if (cause == "simulation_ended"
                        and ji in self.action.job_idxs):
                    # placed, then swept at simulation end: accepted at
                    # decision time; its jct comes from the cluster's
                    # adjusted-jct ledger (the SCENARIO-adjusted value —
                    # the lookahead event carries the nominal one; the
                    # partitioned job itself was already unmounted)
                    accepted, cause = True, None
                    jct = float(cluster.job_adjusted_jct[ji])
            _flight.emit("action_decided", t=t_dec, job_idx=ji,
                         degree=action, mask=mask, accepted=accepted,
                         cause=cause, jct=jct)

        # auto-step until another job queues or the episode ends, THEN
        # extract the reward so throughput rewards see the cluster steps in
        # which the placed job actually ran. (Deliberate fix vs the
        # reference, which resets cluster_step_stats at the start of step()
        # and extracts before auto-stepping — :311,391 — so its throughput
        # rewards only ever see the single placement step. Acceptance/JCT
        # rewards are unaffected: they read lookahead values fixed at
        # placement, and no job can be placed or blocked during auto-steps.)
        while len(self.cluster.job_queue) == 0 and not self.cluster.is_done():
            self._step_cluster(Action())

        self.reward = self.reward_function.extract(env=self,
                                                   done=self._is_done())

        self.done = self._is_done()
        if not self.done:
            self._price_candidates()
            self.obs = self._get_observation()
        else:
            # no next decision: stale prices must not leak into terminal info
            self.candidate_prices = {}
        self.info = self.information_function.extract(env=self,
                                                      done=self.done)
        if self.candidate_prices:
            self.info["candidate_prices"] = self.candidate_prices
        self.step_counter += 1
        return self.obs, self.reward, self.done, self.info
