"""Observation encoder for the placement-shaping MDP.

Reference: ddls/environments/ramp_job_placement_shaping/observations/
ramp_job_placement_shaping_observation.py:77-140. Node/edge/graph features
reuse the partitioning encoder, but the job encoded is the *partitioned*
job (a heuristic partitioner ran before the agent acts), and the action
space/mask covers the C*R*S+1 meta-block shapes: a shape (c, r, s) is valid
iff the job's max partition degree <= c*r*s <= free workers AND a first-fit
meta-block search finds a concrete placement of that shape.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ddls_tpu.agents.block_search import find_meta_block, snapshot_free_servers
from ddls_tpu.envs import spaces
from ddls_tpu.envs.obs import (EDGE_FEATURE_DIM, GRAPH_FEATURE_DIM,
                               NODE_FEATURE_DIM,
                               RampJobPartitioningObservation)


def shape_action_table(topology) -> dict:
    """action int -> (c, r, s) shape; 0 -> None (do not place). Enumeration
    order is part of the MDP (reference:
    ramp_job_placement_shaping_environment.py:134-141)."""
    table = {0: None}
    action = 1
    for c in range(1, topology.num_communication_groups + 1):
        for r in range(1, topology.num_racks_per_communication_group + 1):
            for s in range(1, topology.num_servers_per_rack + 1):
                table[action] = (c, r, s)
                action += 1
    return table


class RampJobPlacementShapingObservation(RampJobPartitioningObservation):
    def __init__(self, pad_obs_kwargs: Optional[dict] = None,
                 machine_epsilon: float = 1e-7):
        # max_partitions_per_op is unused by the shaping action space; the
        # base class only needs it for its own mask, which we override
        super().__init__(max_partitions_per_op=0,
                         pad_obs_kwargs=pad_obs_kwargs,
                         machine_epsilon=machine_epsilon)
        self._n_actions: Optional[int] = None

    def reset(self, env) -> None:
        topo = env.cluster.topology
        self._n_actions = (topo.num_communication_groups
                           * topo.num_racks_per_communication_group
                           * topo.num_servers_per_rack + 1)
        n_actions = self._n_actions
        if self.max_nodes:
            max_n, max_e = self.max_nodes, self.max_edges
        else:
            job = self._job_to_encode(env)
            max_n, max_e = job.graph.n_ops, job.graph.n_deps
        self.observation_space = spaces.Dict({
            "action_set": spaces.Box(0, n_actions - 1, (n_actions,),
                                     np.int32),
            "action_mask": spaces.Box(0, 1, (n_actions,), np.int32),
            "node_features": spaces.Box(
                0.0, 1.0, (max_n, NODE_FEATURE_DIM), np.float32),
            "edge_features": spaces.Box(
                0.0, 1.0, (max_e, EDGE_FEATURE_DIM), np.float32),
            "graph_features": spaces.Box(
                0.0, 1.0, (GRAPH_FEATURE_DIM + n_actions,), np.float32),
            "edges_src": spaces.Box(0, max_n - 1, (max_e,), np.int32),
            "edges_dst": spaces.Box(0, max_n - 1, (max_e,), np.int32),
            "node_split": spaces.Box(0, max_n, (1,), np.int32),
            "edge_split": spaces.Box(0, max_e, (1,), np.int32),
        })

    # --------------------------------------------------------------- encode
    def _job_to_encode(self, env):
        """The partitioned job awaiting a shape decision."""
        if env.op_partition is not None and env.op_partition.partitioned_jobs:
            return next(iter(env.op_partition.partitioned_jobs.values()))
        return list(env.cluster.job_queue.jobs.values())[0]

    def extract(self, env, done: bool):
        return self.encode(self._job_to_encode(env), env)

    def get_action_set_and_mask(self, env):
        topo = env.cluster.topology
        ramp_shape = (topo.num_communication_groups,
                      topo.num_racks_per_communication_group,
                      topo.num_servers_per_rack)
        ramp = snapshot_free_servers(env.cluster)
        free_workers = sum(
            1 for w in topo.workers.values() if not w.mounted_job_idx_to_ops)

        action_set = np.arange(self._n_actions, dtype=np.int32)
        mask = np.zeros(self._n_actions, dtype=np.int32)
        mask[0] = 1  # not placing is always valid
        if env.op_partition is None or not env.op_partition.partitioned_jobs:
            mask[:] = 1
            return action_set, mask
        job_id = next(iter(env.op_partition.partitioned_jobs))
        degree = env.op_partition.job_id_to_max_partition_degree[job_id]
        for action, shape in env.action_to_shape.items():
            if shape is None:
                continue
            c, r, s = shape
            if not (degree <= c * r * s <= free_workers):
                continue
            if find_meta_block(ramp, ramp_shape, shape) is not None:
                mask[action] = 1
        return action_set, mask
