"""Minimal observation/action space descriptions.

gym is not a dependency of this framework (the reference subclasses gym.Env;
here environments follow the same reset/step protocol with these lightweight
space descriptors, which carry everything the JAX models need: shapes and
dtypes for building padded device arrays).
"""
from __future__ import annotations

from typing import Dict as TDict

import numpy as np


class Space:
    def sample(self):
        raise NotImplementedError

    def contains(self, x) -> bool:
        raise NotImplementedError


class Discrete(Space):
    def __init__(self, n: int):
        self.n = int(n)

    def sample(self) -> int:
        return int(np.random.randint(self.n))

    def contains(self, x) -> bool:
        return 0 <= int(x) < self.n

    def __repr__(self):
        return f"Discrete({self.n})"


class Box(Space):
    def __init__(self, low, high, shape, dtype=np.float32):
        self.low = low
        self.high = high
        self.shape = tuple(shape)
        self.dtype = dtype

    def sample(self):
        return np.random.uniform(self.low, self.high,
                                 size=self.shape).astype(self.dtype)

    def contains(self, x) -> bool:
        x = np.asarray(x)
        return x.shape == self.shape

    def __repr__(self):
        return f"Box(low={self.low}, high={self.high}, shape={self.shape})"


class Dict(Space):
    def __init__(self, spaces: TDict[str, Space]):
        self.spaces = dict(spaces)

    def sample(self):
        return {k: s.sample() for k, s in self.spaces.items()}

    def contains(self, x) -> bool:
        return all(k in x for k in self.spaces)

    def items(self):
        return self.spaces.items()

    def __getitem__(self, key):
        return self.spaces[key]

    def __repr__(self):
        return f"Dict({self.spaces})"
