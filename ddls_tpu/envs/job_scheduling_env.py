"""Job-scheduling MDP placeholder.

The reference ships this as an empty 19-line stub
(ddls/environments/job_scheduling/job_scheduling_environment.py:1) — the
experiment was never built. Kept for component parity; scheduling decisions
in the working paths are made by the SRPT op/dep schedulers (RAMP) and the
manager-style job schedulers (legacy).
"""
from __future__ import annotations


class JobSchedulingEnvironment:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "JobSchedulingEnvironment is unimplemented in the reference "
            "too (a 19-line stub); use RampJobPartitioningEnvironment or "
            "JobPlacingAllNodesEnvironment")
