"""Cluster network topologies.

* :class:`RampTopology` -- the RAMP all-optical architecture (arXiv
  2211.15226): servers addressed by (communication group ``c``, rack ``r``,
  server ``s``), a fully connected server graph with per-direction wavelength
  channels of bandwidth ``total_node_bandwidth / C``
  (reference: ddls/topologies/ramp.py:11-67).
* :class:`TorusTopology` -- wrap-around 2D/3D torus; in the TPU-native build
  this doubles as the model of a TPU pod slice's ICI mesh
  (reference: ddls/topologies/torus.py:10; SURVEY.md §2.2 TPU mapping note).

No networkx: servers/links/channels live in plain dict tables keyed by server
id strings (``"c-r-s"`` for RAMP), with precomputed shortest-path lists (for
the full RAMP mesh every pair is one hop).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from ddls_tpu.hardware.devices import (DEVICE_TYPES, Channel, Processor,
                                       channel_id)
from ddls_tpu.utils import get_class_from_path


class BaseTopology:
    """Server/channel tables shared by all topologies."""

    def __init__(self) -> None:
        self.server_ids: List[str] = []
        self.links: List[Tuple[str, str]] = []  # undirected node pairs
        self.channel_id_to_channel: Dict[str, Channel] = {}
        # populated by populate_workers:
        self.workers: Dict[str, Processor] = {}          # worker_id -> worker
        self.worker_to_server: Dict[str, str] = {}
        self.server_to_workers: Dict[str, List[str]] = {}
        self.worker_types: set = set()
        # shortest paths: src -> dst -> list of node paths
        self.shortest_paths: Dict[str, Dict[str, List[List[str]]]] = {}

    @property
    def num_workers(self) -> int:
        return len(self.workers)

    @property
    def num_servers(self) -> int:
        return len(self.server_ids)

    def dense_tables(self):
        """Dense-integer runtime tables for the array-native step pipeline.

        Built lazily, once: server ids -> 0..S-1, channel ids -> 0..K-1
        (topology insertion order), and — when every server pair is
        directly connected with exactly one channel per direction (the
        canonical RAMP shape) — a [S, S] matrix mapping a directed server
        pair to its dense channel index. ``pair_channel`` is None for
        multi-channel or non-complete topologies; callers fall back to the
        dict/path pipeline there.
        """
        tables = getattr(self, "_dense_tables", None)
        if tables is not None:
            return tables
        import numpy as np

        server_index = {sid: i for i, sid in enumerate(self.server_ids)}
        channel_ids = list(self.channel_id_to_channel)
        channel_index = {cid: i for i, cid in enumerate(channel_ids)}
        S = len(self.server_ids)
        pair_channel = None
        if (getattr(self, "num_channels", 0) == 1
                and len(channel_ids) == S * (S - 1)):
            pair_channel = np.full((S, S), -1, np.int32)
            complete = True
            for cid, ch in self.channel_id_to_channel.items():
                u = server_index.get(ch.src)
                v = server_index.get(ch.dst)
                if u is None or v is None:
                    complete = False
                    break
                pair_channel[u, v] = channel_index[cid]
            if not complete or (pair_channel < 0).sum() != S:  # diag only
                pair_channel = None
        self._dense_tables = {
            "server_index": server_index,
            "channel_ids": channel_ids,
            "channel_index": channel_index,
            "pair_channel": pair_channel,
        }
        return self._dense_tables

    def _add_bidirectional_channels(self, u: str, v: str, num_channels: int,
                                    bandwidth: float) -> None:
        self.links.append((u, v))
        for n in range(num_channels):
            for src, dst in ((u, v), (v, u)):
                ch = Channel(src, dst, n, channel_bandwidth=bandwidth)
                self.channel_id_to_channel[ch.channel_id] = ch

    def populate_workers(self, node_config: dict,
                         one_worker_per_server: bool = True) -> None:
        """Instantiate one-or-more workers per server from a node_config of
        the reference's shape (env_dev.yaml node_config block). The RAMP
        placer assumes exactly 1 worker per server
        (reference: ramp_cluster_environment.py:180-181), which is enforced
        by default; the legacy Torus cluster passes
        ``one_worker_per_server=False`` (reference run_sim.py mounts 4
        workers per node)."""
        server_iter = iter(self.server_ids)
        for node_type, cfg in node_config.items():
            for _ in range(cfg["num_nodes"]):
                try:
                    server_id = next(server_iter)
                except StopIteration:
                    raise ValueError(
                        "node_config specifies more nodes than the topology "
                        f"has servers ({self.num_servers})")
                self.server_to_workers[server_id] = []
                for worker_cfg in cfg["workers_config"]:
                    if one_worker_per_server and worker_cfg["num_workers"] != 1:
                        raise ValueError(
                            "RAMP supports exactly 1 worker per server "
                            "(reference: ramp_cluster_environment.py:181)")
                    spec = worker_cfg["worker"]
                    if isinstance(spec, str):
                        cls = (DEVICE_TYPES[spec] if spec in DEVICE_TYPES
                               else get_class_from_path(spec))
                    else:
                        cls = spec
                    for k in range(worker_cfg["num_workers"]):
                        worker = cls(
                            processor_id=f"node_{server_id}_worker_{k}")
                        self.workers[worker.processor_id] = worker
                        self.worker_to_server[worker.processor_id] = server_id
                        self.server_to_workers[server_id].append(
                            worker.processor_id)
                        self.worker_types.add(worker.device_type)
        remaining = sum(1 for _ in server_iter)
        if remaining:
            raise ValueError(
                f"node_config populated {self.num_servers - remaining} of "
                f"{self.num_servers} topology servers; counts must match")

    def reset_devices(self) -> None:
        for worker in self.workers.values():
            worker.reset()
        for ch in self.channel_id_to_channel.values():
            ch.reset()


class RampTopology(BaseTopology):
    def __init__(self,
                 num_communication_groups: int = 4,
                 num_racks_per_communication_group: int = 2,
                 num_servers_per_rack: int = 4,
                 num_channels: int = 1,
                 total_node_bandwidth: float = 1.6e12,
                 intra_gpu_propagation_latency: float = 1.25e-6,
                 worker_io_latency: float = 100e-9,
                 **kwargs):
        super().__init__()
        if num_racks_per_communication_group > num_communication_groups:
            raise ValueError(
                f"num_racks_per_communication_group "
                f"({num_racks_per_communication_group}) must be <= "
                f"num_communication_groups ({num_communication_groups})")
        self.num_communication_groups = num_communication_groups
        self.num_racks_per_communication_group = num_racks_per_communication_group
        self.num_servers_per_rack = num_servers_per_rack
        self.num_channels = num_channels
        self.total_node_bandwidth = total_node_bandwidth
        # per-transceiver (a.k.a. per-channel) bandwidth
        self.channel_bandwidth = total_node_bandwidth / num_communication_groups
        self.intra_gpu_propagation_latency = intra_gpu_propagation_latency
        self.worker_io_latency = worker_io_latency

        for c in range(num_communication_groups):
            for r in range(num_racks_per_communication_group):
                for s in range(num_servers_per_rack):
                    self.server_ids.append(f"{c}-{r}-{s}")

        # fully connected server graph, one Channel object per direction
        for u, v in itertools.combinations(self.server_ids, 2):
            self._add_bidirectional_channels(u, v, num_channels,
                                             self.channel_bandwidth)

        # every pair is directly connected -> unique one-hop shortest path
        for u in self.server_ids:
            self.shortest_paths[u] = {
                v: [[u, v]] for v in self.server_ids if v != u}

    @property
    def shape(self) -> Tuple[int, int, int]:
        return (self.num_communication_groups,
                self.num_racks_per_communication_group,
                self.num_servers_per_rack)

    @staticmethod
    def parse_server_id(server_id: str) -> Tuple[int, int, int]:
        c, r, s = server_id.split("-")
        return int(c), int(r), int(s)


class TorusTopology(BaseTopology):
    """x/y(/z) wrap-around torus; the natural model of TPU ICI."""

    def __init__(self,
                 x_dims: int = 4,
                 y_dims: int = 4,
                 z_dims: Optional[int] = None,
                 num_channels: int = 1,
                 channel_bandwidth: float = 1.25e9,
                 **kwargs):
        super().__init__()
        self.x_dims, self.y_dims, self.z_dims = x_dims, y_dims, z_dims
        self.num_channels = num_channels
        self.channel_bandwidth = channel_bandwidth

        dims = [x_dims, y_dims] + ([z_dims] if z_dims else [])
        coords = list(itertools.product(*[range(d) for d in dims]))
        self.server_ids = ["-".join(map(str, c)) for c in coords]
        index = {c: i for i, c in enumerate(coords)}

        seen = set()
        for coord in coords:
            for axis, dim in enumerate(dims):
                if dim < 2:
                    continue
                nbr = list(coord)
                nbr[axis] = (nbr[axis] + 1) % dim
                nbr = tuple(nbr)
                key = tuple(sorted((index[coord], index[nbr])))
                if key in seen:
                    continue
                seen.add(key)
                self._add_bidirectional_channels(
                    self.server_ids[index[coord]], self.server_ids[index[nbr]],
                    num_channels, channel_bandwidth)

        self._compute_shortest_paths(dims, coords, index)

    def _compute_shortest_paths(self, dims, coords, index) -> None:
        """BFS all-pairs shortest paths (torus is small in the legacy path)."""
        adj: Dict[str, List[str]] = {sid: [] for sid in self.server_ids}
        for u, v in self.links:
            adj[u].append(v)
            adj[v].append(u)
        for src in self.server_ids:
            # collect one shortest path per destination via BFS parents
            from collections import deque

            parent = {src: None}
            queue = deque([src])
            while queue:
                node = queue.popleft()
                for nbr in adj[node]:
                    if nbr not in parent:
                        parent[nbr] = node
                        queue.append(nbr)
            self.shortest_paths[src] = {}
            for dst in self.server_ids:
                if dst == src:
                    continue
                path, node = [], dst
                while node is not None:
                    path.append(node)
                    node = parent[node]
                self.shortest_paths[src][dst] = [path[::-1]]


def build_topology(topology_config: dict) -> BaseTopology:
    """(reference: ramp_cluster_environment.py:155-162 _init_topology)"""
    kind = topology_config["type"]
    kwargs = topology_config.get("kwargs", {})
    if kind == "ramp":
        return RampTopology(**kwargs)
    if kind == "torus":
        return TorusTopology(**kwargs)
    raise ValueError(f"unrecognised topology type {kind!r}")
