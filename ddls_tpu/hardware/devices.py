"""Simulated cluster devices: worker processors and link channels.

Counterpart of the reference's ``ddls/devices/`` (A100.py:7, channel.py:7).
Workers track which job's ops are mounted (RAMP rule: at most one job per
worker) plus occupied memory; channels track mounted flow deps per job. Both
also carry the scheduling-priority maps written by the op/dep schedulers.

The device catalogue includes the reference's profiled A100 plus TPU worker
types so topologies can model pod slices; ``device_type`` keys the profiled
compute costs in job graphs.
"""
from __future__ import annotations

from typing import Dict, Optional, Set


class Processor:
    """A worker device mounted in a server node."""

    device_type = "generic"
    memory_capacity = 0

    def __init__(self, processor_id: Optional[str] = None):
        self.processor_id = processor_id if processor_id is not None else str(id(self))
        self.reset()

    def reset(self) -> None:
        self.memory_occupied = 0.0
        self.mounted_job_idx_to_ops: Dict[int, Set[str]] = {}
        self.mounted_job_id: Dict[int, int] = {}
        # job_idx -> {op_id -> priority}: nested so a whole job's
        # priorities drop in O(1) at unmount and bulk-assign at schedule
        self.op_priority: Dict[int, Dict[str, int]] = {}

    def mount(self, job, op_id: str) -> None:
        mem = job.graph.memory_cost(op_id)
        job_idx = job.details["job_idx"]
        if op_id in self.mounted_job_idx_to_ops.get(job_idx, ()):
            raise RuntimeError(
                f"worker {self.processor_id}: op {op_id} of job "
                f"{job.job_id} is already mounted")
        if self.memory_occupied + mem > self.memory_capacity:
            raise MemoryError(
                f"worker {self.processor_id}: op {op_id} of job "
                f"{job.job_id} needs {mem} B but only "
                f"{self.memory_capacity - self.memory_occupied} B free")
        self.mounted_job_idx_to_ops.setdefault(job_idx, set()).add(op_id)
        self.mounted_job_id[job_idx] = job.job_id
        self.memory_occupied += mem

    def mount_ops(self, job, op_ids) -> None:
        """Mount many ops of one job at once: a single memory check over
        the summed costs (equivalent to per-op sequential checks, since
        costs are non-negative) and one set update."""
        job_idx = job.details["job_idx"]
        mem = sum(job.graph.memory_cost(op_id) for op_id in op_ids)
        mounted = self.mounted_job_idx_to_ops.get(job_idx)
        if mounted is not None and not mounted.isdisjoint(op_ids):
            raise RuntimeError(
                f"worker {self.processor_id}: op(s) of job {job.job_id} "
                "already mounted")
        if self.memory_occupied + mem > self.memory_capacity:
            raise MemoryError(
                f"worker {self.processor_id}: ops of job {job.job_id} need "
                f"{mem} B but only "
                f"{self.memory_capacity - self.memory_occupied} B free")
        self.mounted_job_idx_to_ops.setdefault(job_idx, set()).update(op_ids)
        self.mounted_job_id[job_idx] = job.job_id
        self.memory_occupied += mem

    def unmount(self, job, op_id: str) -> None:
        job_idx = job.details["job_idx"]
        if op_id not in self.mounted_job_idx_to_ops.get(job_idx, ()):
            raise RuntimeError(
                f"worker {self.processor_id}: op {op_id} of job "
                f"{job.job_id} is not mounted")
        self.memory_occupied -= job.graph.memory_cost(op_id)
        self.mounted_job_idx_to_ops[job_idx].discard(op_id)
        pri = self.op_priority.get(job_idx)
        if pri is not None:
            pri.pop(op_id, None)
        if not self.mounted_job_idx_to_ops[job_idx]:
            del self.mounted_job_idx_to_ops[job_idx]
            del self.mounted_job_id[job_idx]
            self.op_priority.pop(job_idx, None)

    def unmount_job(self, job) -> None:
        """Drop every op of one job in one pop per structure (bulk
        equivalent of per-op :meth:`unmount`)."""
        job_idx = job.details["job_idx"]
        ops = self.mounted_job_idx_to_ops.pop(job_idx, None)
        if ops:
            memory_cost = job.graph.memory_cost
            self.memory_occupied -= sum(memory_cost(op) for op in ops)
        self.op_priority.pop(job_idx, None)
        self.mounted_job_id.pop(job_idx, None)

    @property
    def memory_free(self) -> float:
        return self.memory_capacity - self.memory_occupied

    def __repr__(self) -> str:
        return f"{self.device_type}({self.processor_id})"


class GPU(Processor):
    """Generic GPU worker with configurable memory (reference's legacy
    ddls/devices/processors/gpus/gpu.py:6; unused by the RAMP path but kept
    for the legacy cluster and custom node configs)."""

    device_type = "GPU"
    memory_capacity = int(32e9)

    def __init__(self, processor_id: Optional[str] = None,
                 memory_capacity: Optional[float] = None):
        if memory_capacity is not None:
            self.memory_capacity = int(memory_capacity)
        super().__init__(processor_id)


class A100(Processor):
    """80 GB HBM GPU worker (reference: ddls/devices/processors/gpus/A100.py)."""

    device_type = "A100"
    memory_capacity = int(80e9)


class TPUv4(Processor):
    """TPU v4 chip: 32 GB HBM."""

    device_type = "TPUv4"
    memory_capacity = int(32e9)


class TPUv5e(Processor):
    """TPU v5e chip: 16 GB HBM."""

    device_type = "TPUv5e"
    memory_capacity = int(16e9)


DEVICE_TYPES = {cls.device_type: cls for cls in (GPU, A100, TPUv4, TPUv5e)}


def channel_id(src: str, dst: str, channel_number: int) -> str:
    """(reference: ddls/utils.py:550 gen_channel_id)"""
    return f"src_{src}_dst_{dst}_channel_{channel_number}"


class Channel:
    """One directed wavelength channel on a link
    (reference: ddls/devices/channels/channel.py:7)."""

    def __init__(self, src: str, dst: str, channel_number: int,
                 channel_bandwidth: float):
        self.src = src
        self.dst = dst
        self.channel_number = channel_number
        self.channel_id = channel_id(src, dst, channel_number)
        self.channel_bandwidth = channel_bandwidth
        self.reset()

    def reset(self) -> None:
        self.mounted_job_idx_to_deps: Dict[int, Set[tuple]] = {}
        self.dep_priority: Dict[int, Dict[tuple, int]] = {}  # job_idx -> {dep -> pri}

    def unmount_job(self, job_idx: int) -> None:
        """Drop every dep of one job (the only unmount granularity the
        cluster needs: deps leave a channel when their job does)."""
        self.mounted_job_idx_to_deps.pop(job_idx, None)
        self.dep_priority.pop(job_idx, None)

    def __repr__(self) -> str:
        return f"Channel({self.channel_id})"
