from ddls_tpu.hardware.devices import A100, TPUv4, TPUv5e, Channel, Processor
from ddls_tpu.hardware.topologies import RampTopology, TorusTopology, build_topology

__all__ = [
    "Processor", "A100", "TPUv4", "TPUv5e", "Channel",
    "RampTopology", "TorusTopology", "build_topology",
]
