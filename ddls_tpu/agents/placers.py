"""Op and dependency placers.

:class:`RampFirstFitOpPlacer` -- the RAMP packing heuristic (reference:
agents/placers/ramp_first_fit_op_placer.py:23 + placers/utils.py:532): walk
the job's forward ops in topological order; for each op try *parent
co-location* (pack sub-ops onto exactly the servers its parent occupies) and
fall back to a *regular* symmetric sub-block search; forward and backward
sub-ops are always placed together on the same server. A failed op fails the
whole job (it is simply absent from the returned placement, which blocks it).

:class:`FirstFitDepPlacer` -- routes every cross-server nonzero dep over the
first (shortest path x channel) combination whose channels carry no other
job; one unroutable flow drops the whole job
(reference: agents/placers/first_fit_dep_placer.py:18).
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ddls_tpu.agents.block_search import (Coord, find_sub_block,
                                          snapshot_free_servers)
from ddls_tpu.graphs.readers import backward_op_id
from ddls_tpu.hardware.devices import channel_id as make_channel_id
from ddls_tpu.sim.partition import partitioned_op_id

# sentinel distinguishing "pair not scanned yet" from "pair has no options"
_PAIR_UNSEEN = object()
# shared marker for non-flow deps (zero size or same server): one tuple
# object serves every such dep
_NONFLOW = (None,)


def _pair_memory(full_graph, op: str, b_op: str) -> float:
    """Combined memory of a forward op and its backward counterpart: both are
    mounted on the same server, so the placer must reserve both (the
    reference reserves only the forward op's memory,
    placers/utils.py:296-312, and can hand the cluster a placement that
    overflows a worker at mount time; accounting for both here keeps
    placements mountable by construction)."""
    mem = full_graph.memory_cost(op)
    if full_graph.has_op(b_op):
        mem += full_graph.memory_cost(b_op)
    return mem


def _try_parent_colocation(ramp, full_graph, op: str, split: int,
                           meta_servers: Set[Coord], parents: List[str],
                           op_to_servers: Dict[str, List[Coord]],
                           n_forward: int,
                           placed: Dict[str, Coord]) -> bool:
    """Pack the op's sub-ops one-per-server onto a parent's exact server set
    (reference: placers/utils.py:258-314). Requires split == number of parent
    servers and per-server free memory for each fwd+bwd sub-op pair."""
    b_op = backward_op_id(op, n_forward)
    per_server = _pair_memory(full_graph, op, b_op) / split
    for parent in parents:
        servers = op_to_servers.get(parent, [])
        if not servers or not set(servers).issubset(meta_servers):
            continue
        if split != len(servers):
            continue
        if any(ramp[s]["mem"] < per_server for s in servers):
            continue
        for i, server in enumerate(servers):
            ramp[server]["mem"] -= per_server
            if split > 1:
                placed[partitioned_op_id(op, i)] = server
                placed[partitioned_op_id(b_op, i)] = server
            else:
                placed[str(int(op))] = server
                placed[str(int(b_op))] = server
            op_to_servers.setdefault(op, []).append(server)
        return True
    return False


def _try_regular_placement(ramp, ramp_shape, full_graph, op: str, split: int,
                           meta_shape: Coord, op_to_servers, n_forward: int,
                           job_idx, placed: Dict[str, Coord]) -> bool:
    """Symmetric sub-block placement, one sub-op per server
    (reference: placers/utils.py:333-383)."""
    b_op = backward_op_id(op, n_forward)
    op_size = _pair_memory(full_graph, op, b_op) / split
    block = find_sub_block(ramp, ramp_shape, meta_shape, num_servers=split,
                           op_size=op_size, job_idx=job_idx)
    if not block:
        return False
    for j, server in enumerate(block):
        ramp[server]["mem"] -= op_size
        if split > 1:
            placed[partitioned_op_id(op, j)] = server
            placed[partitioned_op_id(b_op, j)] = server
        else:
            placed[str(int(op))] = server
            placed[str(int(b_op))] = server
        op_to_servers.setdefault(op, []).append(server)
    return True


def allocate_job(ramp, ramp_shape: Coord, forward_graph, full_graph,
                 split_fwd: Dict[str, int],
                 meta_servers: Set[Coord], meta_shape: Coord,
                 job_idx) -> Optional[Dict[str, Coord]]:
    """Allocate every (sub-)op of one job; returns op_id -> server coord or
    None on failure (reference: placers/utils.py:532 allocate)."""
    n_forward = len(forward_graph.op_ids)
    parents = {op: forward_graph.parents(op) for op in forward_graph.op_ids}
    op_to_servers: Dict[str, List[Coord]] = {}
    placed: Dict[str, Coord] = {}
    for op in forward_graph.topo_order():
        split = split_fwd.get(str(int(op)), 1)
        ok = _try_parent_colocation(ramp, full_graph, op, split,
                                    meta_servers, parents[op], op_to_servers,
                                    n_forward, placed)
        if not ok:
            ok = _try_regular_placement(ramp, ramp_shape, full_graph, op,
                                        split, meta_shape, op_to_servers,
                                        n_forward, job_idx, placed)
        if not ok:
            return None
    return placed


class RampFirstFitOpPlacer:
    def __init__(self, **kwargs):
        pass

    def get(self, op_partition, cluster, meta_block_shapes: Optional[dict] = None,
            verbose: bool = False):
        """``meta_block_shapes`` optionally restricts each job to a chosen
        (c, r, s) meta block (the placement-shaping MDP's action); default is
        the whole cluster (reference: ramp_first_fit_op_placer.py:80-86)."""
        from ddls_tpu.sim.actions import OpPlacement

        topo = cluster.topology
        ramp_shape = topo.shape
        ramp = snapshot_free_servers(cluster)
        placement: Dict[int, Dict[str, str]] = {}

        for job_id in op_partition.action:
            original = op_partition.original_jobs[job_id]
            job_idx = original.details["job_idx"]
            forward_graph = original.graph.forward_view()
            split_fwd = op_partition.job_id_to_split_forward_ops[job_id]

            if meta_block_shapes and job_id in meta_block_shapes:
                from ddls_tpu.agents.block_search import find_meta_block

                meta = find_meta_block(ramp, ramp_shape,
                                       meta_block_shapes[job_id])
                if meta is None:
                    continue
                meta_servers, meta_shape = set(meta[0]), meta[1]
            else:
                meta_servers = {topo.parse_server_id(s)
                                for s in topo.server_ids}
                meta_shape = ramp_shape

            placed = allocate_job(ramp, ramp_shape, forward_graph,
                                  original.graph, split_fwd,
                                  meta_servers, meta_shape, job_idx)
            if placed is None:
                continue
            op_to_worker = {}
            for op_id, coord in placed.items():
                server_id = f"{coord[0]}-{coord[1]}-{coord[2]}"
                # RAMP currently assumes 1 worker per server
                worker_id = topo.server_to_workers[server_id][0]
                op_to_worker[str(op_id)] = worker_id
            placement[job_id] = op_to_worker
            # mark servers as occupied by this job for subsequent jobs in the
            # same step
            for coord in placed.values():
                ramp[coord]["job_idxs"].add(job_idx)

        return OpPlacement(placement, op_partition=op_partition,
                           cluster=cluster)


class RandomOpPlacer:
    """Random valid worker per op, respecting memory and the one-job-per-
    worker rule (reference: agents/placers/random_op_placer.py:13).

    Unlike the first-fit placer this ignores collective symmetry, so jobs it
    places may price collectives pessimistically."""

    def __init__(self, **kwargs):
        pass

    def get(self, op_partition, cluster, meta_block_shapes=None,
            verbose: bool = False):
        # meta_block_shapes is accepted (and ignored) so this placer is
        # drop-in compatible with the shaping env's placer call signature;
        # parameter order mirrors RampFirstFitOpPlacer.get
        from ddls_tpu.sim.actions import OpPlacement

        topo = cluster.topology
        placement: Dict[int, Dict[str, str]] = {}
        free_mem = {wid: w.memory_free for wid, w in topo.workers.items()}
        occupied = {wid: set(w.mounted_job_idx_to_ops)
                    for wid, w in topo.workers.items()}
        for job_id, partitioned in op_partition.partitioned_jobs.items():
            job_idx = partitioned.details["job_idx"]
            op_to_worker: Dict[str, str] = {}
            ok = True
            for op_id in partitioned.graph.op_ids:
                mem = partitioned.graph.memory_cost(op_id)
                candidates = [
                    wid for wid in topo.workers
                    if free_mem[wid] >= mem
                    and (not occupied[wid] or occupied[wid] == {job_idx})]
                if not candidates:
                    ok = False
                    break
                wid = random.choice(candidates)
                op_to_worker[op_id] = wid
                free_mem[wid] -= mem
                occupied[wid].add(job_idx)
            if ok:
                placement[job_id] = op_to_worker
        return OpPlacement(placement, op_partition=op_partition,
                           cluster=cluster)


class FirstFitDepPlacer:
    def __init__(self, **kwargs):
        pass

    def get(self, op_partition, op_placement, cluster, verbose: bool = False):
        from ddls_tpu.sim.actions import DepPlacement

        topo = cluster.topology
        dense = topo.dense_tables()
        if dense["pair_channel"] is not None:
            return self._get_arrays(op_partition, op_placement, cluster,
                                    dense)
        placements = op_placement.action
        result: Dict[int, Dict[Tuple[str, str], tuple]] = {}
        channels_used_by_other_jobs: Set[str] = set()
        worker_to_server = topo.worker_to_server

        for job_id, partitioned in op_partition.partitioned_jobs.items():
            if job_id not in placements:
                continue
            job_idx = partitioned.details["job_idx"]
            placement = placements[job_id]
            arrays = partitioned.graph.finalize()
            op_ids, edge_ids = arrays["op_ids"], arrays["edge_ids"]

            server_of_op = [worker_to_server[placement[op]] for op in op_ids]
            scode, is_flow = partitioned.graph.flow_mask(server_of_op)

            dep_to_channels: Dict[Tuple[str, str], tuple] = {}
            # channel validity for a (src, dst) pair is fixed while this
            # job's deps are being placed, so scan the path x channel space
            # once per pair: first path with any valid channel + that path's
            # valid channel list. Per dep, a uniform pick from the list is
            # distribution-identical to the reference's shuffled first-fit
            # (first_fit_dep_placer.py:118-121) at O(1) instead of
            # O(paths x channels) per flow. The channel-id tuple per
            # (pair, channel) is materialised once and shared by every dep
            # riding it (ids are read-only downstream).
            pair_options: Dict[Tuple[int, int], Optional[tuple]] = {}
            ok = True
            for ei in np.nonzero(~is_flow)[0]:
                dep_to_channels[edge_ids[ei]] = _NONFLOW
            for ei in np.nonzero(is_flow)[0]:
                u, v = edge_ids[ei]
                si, di = scode[arrays["edge_src"][ei]], scode[
                    arrays["edge_dst"][ei]]
                key = (si, di)
                options = pair_options.get(key, _PAIR_UNSEEN)
                if options is _PAIR_UNSEEN:
                    found = self._valid_path_channels(
                        topo, server_of_op[arrays["edge_src"][ei]],
                        server_of_op[arrays["edge_dst"][ei]], job_idx,
                        channels_used_by_other_jobs)
                    if found is None:
                        options = None
                    else:
                        path, valid_channels = found
                        by_ch = {}
                        for ch_num in valid_channels:
                            by_ch[ch_num] = tuple(
                                make_channel_id(path[idx], path[idx + 1],
                                                ch_num)
                                for idx in range(len(path) - 1))
                        options = (valid_channels, by_ch, set())
                    pair_options[key] = options
                if options is None:
                    ok = False
                    break
                valid_channels, by_ch, chosen = options
                # single-channel topologies (the canonical RAMP config) skip
                # the uniform pick — random.choice dominates this loop at
                # ~1.5k placed deps per env step otherwise
                ch_num = (valid_channels[0] if len(valid_channels) == 1
                          else random.choice(valid_channels))
                dep_to_channels[edge_ids[ei]] = by_ch[ch_num]
                chosen.add(ch_num)
            if ok:
                result[job_id] = dep_to_channels
                # commit exactly the channels this job's deps ride (feeds the
                # next job's validity scans within this composite action)
                for options in pair_options.values():
                    if options is not None:
                        _, by_ch, chosen = options
                        for ch_num in chosen:
                            channels_used_by_other_jobs.update(by_ch[ch_num])
        return DepPlacement(result)

    def _get_arrays(self, op_partition, op_placement, cluster, dense):
        """Array fast path (single-channel complete topology): every flow
        dep's channel is the direct (src, dst) link, so placement is one
        vectorised gather + occupancy check per job — same outcome as the
        first-fit scan (there is exactly one path and one channel to try),
        at none of the per-dep dict cost."""
        from ddls_tpu.sim.actions import DepArrays, DepPlacement

        pair_channel = dense["pair_channel"]
        occ = cluster.channel_occ
        placements = op_placement.action
        action: Dict[int, DepArrays] = {}
        # channels claimed by earlier jobs of this same composite action
        taken = None
        for job_id, partitioned in op_partition.partitioned_jobs.items():
            if job_id not in placements:
                continue
            job_idx = partitioned.details["job_idx"]
            sc = op_placement.job_server_codes[job_id]
            arrays = partitioned.graph.finalize()
            is_flow = partitioned.graph.flow_mask_from_codes(sc)
            chan = np.full(arrays["edge_src"].shape[0], -1, np.int32)
            flow_idx = np.nonzero(is_flow)[0]
            chan[flow_idx] = pair_channel[sc[arrays["edge_src"][flow_idx]],
                                          sc[arrays["edge_dst"][flow_idx]]]
            channels = np.unique(chan[flow_idx])
            occ_vals = occ[channels]
            ok = bool(((occ_vals == -1) | (occ_vals == job_idx)).all())
            if ok and taken is not None:
                ok = not bool(taken[channels].any())
            if not ok:
                continue  # a busy channel drops the whole job (reference
                # first_fit_dep_placer.py: one failed flow blocks the job)
            action[job_id] = DepArrays(arrays["edge_ids"], chan, channels)
            if taken is None:
                taken = np.zeros(occ.shape[0], bool)
            taken[channels] = True
        return DepPlacement(action, channel_ids=dense["channel_ids"])

    def _valid_path_channels(self, topo, src_node: str, dst_node: str,
                             job_idx: int,
                             channels_used_by_other_jobs: Set[str]):
        """First path with >=1 valid channel, plus its valid channel nums."""
        for path in topo.shortest_paths[src_node][dst_node]:
            valid = [ch_num for ch_num in range(topo.num_channels)
                     if self._path_channel_valid(
                         topo, path, ch_num, job_idx,
                         channels_used_by_other_jobs)]
            if valid:
                return path, valid
        return None

    def _path_channel_valid(self, topo, path, ch_num: int, job_idx: int,
                            channels_used_by_other_jobs: Set[str]) -> bool:
        for idx in range(len(path) - 1):
            ch_id = make_channel_id(path[idx], path[idx + 1], ch_num)
            channel = topo.channel_id_to_channel[ch_id]
            if job_idx in channel.mounted_job_idx_to_deps:
                continue
            if channel.mounted_job_idx_to_deps:
                return False
            if ch_id in channels_used_by_other_jobs:
                return False
        return True
