"""Heuristic op partitioners.

The SiP-ML rule (reference: agents/partitioners/sip_ml_op_partitioner.py:46):
partition each forward op into

    clamp(ceil(ceil(compute_cost / min_op_run_time_quantum) / 2) * 2,
          1, max_partitions_per_op)

i.e. the smallest even count that brings per-sub-op run time under the
quantum, capped at the allowed maximum; mirrored onto the backward op.
"""
from __future__ import annotations

import math
from typing import Dict

import numpy as np

from ddls_tpu.graphs.op_graph import OpGraph


def sip_ml_num_partitions(compute_cost: float,
                          min_op_run_time_quantum: float,
                          max_partitions_per_op: int) -> int:
    n = math.ceil(math.ceil(compute_cost / min_op_run_time_quantum) / 2) * 2
    return int(max(1, min(n, max_partitions_per_op)))


def build_partition_action(graph: OpGraph,
                           min_op_run_time_quantum: float,
                           max_partitions_per_op: int) -> Dict[str, int]:
    """op -> num_partitions for every fwd+bwd op of one job's graph."""
    action: Dict[str, int] = {}
    for f_op in graph.forward_op_ids():
        n = sip_ml_num_partitions(graph.compute_cost(f_op),
                                  min_op_run_time_quantum,
                                  max_partitions_per_op)
        action[str(int(f_op))] = n
        b_op = graph.counterpart(f_op)
        if b_op is not None:
            action[str(int(b_op))] = n
    return action


class SipMlOpPartitioner:
    def __init__(self, min_op_run_time_quantum: float = 10e-6, **kwargs):
        self.min_op_run_time_quantum = min_op_run_time_quantum

    def get(self, cluster, max_partitions_per_op: int = 2):
        from ddls_tpu.sim.actions import OpPartition

        if max_partitions_per_op < 1 or (
                max_partitions_per_op > 1 and max_partitions_per_op % 2 != 0):
            raise ValueError(
                f"max_partitions_per_op must be 1 or even, got "
                f"{max_partitions_per_op}")
        action = {}
        for job_id, job in cluster.job_queue.jobs.items():
            action[job_id] = build_partition_action(
                job.graph, self.min_op_run_time_quantum, max_partitions_per_op)
        return OpPartition(action, cluster=cluster)


class RandomOpPartitioner:
    """Uniform random even partition count per op
    (reference: agents/partitioners/random_op_partitioner.py:9)."""

    def __init__(self, **kwargs):
        pass

    def get(self, cluster, max_partitions_per_op: int = 2):
        from ddls_tpu.sim.actions import OpPartition

        choices = [1] + [n for n in range(2, max_partitions_per_op + 1, 2)]
        action = {}
        for job_id, job in cluster.job_queue.jobs.items():
            per_op = {}
            for f_op in job.graph.forward_op_ids():
                n = int(np.random.choice(choices))
                per_op[str(int(f_op))] = n
                b_op = job.graph.counterpart(f_op)
                if b_op is not None:
                    per_op[str(int(b_op))] = n
            action[job_id] = per_op
        return OpPartition(action, cluster=cluster)
