"""Symmetric server-block search for RAMP collective placement.

RAMP collectives require symmetric server blocks: a split op's sub-ops must
land on a block of servers whose (c, r, s) shape satisfies the RAMP symmetry
rules. This module provides the first-fit search over candidate block shapes
used by the placer and by action-mask computation
(reference: ddls/environments/ramp_cluster/agents/placers/utils.py:13-530).

Search order is preserved exactly (factor pairs ascending, square shapes
before row/column shapes, diagonal fallback last; origins scanned
c-major/r/s) because "first fit" makes the order part of the semantics.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

Coord = Tuple[int, int, int]


def snapshot_free_servers(cluster) -> Dict[Coord, dict]:
    """Dict snapshot of per-server free memory and occupying jobs
    (reference: placers/utils.py:235 dummy_ramp)."""
    snap: Dict[Coord, dict] = {}
    for server_id in cluster.topology.server_ids:
        coord = cluster.topology.parse_server_id(server_id)
        mem = 0.0
        job_idxs: set = set()
        for worker_id in cluster.topology.server_to_workers.get(server_id, []):
            worker = cluster.topology.workers[worker_id]
            mem += worker.memory_free
            if worker.mounted_job_idx_to_ops:
                job_idxs.update(worker.mounted_job_idx_to_ops.keys())
        snap[coord] = {"mem": mem, "job_idxs": job_idxs}
    return snap


def factor_pairs(n: int) -> List[Tuple[int, int]]:
    """All (n/i, i) integer factor pairs, i ascending
    (reference: placers/utils.py:445)."""
    return [(n // i, i) for i in range(1, n + 1) if n % i == 0]


def block_shapes_for(pairs: Sequence[Tuple[int, int]],
                     meta_shape: Coord) -> List[Coord]:
    """Candidate (C, R, S) block shapes fitting inside ``meta_shape``
    (reference: placers/utils.py:491-530)."""
    shapes: List[Coord] = []
    for a, b in pairs:
        root = math.sqrt(a)
        if (root % 1 == 0 and root <= meta_shape[0]
                and root <= meta_shape[1] and b <= meta_shape[2]):
            shapes.append((int(root), int(root), b))
        if a > meta_shape[0] or a > meta_shape[1] or b > meta_shape[2]:
            continue
        shapes.append((a, 1, b))
        shapes.append((a, b, 1))
    return shapes


def enumerate_block(shape: Coord, ramp_shape: Coord,
                    origin: Coord) -> List[Coord]:
    """Servers covered by a block of ``shape`` at ``origin``. ``shape[2] ==
    -1`` selects the diagonal layout across comm-groups and racks
    (reference: placers/utils.py:464-489)."""
    C, R, S = shape
    i, j, k = origin
    block: List[Coord] = []
    if S == -1:
        for n in range(C):
            block.append(((i + n) % (ramp_shape[0] + 1),
                          (j + n) % (ramp_shape[1] + 1),
                          k % ramp_shape[2]))
    else:
        for c in range(C):
            for r in range(R):
                for s in range(S):
                    block.append(((i + c) % ramp_shape[0],
                                  (j + r) % ramp_shape[1],
                                  (k + s) % ramp_shape[2]))
    return block


def block_ok(ramp: Dict[Coord, dict], block: Sequence[Coord],
             op_size: Optional[float], job_idx) -> bool:
    """Every server in the block must be free of other jobs and have
    ``op_size`` memory available (reference: placers/utils.py:215-233;
    ``op_size=None`` skips the memory check -- the reference's meta-mode call
    passes None, which would TypeError under py3, see SURVEY.md §7.5
    territory)."""
    if not block:
        return False
    for server in block:
        if server not in ramp:
            return False
        occupants = ramp[server]["job_idxs"]
        if occupants and job_idx not in occupants:
            return False
        if op_size is not None and ramp[server]["mem"] < op_size:
            return False
    return True


def first_fit_block(shapes: Sequence[Coord],
                    meta_shape: Coord,
                    ramp_shape: Coord,
                    ramp: Dict[Coord, dict],
                    job_idx,
                    op_size: Optional[float] = None,
                    origin: Coord = (0, 0, 0)) -> Optional[List[Coord]]:
    """First valid block over shapes x origins
    (reference: placers/utils.py:394-443 ff_block)."""
    oc, orr, os_ = origin
    for shape in shapes:
        span = (meta_shape[0] - shape[0] + 1,
                meta_shape[1] - shape[1] + 1,
                meta_shape[2] - shape[2] + 1)
        if span[0] <= 0 or span[1] <= 0 or span[2] <= 0:
            continue
        for i in range(span[0]):
            for j in range(span[1]):
                for k in range(span[2]):
                    block = enumerate_block(
                        shape, ramp_shape, (oc + i, orr + j, os_ + k))
                    if block_ok(ramp, block, op_size, job_idx):
                        return block
    return None


def _ramp_arrays(ramp: Dict[Coord, dict], ramp_shape: Coord, job_idx):
    """C-order mem / blocked views of the snapshot for the C++ kernel.
    A server is blocked when it holds a job other than ``job_idx``
    (block_ok's occupancy rule)."""
    import numpy as np

    rC, rR, rS = ramp_shape
    mem = np.zeros(rC * rR * rS, np.float64)
    blocked = np.ones(rC * rR * rS, np.uint8)  # missing cells invalid
    for (c, r, s), entry in ramp.items():
        if 0 <= c < rC and 0 <= r < rR and 0 <= s < rS:
            idx = (c * rR + r) * rS + s
            mem[idx] = entry["mem"]
            occ = entry["job_idxs"]
            blocked[idx] = 1 if (occ and job_idx not in occ) else 0
    return mem, blocked


def find_sub_block(ramp: Dict[Coord, dict],
                   ramp_shape: Coord,
                   meta_shape: Coord,
                   num_servers: int,
                   op_size: float,
                   job_idx) -> Optional[List[Coord]]:
    """(reference: placers/utils.py:385-392)"""
    shapes = block_shapes_for(factor_pairs(num_servers), meta_shape)
    shapes += [(num_servers, num_servers, -1), (num_servers, 1, 1)]
    from ddls_tpu.native import run_first_fit_block

    found = run_first_fit_block(shapes, meta_shape, ramp_shape,
                                *_ramp_arrays(ramp, ramp_shape, job_idx),
                                op_size=op_size, meta_scan=False)
    if found != "unavailable":
        return found[0] if found else None
    return first_fit_block(shapes, meta_shape, ramp_shape, ramp, job_idx,
                           op_size=op_size)


def find_meta_block(ramp: Dict[Coord, dict],
                    ramp_shape: Coord,
                    meta_shape: Coord):
    """First fully-free block of ``meta_shape``; returns (servers, shape,
    origin) or None (reference: placers/utils.py:117-191)."""
    span = (ramp_shape[0] - meta_shape[0] + 1,
            ramp_shape[1] - meta_shape[1] + 1,
            ramp_shape[2] - meta_shape[2] + 1)
    if span[0] <= 0 or span[1] <= 0 or span[2] <= 0:
        return None
    from ddls_tpu.native import run_first_fit_block

    found = run_first_fit_block([meta_shape], meta_shape, ramp_shape,
                                *_ramp_arrays(ramp, ramp_shape, "__meta__"),
                                op_size=None, meta_scan=True)
    if found != "unavailable":
        if found is None:
            return None
        block, origin = found
        return block, meta_shape, origin
    # meta-mode scans the whole ramp extent (reference: utils.py:176-179)
    for i in range(ramp_shape[0]):
        for j in range(ramp_shape[1]):
            for k in range(ramp_shape[2]):
                block = enumerate_block(meta_shape, ramp_shape, (i, j, k))
                if block_ok(ramp, block, None, job_idx="__meta__"):
                    return block, meta_shape, (i, j, k)
    return None


def meta_block_shape_valid(c: int, r: int, s: int,
                           ramp: Dict[Coord, dict],
                           ramp_shape: Coord,
                           job_max_partition_degree: int,
                           num_available_workers: int) -> bool:
    """Validity of a (c, r, s) meta-block action for a job with the given
    max partition degree (reference: placers/utils.py:13-30)."""
    size = c * r * s
    if not (job_max_partition_degree <= size
            <= min(num_available_workers, job_max_partition_degree)):
        return False
    if size == job_max_partition_degree and c != r:
        # exact-size blocks must pack evenly across racks and comm groups
        return False
    return find_meta_block(ramp, ramp_shape, (c, r, s)) is not None
