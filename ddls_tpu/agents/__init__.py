from ddls_tpu.agents.partitioners import (RandomOpPartitioner,
                                          SipMlOpPartitioner,
                                          sip_ml_num_partitions)
from ddls_tpu.agents.placers import (FirstFitDepPlacer, RampFirstFitOpPlacer,
                                     RandomOpPlacer)
from ddls_tpu.agents.schedulers import SRPTDepScheduler, SRPTOpScheduler
from ddls_tpu.agents.managers import (AllReduceJobCommunicator,
                                      FIFOJobScheduler, JobScheduler,
                                      Placer, RandomJobPlacer,
                                      RandomJobPartitioner, RandomJobScheduler,
                                      SRPTJobPrioritiser,
                                      SRPTJobScheduler)

__all__ = [
    "SipMlOpPartitioner", "RandomOpPartitioner", "sip_ml_num_partitions",
    "RampFirstFitOpPlacer", "RandomOpPlacer", "FirstFitDepPlacer",
    "SRPTOpScheduler", "SRPTDepScheduler",
    "Placer", "JobScheduler", "RandomJobPlacer", "FIFOJobScheduler",
    "SRPTJobScheduler", "RandomJobScheduler", "SRPTJobPrioritiser",
    "RandomJobPartitioner",
    "AllReduceJobCommunicator",
]
