"""SRPT op and dep schedulers.

Shortest-remaining-processing-time priorities: sort the new job's ops per
worker (resp. flow deps globally) by run time *descending* and assign
ascending priority indices, so the shortest item carries the highest priority
number; the lookahead engine picks the max-priority ready item
(reference: agents/schedulers/srpt_op_scheduler.py:14,
srpt_dep_scheduler.py:12).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict

import numpy as np


class SRPTOpScheduler:
    def __init__(self, **kwargs):
        pass

    def get(self, op_partition, op_placement, cluster):
        from ddls_tpu.sim.actions import OpSchedule

        action: Dict[str, Dict[int, Dict[str, int]]] = defaultdict(
            lambda: defaultdict(dict))
        if not op_placement.action:
            return OpSchedule({})
        for worker_id, ops in op_placement.worker_to_ops.items():
            costed = []
            for entry in ops:
                job = op_partition.partitioned_jobs[entry["job_id"]]
                cost = job.graph.compute_cost(entry["op_id"])
                costed.append((entry["job_id"], entry["op_id"], cost))
            costed.sort(key=lambda t: t[2], reverse=True)
            for priority, (job_id, op_id, _) in enumerate(costed):
                action[worker_id][job_id][op_id] = priority
        return OpSchedule({k: dict(v) for k, v in action.items()})


def _srpt_priorities(costs_list):
    """Global SRPT priorities over concatenated per-job cost arrays: one
    stable descending argsort, so every tie class (per-job edge order,
    jobs in action order) resolves identically wherever this is used —
    the single ranking shared by the dict and array scheduler paths."""
    all_costs = (np.concatenate(costs_list) if len(costs_list) > 1
                 else costs_list[0])
    order = np.argsort(-all_costs, kind="stable")
    pri = np.empty(len(order), np.int64)
    pri[order] = np.arange(len(order))
    return pri


class SRPTDepScheduler:
    def __init__(self, **kwargs):
        pass

    def get(self, op_partition, dep_placement, cluster):
        from ddls_tpu.sim.actions import DepArrays, DepSchedule

        if not dep_placement.action:
            return DepSchedule({})
        if any(isinstance(v, DepArrays)
               for v in dep_placement.action.values()):
            return self._get_arrays(op_partition, dep_placement)
        # global SRPT ordering over all newly placed flow deps, priced by the
        # comm model (reference sorts all jobdeps together,
        # srpt_dep_scheduler.py:66-77). Costs come straight from the priced
        # array and the descending sort is one stable argsort. Both paths
        # visit deps in graph edge order (per job, jobs in action order), so
        # every tie class — including a flow priced exactly 0.0 — resolves
        # identically whether or not dep_init_run_time_arr is present.
        jobs, deps_lists, costs_list = [], [], []
        for job_id, dep_to_channels in dep_placement.action.items():
            job = op_partition.partitioned_jobs[job_id]
            arr = getattr(job, "dep_init_run_time_arr", None)
            edge_ids = job.graph.finalize()["edge_ids"]
            # FirstFitDepPlacer keys dep_to_channels with entries drawn
            # from graph.edge_ids (every edge gets a channel tuple or the
            # _NONFLOW marker), so equal length implies the key sets are
            # identical and edge order can stand in for action order
            if arr is not None and len(dep_to_channels) == len(edge_ids):
                deps, costs = edge_ids, arr
            else:
                # iterate in graph edge order so ties (e.g. a flow priced
                # exactly 0.0) land in the same position as the fast path;
                # any placer-added key outside the edge list goes last
                deps = [d for d in edge_ids if d in dep_to_channels]
                if len(deps) != len(dep_to_channels):
                    seen = set(deps)
                    deps += [d for d in dep_to_channels if d not in seen]
                costs = np.array(
                    [job.dep_init_run_time.get(d, 0.0) for d in deps],
                    np.float64)
            jobs.append(job_id)
            deps_lists.append(deps)
            costs_list.append(costs)
        pri = _srpt_priorities(costs_list)

        action: Dict[str, Dict[int, Dict[tuple, int]]] = defaultdict(
            lambda: defaultdict(dict))
        jobdep_to_channels = dep_placement.jobdep_to_channels
        offset = 0
        for job_id, deps in zip(jobs, deps_lists):
            for k, dep_id in enumerate(deps):
                priority = int(pri[offset + k])
                channels = jobdep_to_channels.get((job_id, dep_id), ())
                if not channels:
                    # non-flow dep: keep it under the None channel so the
                    # job still counts as handled by this sub-action (the
                    # reference schedules non-flows onto a None channel key,
                    # srpt_dep_scheduler.py:57-63 + cluster :1404-1415)
                    action[None][job_id][dep_id] = priority
                for ch_id in channels:
                    action[ch_id][job_id][dep_id] = priority
            offset += len(deps)
        return DepSchedule({k: dict(v) for k, v in action.items()})

    def _get_arrays(self, op_partition, dep_placement):
        """Array fast path: the same global stable argsort over the priced
        arrays (per-job edge order, jobs in action order — the identical
        tie classes as the dict path), with priorities written straight
        into each job's DepArrays payload instead of per-channel dicts."""
        from ddls_tpu.sim.actions import DepSchedule

        jobs = list(dep_placement.action)
        costs_list = []
        for job_id in jobs:
            job = op_partition.partitioned_jobs[job_id]
            arr = job.dep_init_run_time_arr
            if arr is None:
                payload = dep_placement.action[job_id]
                arr = np.array([job.dep_init_run_time.get(d, 0.0)
                                for d in payload.edge_ids], np.float64)
            costs_list.append(arr)
        pri = _srpt_priorities(costs_list)
        offset = 0
        schedule_action: dict = {"__arrays__": {}}
        for job_id, costs in zip(jobs, costs_list):
            payload = dep_placement.action[job_id]
            payload.pri = pri[offset:offset + len(costs)]
            schedule_action["__arrays__"][job_id] = payload
            offset += len(costs)
        return DepSchedule(schedule_action)
