"""SRPT op and dep schedulers.

Shortest-remaining-processing-time priorities: sort the new job's ops per
worker (resp. flow deps globally) by run time *descending* and assign
ascending priority indices, so the shortest item carries the highest priority
number; the lookahead engine picks the max-priority ready item
(reference: agents/schedulers/srpt_op_scheduler.py:14,
srpt_dep_scheduler.py:12).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict


class SRPTOpScheduler:
    def __init__(self, **kwargs):
        pass

    def get(self, op_partition, op_placement, cluster):
        from ddls_tpu.sim.actions import OpSchedule

        action: Dict[str, Dict[int, Dict[str, int]]] = defaultdict(
            lambda: defaultdict(dict))
        if not op_placement.action:
            return OpSchedule({})
        for worker_id, ops in op_placement.worker_to_ops.items():
            costed = []
            for entry in ops:
                job = op_partition.partitioned_jobs[entry["job_id"]]
                cost = job.graph.compute_cost(entry["op_id"])
                costed.append((entry["job_id"], entry["op_id"], cost))
            costed.sort(key=lambda t: t[2], reverse=True)
            for priority, (job_id, op_id, _) in enumerate(costed):
                action[worker_id][job_id][op_id] = priority
        return OpSchedule({k: dict(v) for k, v in action.items()})


class SRPTDepScheduler:
    def __init__(self, **kwargs):
        pass

    def get(self, op_partition, dep_placement, cluster):
        from ddls_tpu.sim.actions import DepSchedule

        if not dep_placement.action:
            return DepSchedule({})
        # global SRPT ordering over all newly placed flow deps, priced by the
        # comm model (reference sorts all jobdeps together,
        # srpt_dep_scheduler.py:66-77)
        costed = []
        for job_id, dep_to_channels in dep_placement.action.items():
            job = op_partition.partitioned_jobs[job_id]
            for dep_id in dep_to_channels:
                cost = job.dep_init_run_time.get(dep_id, 0.0)
                costed.append((job_id, dep_id, cost))
        costed.sort(key=lambda t: t[2], reverse=True)

        action: Dict[str, Dict[int, Dict[tuple, int]]] = defaultdict(
            lambda: defaultdict(dict))
        for priority, (job_id, dep_id, _) in enumerate(costed):
            channels = dep_placement.jobdep_to_channels.get(
                (job_id, dep_id), set())
            if not channels:
                # non-flow dep: keep it under the None channel so the job
                # still counts as handled by this sub-action (the reference
                # schedules non-flows onto a None channel key,
                # srpt_dep_scheduler.py:57-63 + cluster :1404-1415)
                action[None][job_id][dep_id] = priority
            for ch_id in channels:
                action[ch_id][job_id][dep_id] = priority
        return DepSchedule({k: dict(v) for k, v in action.items()})
