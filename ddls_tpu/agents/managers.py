"""Legacy manager-style control-plane agents for the dynamic cluster.

Counterpart of the reference's ``ddls/managers/`` package: abstract
Placer / JobScheduler / JobPartitioner / JobPrioritiser / JobCommunicator
interfaces plus the concrete agents the legacy ``scripts/run_sim.py`` demo
drives (RandomJobPlacer, FIFO/SRPT/Random job schedulers; reference:
managers/placers/random_job_placer.py:20,
managers/schedulers/{fifo,srpt,random}_job_scheduler.py).

These operate on the legacy :class:`~ddls_tpu.sim.legacy_cluster.
ClusterEnvironment` action dict shape::

    placement = placer.get_placement(cluster)
    schedule  = scheduler.get_schedule(new_placements=placement, cluster=cluster)
    cluster.step({"job_placement": placement, "job_schedule": schedule})
"""
from __future__ import annotations

import random
from collections import defaultdict
from typing import Dict, Optional

from ddls_tpu.sim.partition import partition_graph


class Placer:
    """(reference: managers/placers/placer.py:3)"""

    def get_placement(self, cluster) -> Dict[int, Dict[str, str]]:
        raise NotImplementedError


class JobScheduler:
    """(reference: managers/schedulers/job_scheduler.py)"""

    def get_schedule(self, new_placements: dict, cluster) -> dict:
        raise NotImplementedError

    @staticmethod
    def _iter_placed_ops(new_placements: dict, cluster):
        """Yield (worker_id, job, op_id) for every op of every placement
        currently relevant: the new placements plus jobs already running."""
        placements = dict(cluster.job_op_placement)
        placements.update(new_placements)
        for job_id, op_to_worker in placements.items():
            job = cluster.job_queue.jobs.get(job_id)
            if job is None:
                job_idx = cluster.job_id_to_job_idx.get(job_id)
                job = cluster.jobs_running.get(job_idx)
            if job is None:
                continue
            for op_id, worker_id in op_to_worker.items():
                yield worker_id, job, op_id


class JobPartitioner:
    """(reference: managers/partitioners/job_partitioner.py)"""

    def get_partitioned_graph(self, graph):
        raise NotImplementedError


class JobPrioritiser:
    """(reference: managers/prioritisers/job_prioritiser.py)"""

    def get_priorities(self, cluster) -> Dict[int, int]:
        raise NotImplementedError


class JobCommunicator:
    """(reference: managers/communicators/job_communicator.py)"""

    def communicate(self, cluster) -> None:
        raise NotImplementedError


class RandomJobPlacer(Placer):
    """Random valid (memory-feasible) worker per op; a job with any
    unplaceable op is left out of the placement entirely
    (reference: managers/placers/random_job_placer.py:20-60)."""

    def get_placement(self, cluster) -> Dict[int, Dict[str, str]]:
        available = {worker_id: worker.memory_free
                     for worker_id, worker in cluster.topology.workers.items()}
        placement: Dict[int, Dict[str, str]] = {}
        for job in cluster.job_queue.jobs.values():
            op_to_worker: Dict[str, str] = {}
            feasible = True
            taken: Dict[str, float] = defaultdict(float)
            for op_id in job.graph.op_ids:
                mem = job.graph.memory_cost(op_id)
                valid = [w for w, free in available.items()
                         if free - taken[w] >= mem]
                if not valid:
                    feasible = False
                    break
                worker_id = random.choice(valid)
                taken[worker_id] += mem
                op_to_worker[op_id] = worker_id
            if feasible:
                for w, used in taken.items():
                    available[w] -= used
                placement[job.job_id] = op_to_worker
        return placement


class FIFOJobScheduler(JobScheduler):
    """Earlier-arrived jobs get higher priority on every worker; ops within
    a job are tie-broken by op id (reference:
    managers/schedulers/fifo_job_scheduler.py)."""

    def get_schedule(self, new_placements: dict, cluster) -> dict:
        worker_rows = defaultdict(list)
        for worker_id, job, op_id in self._iter_placed_ops(new_placements,
                                                           cluster):
            worker_rows[worker_id].append((job, op_id))
        schedule: dict = defaultdict(lambda: defaultdict(dict))
        for worker_id, rows in worker_rows.items():
            rows.sort(key=lambda r: (r[0].details["time_arrived"],
                                     r[0].job_id, str(r[1])))
            for pri, (job, op_id) in enumerate(reversed(rows)):
                schedule[worker_id][job.job_id][op_id] = pri
        return schedule


class SRPTJobScheduler(JobScheduler):
    """Shortest-remaining-processing-time: on each worker the op belonging
    to the job with the least remaining sequential compute gets the highest
    priority (reference: managers/schedulers/srpt_job_scheduler.py:9)."""

    def get_schedule(self, new_placements: dict, cluster) -> dict:
        worker_rows = defaultdict(list)
        for worker_id, job, op_id in self._iter_placed_ops(new_placements,
                                                           cluster):
            remaining_steps = max(
                job.num_training_steps - job.training_step_counter, 1)
            job_remaining = (job.immutable["job_sequential_completion_time"]
                             * remaining_steps / job.num_training_steps)
            worker_rows[worker_id].append((job_remaining, job, op_id))
        schedule: dict = defaultdict(lambda: defaultdict(dict))
        for worker_id, rows in worker_rows.items():
            # longest remaining first -> lowest priority number
            rows.sort(key=lambda r: (-r[0], r[1].job_id, str(r[2])))
            for pri, (_, job, op_id) in enumerate(rows):
                schedule[worker_id][job.job_id][op_id] = pri
        return schedule


class RandomJobScheduler(JobScheduler):
    """(reference: managers/schedulers/random_job_scheduler.py)"""

    def get_schedule(self, new_placements: dict, cluster) -> dict:
        worker_rows = defaultdict(list)
        for worker_id, job, op_id in self._iter_placed_ops(new_placements,
                                                           cluster):
            worker_rows[worker_id].append((job, op_id))
        schedule: dict = defaultdict(lambda: defaultdict(dict))
        for worker_id, rows in worker_rows.items():
            pris = list(range(len(rows)))
            random.shuffle(pris)
            for pri, (job, op_id) in zip(pris, rows):
                schedule[worker_id][job.job_id][op_id] = pri
        return schedule


class RandomJobPartitioner(JobPartitioner):
    """Random even split degree per forward op (reference:
    managers/partitioners/random_job_partitioner.py)."""

    def __init__(self, max_partitions_per_op: int = 2):
        self.max_partitions_per_op = max_partitions_per_op

    def get_partitioned_graph(self, graph):
        action: Dict[str, int] = {}
        for op_id in graph.forward_op_ids():
            degrees = [1] + [n for n in range(2, self.max_partitions_per_op + 1, 2)]
            action[str(int(op_id))] = random.choice(degrees)
        return partition_graph(graph, action)


class SRPTJobPrioritiser(JobPrioritiser):
    """Queued jobs ranked by sequential completion time, shortest first
    (reference: managers/prioritisers/srpt_job_prioritiser.py)."""

    def get_priorities(self, cluster) -> Dict[int, int]:
        jobs = sorted(cluster.job_queue.jobs.values(),
                      key=lambda j: j.immutable[
                          "job_sequential_completion_time"])
        return {job.job_id: pri
                for pri, job in enumerate(reversed(jobs))}


class AllReduceJobCommunicator(JobCommunicator):
    """Parity stub: unimplemented in the reference too
    (managers/communicators/all_reduce_job_communicator.py:4)."""

    def communicate(self, cluster) -> None:
        raise NotImplementedError(
            "AllReduceJobCommunicator is a stub in the reference; the RAMP "
            "path prices collectives analytically instead "
            "(ddls_tpu.sim.comm_model)")
