"""XLA-native graph primitives: masked segment reductions used by the GNN.

The reference delegates message passing to DGL's C++ scatter/gather kernels
(ddls/ml_models/models/mean_pool.py). On TPU the idiomatic equivalent is
``jax.ops.segment_sum`` over padded edge lists — XLA lowers these to fused
scatter-adds that run on-chip, and the fixed shapes make the whole policy
batchable with ``vmap`` (no per-sample graph construction, the reference's
known perf sink, ddls/ml_models/policies/gnn_policy.py:226-253).
"""
from ddls_tpu.ops.segment import (masked_mean, masked_segment_mean,
                                  masked_segment_sum)

__all__ = ["masked_segment_sum", "masked_segment_mean", "masked_mean"]
