"""Masked segment reductions over padded edge lists.

All functions take fixed-shape (padded) arrays plus boolean masks so they are
safe under ``jit``/``vmap``/``pjit`` — padding rows contribute nothing, and
output shapes are static. Padding edges should point at segment 0; the mask
is what removes their contribution, so the index values of padded entries
never matter.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_segment_sum(data: jnp.ndarray,
                       segment_ids: jnp.ndarray,
                       mask: jnp.ndarray,
                       num_segments: int) -> jnp.ndarray:
    """Sum ``data[e]`` into ``out[segment_ids[e]]`` for unmasked edges.

    Args:
      data: [E, F] per-edge values.
      segment_ids: [E] int destination per edge (padding may be 0).
      mask: [E] bool, True for real edges.
      num_segments: static number of output segments (padded node count).

    Returns: [num_segments, F].
    """
    data = jnp.where(mask[:, None], data, 0.0)
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def masked_segment_mean(data: jnp.ndarray,
                        segment_ids: jnp.ndarray,
                        mask: jnp.ndarray,
                        num_segments: int,
                        extra: jnp.ndarray = None) -> jnp.ndarray:
    """Mean of incoming edge values per segment, optionally averaged together
    with one ``extra`` [num_segments, F] value per segment (the GNN's
    self-message: mean over {self} ∪ mailbox).

    Segments with no incoming edges (and no extra) return 0.
    """
    totals = masked_segment_sum(data, segment_ids, mask, num_segments)
    counts = jax.ops.segment_sum(mask.astype(data.dtype), segment_ids,
                                 num_segments=num_segments)
    if extra is not None:
        totals = totals + extra
        counts = counts + 1.0
    return totals / jnp.maximum(counts, 1.0)[:, None]


def masked_mean(data: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Mean over the unmasked rows of ``data`` [N, F]; 0 if all masked."""
    weights = mask.astype(data.dtype)
    total = jnp.sum(data * weights[:, None], axis=0)
    count = jnp.maximum(jnp.sum(weights), 1.0)
    return total / count
