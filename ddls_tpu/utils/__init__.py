from ddls_tpu.utils.profiling import enable_xla_dump, jax_profiler_trace
from ddls_tpu.utils.common import (
    SqliteDict,
    Stopwatch,
    flatten_lists,
    get_class_from_path,
    merge_logs,
    prng_key,
    seed_everything,
    unique_experiment_dir,
    recursive_update,
)

__all__ = [
    "SqliteDict",
    "enable_xla_dump",
    "jax_profiler_trace",
    "Stopwatch",
    "flatten_lists",
    "get_class_from_path",
    "merge_logs",
    "prng_key",
    "seed_everything",
    "unique_experiment_dir",
    "recursive_update",
]
