from ddls_tpu.utils.common import (
    Stopwatch,
    flatten_lists,
    get_class_from_path,
    prng_key,
    seed_everything,
    unique_experiment_dir,
    recursive_update,
)

__all__ = [
    "Stopwatch",
    "flatten_lists",
    "get_class_from_path",
    "prng_key",
    "seed_everything",
    "unique_experiment_dir",
    "recursive_update",
]
