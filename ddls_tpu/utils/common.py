"""Small shared utilities.

TPU-native counterpart of the reference's grab-bag ``ddls/utils.py``
(reference: ddls/utils.py:20-104,485-558). Seeding covers numpy/random and
returns a JAX PRNG key instead of touching torch/CUDA state.
"""
from __future__ import annotations

import glob
import importlib
import pathlib
import pickle
import random
import sqlite3
from typing import Any, Mapping

import numpy as np


class SqliteDict:
    """Minimal persistent dict over stdlib sqlite3 (sqlitedict stand-in used
    by the reference's Logger/cluster save paths)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._conn = sqlite3.connect(self.path)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (key TEXT PRIMARY KEY, val BLOB)")
        self._conn.commit()

    def __setitem__(self, key: str, value: Any) -> None:
        self._conn.execute(
            "REPLACE INTO kv (key, val) VALUES (?, ?)",
            (key, pickle.dumps(value)))

    def __getitem__(self, key: str) -> Any:
        row = self._conn.execute(
            "SELECT val FROM kv WHERE key = ?", (key,)).fetchone()
        if row is None:
            raise KeyError(key)
        return pickle.loads(row[0])

    def get(self, key: str, default: Any = None) -> Any:
        try:
            return self[key]
        except KeyError:
            return default

    def keys(self):
        return [r[0] for r in
                self._conn.execute("SELECT key FROM kv").fetchall()]

    def commit(self) -> None:
        self._conn.commit()

    def close(self) -> None:
        self._conn.commit()
        self._conn.close()


def save_logs_to_dir(out_dir, logs: Mapping[str, Mapping[str, Any]],
                     use_sqlite: bool) -> None:
    """Write each named log dict into ``out_dir`` as either a gzip pickle
    or a SqliteDict database. Callers must pass a SNAPSHOT (not live,
    still-mutating dicts) when invoking this from a background thread."""
    out_dir = pathlib.Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    for log_name, log in logs.items():
        if use_sqlite:
            db = SqliteDict(str(out_dir / f"{log_name}.sqlite"))
            try:
                for key, val in dict(log).items():
                    db[key] = val
                db.commit()
            finally:
                db.close()
        else:
            import gzip

            with gzip.open(out_dir / f"{log_name}.pkl", "wb") as f:
                pickle.dump(dict(log), f)


def snapshot_logs(logs: Mapping[str, Mapping[str, Any]]
                  ) -> dict:
    """Shallow-copy each log's dict and list values on the calling thread
    so a background writer never races the simulator's mutations."""
    return {name: {k: (list(v) if isinstance(v, list) else v)
                   for k, v in log.items()}
            for name, log in logs.items()}


def merge_logs(old: Any, new: Any) -> Any:
    """Extend-by-key merge for incremental log flushes: dicts merge
    recursively, lists extend, scalars overwrite."""
    if isinstance(old, dict) and isinstance(new, dict):
        out = dict(old)
        for k, v in new.items():
            out[k] = merge_logs(out.get(k), v) if k in out else v
        return out
    if isinstance(old, list) and isinstance(new, list):
        return old + new
    return new


class Stopwatch:
    """Simulated wall clock (reference: ddls/utils.py:485)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._time = 0.0

    def tick(self, amount: float = 1.0) -> None:
        self._time += amount

    def time(self) -> float:
        return self._time


def available_cores() -> int:
    """CPU cores this process may use (affinity-aware where supported)."""
    import os

    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


def seed_everything(seed: int) -> None:
    """Seed numpy + stdlib random.

    The reference seeds numpy/random/torch-CUDA globally
    (ddls/utils.py:20-47). JAX randomness is functional; use
    :func:`prng_key` in RL code to thread a key through instead of mutating
    backend state. Deliberately does NOT import jax: the simulator is pure
    host code and must not force accelerator-backend initialisation.
    """
    np.random.seed(seed)
    random.seed(seed)


def prng_key(seed: int):
    """A JAX PRNG key for the learner/rollout code paths."""
    import jax

    return jax.random.PRNGKey(seed)


def flatten_lists(nested) -> list:
    return [item for sub in nested for item in sub]


def get_class_from_path(path: str):
    """Import ``pkg.module.ClassName`` from its dotted path.

    Also accepts reference-repo class paths (``ddls.devices...``) and maps them
    onto their ddls_tpu equivalents so the reference Hydra config trees run
    unchanged (reference: ddls/utils.py:513).
    """
    path = _REFERENCE_CLASS_ALIASES.get(path, path)
    module_path, _, name = path.rpartition(".")
    module = importlib.import_module(module_path)
    return getattr(module, name)


# Class paths appearing in the reference's config trees, mapped to ours.
_REFERENCE_CLASS_ALIASES = {
    "ddls.devices.processors.gpus.A100.A100": "ddls_tpu.hardware.devices.A100",
    "ddls.distributions.fixed.Fixed": "ddls_tpu.demands.distributions.Fixed",
    "ddls.distributions.uniform.Uniform": "ddls_tpu.demands.distributions.Uniform",
    "ddls.distributions.probability_mass_function.ProbabilityMassFunction":
        "ddls_tpu.demands.distributions.ProbabilityMassFunction",
    "ddls.distributions.custom_skew_norm.CustomSkewNorm":
        "ddls_tpu.demands.distributions.CustomSkewNorm",
    "ddls.distributions.list_of_distributions.ListOfDistributions":
        "ddls_tpu.demands.distributions.ListOfDistributions",
    "ddls.environments.ramp_job_partitioning.ramp_job_partitioning_environment.RampJobPartitioningEnvironment":
        "ddls_tpu.envs.partitioning_env.RampJobPartitioningEnvironment",
    "ddls.environments.ramp_job_placement_shaping.ramp_job_placement_shaping_environment.RampJobPlacementShapingEnvironment":
        "ddls_tpu.envs.placement_shaping_env.RampJobPlacementShapingEnvironment",
    "ddls.loops.eval_loop.EvalLoop": "ddls_tpu.train.loops.EvalLoop",
    "ddls.environments.ramp_job_partitioning.agents.random.Random":
        "ddls_tpu.envs.baselines.RandomActor",
    "ddls.environments.ramp_job_partitioning.agents.no_parallelism.NoParallelism":
        "ddls_tpu.envs.baselines.NoParallelism",
    "ddls.environments.ramp_job_partitioning.agents.min_parallelism.MinParallelism":
        "ddls_tpu.envs.baselines.MinParallelism",
    "ddls.environments.ramp_job_partitioning.agents.max_parallelism.MaxParallelism":
        "ddls_tpu.envs.baselines.MaxParallelism",
    "ddls.environments.ramp_job_partitioning.agents.sip_ml.SiPML":
        "ddls_tpu.envs.baselines.SiPML",
    "ddls.environments.ramp_job_partitioning.agents.acceptable_jct.AcceptableJCT":
        "ddls_tpu.envs.baselines.AcceptableJCT",
    "ddls.environments.ramp_job_placement_shaping.agents.first_fit.FirstFit":
        "ddls_tpu.envs.baselines.FirstFitShaper",
    "ddls.environments.ramp_job_placement_shaping.agents.last_fit.LastFit":
        "ddls_tpu.envs.baselines.LastFitShaper",
    "ddls.environments.ramp_job_placement_shaping.agents.random.Random":
        "ddls_tpu.envs.baselines.RandomShaper",
    # legacy simulator path
    "ddls.environments.cluster.cluster_environment.ClusterEnvironment":
        "ddls_tpu.sim.legacy_cluster.ClusterEnvironment",
    "ddls.environments.job_placing.job_placing_all_nodes_environment.JobPlacingAllNodesEnvironment":
        "ddls_tpu.envs.job_placing_env.JobPlacingAllNodesEnvironment",
    "ddls.managers.placers.random_job_placer.RandomJobPlacer":
        "ddls_tpu.agents.managers.RandomJobPlacer",
    "ddls.managers.schedulers.fifo_job_scheduler.FIFOJobScheduler":
        "ddls_tpu.agents.managers.FIFOJobScheduler",
    "ddls.managers.schedulers.srpt_job_scheduler.SRPTJobScheduler":
        "ddls_tpu.agents.managers.SRPTJobScheduler",
    "ddls.managers.schedulers.random_job_scheduler.RandomJobScheduler":
        "ddls_tpu.agents.managers.RandomJobScheduler",
}


def unique_experiment_dir(base: str, name: str) -> str:
    """Create ``base/name/name_<i>/`` with the next free integer suffix
    (reference: ddls/utils.py:530)."""
    root = pathlib.Path(base) / name
    root.mkdir(parents=True, exist_ok=True)
    taken = []
    for item in glob.glob(str(root / f"{name}_*")):
        tail = item.rsplit("_", 1)[-1]
        if tail.isdigit():
            taken.append(int(tail))
    idx = max(taken) + 1 if taken else 0
    out = root / f"{name}_{idx}"
    out.mkdir(parents=True, exist_ok=False)
    return str(out)


def recursive_update(base: dict, overrides: Mapping[str, Any]) -> dict:
    """Deep-merge ``overrides`` into ``base`` (reference: ddls/utils.py:577)."""
    for key, val in overrides.items():
        if key in base and isinstance(base[key], dict) and isinstance(val, Mapping):
            recursive_update(base[key], val)
        else:
            base[key] = val
    return base


def _pid_is_dead(pid: int) -> bool:
    """True only when ``pid`` provably no longer exists (a
    PermissionError means it exists under another uid — alive)."""
    import os

    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except (PermissionError, OSError):
        return False
    return False


def lock_is_stale(path: str) -> bool:
    """A ``.probe/tpu.lock`` whose recorded owner pid is provably dead
    is stale (a hard-killed run cannot unlink its own lock; pid
    liveness is the crash fallback — rl/fused.py ``chip_lock``). A lock
    with NO parseable pid — e.g. written by an external wrapper — is
    conservatively treated as live. Lives here rather than in rl/fused
    because bench.py's probe consult must stay jax-import-free (the
    CPU-fallback decision happens before jax is touched)."""
    try:
        with open(path) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return False
    return _pid_is_dead(pid)
