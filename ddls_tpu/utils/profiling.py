"""Profiling hooks: jax.profiler traces and XLA dumps.

The reference profiles with cProfile only
(scripts/test_heuristic_from_config.py:73-84); on TPU the equivalents are
``jax.profiler`` traces (viewable in TensorBoard/Perfetto/xprof) and XLA
HLO dumps (SURVEY.md §5.1). Both are wired into the CLI entry points via
``experiment.profile_jax`` / ``experiment.xla_dump_to`` config flags.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional


@contextlib.contextmanager
def jax_profiler_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Trace device/host activity for the enclosed block; no-op when
    ``trace_dir`` is falsy. Output is a TensorBoard-compatible profile
    under ``trace_dir``."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(str(trace_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def enable_xla_dump(dump_dir: str) -> None:
    """Ask XLA to dump HLO (text + optimised) for every compilation.

    Must run BEFORE the first jax backend initialisation — XLA_FLAGS is
    read once at backend start, which is why the CLI entry points call
    this before building any epoch loop or learner.
    """
    # replace any existing --xla_dump_to flag (keyed comparison, not a raw
    # substring check, so a stale dump dir never shadows the requested one)
    kept = [f for f in os.environ.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_dump_to=")]
    kept.append(f"--xla_dump_to={dump_dir}")
    os.environ["XLA_FLAGS"] = " ".join(kept)
