"""Lint engine primitives: findings, parsed sources, config, suppressions.

Everything here is deliberately dependency-free (stdlib ``ast`` + a TOML
reader): the engine runs as a tier-1 guard on the CPU box and must never
drag jax into a lint invocation.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: inline suppression syntax — the reason after ``--`` is mandatory:
#: ``# ddls-lint: allow(rule-id[, rule-id...]) -- <why this is deliberate>``
SUPPRESS_RE = re.compile(
    r"#\s*ddls-lint:\s*allow\(([^)]*)\)\s*(?:--\s*(.*\S))?\s*$")


@dataclass
class Finding:
    """One rule violation (or engine-level error) at ``rel``:``line``."""

    rule: str
    rel: str
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: Optional[str] = None

    def as_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "file": self.rel, "line": self.line,
                "message": self.message, "suppressed": self.suppressed,
                "suppress_reason": self.suppress_reason}

    def __str__(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.rel}:{self.line}: [{self.rule}] {self.message}{tag}"


class SourceFile:
    """A file parsed exactly once; every rule reads this shared view."""

    def __init__(self, path: str, rel: str):
        self.path = path
        self.rel = rel
        with open(path, encoding="utf-8", errors="replace") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(self.text)
        except SyntaxError as e:  # surfaced as a parse-error finding
            self.tree = None
            self.parse_error = e
        # line -> (frozenset of rule ids or {"*"}, reason or None)
        self.suppressions: Dict[int, Tuple[frozenset, Optional[str]]] = {}
        #: (line, ids the bad comment names — empty if none, message);
        #: the ids let a restricted run skip other rules' suppressions
        self.bad_suppressions: List[Tuple[int, frozenset, str]] = []
        for lineno, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            ids = frozenset(p.strip() for p in m.group(1).split(",")
                            if p.strip())
            reason = m.group(2)
            if not ids:
                self.bad_suppressions.append(
                    (lineno, ids, "suppression names no rule id: "
                                  "`# ddls-lint: allow(rule-id) -- "
                                  "reason`"))
                continue
            if not reason:
                self.bad_suppressions.append(
                    (lineno, ids,
                     "suppression without a reason — the reason "
                     "is mandatory: `# ddls-lint: allow("
                     + ", ".join(sorted(ids)) + ") -- <why>`"))
                continue
            self.suppressions[lineno] = (ids, reason)
        self._qualname_spans: Optional[List[Tuple[str, int, int]]] = None

    # ------------------------------------------------------------ helpers
    def suppression_for(self, rule_id: str,
                        line: int) -> Optional[str]:
        """The reason string if ``rule_id`` is allowed on ``line``."""
        entry = self.suppressions.get(line)
        if entry is None:
            return None
        ids, reason = entry
        if rule_id in ids or "*" in ids:
            return reason
        return None

    def qualname_spans(self) -> List[Tuple[str, int, int]]:
        """(qualname, first line, last line) for every function/method,
        innermost-last, e.g. ``RLEpochLoop._harvest_metrics``."""
        if self._qualname_spans is None:
            spans: List[Tuple[str, int, int]] = []

            def walk(node, prefix: str) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        name = prefix + child.name
                        spans.append((name, child.lineno,
                                      child.end_lineno or child.lineno))
                        walk(child, name + ".")
                    elif isinstance(child, ast.ClassDef):
                        name = prefix + child.name
                        spans.append((name, child.lineno,
                                      child.end_lineno or child.lineno))
                        walk(child, name + ".")
                    else:
                        walk(child, prefix)

            if self.tree is not None:
                walk(self.tree, "")
            self._qualname_spans = spans
        return self._qualname_spans

    def enclosing_qualname(self, line: int) -> Optional[str]:
        """Innermost function/method qualname containing ``line``."""
        best: Optional[Tuple[int, str]] = None
        for name, lo, hi in self.qualname_spans():
            if lo <= line <= hi and (best is None or lo >= best[0]):
                best = (lo, name)
        return best[1] if best else None

    def has_qualname(self, qualname: str) -> bool:
        return any(name == qualname for name, _, _ in self.qualname_spans())


class Config:
    """The ``[tool.ddls_lint]`` table (one consolidated allowlist home)."""

    def __init__(self, table: Optional[Dict[str, Any]] = None):
        self.table: Dict[str, Any] = dict(table or {})

    def rule(self, rule_id: str) -> Dict[str, Any]:
        value = self.table.get(rule_id)
        return dict(value) if isinstance(value, dict) else {}


def load_config(repo_root: str) -> Config:
    """Read ``[tool.ddls_lint]`` from ``<repo_root>/pyproject.toml``."""
    path = os.path.join(repo_root, "pyproject.toml")
    if not os.path.exists(path):
        return Config()
    try:
        import tomllib  # Python >= 3.11
    except ImportError:  # Python 3.10: the vendored-everywhere fallback
        import tomli as tomllib  # type: ignore[no-redef]
    with open(path, "rb") as f:
        data = tomllib.load(f)
    return Config(data.get("tool", {}).get("ddls_lint", {}))


@dataclass
class Context:
    """Shared state for one engine run: every parsed file + the config."""

    repo_root: str
    config: Config
    files: Dict[str, SourceFile] = field(default_factory=dict)

    def get(self, rel: str) -> Optional[SourceFile]:
        return self.files.get(rel.replace(os.sep, "/"))


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def returncode(self) -> int:
        return 1 if self.errors else 0


class Rule:
    """A lint rule plugin.

    Subclasses set ``id`` (kebab-case, what suppressions and the config
    table key on), ``pointer`` (the one-line fix hint printed under the
    findings) and ``scope_dirs`` (repo-relative dir prefixes the rule
    inspects; files OUTSIDE the repo package — fixture trees under
    ``--paths`` — are always in scope, mirroring the legacy checkers).
    ``check_file`` runs per parsed file; ``check_tree`` runs once per
    engine invocation for cross-file compares and allowlist validation.
    """

    id: str = ""
    pointer: str = ""
    #: None = every scanned file; otherwise repo-relative dir prefixes
    scope_dirs: Optional[Tuple[str, ...]] = None
    #: repo-relative dirs OUTSIDE the default roots that this rule (and
    #: only this rule) also scans on a default run — the engine parses
    #: them once and gates every other rule off those files; explicit
    #: ``--paths`` runs ignore this (fixture trees keep all-rules
    #: behavior)
    extra_roots: Tuple[str, ...] = ()

    def in_scope(self, rel: str) -> bool:
        if self.scope_dirs is None:
            return True
        if not rel.startswith("ddls_tpu/"):
            return True  # fixture trees outside the package
        return any(rel.startswith(d) for d in self.scope_dirs)

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        return []

    def check_tree(self, ctx: Context) -> List[Finding]:
        return []

    # -------------------------------------------------- shared validators
    def validate_allow_keys(self, ctx: Context, entries: Dict[str, Any],
                            want_qualname: bool = False,
                            table: str = "", entity: str = "function",
                            want_int: bool = False) -> List[Finding]:
        """Stale-allowance guard: every ``path`` (or ``path::qualname``)
        key in a config allowlist must still resolve, every string value
        must carry a non-empty written reason, and (``want_int``) count
        values must be integers — stale or malformed entries are
        themselves lint errors (they rot otherwise). ``table`` names a
        sub-table suffix (e.g. ``.classes``); ``entity`` is the noun for
        qualname findings (function/class)."""
        label = f"[tool.ddls_lint.{self.id}{table}]"
        findings = []
        for key, reason in entries.items():
            rel, _, qual = key.partition("::")
            rel = rel.replace(os.sep, "/")
            if not os.path.exists(os.path.join(ctx.repo_root, rel)):
                findings.append(Finding(
                    self.id, "pyproject.toml", 1,
                    f"stale {label} allowance: "
                    f"{rel!r} does not exist — remove the entry"))
                continue
            if want_qualname:
                if not qual:
                    findings.append(Finding(
                        self.id, "pyproject.toml", 1,
                        f"{label} allowance {key!r} "
                        "must be 'path::qualname'"))
                    continue
                sf = ctx.get(rel)
                if sf is not None and not sf.has_qualname(qual):
                    findings.append(Finding(
                        self.id, "pyproject.toml", 1,
                        f"stale {label} allowance: "
                        f"no {entity} {qual!r} in {rel} — remove or "
                        "update the entry"))
            if want_int and not (isinstance(reason, int)
                                 and not isinstance(reason, bool)):
                findings.append(Finding(
                    self.id, "pyproject.toml", 1,
                    f"{label} allowance {key!r} must be an integer "
                    f"occurrence count (got {type(reason).__name__})"))
            if isinstance(reason, str) and not reason.strip():
                findings.append(Finding(
                    self.id, "pyproject.toml", 1,
                    f"{label} allowance {key!r} has "
                    "an empty reason — the written reason is mandatory"))
        return findings

    @staticmethod
    def int_allowance(entries: Dict[str, Any], rel: str) -> int:
        """The integer allowance for ``rel``, 0 when absent or malformed
        (a malformed value is reported by ``validate_allow_keys(...,
        want_int=True)`` — the per-file pass must not crash on it)."""
        value = entries.get(rel, 0)
        if isinstance(value, int) and not isinstance(value, bool):
            return value
        return 0

    def inline_suppressed_lines(self, sf: SourceFile) -> set:
        """Lines whose inline suppression names this rule (or ``*``)."""
        return {line for line, (ids, _reason) in sf.suppressions.items()
                if self.id in ids or "*" in ids}

    def validate_count_allowances(self, ctx: Context,
                                  entries: Dict[str, Any], count_of,
                                  noun: str) -> List[Finding]:
        """The count-based anti-rot contract, shared by bare-timers and
        shm-unlink: an entry granting more ``noun``s than ``count_of(sf)``
        finds is green headroom for new violations, and a file mixing a
        config count with inline suppressions can mask which occurrence
        is new — both are lint errors."""
        findings = []
        for rel in entries:
            sf = ctx.get(rel)
            if sf is None:  # not in the scanned roots (fixture runs)
                continue
            allowed = self.int_allowance(entries, rel)
            count = count_of(sf)
            if count < allowed:
                findings.append(Finding(
                    self.id, "pyproject.toml", 1,
                    f"stale [tool.ddls_lint.{self.id}] allowance: {rel} "
                    f"has {count} {noun}(s) but the entry grants "
                    f"{allowed} — lower or remove it"))
            if self.inline_suppressed_lines(sf):
                findings.append(Finding(
                    self.id, "pyproject.toml", 1,
                    f"{rel} mixes a [tool.ddls_lint.{self.id}] count "
                    "allowance with inline suppressions — use one "
                    "mechanism (combined, a suppression can mask which "
                    "occurrence is new)"))
        return findings


# --------------------------------------------------------------- AST utils
def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def module_aliases(tree: ast.Module, module_suffix: str,
                   from_name: str) -> set:
    """Names a module binds to ``<pkg>....<module_suffix>`` — covers
    ``import pkg.mod as x``, ``from pkg import mod as x``, relative
    ``from .. import mod``, and plain ``import pkg.mod`` (which binds
    the full DOTTED access path — match call sites with
    ``dotted_name(func.value) in aliases``, not bare Names only)."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            # relative `from .. import telemetry` has module=None; a
            # relative module name (`from ..telemetry import flight`)
            # matches the suffix like an absolute one
            if (node.module is None and node.level > 0) or (
                    node.module and (node.module.endswith(module_suffix)
                                     or node.module == module_suffix)):
                for a in node.names:
                    if a.name == from_name:
                        aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith(f"{module_suffix}.{from_name}"):
                    # no asname: the binding is reached via the full
                    # dotted path (`ddls_tpu.telemetry.inc(...)`)
                    aliases.add(a.asname or a.name)
    return aliases
