"""Engine: discover files, parse each ONCE, run every rule, render.

The whole-tree run must stay cheap (tier-1 budget: well under ~5 s on
the CPU box): one ``os.walk`` per root, one ``ast.parse`` per file
(``SourceFile`` caches the tree; cross-file rules read the same cache),
zero imports of the linted code — AST compare only, so a lint run can
never drag jax in.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from ddls_tpu.lint.core import (Config, Context, Finding, LintResult,
                                Rule, SourceFile, load_config)

#: the engine's own package — excluded from scans: rule sources quote the
#: very tokens they hunt (fixture strings would self-flag)
SELF_DIR = "ddls_tpu/lint/"


def discover(roots: Sequence[str], repo_root: str) -> List[SourceFile]:
    files: List[SourceFile] = []
    seen = set()
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
                if rel in seen or rel.startswith(SELF_DIR):
                    continue
                seen.add(rel)
                files.append(SourceFile(path, rel))
    return files


def run_lint(roots: Optional[Sequence[str]] = None,
             repo_root: Optional[str] = None,
             rules: Optional[Sequence[Rule]] = None,
             config: Optional[Config] = None) -> LintResult:
    """One engine pass: parse every file under ``roots`` once, run all
    ``rules`` (default: the full registry) over the shared ASTs, apply
    inline suppressions, and return every finding (suppressed ones
    included, flagged — ``--json`` consumers track both)."""
    from ddls_tpu.lint.rules import ALL_RULES

    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    if config is None:
        config = load_config(repo_root)
    if rules is None:
        rules = ALL_RULES

    # per-rule extra roots ride the DEFAULT run only: an explicit
    # --paths invocation (fixture trees, the legacy single-rule shims)
    # keeps the current all-rules-over-given-roots behavior
    restricted: Dict[str, set] = {}
    extra_scan: List[tuple] = []
    if roots is None:
        roots = [os.path.join(repo_root, "ddls_tpu")]
        for rule in rules:
            for d in rule.extra_roots:
                extra_scan.append((rule.id, os.path.join(repo_root, d)))

    ctx = Context(repo_root=repo_root, config=config)
    for sf in discover(roots, repo_root):
        ctx.files[sf.rel] = sf
    for rule_id, extra_root in extra_scan:
        for sf in discover([extra_root], repo_root):
            if sf.rel not in ctx.files:
                ctx.files[sf.rel] = sf
                restricted[sf.rel] = set()
            if sf.rel in restricted:
                restricted[sf.rel].add(rule_id)

    active_ids = {rule.id for rule in rules}
    # a suppression naming an id outside the registry suppresses
    # nothing — flagged in EVERY run (mirrors get_rules raising on
    # unknown --rules ids: a typo cannot silently lint nothing)
    known_ids = {rule.id for rule in ALL_RULES} | active_ids | {"*"}
    findings: List[Finding] = []

    def flag_unknown_ids(sf: SourceFile, lineno: int, ids) -> None:
        for rid in sorted(set(ids) - known_ids):
            findings.append(Finding(
                "lint-suppression", sf.rel, lineno,
                f"suppression names unknown rule id {rid!r} (it "
                "suppresses nothing) — available: "
                + ", ".join(sorted(r.id for r in ALL_RULES))))

    for sf in ctx.files.values():
        if sf.parse_error is not None:
            # always reported: an unparseable file can hide violations
            # of ANY rule, restricted run or not
            findings.append(Finding(
                "parse-error", sf.rel, sf.parse_error.lineno or 0,
                f"unparseable: {sf.parse_error.msg}"))
            continue
        for lineno, (ids, _reason) in sf.suppressions.items():
            flag_unknown_ids(sf, lineno, ids)
        for lineno, ids, message in sf.bad_suppressions:
            flag_unknown_ids(sf, lineno, ids)
            # a malformed suppression belongs to the rules it names — a
            # restricted run (the single-rule legacy shims) must not
            # fail on another rule's reasonless comment; one naming NO
            # rule is engine-level garbage and fails every run
            if ids and "*" not in ids and not (ids & active_ids):
                continue
            findings.append(Finding("lint-suppression", sf.rel, lineno,
                                    message))
        allowed_rules = restricted.get(sf.rel)
        for rule in rules:
            if allowed_rules is not None and rule.id not in allowed_rules:
                continue  # file came in via another rule's extra_roots
            if rule.in_scope(sf.rel):
                findings.extend(rule.check_file(sf, ctx))
    for rule in rules:
        findings.extend(rule.check_tree(ctx))

    for f in findings:
        sf = ctx.files.get(f.rel)
        if sf is None or f.rule in ("parse-error", "lint-suppression"):
            continue
        reason = sf.suppression_for(f.rule, f.line)
        if reason is not None:
            f.suppressed = True
            f.suppress_reason = reason

    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return LintResult(findings=findings)


# ------------------------------------------------------------- rendering
def render_text(result: LintResult, rules: Sequence[Rule]) -> str:
    lines: List[str] = []
    errors = result.errors
    if errors:
        lines.append("lint: invariant violations found:")
        for f in errors:
            lines.append(f"  {f.rel}:{f.line}: [{f.rule}] {f.message}")
        for rule in rules:
            if rule.pointer and any(f.rule == rule.id for f in errors):
                lines.append(f"fix({rule.id}): {rule.pointer}")
        if any(f.rule == "lint-suppression" for f in errors):
            lines.append("fix(lint-suppression): every `# ddls-lint: "
                         "allow(rule)` must carry ` -- <reason>`")
    suppressed = [f for f in result.findings if f.suppressed]
    if suppressed:
        lines.append(f"({len(suppressed)} finding(s) suppressed inline "
                     "with reasons)")
    if not errors:
        lines.append("ok: all lint rules clean")
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps({
        "findings": [f.as_dict() for f in result.findings],
        "counts": {
            "errors": len(result.errors),
            "suppressed": sum(f.suppressed for f in result.findings),
        },
        "returncode": result.returncode,
    }, indent=2)


# -------------------------------------------------------------------- CLI
def main(argv: Optional[Sequence[str]] = None,
         rule_ids: Optional[Sequence[str]] = None,
         description: str = "ddls_tpu invariant lint engine",
         repo_root: Optional[str] = None) -> int:
    """CLI driver (scripts/lint.py and the three legacy shims).
    ``rule_ids`` restricts the run (the shim surface); rc 0 clean / 1
    findings, matching the legacy checkers."""
    from ddls_tpu.lint.rules import get_rules

    parser = argparse.ArgumentParser(description=description)
    parser.add_argument("--paths", nargs="*", default=None,
                        help="roots to scan (default: ddls_tpu/ in the "
                             "repo; allowances are keyed relative to "
                             "the repo root)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings (rule id, "
                             "file:line, message, suppression state)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    args = parser.parse_args(argv)

    ids = rule_ids
    if args.rules:
        ids = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        rules = get_rules(ids)
    except ValueError as e:
        # fail loud but clean: a typo'd --rules id must not dump a
        # traceback (or break the --json machine-readable contract)
        print(json.dumps({"error": str(e), "returncode": 2})
              if args.json else f"lint: {e}")
        return 2
    result = run_lint(roots=args.paths, repo_root=repo_root, rules=rules)
    print(render_json(result) if args.json
          else render_text(result, rules))
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
