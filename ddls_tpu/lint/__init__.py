"""Invariant lint engine: one AST pass, all contract rules (ISSUE 9).

The codebase's correctness rests on invariants that used to live only in
CLAUDE.md prose and three standalone checker scripts: metrics-are-futures
on the collect->update path, process-consistent multi-host collective
gates, one-bool telemetry/flight gating, the flow-mask predicate ban,
frozen checkpoint param-tree names, and the host<->jitted backend surface
sync. This package makes them mechanical: every ``.py`` file under
``ddls_tpu/`` is parsed ONCE and every registered rule runs over the
shared AST (plus a few cross-file compare passes), so adding an invariant
is adding a rule plugin, not another 100-line walker script.

Entry points
------------
* ``python scripts/lint.py`` — whole-tree run, text or ``--json`` output,
  rc 0/1 (tier-1: tests/test_lint.py runs it over the real tree).
* ``scripts/check_no_bare_timers.py`` / ``check_flight_gated.py`` /
  ``check_shm_unlink.py`` — thin shims that run their single ported rule
  with the legacy CLI surface (``--paths``, same rc) so existing tests
  and docs references keep working.
* ``run_lint(...)`` — in-process API (what the tests use).

Suppressions and allowlists
---------------------------
Inline: ``# ddls-lint: allow(rule-id) -- <why>`` on the finding's line;
the reason is MANDATORY (a bare ``allow(...)`` is itself a lint error).
Per-rule allowlists live in ONE place, the ``[tool.ddls_lint]`` table in
``pyproject.toml``; stale entries (files or functions that no longer
exist) are themselves lint errors so allowances cannot rot. See
docs/lint.md for the rule catalog and how to add a rule.
"""
from __future__ import annotations

from ddls_tpu.lint.core import (Config, Context, Finding, LintResult,
                                Rule, SourceFile, load_config)
from ddls_tpu.lint.engine import main, render_json, render_text, run_lint
from ddls_tpu.lint.rules import ALL_RULES, get_rules

__all__ = [
    "ALL_RULES", "Config", "Context", "Finding", "LintResult", "Rule",
    "SourceFile", "get_rules", "load_config", "main", "render_json",
    "render_text", "run_lint",
]
