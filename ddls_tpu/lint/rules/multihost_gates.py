"""multihost-deterministic-gates: branches guarding collectives must be
process-consistent.

Multi-host rule (CLAUDE.md, train/loops.py): any branch that decides
whether a jitted sharded call or cross-process collective runs must take
the SAME direction on every process — deterministic gates only (epoch
counters, config values, shared-stream rng). A gate that reads the wall
clock, the process-global ``random`` state, ``os.environ``, or the
filesystem can desync processes, and a desynced collective is a hang,
not an error (Podracer-style fused loops die on exactly this — PAPERS.md
arXiv 2104.06272).

Mechanics: in ``train/`` modules, an ``if``/``while`` condition that
lexically guards a call whose name ends with one of the guarded-call
names (``train_step``, ``update``, ``process_allgather``,
``materialize_group``, ``psum``/``pmean``/``all_gather``) — including
guarding by early return — may not read ``time.*``, ``random.*``,
``np.random.*``, ``os.environ``/``os.getenv``/``os.path``, or call
``open``/``Path``. ``jax.random.*`` stays legal: it is a pure function
of an explicitly-managed key.
"""
from __future__ import annotations

import ast
from typing import List, Sequence, Tuple

from ddls_tpu.lint.core import Context, Finding, Rule, SourceFile

DEFAULT_GUARDED_CALLS = (
    "train_step", "update", "process_allgather", "materialize_group",
    "psum", "pmean", "all_gather", "all_reduce", "broadcast_one_to_all",
    # the fused epoch IS the sharded update (rl/fused.py): a gate that
    # desyncs which process dispatches it is the same hang as a desynced
    # train_step — and the autotuner's fallback gate must stay a pure
    # function of the cached config, never of probe wall-time or env
    "fused_epoch",
)

#: generic method names that only count as guarded calls when the
#: receiver's dotted name mentions one of the listed qualifiers —
#: ``self.learner.update(...)`` is the sharded call, ``cfg.update(...)``
#: is a dict method
RECEIVER_QUALIFIED = {"update": ("learner",)}

#: dotted-name prefixes whose read inside a gate condition is
#: process-inconsistent (jax.random is NOT here: key-driven, shared)
BANNED_PREFIXES = (
    "time.", "random.", "np.random.", "numpy.random.",
    "os.environ", "os.getenv", "os.path", "os.listdir", "os.stat",
    "datetime.",
)
BANNED_CALLS = ("open", "input", "Path", "perf_counter")


def _banned_reads(test: ast.AST) -> List[str]:
    out = []
    for node in ast.walk(test):
        name = None
        if isinstance(node, (ast.Attribute, ast.Name)):
            parts = []
            cur = node
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                parts.append(cur.id)
                name = ".".join(reversed(parts))
        if name:
            if any(name == p.rstrip(".") or name.startswith(p)
                   for p in BANNED_PREFIXES):
                out.append(name)
        if isinstance(node, ast.Call):
            callee = node.func
            if isinstance(callee, ast.Name) and callee.id in BANNED_CALLS:
                out.append(f"{callee.id}()")
    return sorted(set(out))


def _is_early_exit(stmts: Sequence[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


class MultihostGatesRule(Rule):
    id = "multihost-deterministic-gates"
    pointer = ("gates guarding a jitted sharded call or collective must "
               "be process-consistent: epoch counters, config, or "
               "shared-stream jax.random draws only (CLAUDE.md "
               "multi-host rules) — never wall clock, `random`, "
               "os.environ, or filesystem state")
    # train/ loops plus the fused epoch driver: its fused_epoch dispatch
    # and autotuner fallback are collective-shaped decisions too
    scope_dirs = ("ddls_tpu/train/", "ddls_tpu/rl/fused.py")

    def _guarded_calls(self, ctx: Context) -> Tuple[str, ...]:
        extra = tuple(ctx.config.rule(self.id).get("guarded_calls", ()))
        return DEFAULT_GUARDED_CALLS + extra

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        if sf.tree is None:
            return []
        guarded_names = self._guarded_calls(ctx)
        findings: List[Finding] = []

        def collective_calls(node) -> List[ast.Call]:
            out = []
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    callee = sub.func
                    last = (callee.attr if isinstance(callee, ast.Attribute)
                            else callee.id if isinstance(callee, ast.Name)
                            else None)
                    if last not in guarded_names:
                        continue
                    qualifiers = RECEIVER_QUALIFIED.get(last)
                    if qualifiers is not None:
                        receiver = (ast.unparse(callee.value)
                                    if isinstance(callee, ast.Attribute)
                                    else "")
                        if not any(q in receiver for q in qualifiers):
                            continue
                    out.append(sub)
            return out

        def report(test: ast.AST, calls: List[ast.Call]) -> None:
            reads = _banned_reads(test)
            if not reads:
                return
            for call in calls:
                callee = call.func
                last = (callee.attr if isinstance(callee, ast.Attribute)
                        else getattr(callee, "id", "?"))
                findings.append(Finding(
                    self.id, sf.rel, call.lineno,
                    f"collective/sharded call {last}(...) is gated by a "
                    f"process-inconsistent condition (line {test.lineno} "
                    f"reads {', '.join(reads)}) — multi-host gates must "
                    "be deterministic"))

        def visit_block(stmts: Sequence[ast.stmt]) -> None:
            for i, stmt in enumerate(stmts):
                if isinstance(stmt, (ast.If, ast.While)):
                    body_calls = []
                    for s in stmt.body:
                        body_calls.extend(collective_calls(s))
                    orelse_calls = []
                    for s in getattr(stmt, "orelse", []):
                        orelse_calls.extend(collective_calls(s))
                    report(stmt.test, body_calls + orelse_calls)
                    # an early-exit `if` guards the REST of this block
                    # (the `if not ...: return` sync-gate idiom)
                    if (isinstance(stmt, ast.If)
                            and _is_early_exit(stmt.body)
                            and not stmt.orelse):
                        rest_calls = []
                        for s in stmts[i + 1:]:
                            rest_calls.extend(collective_calls(s))
                        report(stmt.test, rest_calls)
                    visit_block(stmt.body)
                    visit_block(getattr(stmt, "orelse", []))
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.ClassDef)):
                    visit_block(stmt.body)
                elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.With,
                                       ast.AsyncWith)):
                    visit_block(stmt.body)
                    visit_block(getattr(stmt, "orelse", []))
                elif isinstance(stmt, ast.Try):
                    visit_block(stmt.body)
                    for h in stmt.handlers:
                        visit_block(h.body)
                    visit_block(stmt.orelse)
                    visit_block(stmt.finalbody)
                elif isinstance(stmt, ast.Match):
                    for case in stmt.cases:
                        visit_block(case.body)

        visit_block(sf.tree.body)
        findings.sort(key=lambda f: f.line)
        return findings
