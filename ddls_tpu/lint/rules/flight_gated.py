"""flight-gated: flight-recorder emits in hot-path sim/env modules must
be gated behind ``if _flight.enabled():``.

Port of ``scripts/check_flight_gated.py`` (now a shim over this rule).
The flight recorder (ddls_tpu/telemetry/flight.py) shares telemetry's
hot-path contract: disabled by default, near-no-op when off. An ungated
``flight.emit(...)`` pays argument construction (dicts, list copies,
clock reads) on EVERY simulator step even with the recorder off; calling
``enable()``/``disable()``/``reset()`` from a hot-path module is flipping
the switch outside the CLI entry points that own it.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from ddls_tpu.lint.core import (Context, Finding, Rule, SourceFile,
                                dotted_name, module_aliases)

EMIT_ATTRS = ("emit", "extend")
SWITCH_ATTRS = ("enable", "disable", "reset")


def iter_guarded_calls(tree: ast.Module) -> Iterator[Tuple[ast.Call, bool]]:
    """Every Call in the module with whether it sits lexically inside an
    ``if`` whose condition mentions ``enabled`` POSITIVELY — the gate
    idiom (covers ``_flight.enabled()``, ``detail_enabled and ...``
    hoisted locals). An inverted gate (``if not _flight.enabled():``)
    guards its ELSE branch, not its body — the body runs exactly when
    the recorder is OFF. Shared by the flight and telemetry gating
    rules."""

    def walk(node, guarded):
        if isinstance(node, ast.If):
            mentions = "enabled" in ast.unparse(node.test)
            negated = (isinstance(node.test, ast.UnaryOp)
                       and isinstance(node.test.op, ast.Not))
            body_guarded = guarded or (mentions and not negated)
            orelse_guarded = guarded or (mentions and negated)
            for child in node.body:
                yield from walk(child, body_guarded)
            for child in node.orelse:
                yield from walk(child, orelse_guarded)
            yield from walk(node.test, guarded)
            return
        if isinstance(node, ast.Call):
            yield node, guarded
        for child in ast.iter_child_nodes(node):
            yield from walk(child, guarded)

    yield from walk(tree, False)


def _is_alias_call(node: ast.Call, aliases: set, attrs) -> bool:
    # dotted_name covers both `_flight.emit(...)` (bare alias) and the
    # unaliased `ddls_tpu.telemetry.flight.emit(...)` access path
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr in attrs
            and dotted_name(node.func.value) in aliases)


class FlightGatedRule(Rule):
    id = "flight-gated"
    pointer = ("gate hot-path recorder calls as `if _flight.enabled(): "
               "_flight.emit(...)` (from ddls_tpu.telemetry import flight "
               "as _flight; docs/telemetry.md \"Flight recorder\") so a "
               "disabled recorder costs one bool check and zero event "
               "objects")
    scope_dirs = ("ddls_tpu/sim/", "ddls_tpu/envs/")

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        if "flight" not in sf.text or sf.tree is None:
            return []
        aliases = module_aliases(sf.tree, "telemetry", "flight")
        if not aliases:
            return []
        findings = []
        for call, guarded in iter_guarded_calls(sf.tree):
            if _is_alias_call(call, aliases, SWITCH_ATTRS):
                findings.append(Finding(
                    self.id, sf.rel, call.lineno,
                    f"hot-path module calls flight.{call.func.attr}() — "
                    "the recorder switch belongs to entry points"))
            elif (_is_alias_call(call, aliases, EMIT_ATTRS)
                  and not guarded):
                findings.append(Finding(
                    self.id, sf.rel, call.lineno,
                    f"ungated flight.{call.func.attr}(...) — wrap in "
                    "`if _flight.enabled():`"))
        findings.sort(key=lambda f: f.line)
        return findings
