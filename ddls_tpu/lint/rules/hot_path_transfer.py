"""hot-path-transfer: pin the metrics-are-futures contract on the
collect->update path.

The pipelined epoch loop (train/loops.py, docs/perf_round6.md) keeps
learner metrics on device as ``LazyMetrics`` futures and drains them in
ONE batched fetch per sync boundary; one innocent ``float()``/``.item()``
/``np.asarray`` on the hot path re-pays the ~116 ms tunnelled-TPU round
trip EVERY update (the CPU-actor transfer tax of arXiv 2012.04210).
This rule flags the *implicit* coercions — ``float(...)``, ``.item()``,
``np.asarray(...)`` — in the collect->update modules; explicit staging
(``jax.device_put``/``jax.device_get``) stays legal because explicitness
is exactly what the contract asks for, and ``train/metrics.py`` is the
one sanctioned home for scalar coercion (``as_float``/``LazyMetrics``).

Boundary functions (eval, W&B flatten, setup, the sequential-mode
contract) are allowlisted per function in
``[tool.ddls_lint.hot-path-transfer.allow]`` as ``"path::qualname" =
"why"`` — the written reason is mandatory and stale entries are lint
errors.
"""
from __future__ import annotations

import ast
import os
from typing import List

from ddls_tpu.lint.core import (Context, Finding, Rule, SourceFile,
                                dotted_name)

#: the collect->update path: the epoch loops, the rollout collectors,
#: and the fused epoch driver (whose in-program epoch makes an implicit
#: coercion doubly expensive: it would re-serialise the ONE dispatch per
#: epoch the fusion exists to amortise)
DEFAULT_MODULES = (
    "ddls_tpu/train/loops.py",
    "ddls_tpu/rl/rollout.py",
    "ddls_tpu/rl/ppo_device.py",
    "ddls_tpu/rl/shm.py",
    "ddls_tpu/rl/ring.py",
    "ddls_tpu/rl/fused.py",
    # the in-kernel lookahead memo rides the carried device state of
    # every collect; an implicit coercion here would fetch the table (or
    # its counters) EVERY decision step
    "ddls_tpu/sim/jax_memo.py",
)

_IMPLICIT_COERCIONS = {"np.asarray", "numpy.asarray"}


class HotPathTransferRule(Rule):
    id = "hot-path-transfer"
    pointer = ("metrics are FUTURES on the collect->update path: route "
               "scalar coercions through ddls_tpu/train/metrics.py "
               "(as_float / LazyMetrics) or make the transfer explicit "
               "(jax.device_get at a sync boundary); genuine boundary "
               "functions go in [tool.ddls_lint.hot-path-transfer.allow] "
               "as \"path::qualname\" = \"why\"")

    def _modules(self, ctx: Context):
        return tuple(ctx.config.rule(self.id).get("modules",
                                                  DEFAULT_MODULES))

    def in_scope(self, rel: str) -> bool:
        # scoping is a module LIST from config, which needs the Context —
        # check_file does the real filter
        return True

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        if sf.tree is None:
            return []
        modules = self._modules(ctx)
        if sf.rel.startswith("ddls_tpu/") and sf.rel not in modules:
            return []
        allow = ctx.config.rule(self.id).get("allow", {})
        findings = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            label = None
            if isinstance(node.func, ast.Name) and node.func.id == "float":
                label = "float(...)"
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item" and not node.args):
                label = ".item()"
            elif dotted_name(node.func) in _IMPLICIT_COERCIONS:
                label = f"{dotted_name(node.func)}(...)"
            if label is None:
                continue
            qual = sf.enclosing_qualname(node.lineno)
            if qual is not None and f"{sf.rel}::{qual}" in allow:
                continue
            findings.append(Finding(
                self.id, sf.rel, node.lineno,
                f"implicit device->host coercion {label} on the "
                f"collect->update path"
                + (f" (in {qual})" if qual else " (module level)")))
        findings.sort(key=lambda f: f.line)
        return findings

    def check_tree(self, ctx: Context) -> List[Finding]:
        findings = self.validate_allow_keys(
            ctx, ctx.config.rule(self.id).get("allow", {}),
            want_qualname=True)
        for rel in ctx.config.rule(self.id).get("modules", ()):
            if not os.path.exists(os.path.join(ctx.repo_root, rel)):
                findings.append(Finding(
                    self.id, "pyproject.toml", 1,
                    f"stale [tool.ddls_lint.{self.id}] modules entry: "
                    f"{rel!r} does not exist"))
        return findings
