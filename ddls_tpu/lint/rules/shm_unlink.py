"""shm-unlink: every ``SharedMemory(create=True)`` must keep its paired
unlink + crash-path finalizer.

Port of ``scripts/check_shm_unlink.py`` (now a shim over this rule).
The shm rollout backend (ddls_tpu/rl/shm.py, docs/perf_round7.md) owns
POSIX shared-memory segments whose names outlive the process if nobody
unlinks them — an interrupted pytest run or a crashed collector would
litter ``/dev/shm`` until reboot. Contract: a file that creates segments
must also carry an ``.unlink()`` call AND a ``weakref.finalize``/
``atexit`` fallback for paths that never reach ``close()``. Deliberate
tracker-owned exceptions go in ``[tool.ddls_lint.shm-unlink.allow]``
with a why-comment.
"""
from __future__ import annotations

import re
from typing import List

from ddls_tpu.lint.core import Context, Finding, Rule, SourceFile

_CREATE_RE = re.compile(r"SharedMemory\s*\([^)]*create\s*=\s*True",
                        re.DOTALL)


class ShmUnlinkRule(Rule):
    id = "shm-unlink"
    pointer = ("pair every SharedMemory(create=True) with an .unlink() on "
               "close AND a weakref.finalize/atexit fallback (see "
               "ddls_tpu/rl/shm.py SlabSet), or the segment outlives a "
               "crashed run in /dev/shm; deliberately tracker-owned "
               "segments go in [tool.ddls_lint.shm-unlink.allow] in "
               "pyproject.toml with a why-comment")
    scope_dirs = None  # the whole package

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        matches = list(_CREATE_RE.finditer(sf.text))
        if not matches:
            return []
        missing = []
        if ".unlink(" not in sf.text:
            missing.append("unlink")
        if ("weakref.finalize" not in sf.text
                and "atexit" not in sf.text):
            missing.append("finalizer (weakref.finalize/atexit)")
        if not missing:
            return []
        allow = ctx.config.rule(self.id).get("allow", {})
        allowed = self.int_allowance(allow, sf.rel)
        # same attribution contract as bare-timers: suppressed creates
        # are excluded (and reported as their own suppressed findings);
        # when the rest exceed the allowance, every unsuppressed create
        # line is flagged — the allowance has no line identity
        lines = [sf.text.count("\n", 0, m.start()) + 1 for m in matches]
        suppressed = self.inline_suppressed_lines(sf)
        sup = [ln for ln in lines if ln in suppressed]
        unsup = [ln for ln in lines if ln not in suppressed]
        findings = [Finding(
            self.id, sf.rel, ln, "SharedMemory create "
            "(inline-suppressed)") for ln in sup]
        if len(unsup) > allowed:
            findings += [Finding(
                self.id, sf.rel, ln,
                f"SharedMemory create without leak-proof pairing "
                f"({len(unsup)} create(s) in file, allowance {allowed}), "
                f"missing {' + '.join(missing)}") for ln in unsup]
        return findings

    def check_tree(self, ctx: Context) -> List[Finding]:
        allow = ctx.config.rule(self.id).get("allow", {})
        return (self.validate_allow_keys(ctx, allow, want_int=True)
                + self.validate_count_allowances(
                    ctx, allow,
                    lambda sf: len(_CREATE_RE.findall(sf.text)),
                    "SharedMemory create"))
