"""telemetry-gated: hot-path telemetry calls must allocate nothing when
telemetry is off.

Generalises the flight-recorder gating check (CLAUDE.md telemetry
invariant) to the module-level telemetry API in ``sim/`` and ``envs/``:
``telemetry.inc/observe/set_gauge/record_event/span`` are one-bool
no-ops while disabled, but their ARGUMENTS are evaluated at the call
site — an f-string metric name, a ``sum(...)`` payload, or a dict built
inline pays allocation on every simulator step with telemetry off. Calls
whose arguments are trivial (constants, bare names, attribute reads)
stay legal ungated; anything that computes must sit inside the
``if telemetry.enabled():`` idiom. Flipping the global switch
(``enable``/``disable``/``reset``) from a hot-path module is always
flagged — that belongs to CLI entry points and tests.
"""
from __future__ import annotations

import ast
from typing import List

from ddls_tpu.lint.core import (Context, Finding, Rule, SourceFile,
                                dotted_name, module_aliases)
from ddls_tpu.lint.rules.flight_gated import iter_guarded_calls

GATED_ATTRS = ("inc", "observe", "set_gauge", "record_event", "span")
SWITCH_ATTRS = ("enable", "disable", "reset")


def _is_trivial(node: ast.AST) -> bool:
    """No allocation / computation at call time: constants, bare names,
    attribute reads, and unary/conditional combinations thereof."""
    if isinstance(node, (ast.Constant, ast.Name, ast.Attribute)):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_trivial(node.operand)
    if isinstance(node, ast.IfExp):
        return (_is_trivial(node.test) and _is_trivial(node.body)
                and _is_trivial(node.orelse))
    return False


class TelemetryGatedRule(Rule):
    id = "telemetry-gated"
    pointer = ("gate allocating telemetry calls as `if telemetry."
               "enabled(): telemetry.inc(...)` (docs/telemetry.md hot-"
               "path contract: one bool check, zero allocations when "
               "off); constant-argument calls may stay ungated")
    scope_dirs = ("ddls_tpu/sim/", "ddls_tpu/envs/")

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        if "telemetry" not in sf.text or sf.tree is None:
            return []
        aliases = module_aliases(sf.tree, "ddls_tpu", "telemetry")
        if not aliases:
            return []
        findings = []
        for call, guarded in iter_guarded_calls(sf.tree):
            func = call.func
            # dotted_name covers both the bare alias (`telemetry.inc`)
            # and the unaliased `ddls_tpu.telemetry.inc` access path
            if not (isinstance(func, ast.Attribute)
                    and dotted_name(func.value) in aliases):
                continue
            if func.attr in SWITCH_ATTRS:
                findings.append(Finding(
                    self.id, sf.rel, call.lineno,
                    f"hot-path module calls telemetry.{func.attr}() — "
                    "the global switch belongs to entry points"))
            elif func.attr in GATED_ATTRS and not guarded:
                args = list(call.args) + [kw.value for kw in call.keywords]
                if all(_is_trivial(a) for a in args):
                    continue
                findings.append(Finding(
                    self.id, sf.rel, call.lineno,
                    f"ungated telemetry.{func.attr}(...) with computed "
                    "arguments — wrap in `if telemetry.enabled():` (the "
                    "args are evaluated even while telemetry is off)"))
        findings.sort(key=lambda f: f.line)
        return findings
