"""backend-surface-parity: the host<->jitted decision surfaces must stay
in sync — checked by AST compare, no jax import.

Six cheap cross-file compares over the shared parse (CLAUDE.md
four-backend invariant, tests pin the VALUES — this rule pins the
SURFACES so a rename fails at lint time, not at the first x64 parity
run):

1. The jitted env's cause-code tables (``sim/jax_env.py``):
   ``CAUSE_*`` constants pairwise distinct, ``CAUSE_CODE_TO_STR``
   covering every constant exactly once, string values unique
   (bijective).
2. Cause-string vocabulary: every non-None jitted cause string (and
   every explicit ``CAUSE_STR_TO_CODE[...]`` alias) must exist as a
   string literal on the host side (``sim/cluster.py`` /
   ``sim/actions.py``), except the configured jitted-only causes
   (``engine_failure``: the host raises instead of blocking).
3. Episode-counter fields: every ``trace["ep_*"]`` key the device
   collector consumes (``rl/ppo_device.py``) must be traced by
   ``make_segment_fn``'s per-step dict, and every episode-record key the
   collector emits must be a key the host's ``harvest_episode_record``
   (``rl/rollout.py``) knows — device- and host-collected records must
   stay interchangeable.
4. The in-kernel lookahead memo's key surface (``sim/jax_memo.py``,
   ISSUE 13): every host key builder the memo declares it mirrors
   (``HOST_KEY_SURFACE``) must still exist as a function in
   ``sim/cluster.py`` — a host key-builder rename fails here, not at
   the first stale-memo debugging session — and every memo counter key
   (``MEMO_TRACE_KEYS``) must be traced by ``make_segment_fn``, so the
   counters drain with the episode counters rather than silently
   vanishing from the compact trace.
5. The wide-probe masking surface (``sim/jax_memo.py``, ISSUE 17): the
   batched memo probe masks hit lanes out of the lookahead while_loop
   through the entry point + keyword the memo declares in
   ``WIDE_PROBE_SURFACE`` — the named function must still exist in
   ``sim/jax_lookahead.py`` with the named parameter, and
   ``sim/jax_env.py`` must still forward that keyword at a call site.
   Losing the forward would not fail any parity test (an unmasked
   probe is correct, just inert) — it would silently re-run the full
   while_loop on every memo-hit lane.
6. The scenario failure-event vocabulary (``scenarios/failures.py``,
   ISSUE 16): the ``FAILURE_*`` kind codes pairwise distinct,
   ``FAILURE_KIND_TO_EVENT`` a bijection over them, and every event
   string present in BOTH backend vocabularies — the flight recorder's
   ``EVENT_KINDS`` tuple (``telemetry/flight.py``) and a string literal
   at the host emission site (``sim/cluster.py``) — so a failure-kind
   rename cannot leave one backend emitting events the other side
   filters out.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from ddls_tpu.lint.core import Context, Finding, Rule, SourceFile

DEFAULT_PATHS = {
    "jax_env": "ddls_tpu/sim/jax_env.py",
    "ppo_device": "ddls_tpu/rl/ppo_device.py",
    "rollout": "ddls_tpu/rl/rollout.py",
    "jax_memo": "ddls_tpu/sim/jax_memo.py",
    "jax_lookahead": "ddls_tpu/sim/jax_lookahead.py",
    "failures": "ddls_tpu/scenarios/failures.py",
    "flight": "ddls_tpu/telemetry/flight.py",
    "host_cause_files": ["ddls_tpu/sim/cluster.py",
                         "ddls_tpu/sim/actions.py"],
}
DEFAULT_JITTED_ONLY = ["engine_failure"]


def _get_sf(ctx: Context, rel: str) -> Optional[SourceFile]:
    """The shared parsed file; files outside the scanned roots are parsed
    at most once here and cached into the context."""
    sf = ctx.get(rel)
    if sf is not None:
        return sf
    path = os.path.join(ctx.repo_root, rel)
    if not os.path.exists(path):
        return None
    sf = SourceFile(path, rel.replace(os.sep, "/"))
    ctx.files[sf.rel] = sf
    return sf


def _str_constants(tree: ast.AST) -> Set[str]:
    """String literals in CODE positions — docstrings and bare prose
    statements are skipped, so a cause word surviving only in a
    docstring cannot keep the drift check green."""
    out: Set[str] = set()
    stack = [tree]
    while stack:
        node = stack.pop()
        if (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _function(tree: ast.Module, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


class BackendSurfaceParityRule(Rule):
    id = "backend-surface-parity"
    pointer = ("host and jitted decision surfaces move TOGETHER "
               "(CLAUDE.md: any semantic change lands in all backends): "
               "keep CAUSE_CODE_TO_STR bijective over the CAUSE_* "
               "constants, host cause strings in sim/cluster.py//"
               "sim/actions.py, make_segment_fn's ep_* trace keys in "
               "sync with rl/ppo_device.py + rollout.py's "
               "harvest_episode_record keys, scenarios/failures.py's "
               "FAILURE_KIND_TO_EVENT events in flight EVENT_KINDS + "
               "cluster.py literals, and jax_memo's WIDE_PROBE_SURFACE "
               "bound to sim/jax_lookahead.py + forwarded by jax_env.py")
    scope_dirs = ()  # tree-level rule: no per-file pass

    def in_scope(self, rel: str) -> bool:
        return False

    # ------------------------------------------------------------- helpers
    def _paths(self, ctx: Context) -> Dict[str, object]:
        cfg = ctx.config.rule(self.id)
        paths = dict(DEFAULT_PATHS)
        paths.update({k: cfg[k] for k in DEFAULT_PATHS if k in cfg})
        return paths

    def check_tree(self, ctx: Context) -> List[Finding]:
        paths = self._paths(ctx)
        jitted_only = set(ctx.config.rule(self.id).get(
            "jitted_only_causes", DEFAULT_JITTED_ONLY))
        findings: List[Finding] = []

        jax_env = _get_sf(ctx, str(paths["jax_env"]))
        ppo_device = _get_sf(ctx, str(paths["ppo_device"]))
        rollout = _get_sf(ctx, str(paths["rollout"]))
        jax_memo = _get_sf(ctx, str(paths["jax_memo"]))
        jax_lookahead = _get_sf(ctx, str(paths["jax_lookahead"]))
        failures = _get_sf(ctx, str(paths["failures"]))
        flight = _get_sf(ctx, str(paths["flight"]))
        host_files = [_get_sf(ctx, str(p))
                      for p in paths["host_cause_files"]]
        for rel, sf in ([(paths["jax_env"], jax_env),
                         (paths["ppo_device"], ppo_device),
                         (paths["rollout"], rollout),
                         (paths["jax_memo"], jax_memo),
                         (paths["jax_lookahead"], jax_lookahead),
                         (paths["failures"], failures),
                         (paths["flight"], flight)]
                        + list(zip(paths["host_cause_files"],
                                   host_files))):
            if sf is None or sf.tree is None:
                findings.append(Finding(
                    self.id, "pyproject.toml", 1,
                    f"backend-surface-parity cannot read {rel!r} — fix "
                    "the [tool.ddls_lint.backend-surface-parity] path"))
        if any(sf is None or sf.tree is None
               for sf in (jax_env, ppo_device, rollout)):
            return findings

        if all(sf is not None and sf.tree is not None
               for sf in host_files):
            # a missing host file is already a finding above; comparing
            # against half the host vocabulary would add spurious
            # drift noise on top
            findings.extend(self._check_cause_tables(
                jax_env, list(host_files), jitted_only))
        findings.extend(self._check_episode_fields(
            jax_env, ppo_device, rollout))
        if (jax_memo is not None and jax_memo.tree is not None
                and host_files and host_files[0] is not None
                and host_files[0].tree is not None):
            findings.extend(self._check_memo_surface(
                jax_memo, host_files[0], jax_env))
        if (jax_memo is not None and jax_memo.tree is not None
                and jax_lookahead is not None
                and jax_lookahead.tree is not None):
            findings.extend(self._check_wide_probe_surface(
                jax_memo, jax_lookahead, jax_env))
        if all(sf is not None and sf.tree is not None
               for sf in (failures, flight)) \
                and host_files and host_files[0] is not None \
                and host_files[0].tree is not None:
            findings.extend(self._check_failure_surface(
                failures, flight, host_files[0]))
        return findings

    # --------------------------------------------------------- cause codes
    def _check_cause_tables(self, jax_env: SourceFile,
                            host_files: List[SourceFile],
                            jitted_only: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        constants: Dict[str, int] = {}
        table: Dict[str, object] = {}
        table_line = 1
        aliases: Dict[str, int] = {}
        for node in jax_env.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if (isinstance(target, ast.Name)
                    and target.id.startswith("CAUSE_")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)):
                constants[target.id] = node.value.value
            elif (isinstance(target, ast.Name)
                  and target.id == "CAUSE_CODE_TO_STR"
                  and isinstance(node.value, ast.Dict)):
                table_line = node.lineno
                for k, v in zip(node.value.keys, node.value.values):
                    kname = (k.id if isinstance(k, ast.Name) else
                             ast.unparse(k))
                    table[kname] = (v.value if isinstance(v, ast.Constant)
                                    else ast.unparse(v))
            elif (isinstance(target, ast.Subscript)
                  and isinstance(target.value, ast.Name)
                  and target.value.id == "CAUSE_STR_TO_CODE"
                  and isinstance(target.slice, ast.Constant)):
                aliases[str(target.slice.value)] = node.lineno

        if not constants or not table:
            findings.append(Finding(
                self.id, jax_env.rel, 1,
                "could not locate the CAUSE_* constants / "
                "CAUSE_CODE_TO_STR table — the jitted cause-code surface "
                "moved; update backend-surface-parity"))
            return findings

        values = sorted(constants.values())
        if len(set(values)) != len(values):
            findings.append(Finding(
                self.id, jax_env.rel, table_line,
                f"CAUSE_* constants are not pairwise distinct: "
                f"{constants}"))
        missing = sorted(set(constants) - set(table))
        extra = sorted(set(table) - set(constants))
        if missing or extra:
            findings.append(Finding(
                self.id, jax_env.rel, table_line,
                f"CAUSE_CODE_TO_STR is not a bijection over the CAUSE_* "
                f"constants (missing {missing}, unknown {extra})"))
        strings = [v for v in table.values() if isinstance(v, str)]
        dupes = sorted({s for s in strings if strings.count(s) > 1})
        if dupes:
            findings.append(Finding(
                self.id, jax_env.rel, table_line,
                f"CAUSE_CODE_TO_STR string values are not unique "
                f"(duplicated: {dupes}) — the str->code inverse is "
                "ambiguous"))

        host_strings: Set[str] = set()
        for sf in host_files:
            host_strings |= _str_constants(sf.tree)
        for cause in sorted((set(strings) | set(aliases)) - jitted_only):
            if cause not in host_strings:
                findings.append(Finding(
                    self.id, jax_env.rel,
                    aliases.get(cause, table_line),
                    f"jitted cause string {cause!r} does not exist on "
                    "the host side (sim/cluster.py / sim/actions.py) — "
                    "host and jitted cause vocabularies drifted"))
        return findings

    # --------------------------------------------------- memo key surface
    def _check_memo_surface(self, jax_memo: SourceFile,
                            cluster: SourceFile,
                            jax_env: SourceFile) -> List[Finding]:
        """The in-kernel memo key contract (sim/jax_memo.py): the host
        key builders it declares in ``HOST_KEY_SURFACE`` must still be
        functions in sim/cluster.py, and its ``MEMO_TRACE_KEYS`` must be
        traced by ``make_segment_fn`` so they drain with the episode
        counters."""
        findings: List[Finding] = []
        tables: Dict[str, List[str]] = {}
        lines: Dict[str, int] = {}
        for node in jax_memo.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if (isinstance(target, ast.Name)
                    and target.id in ("HOST_KEY_SURFACE",
                                      "MEMO_TRACE_KEYS")
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                vals = [e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
                tables[target.id] = vals
                lines[target.id] = node.lineno
        for name in ("HOST_KEY_SURFACE", "MEMO_TRACE_KEYS"):
            if name not in tables:
                findings.append(Finding(
                    self.id, jax_memo.rel, 1,
                    f"could not locate the {name} tuple — the in-kernel "
                    "memo key surface moved; update "
                    "backend-surface-parity"))
        host_fns = {node.name for node in ast.walk(cluster.tree)
                    if isinstance(node, ast.FunctionDef)}
        for fn in tables.get("HOST_KEY_SURFACE", ()):
            if fn not in host_fns:
                findings.append(Finding(
                    self.id, jax_memo.rel,
                    lines.get("HOST_KEY_SURFACE", 1),
                    f"memo HOST_KEY_SURFACE names {fn!r} but no such "
                    f"function exists in {cluster.rel} — the host memo-"
                    "key builders moved without the in-kernel mirror"))
        # the segment kernel emits the counters through
        # memo_trace_counters (ONE naming home), so the traced
        # vocabulary is make_segment_fn's literals plus that helper's
        segment_fn = _function(jax_env.tree, "make_segment_fn")
        traced = (_str_constants(segment_fn)
                  if segment_fn is not None else set())
        emitter = _function(jax_memo.tree, "memo_trace_counters")
        if emitter is not None:
            traced |= _str_constants(emitter)
        for key in tables.get("MEMO_TRACE_KEYS", ()):
            if key not in traced:
                findings.append(Finding(
                    self.id, jax_memo.rel,
                    lines.get("MEMO_TRACE_KEYS", 1),
                    f"memo counter key {key!r} is not traced by "
                    "make_segment_fn (nor emitted by "
                    "memo_trace_counters) — memo counters would not "
                    "drain with the episode counters"))
        return findings

    # ------------------------------------------------ wide-probe surface
    def _check_wide_probe_surface(self, jax_memo: SourceFile,
                                  jax_lookahead: SourceFile,
                                  jax_env: Optional[SourceFile],
                                  ) -> List[Finding]:
        """The batched memo probe's hit-lane masking contract (ISSUE
        17): ``WIDE_PROBE_SURFACE = (entry_fn, keyword)`` in
        sim/jax_memo.py names the lookahead entry point and the masking
        keyword — the function must still exist in sim/jax_lookahead.py
        with that parameter, and sim/jax_env.py must still forward the
        keyword at a call site. An unmasked probe is CORRECT but inert
        (both memo branches run under vmap), so no parity test catches
        the drift — only this surface check does."""
        findings: List[Finding] = []
        surface: List[str] = []
        line = 1
        for node in jax_memo.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if (isinstance(target, ast.Name)
                    and target.id == "WIDE_PROBE_SURFACE"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                surface = [e.value for e in node.value.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, str)]
                line = node.lineno
        if len(surface) != 2:
            return [Finding(
                self.id, jax_memo.rel, line,
                "could not locate the WIDE_PROBE_SURFACE (entry_fn, "
                "keyword) tuple — the wide-probe masking surface moved; "
                "update backend-surface-parity")]
        fn_name, kw_name = surface

        fn = _function(jax_lookahead.tree, fn_name)
        if fn is None:
            findings.append(Finding(
                self.id, jax_memo.rel, line,
                f"WIDE_PROBE_SURFACE names {fn_name!r} but no such "
                f"function exists in {jax_lookahead.rel} — the masked "
                "lookahead entry point moved without the memo mirror"))
        else:
            params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs
                                      + fn.args.posonlyargs)}
            if kw_name not in params:
                findings.append(Finding(
                    self.id, jax_lookahead.rel, fn.lineno,
                    f"{fn_name}() has no {kw_name!r} parameter — the "
                    "batched memo probe's hit-lane mask "
                    "(WIDE_PROBE_SURFACE) has nothing to bind to"))

        if jax_env is None or jax_env.tree is None:
            return findings
        forwarded = False
        for node in ast.walk(jax_env.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            name = (callee.id if isinstance(callee, ast.Name)
                    else callee.attr if isinstance(callee, ast.Attribute)
                    else None)
            if name == fn_name and any(k.arg == kw_name
                                       for k in node.keywords):
                forwarded = True
                break
        if not forwarded:
            findings.append(Finding(
                self.id, jax_env.rel, 1,
                f"no call to {fn_name}() in {jax_env.rel} forwards "
                f"{kw_name}= — memo-hit lanes would re-run the full "
                "lookahead while_loop (correct but inert; the wide "
                "probe's masking is the speedup)"))
        return findings

    # ------------------------------------------------- failure vocabulary
    def _check_failure_surface(self, failures: SourceFile,
                               flight: SourceFile,
                               cluster: SourceFile) -> List[Finding]:
        """The scenario failure-event contract (scenarios/failures.py):
        FAILURE_* kind codes pairwise distinct, FAILURE_KIND_TO_EVENT a
        bijection over them, and every event string present in BOTH
        backend vocabularies — the flight recorder's EVENT_KINDS tuple
        and a string literal at the host emission site (sim/cluster.py),
        where the lint contract requires LITERAL kinds."""
        findings: List[Finding] = []
        constants: Dict[str, int] = {}
        table: Dict[str, object] = {}
        table_line = 1
        for node in failures.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            if (target.id.startswith("FAILURE_")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)):
                constants[target.id] = node.value.value
            elif (target.id == "FAILURE_KIND_TO_EVENT"
                  and isinstance(node.value, ast.Dict)):
                table_line = node.lineno
                for k, v in zip(node.value.keys, node.value.values):
                    kname = (k.id if isinstance(k, ast.Name) else
                             ast.unparse(k))
                    table[kname] = (v.value if isinstance(v, ast.Constant)
                                    else ast.unparse(v))
        if not constants or not table:
            return [Finding(
                self.id, failures.rel, 1,
                "could not locate the FAILURE_* constants / "
                "FAILURE_KIND_TO_EVENT table — the failure-event surface "
                "moved; update backend-surface-parity")]

        values = sorted(constants.values())
        if len(set(values)) != len(values):
            findings.append(Finding(
                self.id, failures.rel, table_line,
                f"FAILURE_* kind codes are not pairwise distinct: "
                f"{constants}"))
        missing = sorted(set(constants) - set(table))
        extra = sorted(set(table) - set(constants))
        if missing or extra:
            findings.append(Finding(
                self.id, failures.rel, table_line,
                f"FAILURE_KIND_TO_EVENT is not a bijection over the "
                f"FAILURE_* kind codes (missing {missing}, "
                f"unknown {extra})"))
        events = [v for v in table.values() if isinstance(v, str)]
        dupes = sorted({e for e in events if events.count(e) > 1})
        if dupes:
            findings.append(Finding(
                self.id, failures.rel, table_line,
                f"FAILURE_KIND_TO_EVENT event strings are not unique "
                f"(duplicated: {dupes}) — the event->kind inverse is "
                "ambiguous"))

        kinds: Set[str] = set()
        for node in flight.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "EVENT_KINDS"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                kinds = {e.value for e in node.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)}
        if not kinds:
            findings.append(Finding(
                self.id, flight.rel, 1,
                "could not locate the EVENT_KINDS tuple — the flight "
                "event vocabulary moved; update backend-surface-parity"))
        host_strings = _str_constants(cluster.tree)
        for event in sorted(set(events)):
            if kinds and event not in kinds:
                findings.append(Finding(
                    self.id, failures.rel, table_line,
                    f"failure event {event!r} is not in the flight "
                    f"recorder's EVENT_KINDS ({flight.rel}) — the "
                    "recorder would drop it at load/validate time"))
            if event not in host_strings:
                findings.append(Finding(
                    self.id, failures.rel, table_line,
                    f"failure event {event!r} is never a string literal "
                    f"in {cluster.rel} — no host emission site (the "
                    "flight-gated contract requires literal kinds at "
                    "the emit call)"))
        return findings

    # ----------------------------------------------------- episode fields
    def _check_episode_fields(self, jax_env: SourceFile,
                              ppo_device: SourceFile,
                              rollout: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        segment_fn = _function(jax_env.tree, "make_segment_fn")
        if segment_fn is None:
            return [Finding(
                self.id, jax_env.rel, 1,
                "make_segment_fn not found — the segment-trace surface "
                "moved; update backend-surface-parity")]
        traced = {k for k in _str_constants(segment_fn)
                  if k.startswith("ep_")}

        consumed = set()
        for node in ast.walk(ppo_device.tree):
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and node.slice.value.startswith("ep_")):
                consumed.add(node.slice.value)
        for key in sorted(consumed - traced):
            findings.append(Finding(
                self.id, ppo_device.rel, 1,
                f"device collector consumes trace[{key!r}] but "
                "make_segment_fn does not trace it — episode-counter "
                "fields drifted"))

        harvest = _function(rollout.tree, "harvest_episode_record")
        if harvest is None:
            return findings + [Finding(
                self.id, rollout.rel, 1,
                "harvest_episode_record not found — the host episode-"
                "record surface moved; update backend-surface-parity")]
        host_keys = _str_constants(harvest) | {
            f"mean_{k}" for k in _str_constants(harvest)}
        device_harvest = _function(ppo_device.tree, "_harvest_episodes")
        if device_harvest is not None:
            for node in ast.walk(device_harvest):
                if not isinstance(node, ast.Dict):
                    continue
                for k in node.keys:
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            and k.value not in host_keys):
                        findings.append(Finding(
                            self.id, ppo_device.rel, k.lineno,
                            f"device episode record key {k.value!r} is "
                            "not a host harvest_episode_record key — "
                            "device/host episode records drifted"))
        return findings
