"""frozen-param-tree: model ``setup()`` attribute names are frozen by
the shipped checkpoints.

Flax param-tree paths are the ``setup()`` attribute names (CLAUDE.md:
``gnn``/``graph_module``/``logit_head``/``value_head`` for
``GNNPolicy``); renaming one — or adding a head — silently orphans every
shipped checkpoint at restore time. Each class in ``ddls_tpu/models/``
that defines ``setup()`` must have a frozen-name entry in
``[tool.ddls_lint.frozen-param-tree.classes]`` (``"path::Class" =
["name", ...]``), and its self-assignments must match that list EXACTLY:
a new class or a changed name set fails lint until the config entry is
deliberately updated — which is the checkpoint-compatibility review this
rule exists to force.
"""
from __future__ import annotations

import ast
from typing import Dict, List

from ddls_tpu.lint.core import Context, Finding, Rule, SourceFile


def _setup_assigned_names(setup: ast.FunctionDef) -> Dict[str, int]:
    """``self.<name> = ...`` targets in a setup() body -> first line."""
    names: Dict[str, int] = {}
    for node in ast.walk(setup):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                names.setdefault(t.attr, t.lineno)
    return names


class FrozenParamTreeRule(Rule):
    id = "frozen-param-tree"
    pointer = ("setup() attribute names ARE the checkpoint param-tree "
               "paths — keep them equal to the frozen list in "
               "[tool.ddls_lint.frozen-param-tree.classes]; changing "
               "them means every shipped checkpoint must be migrated "
               "(CLAUDE.md batched_policy_apply invariant)")
    scope_dirs = ("ddls_tpu/models/",)

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        if sf.tree is None or "def setup" not in sf.text:
            return []
        classes = ctx.config.rule(self.id).get("classes", {})
        findings = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            setup = next((n for n in node.body
                          if isinstance(n, ast.FunctionDef)
                          and n.name == "setup"), None)
            if setup is None:
                continue
            key = f"{sf.rel}::{node.name}"
            frozen = classes.get(key)
            if frozen is None:
                findings.append(Finding(
                    self.id, sf.rel, setup.lineno,
                    f"{node.name}.setup() has no frozen-param-tree "
                    f"entry — add '{key}' to [tool.ddls_lint."
                    "frozen-param-tree.classes] (its attribute names "
                    "freeze the checkpoint param-tree paths)"))
                continue
            assigned = _setup_assigned_names(setup)
            extra = sorted(set(assigned) - set(frozen))
            missing = sorted(set(frozen) - set(assigned))
            if extra or missing:
                detail = []
                if extra:
                    detail.append(f"unexpected {extra}")
                if missing:
                    detail.append(f"missing {missing}")
                findings.append(Finding(
                    self.id, sf.rel,
                    min(assigned.values(), default=setup.lineno),
                    f"{node.name}.setup() attribute names drifted from "
                    f"the frozen param-tree list: {'; '.join(detail)} "
                    f"(frozen: {sorted(frozen)})"))
        findings.sort(key=lambda f: f.line)
        return findings

    def check_tree(self, ctx: Context) -> List[Finding]:
        return self.validate_allow_keys(
            ctx, ctx.config.rule(self.id).get("classes", {}),
            want_qualname=True, table=".classes", entity="class")
