"""frozen-param-tree: model ``setup()`` attribute names are frozen by
the shipped checkpoints.

Flax param-tree paths are the ``setup()`` attribute names (CLAUDE.md:
``gnn``/``graph_module``/``logit_head``/``value_head`` for
``GNNPolicy``); renaming one — or adding a head — silently orphans every
shipped checkpoint at restore time. Each class in ``ddls_tpu/models/``
that defines ``setup()`` must have a frozen-name entry in
``[tool.ddls_lint.frozen-param-tree.classes]`` (``"path::Class" =
["name", ...]``), and its self-assignments must match that list EXACTLY:
a new class or a changed name set fails lint until the config entry is
deliberately updated — which is the checkpoint-compatibility review this
rule exists to force.

The partition-rule table (``ddls_tpu/parallel/partition.py``) is the
other face of the same contract: its regexes NAME the frozen param-tree
paths, so a renamed module or a typo'd rule silently stops sharding
what it claims to shard. Any module assigning ``PARTITION_RULES`` is
cross-validated against its ``CANONICAL_PARAM_PATHS`` literal, purely
from the AST (the lint engine never imports linted code): every rule
regex must match >= 1 canonical path (a stale rule is an error), every
canonical path must match some rule of every layout (placement is
exhaustive by construction — ``match_partition_rules`` raises at
runtime; lint catches it first), and every ``LARGE_KERNEL_PATHS`` entry
must FIRST-match a rule that actually names a mesh axis in each
non-replicated layout (an uncovered large leaf would silently
replicate the very kernels the layout exists to shard).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from ddls_tpu.lint.core import Context, Finding, Rule, SourceFile


def _setup_assigned_names(setup: ast.FunctionDef) -> Dict[str, int]:
    """``self.<name> = ...`` targets in a setup() body -> first line."""
    names: Dict[str, int] = {}
    for node in ast.walk(setup):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                names.setdefault(t.attr, t.lineno)
    return names


def _top_level_nodes(tree: ast.Module) -> Dict[str, ast.AST]:
    """Top-level single-Name assignments -> their value node."""
    out: Dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.value
    return out


def _const_str(node: ast.AST, env: Dict[str, str]) -> Optional[str]:
    """String value of a literal or a module-level str-constant Name."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    return None


def _str_tuple(node: ast.AST, env: Dict[str, str]) -> Optional[List[str]]:
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for el in node.elts:
        s = _const_str(el, env)
        if s is None:
            return None
        out.append(s)
    return out


def _spec_names_axis(node: ast.AST) -> bool:
    """True when a ``P(...)``/``PartitionSpec(...)`` call literal names at
    least one real mesh axis (a non-None positional arg) — i.e. the rule
    actually SHARDS rather than replicates."""
    if not isinstance(node, ast.Call):
        return False
    for arg in node.args:
        if not (isinstance(arg, ast.Constant) and arg.value is None):
            return True
    return False


class FrozenParamTreeRule(Rule):
    id = "frozen-param-tree"
    pointer = ("setup() attribute names ARE the checkpoint param-tree "
               "paths — keep them equal to the frozen list in "
               "[tool.ddls_lint.frozen-param-tree.classes]; changing "
               "them means every shipped checkpoint must be migrated "
               "(CLAUDE.md batched_policy_apply invariant); the "
               "partition-rule table in parallel/partition.py must name "
               "those same paths (stale rule / uncovered large leaf = "
               "error)")
    scope_dirs = ("ddls_tpu/models/", "ddls_tpu/parallel/")

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        if sf.tree is None:
            return []
        findings: List[Finding] = []
        if "PARTITION_RULES" in sf.text:
            findings += self._check_partition_table(sf)
        if "def setup" not in sf.text:
            findings.sort(key=lambda f: f.line)
            return findings
        classes = ctx.config.rule(self.id).get("classes", {})
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            setup = next((n for n in node.body
                          if isinstance(n, ast.FunctionDef)
                          and n.name == "setup"), None)
            if setup is None:
                continue
            key = f"{sf.rel}::{node.name}"
            frozen = classes.get(key)
            if frozen is None:
                findings.append(Finding(
                    self.id, sf.rel, setup.lineno,
                    f"{node.name}.setup() has no frozen-param-tree "
                    f"entry — add '{key}' to [tool.ddls_lint."
                    "frozen-param-tree.classes] (its attribute names "
                    "freeze the checkpoint param-tree paths)"))
                continue
            assigned = _setup_assigned_names(setup)
            extra = sorted(set(assigned) - set(frozen))
            missing = sorted(set(frozen) - set(assigned))
            if extra or missing:
                detail = []
                if extra:
                    detail.append(f"unexpected {extra}")
                if missing:
                    detail.append(f"missing {missing}")
                findings.append(Finding(
                    self.id, sf.rel,
                    min(assigned.values(), default=setup.lineno),
                    f"{node.name}.setup() attribute names drifted from "
                    f"the frozen param-tree list: {'; '.join(detail)} "
                    f"(frozen: {sorted(frozen)})"))
        findings.sort(key=lambda f: f.line)
        return findings

    def check_tree(self, ctx: Context) -> List[Finding]:
        return self.validate_allow_keys(
            ctx, ctx.config.rule(self.id).get("classes", {}),
            want_qualname=True, table=".classes", entity="class")

    def _check_partition_table(self, sf: SourceFile) -> List[Finding]:
        """AST cross-validation of a module's ``PARTITION_RULES`` against
        its ``CANONICAL_PARAM_PATHS``/``LARGE_KERNEL_PATHS`` literals
        (parallel/partition.py) — no import of the linted module."""
        top = _top_level_nodes(sf.tree)
        rules_node = top.get("PARTITION_RULES")
        if not isinstance(rules_node, ast.Dict):
            return []
        # module-level str constants (FSDP_AXIS/TP_AXIS) for Name refs
        env = {name: node.value for name, node in top.items()
               if isinstance(node, ast.Constant)
               and isinstance(node.value, str)}
        findings: List[Finding] = []
        paths = _str_tuple(top.get("CANONICAL_PARAM_PATHS",
                                   ast.Constant(value=None)), env)
        if paths is None:
            return [Finding(
                self.id, sf.rel, rules_node.lineno,
                "PARTITION_RULES without a literal CANONICAL_PARAM_PATHS "
                "tuple — the rule table cannot be cross-validated "
                "against the frozen param-tree paths")]
        large = _str_tuple(top.get("LARGE_KERNEL_PATHS",
                                   ast.Constant(value=None)), env) or []
        for lk in large:
            if lk not in paths:
                findings.append(Finding(
                    self.id, sf.rel, rules_node.lineno,
                    f"LARGE_KERNEL_PATHS entry '{lk}' is not a "
                    "CANONICAL_PARAM_PATHS member — stale path (renamed "
                    "module?)"))
        for key_node, val_node in zip(rules_node.keys, rules_node.values):
            layout = _const_str(key_node, env)
            if layout is None or not isinstance(val_node,
                                                (ast.Tuple, ast.List)):
                continue
            rules = []  # (lineno, regex, names_axis) in table order
            for el in val_node.elts:
                if not (isinstance(el, (ast.Tuple, ast.List))
                        and len(el.elts) == 2):
                    continue
                pat = _const_str(el.elts[0], env)
                if pat is None:
                    continue
                try:
                    rx = re.compile(pat)
                except re.error as e:
                    findings.append(Finding(
                        self.id, sf.rel, el.lineno,
                        f"PARTITION_RULES[{layout!r}] regex {pat!r} does "
                        f"not compile: {e}"))
                    continue
                rules.append((el.lineno, pat, rx,
                              _spec_names_axis(el.elts[1])))
            for lineno, pat, rx, _ in rules:
                if not any(rx.search(p) for p in paths):
                    findings.append(Finding(
                        self.id, sf.rel, lineno,
                        f"PARTITION_RULES[{layout!r}] rule {pat!r} "
                        "matches no CANONICAL_PARAM_PATHS entry — stale "
                        "rule (param-tree path renamed or typo'd regex)"))
            for p in paths:
                first = next((r for r in rules if r[2].search(p)), None)
                if first is None:
                    findings.append(Finding(
                        self.id, sf.rel, rules_node.lineno,
                        f"PARTITION_RULES[{layout!r}] covers no rule for "
                        f"canonical path '{p}' — match_partition_rules "
                        "would raise at runtime; add a rule (or a "
                        "replicate-P() fallback)"))
                elif layout != "replicated" and p in large \
                        and not first[3]:
                    findings.append(Finding(
                        self.id, sf.rel, first[0],
                        f"PARTITION_RULES[{layout!r}]: large kernel "
                        f"'{p}' first-matches the replicate rule "
                        f"{first[1]!r} — the layout silently leaves its "
                        "biggest leaf unsharded; order a sharding rule "
                        "(P with a mesh axis) ahead of it"))
        return findings
