"""flow-mask: never re-inline the flow-ness predicate.

Design invariant (CLAUDE.md, docs/perf_round3.md): a dep is a *flow* iff
its size is nonzero AND its endpoints sit on different servers — and that
predicate has exactly one home, ``OpGraph.flow_mask`` /
``flow_mask_from_codes`` (graphs/op_graph.py), so the host engine, the
C++ engine, the packers, and the dep placer can never disagree on
flow-ness. A re-inlined copy drifts silently the day the canonical
definition changes.

Mechanics: outside the defining module, flag any single boolean
expression (``and`` / ``&`` chain) that combines a ``<something
size-ish> > 0`` comparison with a ``!=`` comparison — the predicate's
structural fingerprint. The one sanctioned re-statement (the traced
mirror inside the jitted env, which cannot call the numpy helper under
trace) carries an inline suppression with its reason.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from ddls_tpu.lint.core import Context, Finding, Rule, SourceFile

DEFINING_MODULE = "ddls_tpu/graphs/op_graph.py"


def _bool_chain(node: ast.AST) -> Iterator[ast.AST]:
    """Flatten an ``and``/``&`` chain into its comparison leaves."""
    if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
        for value in node.values:
            yield from _bool_chain(value)
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitAnd):
        yield from _bool_chain(node.left)
        yield from _bool_chain(node.right)
    else:
        yield node


def _mentions_size(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "size" in sub.id:
            return True
        if isinstance(sub, ast.Attribute) and "size" in sub.attr:
            return True
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and "size" in sub.value):
            return True
    return False


def _is_size_gt_zero(leaf: ast.AST) -> bool:
    if not (isinstance(leaf, ast.Compare) and len(leaf.ops) == 1):
        return False
    op, right = leaf.ops[0], leaf.comparators[0]
    if (isinstance(op, ast.Gt) and isinstance(right, ast.Constant)
            and right.value == 0):
        return _mentions_size(leaf.left)
    if (isinstance(op, ast.Lt) and isinstance(leaf.left, ast.Constant)
            and leaf.left.value == 0):
        return _mentions_size(right)
    return False


def _is_noteq(leaf: ast.AST) -> bool:
    return (isinstance(leaf, ast.Compare) and len(leaf.ops) == 1
            and isinstance(leaf.ops[0], ast.NotEq))


class FlowMaskRule(Rule):
    id = "flow-mask"
    pointer = ("flow-ness has one home: OpGraph.flow_mask / "
               "flow_mask_from_codes (graphs/op_graph.py) — build the "
               "per-op server codes and index the returned mask instead "
               "of re-stating `size > 0 and src_server != dst_server` "
               "(see cluster.py _register_running_job for the idiom)")
    scope_dirs = None  # the whole package

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        defining = ctx.config.rule(self.id).get("defining_module",
                                                DEFINING_MODULE)
        if sf.rel == defining or sf.tree is None:
            return []
        findings = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.BoolOp, ast.BinOp)):
                continue
            # only inspect chain ROOTS (a parent BoolOp/BinOp already
            # covered its nested parts)
            leaves = list(_bool_chain(node))
            if len(leaves) < 2:
                continue
            if (any(_is_size_gt_zero(l) for l in leaves)
                    and any(_is_noteq(l) for l in leaves)):
                findings.append(Finding(
                    self.id, sf.rel, node.lineno,
                    "re-inlined flow predicate (`size > 0` AND `!=` in "
                    "one boolean chain) — route through "
                    "OpGraph.flow_mask/flow_mask_from_codes"))
        # a nested BinOp inside a flagged root would double-report the
        # same expression: dedupe by line
        seen = set()
        unique = []
        for f in sorted(findings, key=lambda f: f.line):
            if f.line not in seen:
                seen.add(f.line)
                unique.append(f)
        return unique
