"""socket-lifecycle: every listening/accepted/connected socket must keep
its paired close + crash-path finalizer.

The shm-unlink contract, extended to the fragment transport
(ddls_tpu/rl/fragments.py, docs/perf_round14.md): a learner that binds a
Unix-domain listener owns a filesystem path, N actor-host subprocesses,
and the fds between them — an interrupted run that never reaches
``close()`` would leak all three. Contract: a file that creates sockets
(``socket.socket(``, ``create_connection(``, or ``.accept(``) must also
carry a ``.close(`` call AND a ``weakref.finalize``/``atexit`` fallback.
Pure ``import socket`` uses (e.g. ``socket.gethostname()`` in
telemetry/runlog.py) create nothing and are not flagged. Deliberate
externally-owned sockets go in
``[tool.ddls_lint.socket-lifecycle.allow]`` with a why-comment.
"""
from __future__ import annotations

import re
from typing import List

from ddls_tpu.lint.core import Context, Finding, Rule, SourceFile

_CREATE_RE = re.compile(
    r"socket\s*\.\s*socket\s*\(|create_connection\s*\(|\.accept\s*\(")


class SocketLifecycleRule(Rule):
    id = "socket-lifecycle"
    pointer = ("pair every socket.socket()/create_connection()/.accept() "
               "with a .close() on shutdown AND a weakref.finalize/atexit "
               "fallback (see ddls_tpu/rl/fragments.py LearnerFragment), "
               "or the fd/unix-socket path outlives a crashed run; "
               "deliberately externally-owned sockets go in "
               "[tool.ddls_lint.socket-lifecycle.allow] in pyproject.toml "
               "with a why-comment")
    scope_dirs = None  # the whole package

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        matches = list(_CREATE_RE.finditer(sf.text))
        if not matches:
            return []
        missing = []
        if ".close(" not in sf.text:
            missing.append("close")
        if ("weakref.finalize" not in sf.text
                and "atexit" not in sf.text):
            missing.append("finalizer (weakref.finalize/atexit)")
        if not missing:
            return []
        allow = ctx.config.rule(self.id).get("allow", {})
        allowed = self.int_allowance(allow, sf.rel)
        # same attribution contract as shm-unlink: suppressed creates
        # are excluded (and reported as their own suppressed findings);
        # when the rest exceed the allowance, every unsuppressed create
        # line is flagged — the allowance has no line identity
        lines = [sf.text.count("\n", 0, m.start()) + 1 for m in matches]
        suppressed = self.inline_suppressed_lines(sf)
        sup = [ln for ln in lines if ln in suppressed]
        unsup = [ln for ln in lines if ln not in suppressed]
        findings = [Finding(
            self.id, sf.rel, ln, "socket create "
            "(inline-suppressed)") for ln in sup]
        if len(unsup) > allowed:
            findings += [Finding(
                self.id, sf.rel, ln,
                f"socket create without leak-proof pairing "
                f"({len(unsup)} create(s) in file, allowance {allowed}), "
                f"missing {' + '.join(missing)}") for ln in unsup]
        return findings

    def check_tree(self, ctx: Context) -> List[Finding]:
        allow = ctx.config.rule(self.id).get("allow", {})
        return (self.validate_allow_keys(ctx, allow, want_int=True)
                + self.validate_count_allowances(
                    ctx, allow,
                    lambda sf: len(_CREATE_RE.findall(sf.text)),
                    "socket create"))
