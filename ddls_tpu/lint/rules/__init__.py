"""Rule registry: one import surface for every lint rule plugin.

Adding a rule = adding a module here with a ``Rule`` subclass and
listing an instance in ``ALL_RULES`` (docs/lint.md "Adding a rule").
Order is display order in the text report.
"""
from __future__ import annotations

from typing import Iterable, List, Optional

from ddls_tpu.lint.core import Rule
from ddls_tpu.lint.rules.backend_parity import BackendSurfaceParityRule
from ddls_tpu.lint.rules.bare_timers import BareTimersRule
from ddls_tpu.lint.rules.flight_gated import FlightGatedRule
from ddls_tpu.lint.rules.flow_mask import FlowMaskRule
from ddls_tpu.lint.rules.hot_path_transfer import HotPathTransferRule
from ddls_tpu.lint.rules.multihost_gates import MultihostGatesRule
from ddls_tpu.lint.rules.param_tree import FrozenParamTreeRule
from ddls_tpu.lint.rules.shm_unlink import ShmUnlinkRule
from ddls_tpu.lint.rules.socket_lifecycle import SocketLifecycleRule
from ddls_tpu.lint.rules.telemetry_gated import TelemetryGatedRule

#: the three ported tier-1 guards first, then the seven prose-invariant
#: rules (socket-lifecycle rides next to its shm-unlink sibling)
ALL_RULES: List[Rule] = [
    BareTimersRule(),
    FlightGatedRule(),
    ShmUnlinkRule(),
    SocketLifecycleRule(),
    HotPathTransferRule(),
    MultihostGatesRule(),
    TelemetryGatedRule(),
    FlowMaskRule(),
    FrozenParamTreeRule(),
    BackendSurfaceParityRule(),
]


def get_rules(ids: Optional[Iterable[str]] = None) -> List[Rule]:
    """The registered rules, optionally restricted to ``ids`` (what the
    legacy shims use); unknown ids raise so a typo cannot silently lint
    nothing."""
    if ids is None:
        return list(ALL_RULES)
    by_id = {r.id: r for r in ALL_RULES}
    unknown = sorted(set(ids) - set(by_id))
    if unknown:
        raise ValueError(
            f"unknown lint rule(s) {unknown}; available: {sorted(by_id)}")
    return [by_id[i] for i in ids]
