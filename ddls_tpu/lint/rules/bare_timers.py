"""bare-timers: flag ad-hoc ``time.perf_counter`` timing in ``ddls_tpu/``.

Port of ``scripts/check_no_bare_timers.py`` (now a shim over this rule).
The telemetry layer (docs/telemetry.md) is the one vocabulary for timing
evidence — ``t0 = time.perf_counter(); ...; dt = time.perf_counter() -
t0`` pairs produce numbers nothing can aggregate or ship to a sink. The
audited per-file occurrence allowance (clock *parameters* and control
decisions, never reporting) lives in ``[tool.ddls_lint.bare-timers.allow]``
in pyproject.toml, each entry with a why-comment — that review friction
is the point.
"""
from __future__ import annotations

from typing import List

from ddls_tpu.lint.core import Context, Finding, Rule, SourceFile

TOKEN = "perf_counter"


class BareTimersRule(Rule):
    id = "bare-timers"
    pointer = ("use `with telemetry.span(\"name\"): ...` "
               "(from ddls_tpu import telemetry; docs/telemetry.md) so "
               "the timing lands in snapshots, W&B, and JSONL sinks "
               "instead of a local variable; legitimate clock plumbing "
               "goes in [tool.ddls_lint.bare-timers.allow] in "
               "pyproject.toml with a why-comment")
    scope_dirs = None  # the whole package
    # timing evidence in tooling matters as much as in the package: new
    # scripts/ timers must ride telemetry.span too (ISSUE 18); only this
    # rule sees the scripts tree on a default run
    extra_roots = ("scripts",)

    def check_file(self, sf: SourceFile, ctx: Context) -> List[Finding]:
        occ_lines = [i for i, line in enumerate(sf.lines, start=1)
                     for _ in range(line.count(TOKEN))]
        if not occ_lines:
            return []
        allow = ctx.config.rule(self.id).get("allow", {})
        allowed = self.int_allowance(allow, sf.rel)
        # inline-suppressed occurrences are excluded from the count and
        # reported as their own (suppressed) findings; when the REST
        # exceed the allowance, EVERY unsuppressed line is flagged — a
        # count allowance has no line identity, so pointing at a subset
        # could name an audited occurrence instead of the new one
        suppressed = self.inline_suppressed_lines(sf)
        sup = [ln for ln in occ_lines if ln in suppressed]
        unsup = [ln for ln in occ_lines if ln not in suppressed]
        findings = [Finding(
            self.id, sf.rel, ln, "bare perf_counter timing "
            "(inline-suppressed occurrence)") for ln in sup]
        if len(unsup) > allowed:
            findings += [Finding(
                self.id, sf.rel, ln,
                f"bare perf_counter timing ({len(unsup)} occurrence(s) "
                f"in file, allowance {allowed} — remove the new timer "
                "or re-audit the allowance)") for ln in unsup]
        return findings

    def check_tree(self, ctx: Context) -> List[Finding]:
        allow = ctx.config.rule(self.id).get("allow", {})
        return (self.validate_allow_keys(ctx, allow, want_int=True)
                + self.validate_count_allowances(
                    ctx, allow, lambda sf: sf.text.count(TOKEN),
                    f"'{TOKEN}' occurrence"))
