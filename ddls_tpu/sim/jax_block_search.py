"""Jittable RAMP block search: the placement half of the HBM-resident
rollout north star.

The jax-lookahead go/no-go (docs/jax_lookahead_gonogo.md point 3) left
device-resident rollouts gated on one blocker: the first-fit block search
(`agents/block_search.py`), a sequential scan over shapes × origins with
per-cell dict lookups. This module is the array formulation of its inner
primitive: for a boolean free-server grid and a static list of candidate
block shapes, find the SAME (shape, origin) the host's
``first_fit_block`` returns — first valid in (shape order, then
lexicographic origin) — as a jittable, vmappable computation.

Design: a block of shape (dc, dr, ds) anchored at (i, j, k) is free iff
every cell of the window is free; the valid-anchor mask for one shape is
the AND of the grid rolled by every in-window offset (window volumes are
tiny — ≤ the cluster size — and shapes are static, so the rolls unroll at
trace time). First-fit order is recovered by ranking anchors
lexicographically and taking the minimum rank over valid anchors of the
first shape that has any. Regular (non-diagonal) blocks anchored inside
the meta shape never actually wrap (span = meta - shape + 1 bounds the
origin), matching ``enumerate_block``'s modulo arithmetic exactly. The
reference's diagonal S == -1 layout is handled by the full jitted placer
(`sim/jax_env.py` ShapeTables carries the diagonal shapes with their
wrap bases), which folded the per-op loop with parent-colocation
preferences and occupancy updates into a `lax.scan`
(`jax_env.jax_allocate_job`, parity-fuzzed in tests/test_jax_placer.py);
this module remains the search *primitive* that scan consumes.
"""
from __future__ import annotations

from functools import lru_cache
from typing import List, Sequence, Tuple

import numpy as np

Coord = Tuple[int, int, int]


def valid_anchor_mask(free, shape: Coord, meta_shape: Coord):
    """Boolean [C, R, S] grid of anchors where a ``shape`` block fits
    entirely on free servers, anchored inside ``meta_shape``."""
    import jax.numpy as jnp

    ok = free
    for dc in range(shape[0]):
        for dr in range(shape[1]):
            for ds in range(shape[2]):
                if dc == dr == ds == 0:
                    continue
                ok = ok & jnp.roll(free, shift=(-dc, -dr, -ds),
                                   axis=(0, 1, 2))
    C, R, S = free.shape
    span = (meta_shape[0] - shape[0] + 1, meta_shape[1] - shape[1] + 1,
            meta_shape[2] - shape[2] + 1)
    ii, jj, kk = jnp.meshgrid(jnp.arange(C), jnp.arange(R), jnp.arange(S),
                              indexing="ij")
    in_span = (ii < max(span[0], 0)) & (jj < max(span[1], 0)) \
        & (kk < max(span[2], 0))
    return ok & in_span


def first_fit_block_jax(free, shapes: Sequence[Coord], meta_shape: Coord):
    """(shape_idx, i, j, k, found) of the host ``first_fit_block`` result.

    ``free``: bool [C, R, S] (True = this server can host the op: no other
    job AND enough memory — the caller folds the memory check in, exactly
    like ``block_ok``'s per-server conjunction). ``shapes``/``meta_shape``
    are static. Fully jittable and vmappable over a batch of grids.
    """
    import jax.numpy as jnp

    C, R, S = free.shape
    n_cells = C * R * S
    big = n_cells + 1

    best_shape = jnp.int32(-1)
    best_rank = jnp.int32(big)
    found_any = jnp.bool_(False)
    ii, jj, kk = jnp.meshgrid(jnp.arange(C), jnp.arange(R), jnp.arange(S),
                              indexing="ij")
    lex_rank = (ii * (R * S) + jj * S + kk).astype(jnp.int32)

    for si, shape in enumerate(shapes):
        span_ok = (meta_shape[0] >= shape[0] and meta_shape[1] >= shape[1]
                   and meta_shape[2] >= shape[2])
        if not span_ok:
            continue
        mask = valid_anchor_mask(free, shape, meta_shape)
        any_valid = mask.any()
        rank = jnp.where(mask, lex_rank, big).min().astype(jnp.int32)
        take = any_valid & ~found_any
        best_shape = jnp.where(take, jnp.int32(si), best_shape)
        best_rank = jnp.where(take, rank, best_rank)
        found_any = found_any | any_valid

    i = best_rank // (R * S)
    j = (best_rank // S) % R
    k = best_rank % S
    return best_shape, i, j, k, found_any


@lru_cache(maxsize=None)
def jitted_first_fit(shapes: Tuple[Coord, ...], meta_shape: Coord):
    """jit-compiled closure over the static shape list; vmap over grids
    with ``jax.vmap`` for batched (multi-env) searches."""
    import jax

    return jax.jit(lambda free: first_fit_block_jax(free, shapes,
                                                    meta_shape))


def block_cells(shape: Coord, origin: Coord,
                ramp_shape: Coord) -> List[Coord]:
    """Servers covered by the found block — delegated to the host's
    ``enumerate_block`` so the geometry can never diverge from it."""
    from ddls_tpu.agents.block_search import enumerate_block

    return enumerate_block(shape, ramp_shape, origin)


def free_grid_from_ramp(ramp, ramp_shape: Coord, job_idx,
                        op_size=None) -> np.ndarray:
    """Fold ``block_ok``'s per-server conjunction into one boolean grid:
    free of other jobs AND (when ``op_size`` given) enough memory."""
    grid = np.zeros(ramp_shape, dtype=bool)
    for coord, entry in ramp.items():
        occupants = entry["job_idxs"]
        # exactly block_ok's test: blocked iff occupied by OTHER jobs
        ok = (not occupants) or (job_idx in occupants)
        if ok and op_size is not None:
            ok = entry["mem"] >= op_size
        grid[coord] = ok
    return grid
