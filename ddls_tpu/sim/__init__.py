from ddls_tpu.sim.comm_model import one_to_one_time, ramp_all_reduce_time
from ddls_tpu.sim.cluster import RampClusterEnvironment
from ddls_tpu.sim.legacy_cluster import ClusterEnvironment
from ddls_tpu.sim.actions import (Action, DepPlacement, DepSchedule,
                                  OpPartition, OpPlacement, OpSchedule)
from ddls_tpu.sim.partition import partition_graph, partitioned_op_id

__all__ = [
    "one_to_one_time", "ramp_all_reduce_time",
    "RampClusterEnvironment", "ClusterEnvironment",
    "Action", "OpPartition", "OpPlacement", "OpSchedule",
    "DepPlacement", "DepSchedule",
    "partition_graph", "partitioned_op_id",
]
