from ddls_tpu.sim.comm_model import (
    one_to_one_time,
    ramp_all_reduce_time,
)

__all__ = [
    "one_to_one_time",
    "ramp_all_reduce_time",
]
