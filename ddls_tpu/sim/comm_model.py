"""Analytical RAMP communication-time model.

The cost of a RAMP all-reduce is modeled as reduce-scatter + all-gather over a
hierarchy of subgroups -- communication groups, per-rack server ids, racks, and
ceil(servers / num_comm_groups) -- with per-step effective-transceiver
bandwidth, propagation + 2x IO latency, and a roofline parallel-add compute
term (memory frequency vs peak FLOPs). One-to-one transfers cost
latency + 2 x IO + size / rate.

This replicates the reference's formulas exactly
(ddls/environments/ramp_cluster/actions/utils.py:42-124), including its
quirks, because simulated JCTs (and hence RL rewards) derive from them:

* the per-transceiver data rate is the *channel* bandwidth (already
  ``total / x``) divided by ``x`` again (actions/utils.py:62 with the
  call-site passing ``cluster.topology.channel_bandwidth`` at :141);
* ``cont_racks`` is effectively always 1: the reference derives rack/cg ids
  from the server id, so the conflict test can never fire
  (actions/utils.py:221-232);
* the hierarchy sizes are counts of *distinct* cg ids, rack ids, and
  server-within-rack ids used by the collective.

Everything here is a pure scalar function -- trivially jittable/vmappable if a
JAX-resident environment needs it (``jnp`` works through these ops).
"""
from __future__ import annotations

import math
from typing import Sequence

import numpy as np


def effective_transceivers(cg: int, d: float, J: int = 1) -> float:
    """Usable transceivers per communicator for a subgroup of ``d`` devices in
    a network of ``cg`` communication groups with ``J`` contending racks
    (reference: actions/utils.py:101-106)."""
    if d == 1:
        return 0.0
    spare = min(cg // J, cg // (d - 1)) - 1
    return 1.0 + spare


def parallel_add_time(data_sz: float,
                      devices: float,
                      mem_frequency: float = 2e12,
                      peak_flops: float = 130e12,
                      bytes_per_comp: int = 2) -> float:
    """Roofline estimate of the parallel-add compute inside a collective
    (reference: actions/utils.py:108-117)."""
    n_op = np.ceil(np.log2(devices))
    n_bytes = (devices + 1) * bytes_per_comp
    arithmetic_intensity = n_op / n_bytes
    total_ops = n_op * (data_sz / devices) / bytes_per_comp
    return float(total_ops / min(mem_frequency * arithmetic_intensity,
                                 peak_flops))


def ramp_all_reduce_time(message_size: float,
                         num_servers: int,
                         num_racks: int,
                         num_comm_groups: int,
                         network_comm_groups: int = 32,
                         data_rate: float = 1.6e12,
                         contending_racks: int = 1,
                         mem_frequency: float = 2e12,
                         peak_flops: float = 130e12,
                         bytes_per_comp: int = 2,
                         propagation_latency: float = 1.25e-6,
                         io_latency: float = 100e-9) -> float:
    """Time for an all-reduce of ``message_size`` bytes across a collective
    spanning ``num_comm_groups`` distinct communication groups,
    ``num_racks`` distinct rack ids, and ``num_servers`` distinct
    server-within-rack ids, in a network of ``network_comm_groups`` total
    groups (reference: actions/utils.py:42-88)."""
    x = network_comm_groups
    data_per_tx = data_rate / x
    subgroups = [num_comm_groups,
                 min(num_comm_groups, num_servers),
                 num_racks,
                 np.ceil(num_servers / x)]

    msg_sizes = [np.ceil(message_size / subgroups[0])]
    for sub in subgroups[1:]:
        msg_sizes.append(np.ceil(msg_sizes[-1] / sub))

    comm_time = 0.0
    comp_time = 0.0
    for step, sub in enumerate(subgroups):
        if sub > 1:
            comp_time += parallel_add_time(
                msg_sizes[step] * sub, sub, mem_frequency=mem_frequency,
                peak_flops=peak_flops, bytes_per_comp=bytes_per_comp)
            bw = effective_transceivers(x, sub, contending_racks) * data_per_tx
            comm_time += (propagation_latency + 2 * io_latency
                          + msg_sizes[step] / bw)
    # x2: all-reduce = reduce-scatter + all-gather
    total = 2 * comm_time + comp_time
    if math.isinf(total):
        raise ValueError("infinite RAMP all-reduce time computed")
    return float(total)


def one_to_one_time(message_size: float,
                    data_rate: float = 1.6e12,
                    propagation_latency: float = 1.25e-6,
                    io_latency: float = 100e-9) -> float:
    """(reference: actions/utils.py:90-99)"""
    t = propagation_latency + 2 * io_latency + message_size / data_rate
    if math.isinf(t):
        raise ValueError("infinite one-to-one communication time computed")
    return float(t)


def collective_span(server_ids: Sequence[str]):
    """Distinct (comm-group, rack, server) counts spanned by a set of RAMP
    server ids ``"c-r-s"`` (reference: actions/utils.py:169-245
    get_collective_info)."""
    cgs, racks, servers, full = set(), set(), set(), set()
    for sid in server_ids:
        c, r, s = sid.split("-")
        cgs.add(c)
        racks.add(r)
        servers.add(s)
        full.add(sid)
    return len(cgs), len(racks), len(servers), len(full)
