"""The legacy dynamic-tick cluster simulator (Torus path).

Counterpart of the reference's older ``ClusterEnvironment``
(ddls/environments/cluster/cluster_environment.py:28): unlike the RAMP
simulator's one-shot lookahead (possible only because RAMP's rules forbid
contention), this engine ticks *live* jobs that share the cluster -- each
tick every worker runs its highest-priority ready mounted op, the clock
advances by the shortest remaining run time (capped at the next arrival /
simulation end), and completed ops satisfy their child dependencies at zero
cost (the reference's documented simplification, "assume no network
communication overhead", cluster_environment.py:286). Jobs execute
``num_training_steps`` training steps to completion, workers may hold many
jobs at once (no RAMP exclusivity), and servers hold many workers
(reference run_sim.py: 16 nodes x 4 A100s).

Actions are the legacy dict shape (cluster_environment.py:246):

    {"job_placement": {job_id: {op_id: worker_id}},
     "job_schedule":  {worker_id: {job_id: {op_id: priority}}}}

built by the manager-style agents in :mod:`ddls_tpu.agents.managers`.
"""
from __future__ import annotations

import pathlib
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Set

import numpy as np

from ddls_tpu.demands.job import ExecState, Job
from ddls_tpu.demands.job_queue import JobQueue
from ddls_tpu.demands.jobs_generator import JobsGenerator
from ddls_tpu.hardware.topologies import build_topology
from ddls_tpu.utils import Stopwatch, seed_everything, unique_experiment_dir
from ddls_tpu.utils.common import save_logs_to_dir, snapshot_logs


class ClusterEnvironment:
    def __init__(self,
                 topology_config: dict,
                 node_config: dict,
                 name: str = "cluster",
                 path_to_save: Optional[str] = None,
                 save_freq: int = 1,
                 use_sqlite_database: bool = False):
        self.name = name
        self.topology_config = topology_config
        self.node_config = node_config
        self.save_freq = save_freq
        self.use_sqlite_database = use_sqlite_database
        self.path_to_save = (unique_experiment_dir(path_to_save, name)
                             if path_to_save is not None else None)

        self.topology = build_topology(topology_config)
        self.topology.populate_workers(node_config,
                                       one_worker_per_server=False)
        self.stopwatch = Stopwatch()
        self.reset_counter = 0
        self._save_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ reset
    def reset(self,
              jobs_config,
              max_simulation_run_time: float = float("inf"),
              job_queue_capacity: int = 10,
              seed: Optional[int] = None,
              verbose: bool = False):
        self.reset_counter += 1
        if seed is not None:
            seed_everything(seed)
        self.stopwatch.reset()
        self.topology.reset_devices()

        if isinstance(jobs_config, JobsGenerator):
            self.jobs_generator = jobs_config
        else:
            self.jobs_generator = JobsGenerator(**jobs_config)
        self.max_simulation_run_time = (
            float("inf") if max_simulation_run_time is None
            else max_simulation_run_time)
        self.job_queue = JobQueue(queue_capacity=job_queue_capacity)

        self.num_jobs_arrived = 0
        self.jobs_running: Dict[int, Job] = {}
        self.jobs_completed: Dict[int, Job] = {}
        self.jobs_blocked: Dict[int, Job] = {}
        self.exec_states: Dict[int, ExecState] = {}
        self.job_op_to_worker: Dict[tuple, str] = {}
        self.job_op_placement: Dict[int, Dict[str, str]] = {}
        self.job_id_to_job_idx: Dict[int, int] = {}
        self.step_counter = 0

        self.steps_log = defaultdict(list)
        self.sim_log = defaultdict(list)
        self.step_stats = self._init_step_stats()

        self.time_next_job_to_arrive = 0.0
        self.job_queue.add(self._get_next_job())
        return None

    def _init_step_stats(self) -> dict:
        s = defaultdict(float)
        s["step_counter"] = self.step_counter
        s["step_start_time"] = self.stopwatch.time()
        s["mean_num_active_workers"] = []
        for key in ("num_jobs_completed", "num_jobs_running",
                    "num_jobs_arrived", "num_jobs_blocked"):
            s[key] = 0
        return s

    # --------------------------------------------------------------- arrivals
    def _get_next_job(self) -> Job:
        job = self.jobs_generator.sample_job()
        job_idx = self.num_jobs_arrived
        job.register_arrived(time_arrived=self.stopwatch.time(),
                             job_idx=job_idx)
        self.job_id_to_job_idx[job.job_id] = job_idx
        self.time_next_job_to_arrive += (
            self.jobs_generator.sample_interarrival_time())
        self.num_jobs_arrived += 1
        return job

    # ------------------------------------------------------------------- step
    def step(self, actions: dict, verbose: bool = False):
        self.step_stats = self._init_step_stats()

        self._place_jobs(actions.get("job_placement") or {})
        self._schedule_jobs(actions.get("job_schedule") or {})
        self.step_stats["num_jobs_running"] = len(self.jobs_running)

        step_done = False
        while not step_done:
            time_before = self.stopwatch.time()
            max_tick = min(
                self.time_next_job_to_arrive - self.stopwatch.time(),
                self.max_simulation_run_time - self.stopwatch.time())
            completed_ops = self._tick_workers(max_tick=max(max_tick, 0.0))

            # zero-cost dependency satisfaction (reference hack :286): a
            # completed op's out-deps finish instantly, readying children
            for job_idx, op_is in completed_ops.items():
                state = self.exec_states[job_idx]
                for ei in sorted(state.deps_ready):
                    state.tick_dep(ei, state.remaining_dep[ei])

            # training-step / job completion
            for job_idx in list(completed_ops):
                job = self.jobs_running[job_idx]
                state = self.exec_states[job_idx]
                if state.is_training_step_complete():
                    job.training_step_counter += 1
                    if job.training_step_counter >= job.num_training_steps:
                        self._register_completed_job(job)
                        step_done = True
                    else:
                        self.exec_states[job_idx] = job.reset_training_step()

            # arrivals
            if len(self.jobs_generator) > 0:
                if (self.stopwatch.time() >= self.time_next_job_to_arrive):
                    nxt = self._get_next_job()
                    self.step_stats["num_jobs_arrived"] += 1
                    if self.job_queue.can_fit(nxt):
                        self.job_queue.add(nxt)
                    else:
                        self._register_blocked_job(nxt)
                    step_done = True
            else:
                self.time_next_job_to_arrive = float("inf")

            if self.is_done():
                step_done = True

            if (not step_done and not completed_ops
                    and self.stopwatch.time() == time_before):
                # no clock progress, no completions, no event: nothing can
                # change without a new action (e.g. a queued job the caller
                # left unplaced after the generator drained) — hand control
                # back instead of spinning forever
                step_done = True

        # step epilogue
        s = self.step_stats
        s["step_end_time"] = self.stopwatch.time()
        s["mean_num_active_workers"] = (
            float(np.mean(s["mean_num_active_workers"]))
            if len(s["mean_num_active_workers"]) else 0.0)
        s["mean_worker_compute_utilisation"] = (
            s["mean_num_active_workers"] / self.topology.num_workers)
        s["job_queue_length"] = len(self.job_queue)
        for key, val in s.items():
            self.steps_log[key].append(val)
        self.step_counter += 1

        if self.path_to_save is not None and (
                self.step_counter % self.save_freq == 0 or self.is_done()):
            self.save()
            if self.is_done() and self._save_thread is not None:
                self._save_thread.join()
        return None, None, None, self.is_done(), None

    # ------------------------------------------------------------ sub-steps
    def _place_jobs(self, job_placement: dict) -> None:
        for job_id, op_to_worker in job_placement.items():
            if job_id not in self.job_queue.jobs:
                continue
            job = self.job_queue.jobs[job_id]
            job_idx = job.details["job_idx"]
            for op_id, worker_id in op_to_worker.items():
                worker = self.topology.workers[worker_id]
                worker.mount(job, op_id)
                job.details["mounted_workers"].add(worker_id)
                self.job_op_to_worker[(job_idx, op_id)] = worker_id
            self.job_op_placement[job_id] = dict(op_to_worker)
            job.register_running(time_started=self.stopwatch.time())
            self.jobs_running[job_idx] = job
            self.job_queue.remove(job)
            # legacy engine: every dep is free (no comm model)
            self.exec_states[job_idx] = job.reset_training_step()

    def _schedule_jobs(self, job_schedule: dict) -> None:
        for worker_id, job_to_ops in job_schedule.items():
            worker = self.topology.workers[worker_id]
            for job_id, op_to_pri in job_to_ops.items():
                job_idx = self.job_id_to_job_idx[job_id]
                worker.op_priority.setdefault(job_idx, {}).update(
                    op_to_pri)

    def _tick_workers(self, max_tick: float) -> Dict[int, List[int]]:
        """One cluster tick: each worker's highest-priority ready op runs
        for min(shortest remaining run time, max_tick)
        (reference: _tick_workers, cluster_environment.py:377)."""
        worker_to_choice: Dict[str, tuple] = {}
        shortest = float("inf")
        for worker_id, worker in self.topology.workers.items():
            best = None
            for job_idx in worker.mounted_job_idx_to_ops:
                if job_idx not in self.exec_states:
                    continue  # job still queued (mounted mid-step)
                state = self.exec_states[job_idx]
                pri_map = worker.op_priority.get(job_idx, {})
                for op_id in sorted(worker.mounted_job_idx_to_ops[job_idx]):
                    oi = state.op_index[op_id]
                    if oi not in state.ops_ready:
                        continue
                    pri = pri_map.get(op_id, 0)
                    if best is None or pri > best[0]:
                        best = (pri, job_idx, oi)
            if best is not None:
                worker_to_choice[worker_id] = best
                shortest = min(
                    shortest,
                    self.exec_states[best[1]].remaining_op[best[2]])

        tick = min(shortest, max_tick)
        if not np.isfinite(tick):
            # nothing runnable: jump straight to the next event
            tick = max_tick if np.isfinite(max_tick) else 0.0

        completed: Dict[int, List[int]] = defaultdict(list)
        self.step_stats["mean_num_active_workers"].append(
            len(worker_to_choice))
        for worker_id, (pri, job_idx, oi) in worker_to_choice.items():
            state = self.exec_states[job_idx]
            if state.tick_op(oi, tick):
                completed[job_idx].append(oi)
        self.stopwatch.tick(tick)
        return completed

    # -------------------------------------------------------------- lifecycle
    def _register_completed_job(self, job: Job) -> None:
        job.register_completed(time_completed=self.stopwatch.time())
        job_idx = job.details["job_idx"]
        self.jobs_completed[job_idx] = job
        self.step_stats["num_jobs_completed"] += 1
        self.sim_log["job_completion_time"].append(
            job.details["time_completed"] - job.details["time_arrived"])
        self.sim_log["jobs_completed_num_nodes"].append(job.graph.n_ops)
        self.sim_log["jobs_completed_num_edges"].append(job.graph.n_deps)
        self.sim_log["jobs_completed_total_operation_memory_cost"].append(
            job.immutable["job_total_op_memory_cost"])
        self.sim_log["jobs_completed_total_dependency_size"].append(
            job.immutable["job_total_dep_size"])
        self._remove_job(job)

    def _register_blocked_job(self, job: Job) -> None:
        self.jobs_blocked[job.details["job_idx"]] = job
        self.step_stats["num_jobs_blocked"] += 1
        self.sim_log["jobs_blocked_num_nodes"].append(job.graph.n_ops)
        self.sim_log["jobs_blocked_num_edges"].append(job.graph.n_deps)
        self.sim_log["jobs_blocked_total_operation_memory_cost"].append(
            job.immutable["job_total_op_memory_cost"])
        self.sim_log["jobs_blocked_total_dependency_size"].append(
            job.immutable["job_total_dep_size"])

    def _remove_job(self, job: Job) -> None:
        job_idx = job.details["job_idx"]
        del self.jobs_running[job_idx]
        self.exec_states.pop(job_idx, None)
        for op_id in job.graph.op_ids:
            worker_id = self.job_op_to_worker.pop((job_idx, op_id), None)
            if worker_id is not None:
                self.topology.workers[worker_id].unmount(job, op_id)
        self.job_op_placement.pop(job.job_id, None)

    def is_done(self, verbose: bool = False) -> bool:
        if (self.max_simulation_run_time is not None
                and self.stopwatch.time() >= self.max_simulation_run_time):
            return True
        return (len(self.jobs_generator) == 0 and not self.jobs_running
                and len(self.job_queue) == 0)

    # ------------------------------------------------------------------- save
    def _save_logs(self, logs: dict) -> None:
        save_logs_to_dir(
            pathlib.Path(self.path_to_save) / f"reset_{self.reset_counter}",
            logs, use_sqlite=self.use_sqlite_database)

    def save(self) -> None:
        if self._save_thread is not None:
            self._save_thread.join()
        snapshot = snapshot_logs({"steps_log": self.steps_log,
                                  "sim_log": self.sim_log})
        self._save_thread = threading.Thread(target=self._save_logs,
                                             args=(snapshot,))
        self._save_thread.start()
