"""Batched candidate-degree pricing: lookahead JCTs for EVERY valid
partition degree of the queued job, without mutating cluster state.

The integration point the jax-lookahead go/no-go named (VERDICT r2 next
#3; docs/jax_lookahead_gonogo.md point 2): a policy/heuristic deciding a
job's partition degree wants the lookahead outcome of all ~16 candidate
actions, not just the one it takes. Pricing them one-by-one through the
host tick engine costs ~100 ms each at bench scale; here each candidate's
control-plane (partition -> first-fit placement -> SRPT schedules ->
pricing) runs on host over the array pipeline, and the tick engines
evaluate the batch — the C++ engine per candidate (~0.2 ms, bit-exact
f64; the measured default everywhere, docs/perf_round4.md), or the
opt-in vmapped jitted call (kept for parity testing; measured ~50x
slower through the tunnelled TPU).

Every priced candidate is inserted into ``cluster.lookahead_cache`` under
its exact memo key, so the subsequent ``env.step`` with any priced action
is a guaranteed cache hit — pricing is also prefetching.

Requires the dense array dep pipeline (single-channel complete topology,
the canonical RAMP shape); returns {} on other topologies or when the
op placer is non-deterministic w.r.t. replays (RandomOpPlacer), where a
prefetched key could never be hit again.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

PriceTuple = Tuple[float, float, float, float]  # scaled (jct, comm, comp, busy)


def price_candidate_degrees(env, degrees=None,
                            backend: str = "auto"
                            ) -> Dict[int, Optional[PriceTuple]]:
    """Price candidate max-partition degrees for the head-of-queue job.

    Returns {degree: (jct, comm_oh, comp_oh, busy) | None} where None
    means the candidate is unplaceable (no worker block / busy channels).
    Values are scaled by ``num_training_steps`` exactly like the cluster's
    own lookahead results.
    """
    from ddls_tpu.agents.placers import RandomOpPlacer
    from ddls_tpu.sim.actions import DepArrays, OpPartition

    cluster = env.cluster
    if len(cluster.job_queue) == 0:
        return {}
    if isinstance(env.op_placer, RandomOpPlacer):
        return {}
    job_id, job = next(iter(cluster.job_queue.jobs.items()))
    if degrees is None:
        # compute action validity directly: pricing now runs BEFORE the
        # observation is extracted (so price features can describe the
        # current job), and env.obs would be the PREVIOUS decision's mask
        from ddls_tpu.envs.obs import action_is_valid

        degrees = [a for a in env.action_set
                   if a != 0 and action_is_valid(a, env)]

    results: Dict[int, Optional[PriceTuple]] = {}
    pending = []  # (degree, key, partitioned, context)
    for d in degrees:
        partition_map = {job_id: env._partition_action_for(job, d)}
        op_partition = OpPartition(partition_map, cluster=cluster)
        op_placement = env.op_placer.get(op_partition=op_partition,
                                         cluster=cluster)
        if job_id not in op_placement.action:
            results[d] = None
            continue
        op_schedule = env.op_scheduler.get(
            op_partition=op_partition, op_placement=op_placement,
            cluster=cluster)
        dep_placement = env.dep_placer.get(
            op_partition=op_partition, op_placement=op_placement,
            cluster=cluster)
        if job_id not in dep_placement.action:
            results[d] = None
            continue
        env.dep_scheduler.get(op_partition=op_partition,
                              dep_placement=dep_placement, cluster=cluster)
        payload = dep_placement.action[job_id]
        if not isinstance(payload, DepArrays):
            return {}  # dict pipeline: unsupported (see module docstring)
        partitioned = op_partition.partitioned_jobs[job_id]
        # register-time zeroing parity: the mounted path zeroes non-flow
        # dep times in _register_running_job before the memo key is built
        sc = op_placement.job_server_codes[job_id]
        is_flow = partitioned.graph.flow_mask_from_codes(sc)
        partitioned.set_dep_init_run_times_bulk(
            np.where(is_flow, partitioned.dep_init_run_time_arr, 0.0))

        split = tuple(sorted(
            op_partition.job_id_to_split_forward_ops[job_id].items()))
        key = cluster.lookahead_key_for(partitioned, split,
                                        op_placement.action[job_id])
        cached = cluster.lookahead_cache.get(key)
        if cached is not None:
            results[d] = cached
            continue
        op_pri: Dict[str, int] = {}
        for worker_id, job_map in op_schedule.action.items():
            op_pri.update(job_map.get(job_id, {}))
        context = {"op_to_worker": op_placement.action[job_id],
                   "op_pri": op_pri, "payload": payload}
        pending.append((d, key, partitioned, context))

    if pending:
        for (d, key, partitioned, _), res in zip(
                pending, _evaluate(cluster, pending, backend)):
            if res is None:
                results[d] = None
                continue
            t, comm, comp, busy = res
            steps = partitioned.num_training_steps
            scaled = (t * steps, comm * steps, comp * steps, busy)
            cluster.lookahead_cache[key] = scaled
            results[d] = scaled
    return results


def _resolve_backend(backend: str) -> str:
    if backend != "auto":
        return backend
    # Measured on the real tunnelled v5e (docs/perf_round4.md, VERDICT r3
    # item 9): jax pricing averages ~1.2 s/decision through the tunnel
    # (dispatch RTTs + a retrace per distinct candidate-batch size) vs
    # ~23 ms for the C++ engine on host — the accelerator hypothesis the
    # old auto rule encoded lost by ~50x, so auto is native everywhere
    # the native engine exists (toolchain-less hosts fall back to jax:
    # slow prices beat every candidate silently reading "unplaceable").
    # The jitted env (sim/jax_env.py) prices IN-kernel instead; this host
    # helper's jax backend remains opt-in for parity tests.
    from ddls_tpu.native import native_available

    return "native" if native_available() else "jax"


def _evaluate(cluster, pending, backend: str):
    """Run the tick engine over the pending candidates; returns a list of
    per-step (t, comm, comp, busy) tuples (None = engine failed)."""
    from ddls_tpu.sim.jax_lookahead import (arrays_as_args,
                                            batched_lookahead_fn,
                                            build_lookahead_arrays,
                                            build_native_lookahead_arrays)

    backend = _resolve_backend(backend)
    if backend == "native":
        from ddls_tpu.native import run_lookahead

        out = []
        for _, _, partitioned, ctx in pending:
            arrays = build_native_lookahead_arrays(cluster, partitioned,
                                                   context=ctx)
            out.append(run_lookahead(arrays))
        return out
    if backend != "jax":
        raise ValueError(f"unknown candidate-pricing backend {backend!r}"
                         " (native | jax | auto)")

    def bucket(x: int) -> int:
        size = 16
        while size < x:
            size *= 2
        return size

    pad_ops = bucket(max(p.graph.n_ops for _, _, p, _ in pending))
    pad_deps = bucket(max(p.graph.n_deps for _, _, p, _ in pending))
    batch = [build_lookahead_arrays(cluster, p, pad_ops, pad_deps,
                                    context=ctx)
             for _, _, p, ctx in pending]
    num_workers = max(a.num_workers for a in batch)
    num_channels = max(a.num_channels for a in batch)
    fn = batched_lookahead_fn(num_workers, num_channels)
    stacked = [np.stack(parts) for parts in
               zip(*(arrays_as_args(a) for a in batch))]
    t, comm, comp, busy, ok = (np.asarray(x) for x in fn(*stacked))
    return [((float(t[i]), float(comm[i]), float(comp[i]), float(busy[i]))
             if bool(ok[i]) else None)
            for i in range(len(pending))]
