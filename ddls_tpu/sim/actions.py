"""Composite cluster actions: partition, placement, scheduling decisions.

A cluster step consumes an :class:`Action` bundling five sub-decisions
(reference: ddls/environments/ramp_cluster/actions/):

* :class:`OpPartition`   -- job -> op -> num_partitions; builds partitioned Jobs
* :class:`OpPlacement`   -- job -> op -> worker; prices dependency run times
* :class:`OpSchedule`    -- worker -> job -> op -> priority
* :class:`DepPlacement`  -- job -> dep -> channel ids
* :class:`DepSchedule`   -- channel -> job -> dep -> priority

``Action`` keeps only jobs handled by *all* sub-actions and records which
sub-action dropped a job (the blocking cause)
(reference: actions/action.py:36-78).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from ddls_tpu import telemetry as _telemetry
from ddls_tpu.demands.job import Job
from ddls_tpu.telemetry import flight as _flight
from ddls_tpu.graphs.readers import backward_op_id
from ddls_tpu.sim.comm_model import one_to_one_time, ramp_all_reduce_time
from ddls_tpu.sim.partition import partition_graph, partitioned_op_id

EdgeId = Tuple[str, str]


class OpPartition:
    """(reference: actions/op_partition.py:8)"""

    def __init__(self, action: Dict[int, Dict[str, int]], cluster):
        self.action = {job_id: dict(ops) for job_id, ops in action.items()}
        self.job_ids: Set[int] = set(self.action)
        self.original_jobs: Dict[int, Job] = {}
        self.partitioned_jobs: Dict[int, Job] = {}
        self.job_id_to_max_partition_degree: Dict[int, int] = defaultdict(lambda: 1)
        self.job_id_to_split_forward_ops: Dict[int, Dict[str, int]] = {}
        # partition-cache entries, so dep pricing can reuse/memoise the
        # per-graph collective grouping arrays
        self.job_id_to_cache_entry: Dict[int, dict] = {}

        for job_id, op_to_n in self.action.items():
            for op_id, n in op_to_n.items():
                if n != 1 and n % 2 != 0:
                    raise ValueError(
                        f"job {job_id} op {op_id}: num_partitions must be 1 "
                        f"or even, got {n}")

        for job_id in self.action:
            job = cluster.job_queue.jobs[job_id]
            self.original_jobs[job_id] = job

            # forward split map in graph order
            split_fwd: Dict[str, int] = {}
            max_degree = 1
            for op in job.graph.forward_op_ids():
                n = int(self.action[job_id].get(str(int(op)), 1))
                if n > 1:
                    split_fwd[str(int(op))] = n
                    max_degree = max(max_degree, n)
            self.job_id_to_split_forward_ops[job_id] = split_fwd
            self.job_id_to_max_partition_degree[job_id] = max_degree

            # memoised partitioned graph + immutable details. The reference
            # keys by (model, max partition degree)
            # (op_partition.py:44-66 + cluster memo tables) which is unsound
            # for partitioners that vary the per-op split map at a fixed max
            # degree (e.g. random); key on the full split map instead -- the
            # SiP-ML/PAC-ML path still hits because its map is a pure
            # function of (model, degree, quantum).
            model = job.details["model"]
            cache_key = (model, tuple(sorted(split_fwd.items())))
            cached = cluster.partition_cache.get(cache_key)
            if _telemetry.enabled():
                _telemetry.inc("sim.partition_cache.hit" if cached is not None
                               else "sim.partition_cache.miss")
            if cached is None:
                pgraph = partition_graph(job.graph, self.action[job_id])
                cached = {"graph": pgraph, "immutable": None}
                cluster.partition_cache[cache_key] = cached
            pgraph = cached["graph"]
            self.job_id_to_cache_entry[job_id] = cached

            details = {"model": model,
                       "job_idx": job.details.get("job_idx"),
                       "time_arrived": job.details.get("time_arrived"),
                       "max_partitions_per_op": max_degree}
            partitioned = Job(graph=pgraph,
                              num_training_steps=job.num_training_steps,
                              max_acceptable_jct_frac=job.max_acceptable_jct_frac,
                              job_id=job_id,
                              details=details,
                              immutable_details=cached["immutable"],
                              original_job=job)
            if cached["immutable"] is None:
                cached["immutable"] = partitioned.immutable
            self.partitioned_jobs[job_id] = partitioned
            if _flight.enabled():
                _flight.emit("partitioned", t=cluster.stopwatch.time(),
                             job_idx=details["job_idx"], job_id=job_id,
                             max_degree=max_degree,
                             n_ops=pgraph.n_ops, n_deps=pgraph.n_deps)

    def __len__(self) -> int:
        return len(self.action)


class JobPlacementShape:
    """job -> (c, r, s) meta-block shape chosen for the job (reference:
    actions/job_placement_shape.py:1). Consumed by the placement-shaping
    env/placer; carried on the composite Action for parity."""

    def __init__(self, action: Dict[int, Tuple[int, int, int]]):
        self.action = {job_id: tuple(shape)
                       for job_id, shape in action.items()}
        self.job_ids: Set[int] = set(self.action)

    def __len__(self) -> int:
        return len(self.action)


class OpPlacement:
    """job -> op -> worker map; prices all dependency run times on
    construction (reference: actions/op_placement.py:7 + actions/utils.py:13
    update_dep_run_times)."""

    def __init__(self, action: Dict[int, Dict[str, str]],
                 op_partition: OpPartition, cluster):
        self.action = {job_id: dict(ops) for job_id, ops in action.items()}
        self.job_ids: Set[int] = set(self.action)
        self.worker_to_ops: Dict[str, List[dict]] = defaultdict(list)
        self.job_id_to_worker_ids: Dict[int, Set[str]] = defaultdict(set)
        # job_id -> per-op dense server codes (cluster server-table order),
        # stashed by the pricing pass for the array dep pipeline
        self.job_server_codes: Dict[int, Any] = {}
        for job_id, op_to_worker in self.action.items():
            for op_id, worker_id in op_to_worker.items():
                self.worker_to_ops[worker_id].append(
                    {"op_id": op_id, "job_id": job_id})
                self.job_id_to_worker_ids[job_id].add(worker_id)

        assign_dep_run_times(cluster, op_partition, self)


class OpSchedule:
    """(reference: actions/op_schedule.py:3)"""

    def __init__(self, action: Dict[str, Dict[int, Dict[str, int]]]):
        self.action = action
        self.job_ids: Set[int] = set()
        for worker_id in self.action:
            self.job_ids.update(self.action[worker_id].keys())


class DepArrays:
    """Array-native dep placement/schedule for one job (the fast path on
    dense single-channel complete topologies — the canonical RAMP shape).

    ``chan[i]`` is the dense channel index carrying dep i (-1 = non-flow),
    aligned with ``graph.finalize()['edge_ids']``; ``channels`` the unique
    dense channels the job rides; ``pri`` the SRPT priorities (filled by
    the scheduler). One payload replaces the per-dep dict chain
    placer -> DepPlacement views -> schedule dicts -> channel mounts
    (docs/round3_notes.md item 2: "dep placement -> schedule -> mount over
    int arrays, Python dict mirrors as lazy views")."""

    __slots__ = ("edge_ids", "chan", "channels", "pri")

    def __init__(self, edge_ids, chan, channels, pri=None):
        self.edge_ids = edge_ids
        self.chan = chan
        self.channels = channels
        self.pri = pri

    def to_dep_dict(self, channel_ids) -> Dict[EdgeId, tuple]:
        """Materialise the dict view (dep -> channel-id tuple) for legacy
        readers; ``channel_ids`` maps dense index -> string channel id."""
        out: Dict[EdgeId, tuple] = {}
        cache: Dict[int, tuple] = {}
        for dep_id, c in zip(self.edge_ids, self.chan.tolist()):
            if c < 0:
                out[dep_id] = _NONFLOW_VIEW
            else:
                view = cache.get(c)
                if view is None:
                    view = cache.setdefault(c, (channel_ids[c],))
                out[dep_id] = view
        return out


_NONFLOW_VIEW = (None,)


class DepPlacement:
    """job -> dep -> channel-id tuple (or any iterable); a None entry means
    not a flow (reference: actions/dep_placement.py:6).

    The placer hands many deps the *same* channel tuple (all deps of one
    server pair ride the same channels), so the real-channel views are
    deduplicated per distinct tuple and shared — they are read-only
    downstream. On the array fast path the per-job value is a
    ``DepArrays`` payload instead of a dict, and the dict views are
    materialised lazily (``jobdep_to_channels`` property) only if a legacy
    reader asks."""

    def __init__(self, action: Dict[int, Dict[EdgeId, tuple]],
                 channel_ids: Optional[List[str]] = None):
        self.action = action
        self.job_ids: Set[int] = set(self.action)
        self._channel_ids = channel_ids  # dense -> string id (arrays path)
        self._jobdep_to_channels: Optional[Dict] = None
        if not any(isinstance(v, DepArrays) for v in action.values()):
            self._build_views()

    def _build_views(self) -> None:
        self._jobdep_to_channels = {}
        views: Dict[int, frozenset] = {}
        for job_id, dep_to_channels in self.action.items():
            if isinstance(dep_to_channels, DepArrays):
                dep_to_channels = dep_to_channels.to_dep_dict(
                    self._channel_ids)
            for dep_id, channels in dep_to_channels.items():
                key = id(channels)
                real = views.get(key)
                if real is None:
                    real = frozenset(
                        c for c in channels if c is not None)
                    views[key] = real
                self._jobdep_to_channels[(job_id, dep_id)] = real

    @property
    def jobdep_to_channels(self) -> Dict[Tuple[int, EdgeId], frozenset]:
        if self._jobdep_to_channels is None:
            self._build_views()
        return self._jobdep_to_channels


class DepSchedule:
    """(reference: actions/dep_schedule.py:3)"""

    def __init__(self, action: Dict[str, Dict[int, Dict[EdgeId, int]]]):
        self.action = action
        self.job_ids: Set[int] = set()
        for channel_id in self.action:
            self.job_ids.update(self.action[channel_id].keys())


class Action:
    """Bundle of the five sub-actions; a job survives only if every
    sub-action handled it (reference: actions/action.py:3)."""

    SUB_ACTIONS = ("op_partition", "op_placement", "op_schedule",
                   "dep_placement", "dep_schedule")

    def __init__(self,
                 op_partition: Optional[OpPartition] = None,
                 op_placement: Optional[OpPlacement] = None,
                 op_schedule: Optional[OpSchedule] = None,
                 dep_placement: Optional[DepPlacement] = None,
                 dep_schedule: Optional[DepSchedule] = None,
                 job_placement_shape: Optional[JobPlacementShape] = None):
        self.job_placement_shape = job_placement_shape
        self.actions = {
            "op_partition": op_partition,
            "op_placement": op_placement,
            "op_schedule": op_schedule,
            "dep_placement": dep_placement,
            "dep_schedule": dep_schedule,
        }
        present = {k: a for k, a in self.actions.items() if a is not None}
        self.cause_of_unsuccessful_handling: Optional[str] = None
        # per-job blocking cause: first sub-action (in pipeline order) that
        # failed to handle the job (reference: actions/action.py:36-48)
        self.job_id_to_cause_of_unsuccessful_handling: Dict[int, str] = {}
        if present:
            self.job_ids = set.intersection(
                *[set(a.job_ids) for a in present.values()])
            union = set.union(*[set(a.job_ids) for a in present.values()])
            for job_id in union - self.job_ids:
                for key in self.SUB_ACTIONS:
                    act = self.actions[key]
                    if act is not None and job_id not in act.job_ids:
                        self.job_id_to_cause_of_unsuccessful_handling[
                            job_id] = key
                        break
            for key, act in present.items():
                if not act.job_ids:
                    self.cause_of_unsuccessful_handling = key
                    break
            self.job_idxs = {
                op_partition.partitioned_jobs[j].details["job_idx"]
                for j in self.job_ids} if op_partition is not None else set()
        else:
            self.job_ids = set()
            self.job_idxs = set()

        # filter unhandled jobs out of every sub-action
        for key, act in present.items():
            if key in ("op_partition", "op_placement", "dep_placement"):
                for job_id in list(act.action):
                    if job_id not in self.job_ids:
                        del act.action[job_id]
            else:  # schedules keyed by device
                for device_id in act.action:
                    for job_id in list(act.action[device_id]):
                        if job_id not in self.job_ids:
                            del act.action[device_id][job_id]


# --------------------------------------------------------------- dep run times
def group_collectives(original_job: Job,
                      partitioned_job: Job,
                      split_fwd_ops: Dict[str, int]):
    """Group the partitioned job's deps into collectives and one-to-one
    communications (reference: actions/utils.py:247-393).

    For each original forward op f (and its backward counterpart b):

    * f split n ways: out-edges of the f sub-ops form a *candidate* forward
      collective; non-sync in-edges of the b sub-ops a candidate backward
      collective; the bidirectional sync pairs between b sub-ops are each a
      2-edge collective.
    * f unsplit: out-edges of f and in-edges of b are one-to-one.

    Whether a candidate group is a real collective depends on placement
    symmetry, checked later. Each dep is claimed exactly once, first claim
    wins (the reference double-visits the fwd->bwd join edge when the last
    forward op is split and would trip its own conservation check;
    deterministic first-claim avoids that while preserving grouping for all
    other edges).

    Returns (candidate_groups, sync_groups, one_to_one) where candidate
    groups still need the placement symmetry test.
    """
    graph = partitioned_job.graph
    n_fwd = len(original_job.graph.forward_op_ids())
    claimed: Set[EdgeId] = set()
    candidate_groups: List[List[EdgeId]] = []
    sync_groups: List[List[EdgeId]] = []
    one_to_one: List[EdgeId] = []

    def claim(edges: List[EdgeId]) -> List[EdgeId]:
        fresh = [e for e in edges if e not in claimed]
        claimed.update(fresh)
        return fresh

    for f_op in original_job.graph.forward_op_ids():
        f_op = str(int(f_op))
        b_op = backward_op_id(f_op, n_fwd)
        if f_op in split_fwd_ops:
            n = split_fwd_ops[f_op]
            fwd_deps: List[EdgeId] = []
            bwd_deps: List[EdgeId] = []
            sync_pairs: List[List[EdgeId]] = []
            seen_sync: Set[frozenset] = set()
            for i in range(n):
                f_sub = partitioned_op_id(f_op, i)
                fwd_deps.extend(graph.out_edges(f_sub))
                b_sub = partitioned_op_id(b_op, i)
                for (u, v) in graph.in_edges(b_sub):
                    if u in graph.successors(v):
                        key = frozenset((u, v))
                        if key not in seen_sync:
                            seen_sync.add(key)
                            sync_pairs.append([(u, v), (v, u)])
                    else:
                        bwd_deps.append((u, v))
            fwd_deps = claim(fwd_deps)
            if fwd_deps:
                candidate_groups.append(fwd_deps)
            bwd_deps = claim(bwd_deps)
            if bwd_deps:
                candidate_groups.append(bwd_deps)
            for pair in sync_pairs:
                pair = claim(pair)
                if pair:
                    sync_groups.append(pair)
        else:
            one_to_one.extend(claim(graph.out_edges(f_op)))
            one_to_one.extend(claim(graph.in_edges(b_op)))

    total = (sum(len(g) for g in candidate_groups)
             + sum(len(g) for g in sync_groups) + len(one_to_one))
    if total != graph.n_deps:
        raise RuntimeError(
            f"collective grouping covered {total} of {graph.n_deps} deps of "
            f"job {partitioned_job.job_id}; grouping bug")
    return candidate_groups, sync_groups, one_to_one


def build_grouping_arrays(original: Job, partitioned: Job,
                          split_fwd: Dict[str, int]) -> dict:
    """Index-array form of the collective grouping, static per partitioned
    graph and therefore memoised alongside it in the cluster's partition
    cache (pricing then touches numpy arrays, not per-edge dicts)."""
    import numpy as np

    cand, sync, o2o = group_collectives(original, partitioned, split_fwd)
    arrays = partitioned.graph.finalize()
    eidx, oidx = arrays["edge_index"], arrays["op_index"]
    sizes = arrays["edge_size"]

    def pack(group, is_sync):
        e = np.fromiter((eidx[d] for d in group), np.int64, len(group))
        u = np.fromiter((oidx[d[0]] for d in group), np.int64, len(group))
        v = np.fromiter((oidx[d[1]] for d in group), np.int64, len(group))
        # plain-list mirrors: groups are mostly tiny (2-edge sync pairs),
        # where Python set/sort constants beat numpy's per-call overhead
        return {"edges": e, "u": u, "v": v,
                "u_list": u.tolist(), "v_list": v.tolist(),
                "msg": float(sizes[e].sum()), "sync": is_sync}

    return {
        "groups": ([pack(g, False) for g in cand]
                   + [pack(g, True) for g in sync]),
        "o2o_edges": np.fromiter((eidx[d] for d in o2o), np.int64,
                                 len(o2o)),
        "o2o_u": np.fromiter((oidx[d[0]] for d in o2o), np.int64, len(o2o)),
        "o2o_v": np.fromiter((oidx[d[1]] for d in o2o), np.int64, len(o2o)),
    }


def _server_code_tables(cluster):
    """server_id -> dense code, plus (comm group, rack, server) component
    lists indexed by code; built once per cluster (the topology is fixed
    for its lifetime) and stored with the cluster's other memo caches."""
    tables = cluster._server_code_tables
    if tables is None:
        ids = cluster.topology.server_ids
        code = {sid: i for i, sid in enumerate(ids)}
        parts = [[0, 0, 0] for _ in ids]
        for i, sid in enumerate(ids):
            for axis, val in enumerate(sid.split("-")[:3]):
                parts[i][axis] = int(val)
        tables = (code,
                  [p[0] for p in parts],
                  [p[1] for p in parts],
                  [p[2] for p in parts])
        cluster._server_code_tables = tables
    return tables


def assign_dep_run_times(cluster, op_partition: OpPartition,
                         op_placement: "OpPlacement") -> None:
    """Price every dep of every placed job given op placements and topology
    (reference: actions/utils.py:13-167).

    Array formulation of the reference's per-edge walk: the grouping is a
    cached index-array structure, placements become a dense op->server-code
    vector, symmetry tests are sorted-array comparisons, and all one-to-one
    deps are priced in one vectorised expression.
    """
    import numpy as np

    if not op_placement.job_ids:
        return
    topo = cluster.topology
    code, c_list, r_list, s_list = _server_code_tables(cluster)
    span_cache = cluster._span_cache
    worker_to_server = topo.worker_to_server
    rate = topo.channel_bandwidth
    prop = topo.intra_gpu_propagation_latency
    io = topo.worker_io_latency
    allreduce_cache = cluster.comm_time_cache

    for job_id in op_partition.action:
        if job_id not in op_placement.action:
            continue
        original = op_partition.original_jobs[job_id]
        partitioned = op_partition.partitioned_jobs[job_id]
        placement = op_placement.action[job_id]
        split_fwd = op_partition.job_id_to_split_forward_ops[job_id]

        cache_entry = op_partition.job_id_to_cache_entry.get(job_id)
        grouping = (cache_entry or {}).get("grouping")
        if grouping is None:
            grouping = build_grouping_arrays(original, partitioned,
                                             split_fwd)
            if cache_entry is not None:
                cache_entry["grouping"] = grouping

        arrays = partitioned.graph.finalize()
        sc_list = [code[worker_to_server[placement[op]]]
                   for op in arrays["op_ids"]]
        sc = np.asarray(sc_list, np.int64)
        # dense per-op server codes double as the array dep-pipeline's
        # src/dst lookup (cluster server-table order == topology dense
        # order); stashing here saves the placer a per-op dict walk
        op_placement.job_server_codes[job_id] = sc

        # whole-result memo: the priced array depends only on (partitioned
        # graph, per-op server codes) — topology and comm params are fixed
        # per cluster — so repeated placements of a repeated workload skip
        # the group walk entirely. Scoped inside the partition-cache entry,
        # it inherits that cache's exact (model, split map) key and its
        # workload-signature invalidation.
        pricing_memo = (cache_entry.setdefault("pricing", {})
                        if cache_entry is not None else None)
        sc_key = sc.tobytes()
        if pricing_memo is not None:
            cached_times = pricing_memo.get(sc_key)
            if cached_times is not None:
                partitioned.set_dep_init_run_times_bulk(cached_times)
                continue

        times = np.zeros(partitioned.graph.n_deps, np.float64)
        extra_e, extra_u, extra_v = [], [], []
        for group in grouping["groups"]:
            u_codes = [sc_list[i] for i in group["u_list"]]
            v_codes = [sc_list[i] for i in group["v_list"]]
            # placement-symmetric parent/child multisets -> true collective
            if not group["sync"] and sorted(u_codes) != sorted(v_codes):
                extra_e.append(group["edges"])
                extra_u.append(group["u"])
                extra_v.append(group["v"])
                continue
            servers = frozenset(u_codes).union(v_codes)
            if len(servers) == 1:
                run_time = 0.0
            else:
                span = span_cache.get(servers)
                if span is None:
                    span = (len({s_list[s] for s in servers}),
                            len({r_list[s] for s in servers}),
                            len({c_list[s] for s in servers}))
                    span_cache[servers] = span
                key = (group["msg"],) + span
                run_time = allreduce_cache.get(key)
                if run_time is None:
                    run_time = ramp_all_reduce_time(
                        message_size=group["msg"],
                        num_servers=span[0],
                        num_racks=span[1],
                        num_comm_groups=span[2],
                        network_comm_groups=topo.num_communication_groups,
                        data_rate=rate,
                        propagation_latency=prop,
                        io_latency=io)
                    allreduce_cache[key] = run_time
            times[group["edges"]] = run_time

        o2o_e = np.concatenate([grouping["o2o_edges"]] + extra_e)
        o2o_u = np.concatenate([grouping["o2o_u"]] + extra_u)
        o2o_v = np.concatenate([grouping["o2o_v"]] + extra_v)
        sizes = arrays["edge_size"][o2o_e]
        free = (sc[o2o_u] == sc[o2o_v]) | (sizes == 0)
        times[o2o_e] = np.where(free, 0.0, prop + 2 * io + sizes / rate)
        if not np.all(np.isfinite(times)):
            raise ValueError(
                f"non-finite communication time priced for job {job_id}")

        if pricing_memo is not None:
            pricing_memo[sc_key] = times
        partitioned.set_dep_init_run_times_bulk(times)
