"""Fully-jitted canonical-RAMP environment stepping (the §5.8 north star).

This module composes the proven jitted pieces — the block-search primitive
(`sim/jax_block_search.py`), the lookahead tick engine (`sim/jax_lookahead.py`)
— with a `lax.scan`-ified `allocate_job` (reference:
ddls/environments/ramp_cluster/agents/placers/utils.py:532 ``allocate``, here
re-derived from `agents/placers.py:allocate_job`) and array formulations of
dep placement/pricing/scheduling into ONE jitted decision step and a jitted
episode loop for the canonical RAMP partitioning environment
(single-channel complete topology, whole-cluster meta block, one decided job
per step — the `RampJobPartitioningEnvironment` path).

Design: everything that depends only on (model, partition degree) is
precomputed on the host into padded, stacked *config tables* — the
partitioned graph arrays, placement scan order, collective grouping, SRPT
tie ranks, candidate block shapes per split — and everything that depends on
cluster state (free memory, server/channel occupancy, running jobs, the
arrival clock) lives in small state arrays. A decision is then: gather the
config row -> scan the padded forward-op sequence placing each op (parent
co-location, else generic first-fit block search) -> price deps (collective
symmetry test + the RAMP all-reduce formula) -> SRPT scores -> the jitted
lookahead -> SLA gate -> masked commit. The episode loop advances the event
clock (completions, arrivals) between decisions exactly like
``RampClusterEnvironment.step``'s tick loop.

Build state: ALL stages are landed and parity-pinned — the table
builders and the scan-ified `jax_allocate_job` kernel (parity-fuzzed in
tests/test_jax_placer.py), the pricing/score kernels
(tests/test_jax_pricing.py), the replay/policy/oracle episode kernels
(x64 full-episode drivers tests/test_jax_episode.py,
test_jax_policy_episode.py, test_jax_oracle_episode.py) and the
fixed-length segment kernel feeding the device PPO collector
(tests/test_ppo_device.py). The in-kernel observation (`_kernel_obs`)
is BIT-equal to `envs/obs.py` (same formulas, same f64-then-f32 cast
order — CLAUDE.md invariant).

Numerics: tables are built in f64; under ``JAX_ENABLE_X64=1`` the whole
step runs in f64 and reproduces host decisions exactly (the parity
drivers run that way); under default f32 results carry f32 rounding —
same trade as ``use_jax_lookahead``.

Scope (honest): the placement-shaping env's restricted meta blocks and
multi-channel topologies stay host-side, and price-feature observations
are episode-kernel-only (the compact segment trace carries no pricing
state — `make_segment_fn` rejects them loudly).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ddls_tpu.agents.block_search import block_shapes_for, factor_pairs
from ddls_tpu.agents.partitioners import build_partition_action
from ddls_tpu.graphs.readers import backward_op_id
from ddls_tpu.sim import jax_memo
from ddls_tpu.sim.partition import partition_graph, partitioned_op_id

#: episode-kernel default: the in-kernel lookahead memo (sim/jax_memo.py)
#: is ON for the episode builders at EVERY lane count — memoised and
#: recomputed lookaheads are bitwise identical by construction, so the
#: x64 parity suites run with it enabled unchanged, and the batched
#: probe masks hit lanes out of the lookahead while_loop so multi-lane
#: vmap callers (es_device, bench vmap8) hit the cache too (ISSUE 17;
#: each vmapped lane carries its own table).
DEFAULT_EPISODE_MEMO = jax_memo.MemoConfig()

Coord = Tuple[int, int, int]


# =========================================================================
# Shape system: the static candidate-block geometry for one RAMP topology.
# =========================================================================

@dataclasses.dataclass
class ShapeTables:
    """Distinct candidate block shapes for every possible split value, in
    host `find_sub_block` order, as padded index tables.

    ``row[s]`` lists (possibly duplicated) shape ids for split value ``s``
    in exactly the host's scan order (`block_shapes_for` + the diagonal
    fallback + the trailing (s,1,1)), with shapes whose origin span is
    empty already dropped (the host skips them inside `first_fit_block`).
    """
    ramp_shape: Coord
    shapes: List[Coord]            # distinct shapes (S==-1 -> diagonal)
    row: np.ndarray                # [max_split+1, MAX_SHAPES] i32, -1 pad
    offsets: np.ndarray            # [n_shapes, MAX_CELLS, 3] i32 cell offsets
    counts: np.ndarray             # [n_shapes] i32 servers per block
    bases: np.ndarray              # [n_shapes, 3] i32 modulo base per axis
    spans: np.ndarray              # [n_shapes, 3] i32 origin span extents
    diagonal: np.ndarray           # [n_shapes] bool


def _shape_span(shape: Coord, meta: Coord) -> Coord:
    # identical to first_fit_block's span arithmetic, including the S == -1
    # quirk span[2] = meta[2] + 2 (agents/block_search.py:115-118)
    return (meta[0] - shape[0] + 1, meta[1] - shape[1] + 1,
            meta[2] - shape[2] + 1)


def _shape_cells(shape: Coord) -> List[Coord]:
    """Cell offsets at origin (0,0,0) — delegated to the host's
    `enumerate_block` (with a huge phantom ramp so no modulo fires) so the
    enumeration order can never diverge from it."""
    from ddls_tpu.agents.block_search import enumerate_block

    big = (1 << 20, 1 << 20, 1 << 20)
    return enumerate_block(shape, big, (0, 0, 0))


def build_shape_tables(ramp_shape: Coord, max_split: int) -> ShapeTables:
    meta = tuple(ramp_shape)
    per_split: Dict[int, List[Coord]] = {}
    for s in range(1, max_split + 1):
        if s != 1 and s % 2 != 0:
            continue  # odd splits >1 cannot occur (RAMP symmetry)
        shapes = block_shapes_for(factor_pairs(s), meta)
        shapes += [(s, s, -1), (s, 1, 1)]
        shapes = [sh for sh in shapes
                  if all(x > 0 for x in _shape_span(sh, meta))]
        per_split[s] = shapes

    distinct: List[Coord] = []
    index: Dict[Coord, int] = {}
    for shapes in per_split.values():
        for sh in shapes:
            if sh not in index:
                index[sh] = len(distinct)
                distinct.append(sh)

    max_row = max((len(v) for v in per_split.values()), default=1)
    row = np.full((max_split + 1, max_row), -1, np.int32)
    for s, shapes in per_split.items():
        for p, sh in enumerate(shapes):
            row[s, p] = index[sh]

    n_shapes = max(len(distinct), 1)
    cell_lists = [_shape_cells(sh) for sh in distinct]
    max_cells = max((len(c) for c in cell_lists), default=1)
    offsets = np.zeros((n_shapes, max_cells, 3), np.int32)
    counts = np.zeros(n_shapes, np.int32)
    bases = np.zeros((n_shapes, 3), np.int32)
    spans = np.zeros((n_shapes, 3), np.int32)
    diagonal = np.zeros(n_shapes, bool)
    for i, sh in enumerate(distinct):
        cells = cell_lists[i]
        counts[i] = len(cells)
        offsets[i, :len(cells)] = cells
        diagonal[i] = sh[2] == -1
        # enumerate_block's modulo: regular blocks wrap at ramp dims (a
        # no-op inside the span), diagonals at (dim+1, dim+1, dim)
        bases[i] = ((ramp_shape[0] + 1, ramp_shape[1] + 1, ramp_shape[2])
                    if sh[2] == -1 else ramp_shape)
        spans[i] = _shape_span(sh, meta)
    return ShapeTables(ramp_shape=meta, shapes=distinct, row=row,
                       offsets=offsets, counts=counts, bases=bases,
                       spans=spans, diagonal=diagonal)


# =========================================================================
# Config tables: everything static per (model, partition degree).
# =========================================================================

@dataclasses.dataclass
class ConfigPads:
    n_ops: int        # N: padded partitioned-op slots
    n_deps: int       # M: padded dep slots
    n_fwd: int        # F: padded forward-op scan slots
    n_parents: int    # P: padded parent-candidate slots
    max_split: int    # maximum sub-ops per op (block size)
    n_groups: int     # G: padded candidate collective groups
    group_edges: int  # Eg: padded edges per candidate group
    n_sync: int       # padded 2-edge sync pairs
    n_o2o: int        # padded one-to-one edges


def config_tables_for(graph, degree: int, quantum: float) -> dict:
    """Unpadded per-(model, degree) tables (numpy, f64).

    ``graph`` is the job's raw profile graph; ``degree`` the action (the
    per-op split cap fed to the SiP-ML rule, reference:
    agents/partitioners/sip_ml_op_partitioner.py:46).
    """
    from ddls_tpu.demands.job import Job
    from ddls_tpu.sim.actions import build_grouping_arrays

    if degree != 1 and degree % 2 != 0:
        # build_shape_tables has no rows for odd splits > 1 (the RAMP
        # symmetry rule the partitioners enforce); a silent all-fail row
        # would diverge from the host placer, which happily scans
        # factor_pairs(3) shapes
        raise ValueError(f"degree must be 1 or even, got {degree}")
    action = build_partition_action(graph, quantum, degree)
    pgraph = partition_graph(graph, action)
    arrays = pgraph.finalize()
    n, m = pgraph.n_ops, pgraph.n_deps
    op_index = arrays["op_index"]

    original = Job(graph=graph, num_training_steps=1,
                   max_acceptable_jct_frac=1.0, job_id=0,
                   details={"model": "cfg", "job_idx": 0})
    partitioned = Job(graph=pgraph, num_training_steps=1,
                      max_acceptable_jct_frac=1.0, job_id=0,
                      details={"model": "cfg", "job_idx": 0},
                      original_job=original)

    forward_graph = graph.forward_view()
    n_forward = len(forward_graph.op_ids)
    split_fwd = {str(int(op)): int(action.get(str(int(op)), 1))
                 for op in forward_graph.op_ids}
    split_fwd = {k: v for k, v in split_fwd.items() if v > 1}

    topo = forward_graph.topo_order()
    fwd_slot = {str(int(op)): i for i, op in enumerate(topo)}

    f_split = np.zeros(len(topo), np.int32)
    f_mem = np.zeros(len(topo), np.float64)
    f_parents = []
    f_sub_fwd = np.full((len(topo), degree if degree > 0 else 1), -1,
                        np.int32)
    f_sub_bwd = np.full_like(f_sub_fwd, -1)
    insertion_rank = np.full(n, 0, np.int64)
    ins = 0
    for i, op in enumerate(topo):
        op_s = str(int(op))
        split = split_fwd.get(op_s, 1)
        b_op = backward_op_id(op_s, n_forward)
        mem = graph.memory_cost(op_s)
        if graph.has_op(b_op):
            mem += graph.memory_cost(b_op)
        f_split[i] = split
        f_mem[i] = mem / split
        f_parents.append([fwd_slot[str(int(p))]
                          for p in forward_graph.parents(op)])
        for k in range(split):
            if split > 1:
                fid = op_index[partitioned_op_id(op_s, k)]
                bid = op_index[partitioned_op_id(b_op, k)]
            else:
                fid = op_index[op_s]
                bid = op_index[b_op]
            f_sub_fwd[i, k] = fid
            f_sub_bwd[i, k] = bid
            # host insertion order: per placed server, fwd sub then bwd sub
            # (agents/placers.py:67-74,91-98) — feeds the SRPT stable-sort
            # tie-break (OpPlacement.worker_to_ops insertion order)
            insertion_rank[fid] = ins
            insertion_rank[bid] = ins + 1
            ins += 2

    grouping = build_grouping_arrays(original, partitioned, split_fwd)
    cand = [g for g in grouping["groups"] if not g["sync"]]
    sync = [g for g in grouping["groups"] if g["sync"]]
    edge_size = arrays["edge_size"]

    return {
        "n_ops": n, "n_deps": m,
        "op_compute": arrays["compute"].astype(np.float64),
        "op_sorted_rank": arrays["op_sorted_rank"].astype(np.int32),
        "num_parents": arrays["num_parents"].astype(np.int32),
        "insertion_rank": insertion_rank.astype(np.int32),
        "dep_src": arrays["edge_src"].astype(np.int32),
        "dep_dst": arrays["edge_dst"].astype(np.int32),
        "dep_size": edge_size.astype(np.float64),
        "dep_mutual": arrays["edge_mutual"].astype(bool),
        "dep_sorted_rank": arrays["edge_sorted_rank"].astype(np.int32),
        "f_split": f_split, "f_mem": f_mem, "f_parents": f_parents,
        "f_sub_fwd": f_sub_fwd, "f_sub_bwd": f_sub_bwd,
        "groups": cand, "sync": sync,
        "o2o_edges": grouping["o2o_edges"].astype(np.int32),
        "seq_compute": float(arrays["compute"].sum()),
    }


def stack_config_tables(per_cfg: Sequence[dict],
                        shape_tables: ShapeTables) -> Tuple[dict, ConfigPads]:
    """Pad + stack per-config tables along a leading cfg axis."""
    pads = ConfigPads(
        n_ops=max(c["n_ops"] for c in per_cfg),
        n_deps=max(c["n_deps"] for c in per_cfg),
        n_fwd=max(len(c["f_split"]) for c in per_cfg),
        n_parents=max((len(p) for c in per_cfg for p in c["f_parents"]),
                      default=1) or 1,
        max_split=int(shape_tables.counts.max()),
        n_groups=max((len(c["groups"]) for c in per_cfg), default=1) or 1,
        group_edges=max((len(g["edges"]) for c in per_cfg
                         for g in c["groups"]), default=1) or 1,
        n_sync=max((len(c["sync"]) for c in per_cfg), default=1) or 1,
        n_o2o=max((len(c["o2o_edges"]) for c in per_cfg), default=1) or 1,
    )
    K = len(per_cfg)
    N, M, F, P = pads.n_ops, pads.n_deps, pads.n_fwd, pads.n_parents
    S = pads.max_split
    G, Eg, Sy, O = (pads.n_groups, pads.group_edges, pads.n_sync,
                    pads.n_o2o)

    out = {
        "n_ops": np.zeros(K, np.int32),
        "n_deps": np.zeros(K, np.int32),
        "n_fwd": np.zeros(K, np.int32),
        "op_valid": np.zeros((K, N), bool),
        "op_compute": np.zeros((K, N), np.float64),
        "op_sorted_rank": np.zeros((K, N), np.int32),
        "num_parents": np.zeros((K, N), np.int32),
        "insertion_rank": np.zeros((K, N), np.int32),
        "dep_valid": np.zeros((K, M), bool),
        "dep_src": np.zeros((K, M), np.int32),
        "dep_dst": np.zeros((K, M), np.int32),
        "dep_size": np.zeros((K, M), np.float64),
        "dep_mutual": np.zeros((K, M), bool),
        "dep_sorted_rank": np.zeros((K, M), np.int32),
        "f_valid": np.zeros((K, F), bool),
        "f_split": np.ones((K, F), np.int32),
        "f_mem": np.zeros((K, F), np.float64),
        "f_parents": np.full((K, F, P), -1, np.int32),
        "f_sub_fwd": np.full((K, F, S), -1, np.int32),
        "f_sub_bwd": np.full((K, F, S), -1, np.int32),
        "grp_valid": np.zeros((K, G), bool),
        "grp_edges": np.full((K, G, Eg), -1, np.int32),
        "grp_u": np.zeros((K, G, Eg), np.int32),
        "grp_v": np.zeros((K, G, Eg), np.int32),
        "grp_edge_valid": np.zeros((K, G, Eg), bool),
        "grp_msg": np.zeros((K, G), np.float64),
        "sync_valid": np.zeros((K, Sy), bool),
        "sync_edges": np.full((K, Sy, 2), -1, np.int32),
        "sync_u": np.zeros((K, Sy), np.int32),
        "sync_v": np.zeros((K, Sy), np.int32),
        "sync_msg": np.zeros((K, Sy), np.float64),
        "o2o_valid": np.zeros((K, O), bool),
        "o2o_edges": np.zeros((K, O), np.int32),
        "seq_compute": np.zeros(K, np.float64),
    }
    for k, c in enumerate(per_cfg):
        n, m, f = c["n_ops"], c["n_deps"], len(c["f_split"])
        out["n_ops"][k], out["n_deps"][k], out["n_fwd"][k] = n, m, f
        out["op_valid"][k, :n] = True
        out["op_compute"][k, :n] = c["op_compute"]
        out["op_sorted_rank"][k, :n] = c["op_sorted_rank"]
        out["num_parents"][k, :n] = c["num_parents"]
        out["insertion_rank"][k, :n] = c["insertion_rank"]
        out["dep_valid"][k, :m] = True
        out["dep_src"][k, :m] = c["dep_src"]
        out["dep_dst"][k, :m] = c["dep_dst"]
        out["dep_size"][k, :m] = c["dep_size"]
        out["dep_mutual"][k, :m] = c["dep_mutual"]
        out["dep_sorted_rank"][k, :m] = c["dep_sorted_rank"]
        out["f_valid"][k, :f] = True
        out["f_split"][k, :f] = c["f_split"]
        out["f_mem"][k, :f] = c["f_mem"]
        for i, parents in enumerate(c["f_parents"]):
            out["f_parents"][k, i, :len(parents)] = parents
        out["f_sub_fwd"][k, :f, :c["f_sub_fwd"].shape[1]] = c["f_sub_fwd"]
        out["f_sub_bwd"][k, :f, :c["f_sub_bwd"].shape[1]] = c["f_sub_bwd"]
        for gi, g in enumerate(c["groups"]):
            ne = len(g["edges"])
            out["grp_valid"][k, gi] = True
            out["grp_edges"][k, gi, :ne] = g["edges"]
            out["grp_u"][k, gi, :ne] = g["u"]
            out["grp_v"][k, gi, :ne] = g["v"]
            out["grp_edge_valid"][k, gi, :ne] = True
            out["grp_msg"][k, gi] = g["msg"]
        for si, g in enumerate(c["sync"]):
            out["sync_valid"][k, si] = True
            ne = len(g["edges"])
            out["sync_edges"][k, si, :ne] = g["edges"]
            out["sync_u"][k, si] = g["u"][0]
            out["sync_v"][k, si] = g["v"][0]
            out["sync_msg"][k, si] = g["msg"]
        no = len(c["o2o_edges"])
        out["o2o_valid"][k, :no] = True
        out["o2o_edges"][k, :no] = c["o2o_edges"]
        out["seq_compute"][k] = c["seq_compute"]
    return out, pads




# =========================================================================
# The scan-ified allocate_job kernel.
# =========================================================================

def _anchor_masks(free_flat, st: ShapeTables):
    """[n_shapes, n_cells] anchor-validity masks for EVERY distinct shape
    given the flat free-server grid (True = free of other jobs AND enough
    memory — block_ok's conjunction, agents/block_search.py:84-101).

    Shapes and cell counts are static, so the per-cell gathers unroll at
    trace time into pure vector ops on the [C, R, S] grid. Diagonal
    anchors gather through the (dim+1) modulo with explicit in-ramp
    masking (enumerate_block's S == -1 layout)."""
    import jax.numpy as jnp

    C, R, S = st.ramp_shape
    free = free_flat.reshape(C, R, S)
    ii, jj, kk = np.meshgrid(np.arange(C), np.arange(R), np.arange(S),
                             indexing="ij")
    masks = []
    for si in range(len(st.shapes)):
        cnt = int(st.counts[si])
        span = st.spans[si]
        base = st.bases[si]
        ok = jnp.ones((C, R, S), bool)
        for t in range(cnt):
            off = st.offsets[si, t]
            ci = (ii + int(off[0])) % int(base[0])
            cj = (jj + int(off[1])) % int(base[1])
            ck = (kk + int(off[2])) % int(base[2])
            in_ramp = (ci < C) & (cj < R) & (ck < S)
            cell_free = free[np.clip(ci, 0, C - 1),
                             np.clip(cj, 0, R - 1),
                             np.clip(ck, 0, S - 1)]
            ok = ok & jnp.asarray(in_ramp) & cell_free
        # origin span: the host scans diagonal origins k over
        # meta[2] + 2 values, but k and k - S alias the same block, so
        # the k < S anchors cover every class in the same first-fit order
        in_span = jnp.asarray((ii < int(span[0])) & (jj < int(span[1]))
                              & (kk < min(int(span[2]), S)))
        masks.append((ok & in_span).reshape(-1))
    return jnp.stack(masks)


def _first_fit_from_masks(masks, shape_row):
    """First-fit over a (traced) per-split shape-order row: returns
    (shape_id, origin_rank, found) — the first shape in row order with any
    valid anchor, and its smallest lexicographic anchor, exactly
    `first_fit_block`'s (shape order, then origin lex order) semantics."""
    import jax.numpy as jnp

    n_cells = masks.shape[1]
    big = jnp.int32(n_cells + 1)
    lex = jnp.arange(n_cells, dtype=jnp.int32)

    best_shape = jnp.int32(-1)
    best_rank = big
    found = jnp.bool_(False)
    for p in range(shape_row.shape[0]):
        sid = shape_row[p]
        mask = masks[jnp.clip(sid, 0)] & (sid >= 0)
        any_valid = mask.any()
        rank = jnp.where(mask, lex, big).min()
        take = any_valid & ~found
        best_shape = jnp.where(take, sid, best_shape)
        best_rank = jnp.where(take, rank, best_rank)
        found = found | any_valid
    return best_shape, best_rank, found


def jax_allocate_job(mem, other_free, cfg, tables, st: ShapeTables,
                     pads: ConfigPads):
    """Scan-ified `allocate_job` (agents/placers.py:103; reference
    placers/utils.py:532): walk the padded forward-op sequence in topo
    order; per op try parent co-location then the generic first-fit block
    search; scatter memory + op->server assignments between steps.

    ``mem`` [n_srv] free memory per server; ``other_free`` [n_srv] bool
    (True = not occupied by another job; constant during one job's
    allocation); ``cfg`` the traced (model, degree) config row. Returns
    (op_to_server [N] i32, -1 where unplaced, new_mem [n_srv], ok bool).
    On ok=False outputs are partial and must be discarded by the caller
    (the host returns None and the composite action drops the job)."""
    import jax
    import jax.numpy as jnp

    C, R, S = st.ramp_shape
    Smax = pads.max_split
    F, N = pads.n_fwd, pads.n_ops

    row_table = jnp.asarray(st.row)
    offsets_t = jnp.asarray(st.offsets)
    bases_t = jnp.asarray(st.bases)

    f_valid = tables["f_valid"][cfg]
    f_split = tables["f_split"][cfg]
    f_mem = tables["f_mem"][cfg]
    f_parents = tables["f_parents"][cfg]
    f_sub_fwd = tables["f_sub_fwd"][cfg]
    f_sub_bwd = tables["f_sub_bwd"][cfg]

    lane = jnp.arange(Smax)

    def body(carry, f):
        (mem, op_servers, op_count, ots, ok) = carry
        valid = f_valid[f]
        split = f_split[f]
        per_mem = f_mem[f]
        parents = f_parents[f]
        sub_fwd = f_sub_fwd[f]
        sub_bwd = f_sub_bwd[f]

        # ---- parent co-location (placers.py:49-77): first parent whose
        # server count equals split and whose servers all have room
        colo_found = jnp.bool_(False)
        colo_servers = jnp.full((Smax,), -1, jnp.int32)
        for pi in range(parents.shape[0]):
            p = parents[pi]
            servers = op_servers[jnp.clip(p, 0)]
            cnt = op_count[jnp.clip(p, 0)]
            active = lane < cnt
            mem_ok = jnp.all(~active
                             | (mem[jnp.clip(servers, 0)] >= per_mem))
            okp = (p >= 0) & (cnt > 0) & (cnt == split) & mem_ok
            take = okp & ~colo_found
            colo_servers = jnp.where(take, servers, colo_servers)
            colo_found = colo_found | okp

        # ---- regular symmetric block search (find_sub_block order)
        free = other_free & (mem >= per_mem)
        masks = _anchor_masks(free, st)
        shape_row = row_table[jnp.clip(split, 0, row_table.shape[0] - 1)]
        sid, rank, block_found = _first_fit_from_masks(masks, shape_row)

        origin = jnp.stack([rank // (R * S), (rank // S) % R,
                            rank % S]).astype(jnp.int32)
        offs = offsets_t[jnp.clip(sid, 0)]              # [MAX_CELLS, 3]
        base = bases_t[jnp.clip(sid, 0)]                # [3]
        cells = (origin[None, :] + offs) % base[None, :]
        block_servers = ((cells[:, 0] * R + cells[:, 1]) * S
                         + cells[:, 2]).astype(jnp.int32)
        if block_servers.shape[0] < Smax:
            block_servers = jnp.pad(block_servers,
                                    (0, Smax - block_servers.shape[0]))
        else:
            block_servers = block_servers[:Smax]

        servers = jnp.where(colo_found, colo_servers, block_servers)
        placed_ok = colo_found | block_found

        # ---- masked commit of this op's fwd+bwd sub-op pairs. Inactive
        # lanes scatter into a trailing dummy slot so they can never
        # collide with a real index.
        active = (lane < split) & placed_ok & valid & (servers >= 0)
        srv = jnp.clip(servers, 0)
        mem = mem - jnp.zeros_like(mem).at[srv].add(
            jnp.where(active, per_mem, jnp.zeros_like(per_mem)))
        idx_f = jnp.where(active & (sub_fwd >= 0), sub_fwd, N)
        idx_b = jnp.where(active & (sub_bwd >= 0), sub_bwd, N)
        ots = ots.at[idx_f].set(servers)
        ots = ots.at[idx_b].set(servers)

        write = valid & placed_ok
        op_servers = jnp.where(write, op_servers.at[f].set(servers),
                               op_servers)
        op_count = jnp.where(write, op_count.at[f].set(split), op_count)
        return ((mem, op_servers, op_count, ots,
                 ok & (placed_ok | ~valid)), None)

    init = (mem,
            jnp.full((F, Smax), -1, jnp.int32),
            jnp.zeros((F,), jnp.int32),
            jnp.full((N + 1,), -1, jnp.int32),   # +1 dummy scatter slot
            jnp.bool_(True))
    carry, _ = jax.lax.scan(body, init, jnp.arange(F, dtype=jnp.int32))
    (new_mem, _, _, ots, ok) = carry
    return ots[:N], new_mem, ok


# =========================================================================
# Dep pricing + SRPT scores (the array mirror of assign_dep_run_times and
# the SRPT schedulers, for a single placed job).
# =========================================================================

def _jnp_all_reduce_time(msg, n_servers, n_racks, n_cgs, *, x, rate,
                         prop, io):
    """Vectorised mirror of `sim/comm_model.py:ramp_all_reduce_time`
    (reference: actions/utils.py:42-88), identical accumulation order so
    f64 results match the host bit-for-bit. All span inputs are traced
    f64 >= 1; ``msg`` static per group."""
    import jax.numpy as jnp

    mem_frequency, peak_flops, bytes_per_comp = 2e12, 130e12, 2
    data_per_tx = rate / x

    subs = [n_cgs, jnp.minimum(n_cgs, n_servers), n_racks,
            jnp.ceil(n_servers / x)]
    msg_sizes = [jnp.ceil(msg / subs[0])]
    for sub in subs[1:]:
        msg_sizes.append(jnp.ceil(msg_sizes[-1] / sub))

    comm = jnp.zeros_like(msg)
    comp = jnp.zeros_like(msg)
    for step, sub in enumerate(subs):
        live = sub > 1
        safe_sub = jnp.where(live, sub, 2.0)
        # parallel_add_time (comm_model.py:44-56)
        n_op = jnp.ceil(jnp.log2(safe_sub))
        n_bytes = (safe_sub + 1) * bytes_per_comp
        ai = n_op / n_bytes
        # host: parallel_add_time(msg_sizes[step] * sub, sub) computes
        # n_op * (data_sz / devices) / bytes_per_comp; the product and
        # quotient are exact in f64 at these magnitudes
        total_ops = n_op * (msg_sizes[step] * safe_sub / safe_sub) \
            / bytes_per_comp
        add_t = total_ops / jnp.minimum(mem_frequency * ai, peak_flops)
        comp = comp + jnp.where(live, add_t, 0.0)
        # effective_transceivers(x, sub, J=1) (comm_model.py:34-41)
        spare = jnp.minimum(jnp.floor(x / 1.0),
                            jnp.floor(x / (safe_sub - 1))) - 1.0
        bw = (1.0 + spare) * data_per_tx
        comm = comm + jnp.where(
            live, prop + 2 * io + msg_sizes[step] / bw, 0.0)
    return 2 * comm + comp


def jax_price_and_score(sc, cfg, tables, st: ShapeTables,
                        pads: ConfigPads, comm: dict, pair_channel):
    """Price every dep of one placed job and build the SRPT lookahead
    scores — the array mirror of `assign_dep_run_times`
    (sim/actions.py:436), `SRPTOpScheduler`/`SRPTDepScheduler`
    (agents/schedulers.py) and the score assembly in
    `build_native_lookahead_arrays` (sim/jax_lookahead.py:186).

    ``sc`` [N] per-op server codes (grid-flattened, -1 pads). Returns
    (times [M], is_flow [M], chan [M], op_score [N], dep_score [M]).
    """
    import jax.numpy as jnp

    C, R, S = st.ramp_shape
    n_srv = C * R * S
    M, N = pads.n_deps, pads.n_ops
    x = float(comm["x"])
    rate, prop, io = comm["rate"], comm["prop"], comm["io"]

    codes = np.arange(n_srv)
    c_of_np = codes // (R * S)
    r_of_np = (codes // S) % R
    s_of_np = codes % S
    c_of = jnp.asarray(c_of_np, jnp.int32)
    r_of = jnp.asarray(r_of_np, jnp.int32)
    s_of = jnp.asarray(s_of_np, jnp.int32)

    dep_valid = tables["dep_valid"][cfg]
    dep_src = tables["dep_src"][cfg]
    dep_dst = tables["dep_dst"][cfg]
    dep_size = tables["dep_size"][cfg]

    scp = jnp.clip(sc, 0)
    sc_src = scp[jnp.clip(dep_src, 0)]
    sc_dst = scp[jnp.clip(dep_dst, 0)]
    # THE flow predicate, traced: mirrors OpGraph.flow_mask_from_codes
    # (graphs/op_graph.py:268) — the canonical numpy helper cannot run
    # under trace, so this is the one sanctioned re-statement; its parity
    # with the native path is pinned by tests/test_jax_pricing.py's
    # is_flow comparison
    is_flow = dep_valid & (dep_size > 0) & (sc_src != sc_dst)  # ddls-lint: allow(flow-mask) -- the one sanctioned traced mirror of flow_mask_from_codes: the numpy helper cannot run under jit trace; parity pinned by test_jax_pricing.py

    dt = dep_size.dtype
    times = jnp.zeros((M + 1,), dt)

    def span_counts(present):
        """Distinct (s, r, c) component counts among present servers;
        present: [..., n_srv] bool."""
        def cnt(comp_of_np, n_comp):
            onehot = jnp.asarray(np.eye(n_comp)[comp_of_np], dt)
            return ((present.astype(dt) @ onehot) > 0).sum(-1).astype(dt)
        return (cnt(s_of_np, S), cnt(r_of_np, R), cnt(c_of_np, C))

    # ---- candidate collective groups (symmetry-tested)
    grp_valid = tables["grp_valid"][cfg]              # [G]
    grp_edges = tables["grp_edges"][cfg]              # [G, Eg]
    grp_u = tables["grp_u"][cfg]
    grp_v = tables["grp_v"][cfg]
    grp_ev = tables["grp_edge_valid"][cfg]            # [G, Eg]
    grp_msg = tables["grp_msg"][cfg]                  # [G]

    u_codes = scp[jnp.clip(grp_u, 0)]
    v_codes = scp[jnp.clip(grp_v, 0)]
    sentinel = jnp.int32(n_srv + 1)
    u_sorted = jnp.sort(jnp.where(grp_ev, u_codes, sentinel), axis=1)
    v_sorted = jnp.sort(jnp.where(grp_ev, v_codes, sentinel), axis=1)
    symmetric = jnp.all(u_sorted == v_sorted, axis=1) & grp_valid

    G, Eg = grp_u.shape
    rows = jnp.broadcast_to(jnp.arange(G)[:, None], (G, 2 * Eg))
    both = jnp.concatenate([u_codes, v_codes], axis=1)
    both_valid = jnp.concatenate([grp_ev, grp_ev], axis=1)
    present = jnp.zeros((G, n_srv), bool).at[
        rows, jnp.clip(both, 0, n_srv - 1)].max(both_valid)
    n_in_group = present.sum(-1)
    cnt_s, cnt_r, cnt_c = span_counts(present)
    grp_time = _jnp_all_reduce_time(
        grp_msg, jnp.maximum(cnt_s, 1.0), jnp.maximum(cnt_r, 1.0),
        jnp.maximum(cnt_c, 1.0), x=x, rate=rate, prop=prop, io=io)
    grp_time = jnp.where(n_in_group <= 1, jnp.zeros_like(grp_time),
                         grp_time)

    # edges of asymmetric groups fall back to one-to-one pricing
    # (assign_dep_run_times's extra_e path, sim/actions.py:505-540)
    e_size = tables["dep_size"][cfg][jnp.clip(grp_edges, 0)]
    e_same = u_codes == v_codes
    e_o2o = jnp.where(e_same | (e_size == 0), jnp.zeros_like(e_size),
                      prop + 2 * io + e_size / rate)
    e_val = jnp.where(symmetric[:, None], grp_time[:, None], e_o2o)
    times = times.at[jnp.where(grp_ev, grp_edges, M)].set(e_val)

    # ---- sync pairs (always collectives; 2 servers or same-server zero)
    sync_valid = tables["sync_valid"][cfg]            # [Sy]
    sync_edges = tables["sync_edges"][cfg]            # [Sy, 2]
    sync_u = scp[jnp.clip(tables["sync_u"][cfg], 0)]
    sync_v = scp[jnp.clip(tables["sync_v"][cfg], 0)]
    sync_msg = tables["sync_msg"][cfg]
    same = sync_u == sync_v
    scnt_s = jnp.where(s_of[sync_u] == s_of[sync_v], 1.0, 2.0)
    scnt_r = jnp.where(r_of[sync_u] == r_of[sync_v], 1.0, 2.0)
    scnt_c = jnp.where(c_of[sync_u] == c_of[sync_v], 1.0, 2.0)
    sync_time = _jnp_all_reduce_time(sync_msg, scnt_s, scnt_r, scnt_c,
                                     x=x, rate=rate, prop=prop, io=io)
    sync_time = jnp.where(same, jnp.zeros_like(sync_time), sync_time)
    sv = sync_valid[:, None] & (sync_edges >= 0)
    times = times.at[jnp.where(sv, sync_edges, M)].set(
        jnp.broadcast_to(sync_time[:, None], sync_edges.shape))

    # ---- static one-to-one edges
    o2o_valid = tables["o2o_valid"][cfg]
    o2o_edges = tables["o2o_edges"][cfg]
    o_size = tables["dep_size"][cfg][jnp.clip(o2o_edges, 0)]
    o_src = sc_src[jnp.clip(o2o_edges, 0)]
    o_dst = sc_dst[jnp.clip(o2o_edges, 0)]
    o_val = jnp.where((o_src == o_dst) | (o_size == 0),
                      jnp.zeros_like(o_size),
                      prop + 2 * io + o_size / rate)
    times = times.at[jnp.where(o2o_valid, o2o_edges, M)].set(o_val)

    times = times[:M]
    # the cluster zeroes non-flow dep run times at mount
    # (cluster.py:_register_running_job:708-718); SRPT ranking below uses
    # the RAW priced times because the schedulers run before the mount
    mounted_times = jnp.where(is_flow, times, jnp.zeros_like(times))

    # ---- SRPT dep priorities: one stable descending argsort over the
    # priced costs in edge order (agents/schedulers.py:_srpt_priorities)
    m = tables["n_deps"][cfg].astype(dt)
    cost_key = jnp.where(dep_valid, -times, jnp.asarray(jnp.inf, dt))
    order = jnp.lexsort((jnp.arange(M), cost_key))
    dep_pri = jnp.zeros((M,), dt).at[order].set(
        jnp.arange(M, dtype=dt))
    # the lookahead engines read dep priorities off the channel mounts, so
    # only FLOW deps carry their SRPT rank; non-flows score with priority 0
    # (build_native_lookahead_arrays:249-263 prices flow_idx only)
    dep_pri = jnp.where(is_flow, dep_pri, jnp.zeros_like(dep_pri))
    dep_score = dep_pri * (m + 1) + (
        m - tables["dep_sorted_rank"][cfg].astype(dt))

    # ---- SRPT op priorities: per-worker stable sort by compute cost
    # descending, insertion (placement) order breaking ties
    # (agents/schedulers.py:29-38 + OpPlacement.worker_to_ops order)
    op_valid = tables["op_valid"][cfg]
    op_cost = tables["op_compute"][cfg]
    ins = tables["insertion_rank"][cfg]
    same_srv = (sc[:, None] == sc[None, :]) & (sc[:, None] >= 0)
    before = (op_cost[None, :] > op_cost[:, None]) | (
        (op_cost[None, :] == op_cost[:, None]) & (ins[None, :] < ins[:, None]))
    op_pri = (same_srv & before & op_valid[None, :]).sum(1).astype(dt)
    n = tables["n_ops"][cfg].astype(dt)
    op_score = op_pri * (n + 1) + (
        n - tables["op_sorted_rank"][cfg].astype(dt))

    # ---- channels (single-channel complete topology: the direct link)
    chan = jnp.where(is_flow,
                     pair_channel[sc_src, sc_dst], jnp.int32(-1))
    # the host raises on non-finite priced times (comm_model.py:99-100,
    # actions.py:541-543); a traced kernel cannot, so callers must treat
    # finite_ok=False as that hard failure
    finite_ok = jnp.all(jnp.isfinite(mounted_times))
    return mounted_times, is_flow, chan, op_score, dep_score, finite_ok


# =========================================================================
# The jitted decision step + episode loop.
# =========================================================================

# blocked-cause codes in the decision trace (mirrors the host's cause
# strings: actions.py Action.job_id_to_cause_of_unsuccessful_handling +
# cluster._register_blocked_job)
CAUSE_ACCEPTED = 0
CAUSE_NOT_HANDLED = 1        # action 0
CAUSE_OP_PLACEMENT = 2
CAUSE_DEP_PLACEMENT = 3
CAUSE_SLA = 4                # max_acceptable_job_completion_time_exceeded
CAUSE_ENGINE = 5             # lookahead non-convergence / non-finite price
                             # (the host raises; must never appear)

# trace-code <-> host cause-string maps (flight-recorder decision diffs:
# scripts/trace_diff.py converts a jitted decision trace into the same
# `action_decided` events the host env emits). CAUSE_ACCEPTED maps to
# None — accepted decisions carry no blocked cause.
CAUSE_CODE_TO_STR = {
    CAUSE_ACCEPTED: None,
    CAUSE_NOT_HANDLED: "not_handled",
    CAUSE_OP_PLACEMENT: "op_placement",
    CAUSE_DEP_PLACEMENT: "dep_placement",
    CAUSE_SLA: "max_acceptable_job_completion_time_exceeded",
    CAUSE_ENGINE: "engine_failure",
}
CAUSE_STR_TO_CODE = {v: k for k, v in CAUSE_CODE_TO_STR.items()
                     if v is not None}
# the host's per-sub-action causes that collapse onto one code
CAUSE_STR_TO_CODE["op_partition"] = CAUSE_OP_PLACEMENT


@dataclasses.dataclass
class EpisodeTables:
    """Everything static for a jitted canonical-RAMP episode."""
    st: ShapeTables
    tables: dict               # stacked config tables (jnp arrays)
    pads: ConfigPads
    types: List[str]           # model name -> type index (list order)
    degrees: List[int]         # action degree -> cfg column (list order)
    comm: dict                 # {x, rate, prop, io}
    pair_channel: object       # [n_srv, n_srv] jnp i32
    n_chan: int
    n_srv: int
    max_action: int            # env.max_partitions_per_op (action bound)
    sim_end: float
    eps: float                 # cluster.machine_epsilon
    success_reward: float
    fail_reward: float
    worker_mem: float          # per-server memory capacity at reset
    # scenario mirror (ddls_tpu/scenarios): dense speeds + failure
    # windows captured from env.cluster.scenario_runtime; None when the
    # scenario is nominal, so the kernels build NO inflation code and
    # the default episode program stays byte-identical
    scenario: Optional[dict] = None


def build_episode_tables(env, max_degree: Optional[int] = None,
                         quantum: Optional[float] = None) -> EpisodeTables:
    """Assemble the static side of the jitted episode from a host env
    (canonical RAMP single-channel complete topology only)."""
    import jax.numpy as jnp

    topo = env.cluster.topology
    dense = topo.dense_tables()
    if dense["pair_channel"] is None:
        raise ValueError("jitted episode needs a single-channel complete "
                         "topology (canonical RAMP)")
    max_degree = max_degree or env.max_partitions_per_op
    quantum = quantum or env.min_op_run_time_quantum
    if max_degree > topo.num_workers:
        # config columns above num_workers would clamp onto smaller
        # splits' shape rows inside the gather-based block search
        raise ValueError(
            f"max_degree {max_degree} exceeds the {topo.num_workers}-"
            "worker topology; cap max_partitions_per_op")

    gen = env.cluster.jobs_generator
    # one profile graph per distinct model, in sorted-model order
    model_graphs = {}
    for proto in gen.sampler.prototypes:
        model_graphs[proto.details["model"]] = proto.graph
    types = sorted(model_graphs)
    degrees = [d for d in range(1, max_degree + 1)
               if d == 1 or d % 2 == 0]

    st = build_shape_tables(topo.shape, min(max_degree, topo.num_workers))
    cfgs = []
    for m in types:
        for d in degrees:
            cfgs.append(config_tables_for(model_graphs[m], d, quantum))
    tables, pads = stack_config_tables(cfgs, st)
    jt = {k: jnp.asarray(v) for k, v in tables.items()}

    from ddls_tpu.envs.rewards import JobAcceptance

    if not isinstance(env.reward_function, JobAcceptance):
        # other reward families read lookahead details off live Job
        # objects; the jitted trace only carries the acceptance signal
        raise ValueError(
            "jitted episode replay supports the job_acceptance reward "
            f"only, env has {type(env.reward_function).__name__}")
    workers = list(topo.workers.values())
    if len({w.memory_capacity for w in workers}) != 1:
        raise ValueError("jitted episode needs homogeneous worker memory")
    # scenario mirror: completion-time inflation inputs in dense index
    # space (window kind/resource stay HOST ints -> static unroll)
    sr = getattr(env.cluster, "scenario_runtime", None)
    scenario = None
    if sr is not None and not sr.is_nominal:
        scenario = {
            "speeds": np.asarray(sr.speeds, np.float64),
            "t0": np.asarray(sr.win_t0, np.float64),
            "t1": np.asarray(sr.win_t1, np.float64),
            "rate": np.asarray(sr.win_rate, np.float64),
            "kind": [int(k) for k in sr.win_kind],
            "res": [int(r) for r in sr.win_res],
        }
    return EpisodeTables(
        st=st, tables=jt, pads=pads, types=types, degrees=degrees,
        comm={"x": topo.num_communication_groups,
              "rate": topo.channel_bandwidth,
              "prop": topo.intra_gpu_propagation_latency,
              "io": topo.worker_io_latency},
        pair_channel=jnp.asarray(dense["pair_channel"]),
        n_chan=len(dense["channel_ids"]),
        n_srv=topo.num_workers,
        max_action=int(env.max_partitions_per_op),
        sim_end=float(env.max_simulation_run_time),
        eps=env.cluster.machine_epsilon,
        success_reward=getattr(env.reward_function, "success_reward", 1.0),
        fail_reward=getattr(env.reward_function, "fail_reward", -1.0),
        worker_mem=float(workers[0].memory_capacity),
        scenario=scenario)


def build_job_bank(et: EpisodeTables, records: Sequence[dict]) -> dict:
    """Job bank arrays from per-arrival records: each record carries
    {model, num_training_steps, sla_frac, time_arrived}."""
    J = len(records)
    bank = {
        "type": np.zeros(J, np.int32),
        "steps": np.zeros(J, np.float64),
        "sla_frac": np.zeros(J, np.float64),
        "arrival_t": np.zeros(J + 1, np.float64),
    }
    if records and records[0]["time_arrived"] != 0.0:
        # the episode kernel seeds job 0 as queued at t=0, mirroring the
        # cluster reset ("first arrival at t=0", cluster.py:175-177)
        raise ValueError("job bank must start with a t=0 arrival")
    for i, r in enumerate(records):
        bank["type"][i] = et.types.index(r["model"])
        bank["steps"][i] = r["num_training_steps"]
        bank["sla_frac"][i] = r["sla_frac"]
        bank["arrival_t"][i] = r["time_arrived"]
    bank["arrival_t"][J] = np.inf
    return bank


def sample_job_bank(et: EpisodeTables, env, n_jobs: int, seed: int) -> dict:
    """A job bank SAMPLED from the env's own workload machinery — the
    device-collection counterpart of the host cluster's arrival stream
    (cluster.py:224: ``jobs_generator.sample_job()`` +
    ``sample_interarrival_time()``).

    The env's generator is deep-copied so its pool state (sampling-mode
    bookkeeping, job ids) is untouched, and BOTH process-global rngs the
    workload machinery draws from (numpy for the distributions, python's
    ``random`` for pool shuffles on refill) are seeded then
    snapshotted/restored around the draw, so banks are determined by
    ``seed`` alone and building them never perturbs the host envs'
    stochastic streams.

    A ``remove``-mode pool that exhausts before ``n_jobs`` ends the bank
    early — the host counterpart returns an infinite interarrival there
    and the episode simply sees no further arrivals.
    """
    import copy
    import random as _random

    gen = copy.deepcopy(env.cluster.jobs_generator)
    np_state = np.random.get_state()
    py_state = _random.getstate()
    try:
        np.random.seed(seed)
        _random.seed(seed ^ 0x5DEECE66D)
        t, recs = 0.0, []
        for _ in range(n_jobs):
            if len(gen.sampler) == 0:
                break
            job = gen.sample_job()
            recs.append({
                "model": job.details.get("model"),
                "num_training_steps": job.num_training_steps,
                "sla_frac": float(job.max_acceptable_jct_frac),
                "time_arrived": t,
            })
            t += float(gen.sample_interarrival_time())
    finally:
        np.random.set_state(np_state)
        _random.setstate(py_state)
    return build_job_bank(et, recs)


def _episode_kernels(et: EpisodeTables):
    """Shared decision / event-clock / initial-state kernels for the
    replay (`make_episode_fn`) and policy (`make_policy_episode_fn`)
    episodes."""
    import types as _types

    import jax
    import jax.numpy as jnp

    st, pads = et.st, et.pads
    n_srv, n_chan = et.n_srv, et.n_chan
    R = n_srv  # max concurrent jobs: every running job owns >= 1 server
    n_deg = len(et.degrees)
    # action value -> cfg column (-1 for odd/invalid actions); sized by
    # the env's full action bound so no action can clamp onto a valid
    # column through the gather
    deg_col = np.full(max(et.max_action, max(et.degrees)) + 1, -1,
                      np.int32)
    for i, d in enumerate(et.degrees):
        deg_col[d] = i
    deg_col = jnp.asarray(deg_col)
    eps = et.eps
    sim_end = et.sim_end

    # scenario inflation mirror (ddls_tpu/scenarios/failures.py): same
    # shared f64 formula the host applies at lookahead registration —
    # SLA stays judged on the NOMINAL jct (eval_cfg), only the committed
    # completion time and the traced jct are adjusted. None -> no code.
    scenario = et.scenario
    if scenario is not None:
        from ddls_tpu.scenarios.failures import (FAILURE_WORKER_PREEMPT,
                                                 inflate_duration_jax)

        _sdt = et.tables["dep_size"].dtype
        sc_speeds = jnp.asarray(scenario["speeds"], _sdt)
        sc_t0 = jnp.asarray(scenario["t0"], _sdt)
        sc_t1 = jnp.asarray(scenario["t1"], _sdt)
        sc_rate = jnp.asarray(scenario["rate"], _sdt)
        sc_kind, sc_res = scenario["kind"], scenario["res"]

        def scenario_adjusted(t, jct, srv_mask, chan_mask):
            r0 = jnp.min(jnp.where(srv_mask, sc_speeds,
                                   jnp.asarray(jnp.inf, _sdt)))
            affects = [srv_mask[r] if k == FAILURE_WORKER_PREEMPT
                       else chan_mask[r]
                       for k, r in zip(sc_kind, sc_res)]
            return inflate_duration_jax(t, jct, r0, sc_t0, sc_t1,
                                        sc_rate, affects)

    def eval_cfg(bank, carry, row, cfg, memo=None):
        """Evaluate ONE (job, degree) candidate against the live cluster
        state: placement, dep pricing, channel check, lookahead, SLA —
        everything a decision needs, minus the commit. XLA dead-code
        eliminates the commit outputs when a caller (candidate pricing)
        only reads (ok, jct). Returns ``(ev, memo)``; with ``memo`` (the
        in-kernel lookahead memo table, sim/jax_memo.py) the lookahead is
        probed under the host memo-key signature (cfg row, canonical
        worker grouping, mounted dep times) and served from the table on
        a bitwise full-key hit — memoised and recomputed results are
        bit-identical by construction, any precision mode."""
        (t, mem, srv_job, chan_occ, slot_valid, slot_t_done, slot_mem,
         slot_servers, slot_chan) = carry
        dt = mem.dtype
        steps = bank["steps"][row].astype(dt)
        other_free = srv_job < 0
        ots, new_mem, ok_place = jax_allocate_job(
            mem, other_free, cfg, et.tables, st, pads)
        times, is_flow, chan, op_score, dep_score, finite_ok = \
            jax_price_and_score(ots, cfg, et.tables, st, pads,
                                et.comm, et.pair_channel)
        occ_vals = chan_occ[jnp.clip(chan, 0)]
        ok_chan = jnp.all(~is_flow | (occ_vals < 0))

        from ddls_tpu.sim.jax_lookahead import jax_lookahead
        op_valid = et.tables["op_valid"][cfg]

        def run_lookahead(skip=None):
            # ``skip`` is the memo probe's hit mask, threaded into the
            # lookahead while_loop cond (jax_memo.WIDE_PROBE_SURFACE) so
            # hit lanes contribute zero trips to the batched loop
            t_la, _, _, _, ok = jax_lookahead(
                et.tables["op_compute"][cfg], op_valid,
                jnp.where(op_valid, ots, -1), op_score,
                et.tables["num_parents"][cfg], times,
                et.tables["dep_valid"][cfg], et.tables["dep_src"][cfg],
                et.tables["dep_dst"][cfg], et.tables["dep_mutual"][cfg],
                is_flow, dep_score, chan[:, None],
                num_workers=n_srv, num_channels=n_chan, skip=skip)
            return t_la, ok

        if memo is None:
            t_step, ok_la = run_lookahead()
        else:
            groups = jax_memo.canonical_groups(
                jnp.where(op_valid, ots, -1), op_valid)
            (t_step, ok_la), memo = jax_memo.memo_lookahead(
                memo, cfg, groups, times, run_lookahead)
        jct = t_step * steps
        max_jct = (bank["sla_frac"][row].astype(dt)
                   * et.tables["seq_compute"][cfg].astype(dt) * steps)
        sla_ok = ~(jct > max_jct)
        engine_ok = ok_la & finite_ok
        srv_mask = jnp.zeros((n_srv,), bool).at[
            jnp.clip(ots, 0)].max(op_valid & (ots >= 0))
        chan_mask = jnp.zeros((n_chan,), bool).at[
            jnp.clip(chan, 0)].max(is_flow)
        return {"ok_place": ok_place, "ok_chan": ok_chan,
                "engine_ok": engine_ok, "sla_ok": sla_ok, "jct": jct,
                "new_mem": new_mem, "srv_mask": srv_mask,
                "chan_mask": chan_mask}, memo

    def price_all(bank, carry, row):
        """In-kernel candidate pricing: (placeable [n_deg], jct [n_deg])
        for every degree column against the live cluster state — the
        jitted counterpart of sim/candidate_pricing.py. One VMAPPED
        evaluation over the cfg batch (cfg only feeds gathers), so the
        traced program contains the placement/pricing/lookahead kernels
        once, not n_deg times."""
        jtype = bank["type"][row]
        cfgs = jtype * n_deg + jnp.arange(n_deg, dtype=jnp.int32)
        # memo-less on purpose: this vmap batches the CFG axis within
        # one env, whose single memo table cannot absorb n_deg scattered
        # insertions through an in_axes=None carry (the wide probe
        # batches over LANES, each with its own table) — the host
        # counterpart keeps candidate pricing fast through its own
        # prefetch instead
        ev, _ = jax.vmap(eval_cfg, in_axes=(None, None, None, 0))(
            bank, carry, row, cfgs)
        return (ev["ok_place"] & ev["ok_chan"] & ev["engine_ok"],
                ev["jct"])

    def decision(bank, carry, action, row, memo=None):
        (t, mem, srv_job, chan_occ, slot_valid, slot_t_done, slot_mem,
         slot_servers, slot_chan) = carry
        dt = mem.dtype
        jtype = bank["type"][row]
        cfg = jtype * n_deg + deg_col[jnp.clip(action, 0)]

        def heavy(mm):
            ev, mm = eval_cfg(bank, carry, row, cfg, mm)
            accept = (ev["ok_place"] & ev["ok_chan"] & ev["sla_ok"]
                      & ev["engine_ok"])
            cause = jnp.where(
                ~ev["ok_place"], CAUSE_OP_PLACEMENT,
                jnp.where(~ev["ok_chan"], CAUSE_DEP_PLACEMENT,
                          jnp.where(~ev["engine_ok"], CAUSE_ENGINE,
                                    jnp.where(~ev["sla_ok"], CAUSE_SLA,
                                              CAUSE_ACCEPTED))))
            return (accept, cause.astype(jnp.int32), ev["jct"],
                    ev["new_mem"], ev["srv_mask"], ev["chan_mask"]), mm

        def zero(mm):
            return (jnp.bool_(False), jnp.int32(CAUSE_NOT_HANDLED),
                    jnp.zeros((), dt), mem, jnp.zeros((n_srv,), bool),
                    jnp.zeros((n_chan,), bool)), mm

        # actions outside the jitted degree set (odd > 1 — the host
        # coerces masked-invalid actions to 0, partitioning_env.py:195)
        # take the zero path instead of wrapping deg_col's -1 into
        # another config row
        action_ok = (action > 0) & (deg_col[jnp.clip(action, 0)] >= 0)
        ((accept, cause, jct, new_mem, srv_mask, chan_mask),
         memo) = jax.lax.cond(action_ok, heavy, zero, memo)

        if scenario is not None:
            # inflate AFTER the accept/cause decision: admission is
            # failure-blind (host: _register_completed_lookahead)
            jct = scenario_adjusted(t, jct, srv_mask, chan_mask)

        slot = jnp.argmin(slot_valid).astype(jnp.int32)  # first free slot
        accept = accept & ~jnp.all(slot_valid)  # cannot trigger (R=n_srv)
        delta = mem - new_mem
        mem2 = jnp.where(accept, new_mem, mem)
        srv_job2 = jnp.where(accept & srv_mask, slot, srv_job)
        chan_occ2 = jnp.where(accept & chan_mask, slot, chan_occ)
        slot_valid2 = slot_valid.at[slot].set(
            jnp.where(accept, True, slot_valid[slot]))
        slot_t_done2 = slot_t_done.at[slot].set(
            jnp.where(accept, t + jct, slot_t_done[slot]))
        slot_mem2 = slot_mem.at[slot].set(
            jnp.where(accept, delta, slot_mem[slot]))
        slot_servers2 = slot_servers.at[slot].set(
            jnp.where(accept, srv_mask, slot_servers[slot]))
        slot_chan2 = slot_chan.at[slot].set(
            jnp.where(accept, chan_mask, slot_chan[slot]))
        reward = jnp.where(accept, et.success_reward, et.fail_reward)

        return ((t, mem2, srv_job2, chan_occ2, slot_valid2, slot_t_done2,
                 slot_mem2, slot_servers2, slot_chan2),
                (reward.astype(dt), accept, cause, jct), memo)

    def advance(bank, carry, queue_row, ptr, next_arrival, done,
                completed):
        """Tick the event clock until a job queues or the episode ends
        (cluster.py:616-657 + the env's auto-step loop)."""
        (t, mem, srv_job, chan_occ, slot_valid, slot_t_done, slot_mem,
         slot_servers, slot_chan) = carry
        dt = mem.dtype
        J = bank["type"].shape[0]

        def cond(s):
            (_, _, _, _, _, _, _, _, _, queue_row, _, _, done, _) = s
            return (queue_row < 0) & ~done

        def body(s):
            (t, mem, srv_job, chan_occ, slot_valid, slot_t_done,
             slot_mem, slot_servers, slot_chan, queue_row, ptr,
             next_arrival, done, completed) = s
            remaining = jnp.where(slot_valid, slot_t_done - t,
                                  jnp.asarray(jnp.inf, dt))
            tick = jnp.minimum(jnp.minimum(next_arrival - t, sim_end - t),
                               remaining.min())
            tick = jnp.maximum(tick, 0.0)
            t2 = t + tick

            completions = slot_valid & (slot_t_done - t2 - eps <= 0)
            mem2 = mem + (completions.astype(dt) @ slot_mem)
            freed_srv = (completions[:, None] & slot_servers).any(0)
            freed_chan = (completions[:, None] & slot_chan).any(0)
            srv_job2 = jnp.where(freed_srv, -1, srv_job)
            chan_occ2 = jnp.where(freed_chan, -1, chan_occ)
            slot_valid2 = slot_valid & ~completions
            completed2 = completed + completions.sum().astype(jnp.int32)

            arrived = (ptr < J) & (t2 + eps >= next_arrival)
            queue_row2 = jnp.where(arrived, ptr, queue_row)
            ptr2 = ptr + arrived.astype(jnp.int32)
            next_arrival2 = jnp.where(
                arrived, bank["arrival_t"][jnp.clip(ptr2, 0, J)],
                next_arrival)

            done2 = (t2 >= sim_end) | ((ptr2 >= J)
                                       & ~slot_valid2.any()
                                       & (queue_row2 < 0))
            return (t2, mem2, srv_job2, chan_occ2, slot_valid2,
                    slot_t_done, slot_mem, slot_servers, slot_chan,
                    queue_row2, ptr2, next_arrival2, done2, completed2)

        s = carry + (queue_row, ptr, next_arrival, done, completed)
        s = jax.lax.while_loop(cond, body, s)
        return s[:9], s[9], s[10], s[11], s[12], s[13]

    def init_state(bank):
        dt = et.tables["dep_size"].dtype
        carry0 = (jnp.zeros((), dt),                       # t
                  jnp.full((n_srv,), et.worker_mem, dt),   # mem
                  jnp.full((n_srv,), -1, jnp.int32),       # srv_job
                  jnp.full((n_chan,), -1, jnp.int32),      # chan_occ
                  jnp.zeros((R,), bool),                   # slot_valid
                  jnp.zeros((R,), dt),                     # slot_t_done
                  jnp.zeros((R, n_srv), dt),               # slot_mem
                  jnp.zeros((R, n_srv), bool),             # slot_servers
                  jnp.zeros((R, n_chan), bool))            # slot_chan
        return (carry0,
                jnp.int32(0),                              # queue_row: job 0
                jnp.int32(1),                              # ptr
                bank["arrival_t"][1],                      # next arrival
                jnp.bool_(False),
                jnp.int32(0),
                (jnp.int32(0), jnp.int32(0), jnp.zeros((), dt)))

    return _types.SimpleNamespace(decision=decision, advance=advance,
                                  init_state=init_state,
                                  price_all=price_all)


def make_episode_fn(et: EpisodeTables,
                    memo_cfg: Optional[jax_memo.MemoConfig]
                    = DEFAULT_EPISODE_MEMO):
    """Build the jitted episode replay: (bank, actions [n_decisions]) ->
    per-decision traces (reward, accept, cause, jct, t) + final counters.

    One `lax.scan` over decisions; each decision runs the scan-ified
    placer, the pricing/score kernel and the jitted lookahead under a
    `lax.cond` (skipped for action 0), then a `lax.while_loop` advances
    the event clock (completions, arrivals) to the next decision exactly
    like `RampClusterEnvironment.step`'s tick loop (cluster.py:616-657).

    The in-kernel lookahead memo (``memo_cfg``, sim/jax_memo.py) rides
    the scan carry and defaults ON — hits and recomputes are bitwise
    identical, so results never depend on it, and the batched probe
    stays effective under vmap (hit lanes are masked out of the
    lookahead while_loop; each lane carries its own table). With the
    memo on, the output dict carries the final
    ``memo_hits``/``memo_misses``/``memo_evicts`` counters.
    """
    import jax
    import jax.numpy as jnp

    k = _episode_kernels(et)
    decision, advance = k.decision, k.advance

    def episode(bank, actions):
        dt = et.tables["dep_size"].dtype

        def scan_body(sm, action):
            state, memo = sm
            (carry, queue_row, ptr, next_arrival, done, completed,
             counters) = state
            t = carry[0]
            has_job = (queue_row >= 0) & ~done

            def run(mm):
                new_carry, (reward, accept, cause, jct), mm = decision(
                    bank, carry, action, jnp.clip(queue_row, 0), mm)
                return (new_carry, reward, accept, cause, jct), mm

            def skip(mm):
                return (carry, jnp.zeros((), dt), jnp.bool_(False),
                        jnp.int32(-1), jnp.zeros((), dt)), mm

            (new_carry, reward, accept, cause, jct), memo = jax.lax.cond(
                has_job, run, skip, memo)
            accepted, blocked, ret = counters
            counters2 = (accepted + (has_job & accept),
                         blocked + (has_job & ~accept),
                         ret + jnp.where(has_job, reward, 0.0))
            queue_row2 = jnp.where(has_job, -1, queue_row)
            (carry3, queue_row3, ptr3, next_arrival3, done3,
             completed3) = advance(bank, new_carry, queue_row2, ptr,
                                   next_arrival, done, completed)
            out = (reward, accept, cause, jct, t, has_job)
            return (((carry3, queue_row3, ptr3, next_arrival3, done3,
                      completed3, counters2), memo), out)

        memo0 = (jax_memo.memo_init(et, memo_cfg)
                 if memo_cfg is not None else None)
        state0 = (k.init_state(bank), memo0)
        (final, memo), trace = jax.lax.scan(scan_body, state0, actions)
        (carry, queue_row, ptr, next_arrival, done, completed,
         counters) = final
        out = {"trace": trace, "accepted": counters[0],
               "blocked": counters[1], "ret": counters[2],
               "completed": completed, "t": carry[0], "done": done,
               # host episode finalisation blocks anything still running
               # at simulation end (cluster.py:1010-1013); num_jobs_blocked
               # parity = decision blocks + still-running slots
               "blocked_total": (counters[1]
                                 + carry[4].sum().astype(jnp.int32)),
               "arrived": ptr}
        if memo is not None:
            out.update(jax_memo.memo_trace_counters(memo))
        return out

    # bank arrays are traced arguments: one compile serves every bank of
    # the same shape (per-seed episodes, vmapped batches)
    return jax.jit(episode)


# =========================================================================
# In-kernel observations + policy-in-the-loop episodes (the full
# HBM-resident rollout: obs, policy forward, sampling, decision, event
# clock — all inside one lax.scan).
# =========================================================================

def build_obs_tables(env, et: EpisodeTables) -> dict:
    """Static per-type observation rows + the normalisation constants the
    kernel needs to rebuild the job-specific entries.

    Everything in the standard observation (envs/obs.py) except seven
    entries is a pure function of the job's MODEL: node/edge features and
    most graph features. The seven dynamic entries are rebuilt in-kernel:
    graph_features[2,3,8] (sequential JCT / max-acceptable JCT / training
    steps — functions of the bank row), graph_features[4,5] (SLA frac),
    graph_features[15,16] (cluster occupancy), plus the action mask.
    """
    gen = env.cluster.jobs_generator
    obs_fn = env.observation_function
    with_prices = bool(getattr(obs_fn, "include_candidate_prices", False))
    params = gen.jobs_params

    proto_by_model = {}
    for proto in gen.sampler.prototypes:
        proto_by_model.setdefault(proto.details["model"], proto)

    rows = []
    for model in et.types:
        job = proto_by_model[model]
        obs = obs_fn.encode(job, env)
        obs = {k: np.asarray(v) for k, v in obs.items()}
        if with_prices:
            # the template's baked price block is decision-time data of
            # whatever job was queued at encode time — drop it; the
            # kernel rebuilds the block from its own in-kernel pricing
            obs["graph_features"] = obs["graph_features"][
                :-(et.max_action + 1)]
        rows.append(obs)

    def stack(key):
        return np.stack([r[key] for r in rows])

    def bounds(key):
        return (float(params[f"min_{key}"]), float(params[f"max_{key}"]))

    return {
        "node_features": stack("node_features"),
        "edge_features": stack("edge_features"),
        "edges_src": stack("edges_src"),
        "edges_dst": stack("edges_dst"),
        "node_split": stack("node_split"),
        "edge_split": stack("edge_split"),
        "graph_features": stack("graph_features"),
        # the exact compute.sum() the host multiplies by num_training_steps
        # (demands/job.py:55) — dividing seq_completion_time back out by
        # steps would cost an ulp and break the bit-equal obs contract
        "orig_seq_sum": np.array(
            [float(proto_by_model[m].graph.finalize()["compute"].sum())
             for m in et.types], np.float64),
        "seq_bounds": bounds("job_sequential_completion_times"),
        "jct_bounds": bounds("max_acceptable_job_completion_times"),
        "frac_bounds": bounds("max_acceptable_job_completion_time_fracs"),
        "steps_bounds": bounds("job_num_training_steps"),
        # static per-action "a symmetric block shape exists" row
        # (envs/obs.py:action_is_valid:56-59)
        "shapes_exist": np.array(
            [bool(block_shapes_for(factor_pairs(a), et.st.ramp_shape))
             for a in range(et.max_action + 1)], bool),
        "with_prices": with_prices,
    }


def _kernel_action_mask(ot: dict, et: EpisodeTables, n_occupied):
    """The obs action mask (envs/obs.py:action_is_valid) from occupancy:
    0 always; 1 needs a free worker; even a needs a <= free workers AND
    an existing block shape. The ONE in-kernel statement of the rule."""
    import jax.numpy as jnp

    free = et.n_srv - n_occupied
    a = jnp.arange(et.max_action + 1)
    exists = jnp.asarray(ot["shapes_exist"])
    return ((a == 0)
            | ((a == 1) & (free >= 1))
            | ((a > 1) & (a % 2 == 0) & (a <= free) & exists))


def _kernel_obs(ot: dict, et: EpisodeTables, jtype, frac, steps,
                n_occupied, n_running, price_feats=None):
    """Rebuild the exact host observation for one queued job inside jit.

    Dynamic entries are computed with the host's formulas (f64) and the
    whole feature vector is cast to f32 like the host encoder, so the
    policy sees bit-identical inputs."""
    import jax.numpy as jnp

    def norm(val, lo, hi):
        return jnp.where(hi - lo == 0, 1.0, (val - lo) / (hi - lo))

    gf = jnp.asarray(ot["graph_features"])[jtype].astype(jnp.float64)
    seq_ct = jnp.asarray(ot["orig_seq_sum"])[jtype] * steps
    max_jct = frac * seq_ct
    gf = gf.at[2].set(norm(seq_ct, *ot["seq_bounds"]))
    gf = gf.at[3].set(norm(max_jct, *ot["jct_bounds"]))
    gf = gf.at[4].set(norm(frac, *ot["frac_bounds"]))
    gf = gf.at[5].set(frac)
    gf = gf.at[8].set(norm(steps, *ot["steps_bounds"]))
    n_srv = et.n_srv
    gf = gf.at[15].set(n_occupied / n_srv)
    gf = gf.at[16].set(n_running / n_srv)

    mask = _kernel_action_mask(ot, et, n_occupied)
    n_feat = jnp.asarray(ot["graph_features"]).shape[1]
    gf17 = jnp.clip(gf[:n_feat - mask.shape[0]], 0.0, 1.0)
    parts = [gf17, mask.astype(jnp.float64)]
    if ot.get("with_prices"):
        if price_feats is None:
            raise ValueError("obs tables carry price features; pass "
                             "price_feats (envs/obs.py:_price_features)")
        parts.append(price_feats.astype(jnp.float64))
    gf = jnp.concatenate(parts)

    return {
        "action_set": jnp.arange(et.max_action + 1, dtype=jnp.int32),
        "node_features": jnp.asarray(ot["node_features"])[jtype],
        "edge_features": jnp.asarray(ot["edge_features"])[jtype],
        "edges_src": jnp.asarray(ot["edges_src"])[jtype],
        "edges_dst": jnp.asarray(ot["edges_dst"])[jtype],
        "node_split": jnp.asarray(ot["node_split"])[jtype],
        "edge_split": jnp.asarray(ot["edge_split"])[jtype],
        "graph_features": gf.astype(jnp.float32),
        "action_mask": mask.astype(jnp.int32),
    }


def make_policy_episode_fn(et: EpisodeTables, ot: dict, model,
                           greedy: bool = False,
                           memo_cfg: Optional[jax_memo.MemoConfig]
                           = DEFAULT_EPISODE_MEMO):
    """Full policy-in-the-loop jitted episode: (bank, params, rng) ->
    traces. Per decision the kernel rebuilds the observation, runs the
    GNN policy forward, samples (or argmaxes) an action under the mask,
    then executes the decision + event clock exactly like
    `make_episode_fn`. ONE device dispatch per episode — the complete
    §5.8 HBM-resident rollout shape; vmap over (bank, rng) for batched
    collection (the memo stays ON there: the batched probe masks hit
    lanes out of the lookahead while_loop and each lane carries its own
    table — sim/jax_memo.py, ISSUE 17)."""
    import jax
    import jax.numpy as jnp

    k = _episode_kernels(et)

    def episode(bank, params, rng):
        dt = et.tables["dep_size"].dtype

        def scan_body(sm, step_rng):
            state, memo = sm
            (carry, queue_row, ptr, next_arrival, done, completed,
             counters) = state
            t = carry[0]
            has_job = (queue_row >= 0) & ~done
            row = jnp.clip(queue_row, 0)

            def run(mm):
                # obs rebuild + GNN forward + sampling live INSIDE the
                # cond so dead scan steps after episode end cost nothing
                srv_job = carry[2]
                slot_valid = carry[4]
                price_feats = None
                if ot.get("with_prices"):
                    # in-kernel candidate pricing as observation features
                    # (envs/obs.py:_price_features: min(jct/limit, 2)/2,
                    # 1.0 for unpriceable; the host prices only
                    # mask-valid degrees). Limit multiplies in the HOST's
                    # association order frac * (sum * steps)
                    # (demands/job.py:55,273) — bit-equal features
                    frac64 = bank["sla_frac"][row].astype(jnp.float64)
                    steps64 = bank["steps"][row].astype(jnp.float64)
                    ok, jcts = k.price_all(bank, carry, row)
                    limit = jnp.maximum(
                        frac64 * (jnp.asarray(ot["orig_seq_sum"])[
                            bank["type"][row]] * steps64), 1e-30)
                    degs = jnp.asarray(np.array(et.degrees, np.int32))
                    dmask = _kernel_action_mask(
                        ot, et, (srv_job >= 0).sum())[degs]
                    vals = jnp.minimum(jcts.astype(jnp.float64) / limit,
                                       2.0) / 2.0
                    price_feats = jnp.ones(
                        (et.max_action + 1,), jnp.float64).at[degs].set(
                        jnp.where(ok & dmask, vals, 1.0))
                obs = _kernel_obs(
                    ot, et, bank["type"][row],
                    bank["sla_frac"][row].astype(jnp.float64),
                    bank["steps"][row].astype(jnp.float64),
                    (srv_job >= 0).sum(), slot_valid.sum(),
                    price_feats=price_feats)
                logits, value = model.apply(params, obs)
                if greedy:
                    action = jnp.argmax(logits).astype(jnp.int32)
                else:
                    action = jax.random.categorical(
                        step_rng, logits).astype(jnp.int32)
                logp = jax.nn.log_softmax(logits)[action]
                new_carry, (reward, accept, cause, jct), mm = k.decision(
                    bank, carry, action, row, mm)
                return (new_carry, action, logp, value, reward, accept,
                        cause, jct), mm

            def skip(mm):
                f32 = jnp.float32
                return (carry, jnp.int32(0), f32(0.0), f32(0.0),
                        jnp.zeros((), dt), jnp.bool_(False),
                        jnp.int32(-1), jnp.zeros((), dt)), mm

            ((new_carry, action, logp, value, reward, accept, cause,
              jct), memo) = jax.lax.cond(has_job, run, skip, memo)
            accepted, blocked, ret = counters
            counters2 = (accepted + (has_job & accept),
                         blocked + (has_job & ~accept),
                         ret + jnp.where(has_job, reward, 0.0))
            queue_row2 = jnp.where(has_job, -1, queue_row)
            (carry3, queue_row3, ptr3, next_arrival3, done3,
             completed3) = k.advance(bank, new_carry, queue_row2,
                                     ptr, next_arrival, done,
                                     completed)
            out = (action, logp, value, reward, accept, cause, jct, t,
                   has_job)
            return (((carry3, queue_row3, ptr3, next_arrival3, done3,
                      completed3, counters2), memo), out)

        memo0 = (jax_memo.memo_init(et, memo_cfg)
                 if memo_cfg is not None else None)
        state0 = (k.init_state(bank), memo0)
        n_steps = bank["type"].shape[0]
        rngs = jax.random.split(rng, n_steps)
        (final, memo), trace = jax.lax.scan(scan_body, state0, rngs)
        counters = final[6]
        out = {"trace": trace, "accepted": counters[0],
               "blocked": counters[1], "ret": counters[2],
               "completed": final[5], "t": final[0][0],
               "done": final[4],
               # host episode finalisation blocks anything still running
               # at simulation end (cluster.py:1010-1013); num_jobs_blocked
               # parity = decision blocks + still-running slots
               "blocked_total": (counters[1]
                                 + final[0][4].sum().astype(jnp.int32)),
               # ptr = jobs that entered the queue (host num_jobs_arrived
               # semantics, cluster.py:240) — the same expression the
               # segment kernel traces as ep_arrived
               "arrived": final[2]}
        if memo is not None:
            out.update(jax_memo.memo_trace_counters(memo))
        return out

    return jax.jit(episode)


# =========================================================================
# Fixed-length segment collection (the PPO rollout shape): the env lives
# on device across collect calls; episodes reset in-kernel.
# =========================================================================

def segment_init(et: EpisodeTables, bank,
                 memo_cfg: Optional[jax_memo.MemoConfig] = None):
    """Initial carried simulator state for `make_segment_fn`. With
    ``memo_cfg`` the state is ``(env_state, memo_table)`` — pass the
    SAME config the segment fn was built with."""
    state = _episode_kernels(et).init_state(bank)
    if memo_cfg is None:
        return state
    return (state, jax_memo.memo_init(et, memo_cfg))


def make_segment_fn(et: EpisodeTables, ot: dict, model, n_steps: int,
                    trace_obs: bool = False,
                    memo_cfg: Optional[jax_memo.MemoConfig] = None):
    """(bank, params, sim_state, rng) -> (new_sim_state, trace, next_fields)

    Exactly ``n_steps`` policy decisions per call — the [T, B] segment
    shape PPO consumes — with the simulator state carried across calls
    and episodes resetting IN-KERNEL to a fresh run of the same bank when
    they end (``done`` marks the boundary step, so GAE truncates there).

    The trace carries, per step: action, logp, value, reward, done, and
    the compact observation fields (jtype, sla frac, steps, occupied
    count, running count) from which `rebuild_obs_batch` reconstructs the
    exact observation on host for the learner's re-forward.
    ``next_fields`` are the same fields for the bootstrap state after the
    segment.

    ``memo_cfg`` threads the in-kernel lookahead memo (sim/jax_memo.py)
    through the carried state as ``(env_state, memo_table)``; per-step
    cumulative ``memo_hits``/``memo_misses``/``memo_evicts`` counters
    ride the trace next to the episode counters (drained with them at
    sync boundaries). THE PERSISTENCE CONTRACT: the in-kernel episode
    reset below restores the env state to ``fresh`` but NEVER touches
    the memo — the exact mirror of the host ``cluster.lookahead_cache``
    persisting across ``reset()`` under an unchanged workload signature
    (each lane replays one fixed bank, so its signature never changes).
    Effective at EVERY lane count (``jax_memo.resolve_memo_cfg``'s
    "auto" enables it everywhere): under a multi-lane vmap the batched
    probe masks hit lanes out of the lookahead while_loop and each lane
    carries its own table.

    ``trace_obs=True`` additionally carries the FULL observation dict the
    in-scan policy forward consumed (``trace["obs"]``) — the in-scan
    update carry for the fused epoch (rl/fused.py): its learner update
    reads the segment's own obs instead of re-deriving them from the
    compact fields, skipping a second `_kernel_obs` sweep over T x B
    samples. The values are the SAME `_kernel_obs` outputs either way
    (one function, elementwise per sample), so the fused x64 parity
    against the rebuild-from-fields path stays exact; host collectors
    keep ``trace_obs=False`` — shipping full padded obs through the
    per-collect device->host fetch is precisely what the compact trace
    exists to avoid.
    """
    import jax
    import jax.numpy as jnp

    if ot.get("with_prices"):
        raise ValueError(
            "segment collection does not support price-feature "
            "observations (the compact PPO trace carries no pricing "
            "state); build obs tables from an env without "
            "obs_include_candidate_prices")
    k = _episode_kernels(et)

    def obs_fields(bank, state):
        (carry, queue_row, *_rest) = state
        row = jnp.clip(queue_row, 0)
        srv_job = carry[2]
        slot_valid = carry[4]
        return {"jtype": bank["type"][row],
                "frac": bank["sla_frac"][row].astype(jnp.float64),
                "steps": bank["steps"][row].astype(jnp.float64),
                "n_occupied": (srv_job >= 0).sum().astype(jnp.int32),
                "n_running": slot_valid.sum().astype(jnp.int32)}

    def segment(bank, params, sim_state, rng):
        dt = et.tables["dep_size"].dtype
        if memo_cfg is not None:
            sim_state, memo0 = sim_state
        else:
            memo0 = None
        fresh = k.init_state(bank)

        def scan_body(sm, step_rng):
            state, memo = sm
            (carry, queue_row, ptr, next_arrival, done, completed,
             counters) = state
            row = jnp.clip(queue_row, 0)
            fields = obs_fields(bank, state)
            obs = _kernel_obs(ot, et, fields["jtype"], fields["frac"],
                              fields["steps"], fields["n_occupied"],
                              fields["n_running"])
            logits, value = model.apply(params, obs)
            action = jax.random.categorical(step_rng,
                                            logits).astype(jnp.int32)
            logp = jax.nn.log_softmax(logits)[action]

            new_carry, (reward, accept, cause, jct), memo = k.decision(
                bank, carry, action, row, memo)
            accepted, blocked, ret = counters
            # unlike the policy-episode kernel these counters need no
            # has_job guard: every segment step has a queued job by
            # construction (advance exits only on queue_row >= 0 or done,
            # and done states reset to fresh — which queues bank job 0)
            counters2 = (accepted + accept.astype(jnp.int32),
                         blocked + (~accept).astype(jnp.int32),
                         ret + reward)
            (carry3, queue_row3, ptr3, next_arrival3, done3,
             completed3) = k.advance(bank, new_carry, jnp.int32(-1), ptr,
                                     next_arrival, done, completed)
            ended = done3
            state3 = (carry3, queue_row3, ptr3, next_arrival3, done3,
                      completed3, counters2)
            # in-kernel episode reset: a fresh run of the same bank.
            # The memo is deliberately OUTSIDE this tree_map — it
            # persists across resets like the host lookahead_cache
            # (workload signature unchanged: same bank every episode)
            state4 = jax.tree_util.tree_map(
                lambda f, s: jnp.where(ended, f, s), fresh, state3)
            # episode counters ride the trace so the training loop can
            # harvest episode records at done boundaries (the reset wipes
            # them from the carried state the very same step)
            out = {"action": action, "logp": logp, "value": value,
                   "reward": reward.astype(dt), "done": ended,
                   "ep_accepted": counters2[0],
                   # at the episode-end step, fold in the jobs still
                   # running at simulation end — the host finalisation
                   # blocks them (cluster.py:1010-1013), so harvested
                   # num_jobs_blocked/blocking_rate match host records
                   "ep_blocked": counters2[1] + jnp.where(
                       ended, carry3[4].sum().astype(jnp.int32), 0),
                   "ep_return": counters2[2], "ep_completed": completed3,
                   # ptr counts every bank job that has entered the queue,
                   # decided or not — the host's num_jobs_arrived semantics
                   # (cluster.py:240); parity pinned via the policy-episode
                   # kernel's identical expression
                   # (tests/test_jax_policy_episode.py)
                   "ep_arrived": ptr3,
                   **fields}
            if trace_obs:
                out["obs"] = obs
            if memo is not None:
                out.update(jax_memo.memo_trace_counters(memo))
            return (state4, memo), out

        rngs = jax.random.split(rng, n_steps)
        (final, memo), trace = jax.lax.scan(scan_body, (sim_state, memo0),
                                            rngs)
        ret_state = final if memo_cfg is None else (final, memo)
        return ret_state, trace, obs_fields(bank, final)

    return jax.jit(segment)


def vmap_segment_fn(segment, n_lanes: int):
    """Lane-batched wrapper of a `make_segment_fn` kernel:
    ``(banks [B,...], params, states [B,...], rngs [B]) -> outputs with
    a leading B axis``. Real lane counts vmap; ONE lane takes a
    squeeze/expand fast path instead — batching a singleton lane axis
    through the decision kernels costs ~2x on XLA:CPU (measured
    docs/perf_round8.md: 738 -> 392 decisions/s at the degree-2 bench
    regime), and a 1-wide vmap buys nothing anywhere. Shared by the
    device collector and the fused epoch driver so the two paths stay
    the same compiled math at every lane count."""
    import jax

    if n_lanes > 1:
        return jax.vmap(segment, in_axes=(0, None, 0, 0))

    def one_lane(banks, params, states, rngs):
        sq = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)  # noqa: E731
        state, trace, next_fields = segment(sq(banks), params,
                                            sq(states), rngs[0])
        ex = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: x[None], t)
        return ex(state), ex(trace), ex(next_fields)

    return one_lane


def rebuild_obs_batch(et: EpisodeTables, ot: dict, fields: dict):
    """Host-side exact reconstruction of the observations the kernel saw,
    from the compact trace fields (any leading batch shape).

    Implemented as `jax.vmap(_kernel_obs)` over the flattened fields —
    the ONE source of truth for the obs math — so the re-forward
    reproduces the in-kernel logits bit-for-bit under either precision
    mode by construction."""
    import jax
    import jax.numpy as jnp

    if ot.get("with_prices"):
        raise ValueError(
            "rebuild_obs_batch does not support price-feature "
            "observations (the compact trace carries no pricing state)")
    jtype = np.asarray(fields["jtype"])
    shape = jtype.shape

    def one(t, f, s, o, r):
        return _kernel_obs(ot, et, t, f, s, o, r)

    flat = [jnp.asarray(np.asarray(fields[k]).reshape(-1))
            for k in ("jtype", "frac", "steps", "n_occupied", "n_running")]
    obs = jax.jit(jax.vmap(one))(*flat)
    return {k: np.asarray(v).reshape(shape + v.shape[1:])
            for k, v in obs.items()}


# =========================================================================
# The OracleJCT heuristic running entirely in-kernel: candidate pricing,
# action selection, decision, event clock — one dispatch per episode.
# =========================================================================

def make_oracle_episode_fn(et: EpisodeTables, ot: dict,
                           memo_cfg: Optional[jax_memo.MemoConfig]
                           = DEFAULT_EPISODE_MEMO):
    """Jitted OracleJCT episodes: per decision, price EVERY candidate
    degree in-kernel (`price_all`), pick the smallest degree whose priced
    JCT meets the SLA (else the smallest-JCT placeable candidate, else
    the smallest valid degree, else 0 — exactly
    `envs/baselines.py:OracleJCT.compute_action`), then run the decision
    and event clock. (bank) -> traces. The memo serves the DECISION's
    lookahead only (candidate pricing vmaps the cfg axis within one
    env, whose single table cannot take the scattered insertions — see
    `price_all`).
    """
    import jax
    import jax.numpy as jnp

    k = _episode_kernels(et)
    degrees = jnp.asarray(np.array(et.degrees, np.int32))
    n_deg = len(et.degrees)

    def episode(bank):
        dt = et.tables["dep_size"].dtype

        def scan_body(sm, _):
            state, memo = sm
            (carry, queue_row, ptr, next_arrival, done, completed,
             counters) = state
            t = carry[0]
            has_job = (queue_row >= 0) & ~done
            row = jnp.clip(queue_row, 0)

            def run(mm):
                srv_job = carry[2]
                # the obs action mask restricted to the degree columns
                mask = _kernel_action_mask(
                    ot, et, (srv_job >= 0).sum())[degrees]
                ok, jcts = k.price_all(bank, carry, row)
                steps = bank["steps"][row].astype(dt)
                # the host oracle's limit is the ORIGINAL (unpartitioned)
                # job's max_acceptable_jct (baselines.py:143 reads the
                # queue job), not the per-degree partitioned sums the
                # cluster's own SLA gate uses — mirror exactly
                max_jct = (bank["sla_frac"][row].astype(dt)
                           * (jnp.asarray(ot["orig_seq_sum"]).astype(dt)[
                               bank["type"][row]] * steps))
                acceptable = mask & ok & (jcts <= max_jct)
                placeable = mask & ok

                big = jnp.asarray(jnp.inf, dt)
                # 1) smallest acceptable degree
                first_acc = jnp.where(
                    acceptable.any(),
                    degrees[jnp.argmax(acceptable)], -1)
                # 2) else smallest-JCT placeable (first minimum in degree
                # order — strict < scan reproduces the host's min())
                best_jct = big
                best_deg = jnp.int32(-1)
                for d in range(n_deg):
                    take = placeable[d] & (jcts[d] < best_jct)
                    best_jct = jnp.where(take, jcts[d], best_jct)
                    best_deg = jnp.where(take, degrees[d], best_deg)
                # 3) else smallest valid degree, else 0
                first_valid = jnp.where(mask.any(),
                                        degrees[jnp.argmax(mask)], 0)
                action = jnp.where(
                    first_acc >= 0, first_acc,
                    jnp.where(best_deg >= 0, best_deg, first_valid)
                ).astype(jnp.int32)

                new_carry, (reward, accept, cause, jct), mm = k.decision(
                    bank, carry, action, row, mm)
                return (new_carry, action, reward, accept, cause,
                        jct), mm

            def skip(mm):
                return (carry, jnp.int32(0), jnp.zeros((), dt),
                        jnp.bool_(False), jnp.int32(-1),
                        jnp.zeros((), dt)), mm

            ((new_carry, action, reward, accept, cause, jct),
             memo) = jax.lax.cond(has_job, run, skip, memo)
            accepted, blocked, ret = counters
            counters2 = (accepted + (has_job & accept),
                         blocked + (has_job & ~accept),
                         ret + jnp.where(has_job, reward, 0.0))
            queue_row2 = jnp.where(has_job, -1, queue_row)
            (carry3, queue_row3, ptr3, next_arrival3, done3,
             completed3) = k.advance(bank, new_carry, queue_row2, ptr,
                                     next_arrival, done, completed)
            out = (action, reward, accept, cause, jct, t, has_job)
            return (((carry3, queue_row3, ptr3, next_arrival3, done3,
                      completed3, counters2), memo), out)

        memo0 = (jax_memo.memo_init(et, memo_cfg)
                 if memo_cfg is not None else None)
        state0 = (k.init_state(bank), memo0)
        n_steps = bank["type"].shape[0]
        (final, memo), trace = jax.lax.scan(scan_body, state0, None,
                                            length=n_steps)
        counters = final[6]
        out = {"trace": trace, "accepted": counters[0],
               "blocked": counters[1], "ret": counters[2],
               "completed": final[5], "t": final[0][0],
               "done": final[4],
               # host-parity blocked count incl. jobs still running at
               # simulation end (cluster.py:1010-1013)
               "blocked_total": (counters[1]
                                 + final[0][4].sum().astype(jnp.int32)),
               "arrived": final[2]}
        if memo is not None:
            out.update(jax_memo.memo_trace_counters(memo))
        return out

    return jax.jit(episode)
