"""Jittable (vmappable) lookahead tick engine over fixed-size padded arrays.

The north-star prototype (SURVEY.md §3.5, §7.4.2): the host engine
(``cluster._run_lookahead``) simulates one training step of a mounted job by
dependency-driven ticking; this module reproduces those exact semantics as a
``lax.while_loop`` over padded arrays so the lookahead can run inside jit —
one step toward HBM-resident environment rollouts — and be vmapped over a
batch of jobs.

Semantics mirrored from the host engine (cluster.py ``_run_lookahead``):

* per worker, the highest-priority *ready* op is selected (ties break to the
  smallest op id in sorted order); the op bound is the shortest remaining
  time among selected ops;
* ready non-flow deps (zero size or same server) complete at zero cost, and
  any such dep forces a zero tick (host: ``shortest_comm = 0.0``);
* otherwise each channel nominates its highest-priority ready flow dep and
  the comm bound is the shortest remaining among nominated deps, while ALL
  ready flow deps tick in parallel (the reference's documented
  parallel-flow-tick hack, ramp_cluster_environment.py:756);
* deps readied by op completions within a tick do not advance until the next
  tick (the host snapshots ready deps before op ticking);
* mutual (backward-sync) deps never gate their destination op's readiness;
* comm/comp overhead accumulate per tick according to whether ops and/or
  flow deps advanced.

Priorities are combined with sorted-id ranks into a single score so argmax
reproduces the host's deterministic tie-breaking. All arrays are padded to
static shapes; invalid slots carry ``valid=False`` masks.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache as _lru_cache
from typing import Dict, Tuple

import numpy as np

BIG = np.float32(3.4e38)  # stands in for +inf inside the kernel


@dataclasses.dataclass
class LookaheadArrays:
    """Padded single-job lookahead inputs (all numpy, ready for device).

    Shapes: N = padded ops, E = padded deps, L = max channels per flow dep.
    ``op_score``/``dep_score`` are priority-with-rank combined scores
    (higher wins; distinct per valid slot). ``dep_channel`` holds channel
    indices (-1 padding) into a dense per-job channel renumbering.
    """
    op_remaining: np.ndarray   # [N] f32
    op_valid: np.ndarray       # [N] bool
    op_worker: np.ndarray      # [N] i32 (dense worker index, -1 pad)
    op_score: np.ndarray       # [N] f32
    num_parents: np.ndarray    # [N] i32 (non-mutual parent deps)
    dep_remaining: np.ndarray  # [E] f32
    dep_valid: np.ndarray      # [E] bool
    dep_src: np.ndarray        # [E] i32
    dep_dst: np.ndarray        # [E] i32
    dep_mutual: np.ndarray     # [E] bool
    dep_is_flow: np.ndarray    # [E] bool
    dep_score: np.ndarray      # [E] f32
    dep_channel: np.ndarray    # [E, L] i32 (-1 pad)
    num_workers: int           # static
    num_channels: int          # static


def build_lookahead_arrays(cluster, job, pad_ops: int, pad_deps: int,
                           pad_links: int = 1,
                           context: dict | None = None) -> LookaheadArrays:
    """Assemble padded arrays for a job already mounted on the cluster
    (the same inputs the host engine reads). f32: feeds the jitted engine
    (the C++ engine has its own exact-size f64 packer,
    :func:`build_native_lookahead_arrays`). ``context`` as in
    :func:`build_native_lookahead_arrays` (candidate pricing of unmounted
    placements)."""
    job_idx = job.details["job_idx"]
    graph = job.graph
    arrays = graph.finalize()
    n, m = graph.n_ops, graph.n_deps
    if n > pad_ops or m > pad_deps:
        raise ValueError(f"job needs ({n},{m}) > padding ({pad_ops},{pad_deps})")

    topo = cluster.topology
    op_to_worker = (context["op_to_worker"] if context is not None
                    else cluster.job_op_to_worker[job_idx])
    # dense per-job worker renumbering (only workers holding this job matter)
    worker_ids = sorted({op_to_worker[op] for op in graph.op_ids})
    worker_dense = {w: i for i, w in enumerate(worker_ids)}

    op_remaining = np.zeros(pad_ops, np.float32)
    op_remaining[:n] = arrays["compute"]
    op_valid = np.zeros(pad_ops, bool)
    op_valid[:n] = True
    op_worker = np.full(pad_ops, -1, np.int32)
    op_score = np.zeros(pad_ops, np.float32)
    num_parents = np.zeros(pad_ops, np.int32)
    num_parents[:n] = arrays["num_parents"]

    # host tie-break: first op in sorted-id order among priority maxes
    sorted_rank = {op: r for r, op in enumerate(sorted(graph.op_ids))}
    ctx_op_pri = context.get("op_pri") if context is not None else None
    for op_id in graph.op_ids:
        i = arrays["op_index"][op_id]
        w = op_to_worker[op_id]
        op_worker[i] = worker_dense[w]
        if ctx_op_pri is not None:
            pri = ctx_op_pri.get(op_id, 0)
        else:
            pri = topo.workers[w].op_priority.get(job_idx, {}).get(op_id, 0)
        op_score[i] = pri * (n + 1) + (n - sorted_rank[op_id])

    dep_remaining = np.zeros(pad_deps, np.float32)
    dep_valid = np.zeros(pad_deps, bool)
    dep_valid[:m] = True
    dep_src = np.zeros(pad_deps, np.int32)
    dep_dst = np.zeros(pad_deps, np.int32)
    dep_mutual = np.zeros(pad_deps, bool)
    dep_mutual[:m] = arrays["edge_mutual"]
    dep_is_flow = np.zeros(pad_deps, bool)
    dep_score = np.zeros(pad_deps, np.float32)
    dep_channel = np.full((pad_deps, pad_links), -1, np.int32)

    # dense per-job channel renumbering
    chan_dense: Dict[str, int] = {}
    dep_sorted_rank = {e: r for r, e in enumerate(sorted(graph.edge_ids))}
    worker_to_server = topo.worker_to_server
    # array pipeline: channel/priority reads come off the DepArrays
    # payload (the channel dicts stay empty on that path)
    payload = (context.get("payload") if context is not None
               else getattr(cluster, "job_dep_arrays", {}).get(job_idx))
    if payload is not None:
        chan_l = payload.chan.tolist()
        pri_l = (payload.pri.tolist() if payload.pri is not None
                 else [0] * len(chan_l))
        edge_chan = {e: ((c,) if c >= 0 else ())
                     for e, c in zip(payload.edge_ids, chan_l)}
        edge_pri = dict(zip(payload.edge_ids, pri_l))
    else:
        edge_chan = edge_pri = None
    # flow-ness comes from THE canonical predicate (OpGraph.flow_mask);
    # the mask is aligned with finalize()'s edge order, which is exactly
    # what arrays["edge_index"] indexes
    _, edge_flow = graph.flow_mask(
        [worker_to_server[op_to_worker[op]] for op in graph.op_ids])
    for edge in graph.edge_ids:
        ei = arrays["edge_index"][edge]
        u, v = edge
        dep_src[ei] = arrays["op_index"][u]
        dep_dst[ei] = arrays["op_index"][v]
        dep_remaining[ei] = job.dep_init_run_time.get(edge, 0.0)
        is_flow = bool(edge_flow[ei])
        dep_is_flow[ei] = is_flow
        if is_flow:
            if edge_chan is not None:
                channels = edge_chan.get(edge, ())
            else:
                channels = sorted(cluster.job_dep_to_channels.get(
                    job_idx, {}).get(edge, ()))
            if len(channels) > pad_links:
                raise ValueError(
                    f"dep {edge} rides {len(channels)} channels > pad_links "
                    f"{pad_links}")
            for li, ch_id in enumerate(channels):
                dep_channel[ei, li] = chan_dense.setdefault(
                    ch_id, len(chan_dense))
            if edge_pri is not None:
                pri = edge_pri.get(edge, 0) if channels else 0
            else:
                ch = (topo.channel_id_to_channel[channels[0]]
                      if channels else None)
                pri = (ch.dep_priority.get(job_idx, {}).get(edge, 0)
                       if ch is not None else 0)
        else:
            pri = 0
        dep_score[ei] = pri * (m + 1) + (m - dep_sorted_rank[edge])

    return LookaheadArrays(
        op_remaining=op_remaining, op_valid=op_valid, op_worker=op_worker,
        op_score=op_score, num_parents=num_parents,
        dep_remaining=dep_remaining, dep_valid=dep_valid, dep_src=dep_src,
        dep_dst=dep_dst, dep_mutual=dep_mutual, dep_is_flow=dep_is_flow,
        dep_score=dep_score, dep_channel=dep_channel,
        num_workers=max(len(worker_dense), 1),
        num_channels=max(len(chan_dense), 1))


def build_native_lookahead_arrays(cluster, job,
                                  context: dict | None = None
                                  ) -> LookaheadArrays:
    """Exact-size f64 packing for the C++ engine (ddls_tpu/native).

    Produces the same arrays as :func:`build_lookahead_arrays` (same score
    formulas, so results are identical), but vectorised: the only Python
    loops left are one O(n_ops) pass for worker/priority lookups and one
    pass over *flow* deps for channel lists — the O(n_deps) per-edge dict
    walk is replaced by index arithmetic on ``graph.finalize()`` arrays.

    ``context`` supplies placement state for a job NOT mounted on the
    cluster (candidate pricing): {"op_to_worker": {op: worker_id},
    "op_pri": {op: pri}, "payload": DepArrays}. Without it, state is read
    from the cluster's mounted structures.
    """
    job_idx = job.details["job_idx"]
    graph = job.graph
    arrays = graph.finalize()
    n, m = graph.n_ops, graph.n_deps
    topo = cluster.topology
    op_ids = arrays["op_ids"]
    if context is not None:
        op_to_worker = context["op_to_worker"]
        ctx_op_pri = context.get("op_pri") or {}
    else:
        op_to_worker = cluster.job_op_to_worker[job_idx]
        ctx_op_pri = None
    worker_to_server = topo.worker_to_server
    workers = topo.workers

    op_worker = np.empty(n, np.int32)
    op_pri = np.zeros(n, np.float64)
    server_of_op = []
    worker_dense: Dict[str, int] = {}
    pri_maps: Dict[str, Dict[str, int]] = {}
    for i, op_id in enumerate(op_ids):
        w = op_to_worker[op_id]
        wi = worker_dense.get(w)
        if wi is None:
            wi = worker_dense.setdefault(w, len(worker_dense))
            pri_maps[w] = (ctx_op_pri if ctx_op_pri is not None
                           else workers[w].op_priority.get(job_idx, {}))
        op_worker[i] = wi
        server_of_op.append(worker_to_server[w])
        pri = pri_maps[w].get(op_id, 0)
        if pri:
            op_pri[i] = pri

    op_score = op_pri * (n + 1) + (n - arrays["op_sorted_rank"])

    edge_src = arrays["edge_src"].astype(np.int32)
    edge_dst = arrays["edge_dst"].astype(np.int32)
    _, dep_is_flow = graph.flow_mask(server_of_op)

    if getattr(job, "dep_init_run_time_arr", None) is not None:
        dep_remaining = job.dep_init_run_time_arr
    else:
        dep_remaining = np.zeros(m, np.float64)
        edge_index = arrays["edge_index"]
        for edge, t in job.dep_init_run_time.items():
            dep_remaining[edge_index[edge]] = t

    # channels + priorities: flow deps only
    dep_pri = np.zeros(m, np.float64)
    edge_ids = arrays["edge_ids"]
    flow_idx = np.nonzero(dep_is_flow)[0]
    payload = (context.get("payload") if context is not None
               else getattr(cluster, "job_dep_arrays", {}).get(job_idx))
    if payload is not None:
        # array pipeline: channels/priorities straight off the DepArrays
        # payload; per-job local channel renumbering is one searchsorted
        # (numbering order is irrelevant — channels only partition deps).
        # pri=None (placement without a schedule) degrades to priority 0
        # exactly like the host engine's zeros fallback
        pri_src = (payload.pri if payload.pri is not None
                   else np.zeros(m, np.int64))
        dep_pri[flow_idx] = pri_src[flow_idx].astype(np.float64)
        uniq = np.unique(payload.chan[flow_idx])
        n_chan = len(uniq)
        dep_channel = np.full((m, 1), -1, np.int32)
        dep_channel[flow_idx, 0] = np.searchsorted(
            uniq, payload.chan[flow_idx]).astype(np.int32)
    else:
        chan_dense: Dict[str, int] = {}
        dep_to_channels = cluster.job_dep_to_channels.get(job_idx, {})
        channel_id_to_channel = topo.channel_id_to_channel
        flow_channels = []
        links = 1
        for ei in flow_idx:
            edge = edge_ids[ei]
            channels = sorted(dep_to_channels.get(edge, ()))
            dense = []
            for ch_id in channels:
                ci = chan_dense.get(ch_id)
                if ci is None:
                    ci = chan_dense.setdefault(ch_id, len(chan_dense))
                dense.append(ci)
            flow_channels.append(dense)
            if len(dense) > links:
                links = len(dense)
            if channels:
                pri = channel_id_to_channel[channels[0]].dep_priority.get(
                    job_idx, {}).get(edge, 0)
                if pri:
                    dep_pri[ei] = pri
        n_chan = len(chan_dense)
        dep_channel = np.full((m, links), -1, np.int32)
        for ei, dense in zip(flow_idx, flow_channels):
            dep_channel[ei, :len(dense)] = dense

    dep_score = dep_pri * (m + 1) + (m - arrays["edge_sorted_rank"])

    return LookaheadArrays(
        op_remaining=arrays["compute"], op_valid=np.ones(n, bool),
        op_worker=op_worker, op_score=op_score,
        num_parents=arrays["num_parents"].astype(np.int32),
        dep_remaining=dep_remaining, dep_valid=np.ones(m, bool),
        dep_src=edge_src, dep_dst=edge_dst,
        dep_mutual=arrays["edge_mutual"], dep_is_flow=dep_is_flow,
        dep_score=dep_score, dep_channel=dep_channel,
        num_workers=max(len(worker_dense), 1),
        num_channels=max(n_chan, 1))


def jax_lookahead(op_remaining, op_valid, op_worker, op_score, num_parents,
                  dep_remaining, dep_valid, dep_src, dep_dst, dep_mutual,
                  dep_is_flow, dep_score, dep_channel,
                  *, num_workers: int, num_channels: int, skip=None):
    """One-training-step lookahead; returns (t, comm_oh, comp_oh, busy, ok).

    ``busy`` is the worker-busy time integral (sum over ticks of
    active-worker count x tick), the quantity utilisation stats divide by
    mounted-worker count x step time. Pure function of arrays —
    jit/vmap-friendly. ``ok`` is False when the engine could not progress
    (the host raises in that case).

    ``skip`` (optional bool scalar) masks the while_loop cond: a True
    lane exits before its first body iteration and returns the (garbage)
    init accumulators — the memo probe's wide-vmap lever
    (sim/jax_memo.py): jax batches ``lax.while_loop`` to run while ANY
    lane's cond holds, select-freezing finished lanes, so seeding
    memo-HIT lanes with ``skip=True`` makes the batched loop run exactly
    to the max trip count over MISS lanes (zero when every lane hit).
    Miss lanes iterate under their own cond regardless of neighbours, so
    their results stay bit-identical to an unbatched run. ``None`` (the
    default) traces the historical unmasked cond byte-for-byte.
    """
    import jax
    import jax.numpy as jnp

    N = op_remaining.shape[0]
    E = dep_remaining.shape[0]
    max_iters = N + E + 4
    # scalar accumulators follow the input dtype: f32 on the standard
    # path, f64 when the caller runs under JAX_ENABLE_X64 (the jitted
    # env-step parity mode, sim/jax_env.py)
    dt = op_remaining.dtype

    worker_onehot = (jax.nn.one_hot(op_worker, num_workers, dtype=jnp.float32)
                     .T)  # [W, N]; -1 (padding) one-hots to zeros

    def cond(state):
        (_, _, op_done, dep_done, _, _, _, _, _, it, stuck) = state
        all_done = (jnp.all(op_done | ~op_valid)
                    & jnp.all(dep_done | ~dep_valid))
        live = (~all_done) & (it < max_iters) & (~stuck)
        return live if skip is None else live & ~skip

    def body(state):
        (rem_op, rem_dep, op_done, dep_done, parent_done,
         t, comm_oh, comp_oh, busy, it, stuck) = state

        # 1. readiness (snapshotted BEFORE this tick's completions)
        ops_ready = op_valid & ~op_done & (parent_done >= num_parents)
        deps_ready = dep_valid & ~dep_done & op_done[dep_src]
        flow_ready = deps_ready & dep_is_flow
        nonflow_ready = deps_ready & ~dep_is_flow
        any_nonflow = jnp.any(nonflow_ready)

        # 2. per-worker highest-score ready op
        scores = jnp.where(ops_ready, op_score, -1.0)
        per_worker = worker_onehot * scores[None, :]  # [W, N]
        best_score = per_worker.max(axis=1)           # [W]
        has_op = best_score > 0
        # an op is selected iff it is its worker's best ready op
        sel_ops = ops_ready & jnp.any(
            (per_worker == best_score[:, None]) & (best_score[:, None] > 0)
            & (worker_onehot > 0), axis=0)
        shortest_op = jnp.min(jnp.where(sel_ops, rem_op, BIG))

        # 3. per-channel highest-score ready flow dep (scatter-max)
        dscores = jnp.where(flow_ready, dep_score, -1.0)
        ch_best = jnp.full((num_channels,), -1.0)
        for li in range(dep_channel.shape[1]):
            ch_idx = dep_channel[:, li]
            contrib = jnp.where(ch_idx >= 0, dscores, -1.0)
            ch_best = ch_best.at[jnp.clip(ch_idx, 0)].max(contrib)
        # dep nominated iff it is the best on at least one of its channels
        nominated = jnp.zeros((E,), bool)
        for li in range(dep_channel.shape[1]):
            ch_idx = dep_channel[:, li]
            nominated = nominated | (
                (ch_idx >= 0) & flow_ready
                & (dscores >= ch_best[jnp.clip(ch_idx, 0)]) & (dscores > 0))
        shortest_comm = jnp.where(
            any_nonflow, 0.0,
            jnp.min(jnp.where(nominated, rem_dep, BIG)))

        tick = jnp.minimum(shortest_op, shortest_comm)
        new_stuck = tick >= BIG  # nothing can progress: host raises

        # 4. advance ops
        rem_op2 = jnp.where(sel_ops, jnp.maximum(rem_op - tick, 0.0), rem_op)
        op_now_done = sel_ops & (rem_op2 <= 0.0) & ~op_done
        op_done2 = op_done | op_now_done

        # 5. advance deps: the snapshot's non-flow deps if any, else ALL
        # snapshot-ready flow deps (parallel-flow hack)
        dep_tick_mask = jnp.where(any_nonflow, nonflow_ready, flow_ready)
        rem_dep2 = jnp.where(dep_tick_mask,
                             jnp.maximum(rem_dep - tick, 0.0), rem_dep)
        dep_now_done = dep_tick_mask & (rem_dep2 <= 0.0) & ~dep_done
        dep_done2 = dep_done | dep_now_done

        # 6. non-mutual completed deps advance their child's parent count
        inc = (dep_now_done & ~dep_mutual).astype(jnp.int32)
        parent_done2 = parent_done.at[dep_dst].add(inc)

        ticked_ops = jnp.any(sel_ops)
        ticked_flows = (~any_nonflow) & jnp.any(flow_ready)
        safe_tick = jnp.where(new_stuck, 0.0, tick)
        comp_oh2 = comp_oh + jnp.where(ticked_ops, safe_tick, 0.0)
        comm_oh2 = comm_oh + jnp.where(ticked_flows, safe_tick, 0.0)
        busy2 = busy + safe_tick * jnp.sum(sel_ops).astype(dt)
        t2 = t + safe_tick

        return (rem_op2, rem_dep2, op_done2, dep_done2, parent_done2,
                t2, comm_oh2, comp_oh2, busy2, it + 1, stuck | new_stuck)

    init = (op_remaining, dep_remaining,
            jnp.zeros((N,), bool), jnp.zeros((E,), bool),
            jnp.zeros((N,), jnp.int32),
            jnp.zeros((), dt), jnp.zeros((), dt), jnp.zeros((), dt),
            jnp.zeros((), dt), jnp.int32(0), jnp.bool_(False))
    out = jax.lax.while_loop(cond, body, init)
    (_, _, op_done, dep_done, _, t, comm_oh, comp_oh, busy, it,
     stuck) = out
    finished = (jnp.all(op_done | ~op_valid)
                & jnp.all(dep_done | ~dep_valid))
    return t, comm_oh, comp_oh, busy, finished & ~stuck


def lookahead_fn(num_workers: int, num_channels: int):
    """Jitted single-job lookahead closure over static sizes (memoised
    process-wide: identical (workers, channels) share one trace; array
    shapes further specialise inside jax's own jit cache)."""
    return _lookahead_fn_cached(num_workers, num_channels)


@_lru_cache(maxsize=None)
def _lookahead_fn_cached(num_workers: int, num_channels: int):
    import jax
    from functools import partial

    return jax.jit(partial(jax_lookahead, num_workers=num_workers,
                           num_channels=num_channels))


def batched_lookahead_fn(num_workers: int, num_channels: int):
    """vmapped+jitted lookahead over a batch of padded jobs (leading batch
    axis on every array input). Memoised per static (workers, channels)
    pair — a fresh jax.jit object would recompile on every call."""
    return _batched_lookahead_fn_cached(num_workers, num_channels)


@_lru_cache(maxsize=None)
def _batched_lookahead_fn_cached(num_workers: int, num_channels: int):
    import jax
    from functools import partial

    fn = partial(jax_lookahead, num_workers=num_workers,
                 num_channels=num_channels)
    return jax.jit(jax.vmap(fn))


def arrays_as_args(a: LookaheadArrays) -> Tuple[np.ndarray, ...]:
    return (a.op_remaining, a.op_valid, a.op_worker, a.op_score,
            a.num_parents, a.dep_remaining, a.dep_valid, a.dep_src,
            a.dep_dst, a.dep_mutual, a.dep_is_flow, a.dep_score,
            a.dep_channel)
