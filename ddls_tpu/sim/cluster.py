"""The RAMP cluster discrete-event simulator.

Counterpart of the reference's ``RampClusterEnvironment``
(ddls/environments/ramp_cluster/ramp_cluster_environment.py:74). Key design,
identical in spirit: because RAMP's validity rules guarantee no contention
(at most one job per worker and per channel), a job's completion time can be
computed *once* when it is mounted by an internal lookahead simulation of a
single training step (``_run_lookahead``, reference :379); the outer event
loop then only advances wall-clock time between {job arrival, job completion,
simulation end} events (reference step :894).

Lookahead tick semantics (reference :379-467):

1. on each worker holding the job, select the highest-priority *ready* op;
   the shortest remaining run time among selected ops bounds the tick;
2. ready deps that never became flows (zero size, or same source/destination
   server) complete at zero cost and suppress flow consideration this tick;
3. otherwise the highest-priority ready dep per channel is found, channel
   contention is resolved in favour of the highest priority contender, and
   the shortest remaining communication time bounds the tick;
4. tick = min(op bound, dep bound); selected ops are ticked, and -- matching
   the reference's documented simplification (:756) -- *all* ready flow deps
   are ticked in parallel regardless of schedule;
5. communication/computation overlap is accounted per tick (:777).

Memoisation: lookahead results and partitioned graphs are cached per
(model, max partition degree) -- this cache is what makes episodes cheap
(reference :269-277, :469-506).

Deviation from the reference (documented): channel-contention losers are
chosen against the best *contending* priority rather than the global maximum
of all priority deps (reference :642 takes a global argmax, which can delete
non-contending deps); this only affects tick granularity, never which deps
ultimately transfer.
"""
from __future__ import annotations

import math
import pathlib
import threading
from collections import defaultdict
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ddls_tpu import telemetry as _telemetry
from ddls_tpu.demands.job import Job
from ddls_tpu.telemetry import flight as _flight
from ddls_tpu.demands.job_queue import JobQueue
from ddls_tpu.demands.jobs_generator import JobsGenerator
from ddls_tpu.hardware.topologies import build_topology
from ddls_tpu.utils import Stopwatch, seed_everything, unique_experiment_dir
from ddls_tpu.utils.common import save_logs_to_dir, snapshot_logs

EdgeId = Tuple[str, str]


class RampClusterEnvironment:
    def __init__(self,
                 topology_config: dict,
                 node_config: dict,
                 name: str = "ramp_cluster",
                 path_to_save: Optional[str] = None,
                 save_freq: int = 1,
                 use_sqlite_database: bool = False,
                 suppress_warnings: bool = True,
                 use_jax_lookahead: bool = False,
                 use_native_lookahead: str | bool = "auto",
                 machine_epsilon: float = 1e-7,
                 scenario_runtime=None):
        self.name = name
        # scenario subsystem (ddls_tpu/scenarios, docs/scenarios.md):
        # deterministic failure windows + device-speed multipliers,
        # applied as completion-time inflation at lookahead REGISTRATION
        # — every lookahead backend stays nominal, so host/C++/jax
        # lookahead parity is untouched; None (the default) keeps the
        # legacy hot path byte-identical
        self.scenario_runtime = scenario_runtime
        self.use_sqlite_database = use_sqlite_database
        # opt-in array-engine lookahead backend (docs/jax_lookahead_gonogo.md)
        self.use_jax_lookahead = use_jax_lookahead
        # C++ lookahead engine (ddls_tpu/native): bit-exact with the host
        # engine, so "auto" enables it whenever the library builds/loads
        if use_native_lookahead == "auto":
            from ddls_tpu.native import native_available
            use_native_lookahead = native_available()
        self.use_native_lookahead = bool(use_native_lookahead)
        self.machine_epsilon = machine_epsilon
        self.suppress_warnings = suppress_warnings
        self.save_freq = save_freq
        self.path_to_save = (unique_experiment_dir(path_to_save, name)
                             if path_to_save is not None else None)

        self.topology_config = topology_config
        self.node_config = node_config
        self.topology = build_topology(topology_config)
        self.topology.populate_workers(node_config)

        self.stopwatch = Stopwatch()
        self.reset_counter = 0
        self._save_thread: Optional[threading.Thread] = None
        # topology-lifetime pricing caches: server-id code tables and
        # per-server-set spans (populated lazily by sim.actions), and the
        # all-reduce pricing memo keyed by (message_size, servers, racks,
        # comm groups) — topology params are fixed for the cluster's life
        self._server_code_tables: Optional[tuple] = None
        self._span_cache: Dict[frozenset, tuple] = {}
        self.comm_time_cache: Dict[tuple, float] = {}

    # ------------------------------------------------------------------ reset
    def reset(self,
              jobs_config,
              max_simulation_run_time: float = float("inf"),
              job_queue_capacity: int = 10,
              seed: Optional[int] = None,
              verbose: bool = False):
        self.reset_counter += 1
        if seed is not None:
            seed_everything(seed)
        self.seed = seed
        self.stopwatch.reset()

        if isinstance(jobs_config, JobsGenerator):
            self.jobs_generator = jobs_config
        else:
            self.jobs_generator = JobsGenerator(**jobs_config)
        self.max_simulation_run_time = (
            float("inf") if max_simulation_run_time is None
            else max_simulation_run_time)

        self.topology.reset_devices()
        self.job_queue = JobQueue(queue_capacity=job_queue_capacity)

        self.num_jobs_arrived = 0
        # worker-seconds of demand that have ARRIVED (blocked arrivals
        # included): the numerator of the online per-server load estimate
        # rho = sum / elapsed / n_servers that AdaptiveDegreePacking reads
        # (envs/baselines.py). Accumulated at arrival, not at decision
        # time, so queue-capacity-blocked jobs still count — a
        # per-decision estimate is biased low exactly in overload
        # (ADVICE r5 item 2)
        self.sum_arrived_seq_completion_time = 0.0
        self.load_rates: List[float] = []
        self.mounted_workers: Set[str] = set()
        self.mounted_channels: Set[str] = set()
        self.jobs_running: Dict[int, Job] = {}
        self.jobs_completed: Dict[int, Job] = {}
        self.jobs_blocked: Dict[int, Job] = {}
        # job_idx -> {op_id -> worker_id}: nested per job so placement
        # lookups avoid tuple-key hashing and removal drops one entry
        self.job_op_to_worker: Dict[int, Dict[str, str]] = {}
        # values are shared frozensets (one per distinct channel tuple of a
        # dep placement) assigned wholesale in _place_deps — never mutated
        self.job_dep_to_channels: Dict[int, Dict[EdgeId, frozenset]] = {}
        # array dep pipeline (dense single-channel complete topologies):
        # per-channel occupancy (-1 free, else job_idx) + per-job DepArrays
        # payloads; the dict mirrors above stay empty on this path
        self.channel_occ = np.full(
            len(self.topology.channel_id_to_channel), -1, np.int32)
        self.job_dep_arrays: Dict[int, Any] = {}
        # per-op dense server codes per mounted job (stashed from the
        # pricing pass): lets the lookahead memo key canonicalise the
        # worker grouping with one vectorised pass instead of a dict walk
        self.job_server_codes: Dict[int, Any] = {}
        self.job_id_to_job_idx: Dict[int, int] = {}
        self.job_idx_to_job_id: Dict[int, int] = {}
        self.job_op_placement: Dict[int, Dict[str, str]] = {}
        # values are DepPlacement.action entries: dep -> channel-id tuple
        # (shared per server pair; (None,) for non-flows)
        self.job_dep_placement: Dict[int, Dict[EdgeId, tuple]] = {}
        self.step_counter = 0
        self.action = None
        self.op_partition = None
        # scenario bookkeeping: next failure window whose t0-crossing
        # flight event is still unemitted, and the per-job ADJUSTED jct
        # ledger (== nominal when no scenario) that survives unmount —
        # the env's end-of-sim sweep reads it (envs/partitioning_env.py)
        self._scenario_emit_ptr = 0
        self.job_adjusted_jct: Dict[int, float] = {}

        # memo caches: partition_cache is keyed by (model, full split map)
        # and lookahead_cache by (model, split map, canonical worker
        # grouping, priced dep-time bytes) — see _lookahead_cache_key; both
        # key sets fully determine the cached outcomes, so the caches
        # persist across resets while the workload stays the same (training
        # episodes 2+ reuse all partition/lookahead work) and are dropped
        # when the dataset (or num_training_steps, which scales cached
        # lookahead results) changes.
        sig = self._workload_signature()
        if sig != getattr(self, "_cache_signature", object()):
            self._cache_signature = sig
            self.partition_cache: Dict[Tuple[str, int], dict] = {}
            self.lookahead_cache: Dict[Tuple[str, int], tuple] = {}

        self.steps_log = defaultdict(list)
        self.episode_stats = self._init_episode_stats()
        self.step_stats = self._init_step_stats()

        # first arrival at t=0
        self.time_next_job_to_arrive = 0.0
        self.job_queue.add(self._get_next_job())
        return None

    def _workload_signature(self) -> tuple:
        """Workload identity for memo-cache validity across resets.

        Cached partition/lookahead outcomes depend on the graph files (by
        model name) and on ``num_training_steps`` (which scales cached
        lookahead results); anything else in the jobs config (arrival
        process, SLA dists, sampling mode) never enters the caches. The
        fingerprint is computed by the generator at load time from the
        exact files it loaded (or the deterministic synthetic config), so
        later on-disk changes cannot alias two different datasets."""
        fingerprint = getattr(self.jobs_generator, "workload_fingerprint",
                              None)
        if fingerprint is None:
            # duck-typed generator stand-in with no fingerprint: a fresh
            # sentinel never matches, so the caches are always cleared
            # (id()-based identity could alias two workloads after GC)
            return ("no-fingerprint", object())
        return fingerprint

    def _init_step_stats(self) -> dict:
        s = defaultdict(float)
        s["step_counter"] = self.step_counter
        s["step_start_time"] = self.stopwatch.time()
        for key in ("mean_num_mounted_workers", "mean_num_mounted_channels",
                    "mean_num_jobs_running", "mean_compute_overhead_frac",
                    "mean_communication_overhead_frac",
                    "mean_mounted_worker_utilisation_frac",
                    "mean_cluster_worker_utilisation_frac"):
            s[key] = []
        for key in ("num_jobs_completed", "num_jobs_arrived",
                    "num_jobs_blocked"):
            s[key] = 0
        return s

    def _init_episode_stats(self) -> dict:
        e = defaultdict(list)
        e["num_jobs_arrived"] = 0
        e["num_jobs_completed"] = 0
        e["num_jobs_blocked"] = 0
        e["episode_start_time"] = self.stopwatch.time()
        return e

    # ---------------------------------------------------------------- arrivals
    def _get_next_job(self) -> Job:
        job = self.jobs_generator.sample_job()
        job_idx = self.num_jobs_arrived
        job.register_arrived(time_arrived=self.stopwatch.time(), job_idx=job_idx)
        time_last = self.stopwatch.time()
        self.time_next_job_to_arrive += self.jobs_generator.sample_interarrival_time()
        gap = self.time_next_job_to_arrive - time_last
        if gap > 0 and math.isfinite(gap):
            self.load_rates.append(
                (job.immutable["job_total_op_memory_cost"]
                 + job.immutable["job_total_dep_size"]) / gap)
        if job_idx in self.job_idx_to_job_id or job.job_id in self.job_id_to_job_idx:
            raise RuntimeError(
                f"duplicate job idx {job_idx} / id {job.job_id}; ids must be "
                "unique across the simulation")
        self.job_idx_to_job_id[job_idx] = job.job_id
        self.job_id_to_job_idx[job.job_id] = job_idx
        self.num_jobs_arrived += 1
        self.sum_arrived_seq_completion_time += float(
            job.seq_completion_time)
        self.last_job_arrived_job_idx = job_idx
        self.episode_stats["num_jobs_arrived"] += 1
        if _flight.enabled():
            _flight.emit("job_arrived", t=self.stopwatch.time(),
                         job_idx=job_idx, job_id=job.job_id,
                         model=job.details.get("model"),
                         num_training_steps=int(job.num_training_steps),
                         sla_frac=float(job.max_acceptable_jct_frac))
        return job

    # ---------------------------------------------------------------- lookahead
    def _run_lookahead(self, job: Job):
        """Simulate one training step of a freshly mounted job; returns
        (jct, comm_overhead, comp_overhead, busy) where the first three are
        scaled by num_training_steps and ``busy`` is the worker-busy time
        integral (sum of active-worker count x tick) of the single
        simulated step."""
        job_idx = job.details["job_idx"]
        state = job.reset_training_step()
        graph = job.graph

        workers_with_job = [
            w for w in self.topology.workers.values()
            if job_idx in w.mounted_job_idx_to_ops]

        # precompute static per-tick structures (flow-ness, sorted op lists
        # per worker with op indices, per-channel sorted dep indices) --
        # these never change during the lookahead
        op_to_worker = self.job_op_to_worker[job_idx]
        is_flow = np.zeros(graph.n_deps, dtype=bool)
        for ei, (u, v) in enumerate(state.edge_ids):
            if graph.edge_size(u, v) == 0:
                continue
            src_w = op_to_worker[u]
            dst_w = op_to_worker[v]
            is_flow[ei] = (self.topology.worker_to_server[src_w]
                           != self.topology.worker_to_server[dst_w])
        worker_op_lists = []
        for w in workers_with_job:
            pri_map = w.op_priority.get(job_idx, {})
            worker_op_lists.append(
                [(state.op_index[op_id], pri_map.get(op_id, 0))
                 for op_id in sorted(w.mounted_job_idx_to_ops[job_idx])])
        payload = self.job_dep_arrays.get(job_idx)
        if payload is not None:
            # array pipeline: group flow deps per dense channel, each group
            # in sorted-edge-id order (edge_sorted_rank), priorities from
            # the payload — the same lists the dict path builds, read off
            # arrays. SRPT priorities are globally unique, so within- and
            # across-channel ordering can't change any tick outcome.
            rank = graph.finalize()["edge_sorted_rank"]
            chan = payload.chan
            pri_arr = (payload.pri if payload.pri is not None
                       else np.zeros(chan.shape[0], np.int64))
            flow_i = np.nonzero(chan >= 0)[0]
            order = flow_i[np.argsort(rank[flow_i], kind="stable")]
            by_ch: Dict[int, list] = {}
            chan_l = chan.tolist()
            pri_l = pri_arr.tolist()
            for i in order.tolist():
                by_ch.setdefault(chan_l[i], []).append((i, pri_l[i]))
            channel_dep_lists = list(by_ch.items())
        else:
            channels_with_job = [
                ch for ch in self.topology.channel_id_to_channel.values()
                if job_idx in ch.mounted_job_idx_to_deps]
            channel_dep_lists = []
            for ch in channels_with_job:
                pri_map = ch.dep_priority.get(job_idx, {})
                channel_dep_lists.append(
                    (ch.channel_id,
                     [(state.edge_index[dep], pri_map.get(dep, 0))
                      for dep in sorted(ch.mounted_job_idx_to_deps[job_idx])]))

        # flight detail: per-op/flow completion events from THIS engine's
        # ticking (the C++/jax engines return aggregates only, which is
        # why cross-backend diffs exclude these kinds by default); one
        # gate read before the loop, zero cost when off
        detail_enabled = _flight.detail_enabled()
        if detail_enabled:
            op_ids = graph.finalize()["op_ids"]
            t_now = self.stopwatch.time()

        t = comm_oh = comp_oh = busy = 0.0
        guard = 0
        while True:
            guard += 1
            if guard > 1_000_000:
                raise RuntimeError("lookahead failed to converge (engine bug)")

            # 1. highest-priority ready op per worker
            selected_ops: List[int] = []
            for op_list in worker_op_lists:
                best_i, best_pri = None, None
                for oi, pri in op_list:
                    if oi in state.ops_ready and (
                            best_pri is None or pri > best_pri):
                        best_i, best_pri = oi, pri
                if best_i is not None:
                    selected_ops.append(best_i)
            shortest_op = min(
                (state.remaining_op[i] for i in selected_ops),
                default=float("inf"))

            # 2. ready non-flow deps (zero size or same server) are free
            non_flow = [ei for ei in state.deps_ready if not is_flow[ei]]

            # 3. flow bound via per-channel priority deps + contention
            if non_flow:
                shortest_comm = 0.0
            else:
                channel_to_pri_dep: Dict[str, int] = {}
                dep_to_pri: Dict[int, int] = {}
                dep_to_channels: Dict[int, Set[str]] = defaultdict(set)
                for ch_id, dep_list in channel_dep_lists:
                    best_dep, best_pri = None, None
                    for ei, pri in dep_list:
                        if ei in state.deps_ready and (
                                best_pri is None or pri > best_pri):
                            best_dep, best_pri = ei, pri
                    if best_dep is not None:
                        channel_to_pri_dep[ch_id] = best_dep
                        dep_to_pri[best_dep] = best_pri
                        dep_to_channels[best_dep].add(ch_id)
                # contention: among deps sharing a channel keep the highest
                # priority one
                for dep in list(dep_to_channels):
                    if dep not in dep_to_channels:
                        continue
                    contenders = {dep}
                    for ch_id in dep_to_channels[dep]:
                        other = channel_to_pri_dep.get(ch_id)
                        if other is not None and other != dep:
                            contenders.add(other)
                    if len(contenders) > 1:
                        winner = max(contenders, key=lambda d: dep_to_pri[d])
                        for loser in contenders - {winner}:
                            for ch_id in dep_to_channels.get(loser, ()):
                                channel_to_pri_dep.pop(ch_id, None)
                            dep_to_pri.pop(loser, None)
                            dep_to_channels.pop(loser, None)
                shortest_comm = min(
                    (state.remaining_dep[ei]
                     for ei in channel_to_pri_dep.values()),
                    default=float("inf"))

            tick = min(shortest_op, shortest_comm)
            if math.isinf(tick):
                raise RuntimeError(
                    f"infinite lookahead tick for job {job.job_id}: no ready "
                    "ops or deps can progress (engine bug)")

            # snapshot ready deps before op ticking so deps readied by op
            # completions this tick are not advanced a step early
            deps_snapshot = sorted(state.deps_ready,
                                   key=lambda ei: state.edge_ids[ei])

            ticked_ops = False
            active_workers = 0
            for oi in selected_ops:
                finished = state.tick_op(oi, tick)
                ticked_ops = True
                active_workers += 1
                if detail_enabled and finished:
                    _flight.emit("op_completed", t=t_now,
                                 job_idx=job_idx, op=op_ids[oi],
                                 lt=t + tick)

            ticked_flows = False
            if non_flow:
                for ei in sorted(non_flow, key=lambda ei: state.edge_ids[ei]):
                    state.tick_dep(ei, tick)
            else:
                for ei in deps_snapshot:
                    finished = state.tick_dep(ei, tick)
                    ticked_flows = True
                    if detail_enabled and finished:
                        _flight.emit("flow_completed", t=t_now,
                                     job_idx=job_idx,
                                     dep=list(state.edge_ids[ei]),
                                     lt=t + tick)

            if ticked_ops and ticked_flows:
                comm_oh += tick
                comp_oh += tick
            elif ticked_flows:
                comm_oh += tick
            elif ticked_ops:
                comp_oh += tick

            busy += active_workers * tick
            t += tick

            if state.is_training_step_complete():
                break

        steps = job.num_training_steps
        return t * steps, comm_oh * steps, comp_oh * steps, busy

    def _lookahead_cache_key(self, job: Job, job_id: int) -> tuple:
        """A signature that fully determines the lookahead outcome.

        The reference memoises on (model, max partition degree) alone
        (:269-277), which silently reuses results across *different
        placements* of the same model. The outcome is exactly determined by
        (a) the split map (hence the partitioned graph and its costs),
        (b) which ops share a worker (canonicalised worker grouping -- all
        workers are identical and servers are symmetric), and (c) the placed
        per-dep communication times. Keying on those keeps the cache exact
        while still collapsing the common repeated-placement case.
        """
        job_idx = job.details["job_idx"]
        split = tuple(sorted(
            self.op_partition.job_id_to_split_forward_ops[job_id].items()))
        sc = self.job_server_codes.get(job_idx)
        if sc is not None and len(sc) == job.graph.n_ops:
            # worker grouping == server grouping (1 worker/server): the
            # canonical first-appearance renumbering of the code array,
            # fully vectorised. Identical tuple to the dict walk.
            _, first_idx, inv = np.unique(sc, return_index=True,
                                          return_inverse=True)
            rank = np.argsort(np.argsort(first_idx))
            return self._assemble_lookahead_key(job, split,
                                                tuple(rank[inv].tolist()))
        return self.lookahead_key_for(job, split,
                                      self.job_op_to_worker[job_idx])

    @staticmethod
    def lookahead_key_for(job: Job, split: tuple,
                          op_to_worker: Dict[str, str]) -> tuple:
        """The exact lookahead memo key from explicit placement inputs —
        shared by the mounted path (_lookahead_cache_key) and candidate
        pricing (which keys an UNMOUNTED hypothetical placement so the
        eventual real placement hits the same entry)."""
        worker_to_group: Dict[str, int] = {}
        groups = []
        for op in job.graph.op_ids:
            w = op_to_worker[op]
            groups.append(worker_to_group.setdefault(w, len(worker_to_group)))
        return RampClusterEnvironment._assemble_lookahead_key(
            job, split, tuple(groups))

    @staticmethod
    def _assemble_lookahead_key(job: Job, split: tuple,
                                groups: tuple) -> tuple:
        """Single assembly point for the memo key tuple: every key builder
        (dict walk, vectorised code-array path, candidate pricing) must
        come through here so the namespaces can never diverge."""
        # the placed per-dep times as raw bytes: equivalent to (and ~100x
        # cheaper than) a tuple of the same floats in edge order
        arr = getattr(job, "dep_init_run_time_arr", None)
        if arr is not None:
            dep_times = arr.tobytes()
        else:
            dep_times = tuple(job.dep_init_run_time.get(e, 0.0)
                              for e in job.graph.edge_ids)
        return (job.details["model"], split, groups, dep_times)

    def _perform_lookahead_job_completion_time(self, action) -> None:
        for job_id in sorted(action.job_ids):
            job_idx = self.job_id_to_job_idx[job_id]
            job = self.jobs_running[job_idx]
            key = self._lookahead_cache_key(job, job_id)
            cached = self.lookahead_cache.get(key)
            # which engine serves THIS decision's lookahead ("cache" on a
            # memo hit): telemetry counters + the flight lookahead event
            backend = "cache"
            if cached is None:
                # explicit jax opt-in outranks the auto-enabled native
                # engine; host engine is the always-correct fallback
                backend = "host"
                if self.use_jax_lookahead:
                    cached = self._run_jax_lookahead(job)
                    if cached is not None:
                        backend = "jax"
                if cached is None and self.use_native_lookahead:
                    cached = self._run_native_lookahead(job)
                    if cached is not None:
                        backend = "native"
                if cached is None:  # disabled, or padding/shape fallback
                    cached = self._run_lookahead(job)
                self.lookahead_cache[key] = cached
                if _telemetry.enabled():
                    _telemetry.inc("sim.lookahead_cache.miss")
                    _telemetry.inc(f"sim.lookahead.backend.{backend}")
            elif _telemetry.enabled():
                _telemetry.inc("sim.lookahead_cache.hit")
            # one simulated training step happened for this job, whichever
            # backend (host/native/jax) served it and whether or not the
            # memo cache did — keeps job.training_step_counter meaningful
            # independent of engine choice (RAMP-path completion itself is
            # event-driven off the lookahead JCT, not this counter)
            job.training_step_counter += 1
            jct, comm_oh, comp_oh, busy = cached
            if _flight.enabled():
                _flight.emit("lookahead", t=self.stopwatch.time(),
                             job_idx=job_idx, job_id=job_id,
                             backend=backend, jct=jct, comm_oh=comm_oh,
                             comp_oh=comp_oh, busy=busy)
            self._register_completed_lookahead(job, jct, comm_oh, comp_oh,
                                               busy)

    def _run_native_lookahead(self, job: Job):
        """Cache-miss lookahead on the C++ engine (ddls_tpu/native):
        identical semantics AND identical f64 arithmetic order to
        ``_run_lookahead``, so results are bit-exact with the host engine.
        Returns None when the library is unavailable or the engine bails
        (caller falls through to jax/host paths)."""
        from ddls_tpu.native import run_lookahead
        from ddls_tpu.sim.jax_lookahead import build_native_lookahead_arrays

        arrays = build_native_lookahead_arrays(cluster=self, job=job)
        result = run_lookahead(arrays)
        if result is None:
            return None
        t, comm, comp, busy = result
        steps = job.num_training_steps
        return t * steps, comm * steps, comp * steps, busy

    def _run_jax_lookahead(self, job: Job):
        """Cache-miss lookahead on the jitted array engine (opt-in;
        docs/jax_lookahead_gonogo.md). Pads op/dep counts up to power-of-two
        buckets so distinct jobs share compiled kernels; returns None to
        fall back to the host engine when assembly fails (e.g. more
        channels per flow than the pad allows)."""
        from ddls_tpu.sim.jax_lookahead import (arrays_as_args,
                                                build_lookahead_arrays,
                                                lookahead_fn)

        def bucket(n: int) -> int:
            size = 16
            while size < n:
                size *= 2
            return size

        try:
            arrays = build_lookahead_arrays(
                job=job, cluster=self,
                pad_ops=bucket(job.graph.n_ops),
                pad_deps=bucket(job.graph.n_deps),
                pad_links=2)
        except ValueError:
            # padding overflow only; bookkeeping errors (KeyError) must
            # crash as loudly as they would on the host path
            return None
        fn = lookahead_fn(arrays.num_workers, arrays.num_channels)
        t, comm, comp, busy, ok = (float(x) for x in fn(
            *arrays_as_args(arrays)))
        if not ok:
            raise RuntimeError(
                f"jax lookahead failed to converge for job {job.job_id} "
                "(engine bug)")
        steps = job.num_training_steps
        return t * steps, comm * steps, comp * steps, busy

    def _register_completed_lookahead(self, job: Job, jct: float,
                                      comm_oh: float, comp_oh: float,
                                      busy: float) -> None:
        """(reference: :793-892)"""
        if jct > job.max_acceptable_jct:
            # SLA violated: block the original job, unmount the partitioned one
            self._register_blocked_job(
                job.original_job,
                cause="max_acceptable_job_completion_time_exceeded")
            self._remove_job_from_cluster(job)
            return

        # busy covers ONE training step; normalise by the single-step
        # time (jct / num_training_steps), not the full scaled JCT
        n_mounted = max(len(job.details["mounted_workers"]), 1)
        step_time = jct / max(job.num_training_steps, 1)
        util = busy / (n_mounted * step_time) if step_time > 0 else 0.0

        # scenario inflation (ddls_tpu/scenarios): the SLA gate above and
        # util stay NOMINAL (admission is failure-blind by design); only
        # the realized completion time is adjusted. The jitted decision
        # kernel applies the same shared formula (sim/jax_env.py).
        job.details["nominal_lookahead_jct"] = jct
        sr = self.scenario_runtime
        if sr is not None and not sr.is_nominal:
            jct = self._scenario_adjusted_jct(job, jct)
        self.job_adjusted_jct[job.details["job_idx"]] = jct

        job.details["lookahead_job_completion_time"] = jct
        job.details["communication_overhead_time"] = comm_oh
        job.details["computation_overhead_time"] = comp_oh
        job.details["mean_mounted_worker_utilisation_frac"] = util

        # total size of deps that became flows (nonzero placed run time)
        arr = getattr(job, "dep_init_run_time_arr", None)
        if arr is not None:
            flow_size = float(
                job.graph.finalize()["edge_size"][arr != 0].sum())
        else:
            flow_size = 0.0
            for edge, run_time in job.dep_init_run_time.items():
                if run_time != 0:
                    flow_size += job.graph.edge_size(*edge)
        job.details["job_total_flow_size"] = flow_size

    def _scenario_adjusted_jct(self, job: Job, nominal: float) -> float:
        """Adjusted completion time under the attached ScenarioRuntime:
        progress gated at the slowest mounted server's speed, failure
        windows (on mounted servers/channels) multiplied on top — the
        shared formula in scenarios/failures.py, which the jitted
        kernel mirrors with identical f64 op order."""
        from ddls_tpu.scenarios.failures import (FAILURE_WORKER_PREEMPT,
                                                 inflate_duration)

        sr = self.scenario_runtime
        server_index = self.topology.dense_tables()["server_index"]
        w2s = self.topology.worker_to_server
        srv = {server_index[w2s[w]]
               for w in job.details["mounted_workers"]}
        r0 = min((float(sr.speeds[i]) for i in srv), default=1.0)
        chans = job.details["mounted_channels"]
        affects = [
            (w["resource"] in srv)
            if w["kind"] == FAILURE_WORKER_PREEMPT
            else (w["resource"] in chans)
            for w in sr.windows]
        return inflate_duration(job.details["time_started"], nominal, r0,
                                sr.win_t0, sr.win_t1, sr.win_rate, affects)

    # ------------------------------------------------------------------- step
    def step(self, action, verbose: bool = False):
        self.action = action
        self.step_stats = self._init_step_stats()

        # queued jobs not handled by every sub-action are blocked; the cause
        # is the first sub-action that dropped the job (reference:
        # action.py:36-48 surfaced into blocked stats)
        for job_id, job in list(self.job_queue.jobs.items()):
            if job_id not in action.job_ids:
                cause = action.job_id_to_cause_of_unsuccessful_handling.get(
                    job_id, "not_handled")
                self._register_blocked_job(job, cause=cause)

        if action.actions["op_partition"] is not None:
            self._partition_ops(action.actions["op_partition"])
        if action.actions["op_placement"] is not None:
            self._place_ops(action.actions["op_placement"])
        if action.actions["op_schedule"] is not None:
            self._schedule_ops(action.actions["op_schedule"])
        if action.actions["dep_placement"] is not None:
            self._place_deps(action.actions["dep_placement"])
        if action.actions["dep_schedule"] is not None:
            self._schedule_deps(action.actions["dep_schedule"])

        self._perform_lookahead_job_completion_time(action)

        # advance wall clock to the next event
        step_done = False
        while not step_done:
            tick = min(self.time_next_job_to_arrive - self.stopwatch.time(),
                       self.max_simulation_run_time - self.stopwatch.time())
            for job in self.jobs_running.values():
                elapsed = self.stopwatch.time() - job.details["time_started"]
                remaining = (job.details["lookahead_job_completion_time"]
                             - elapsed)
                tick = min(tick, remaining)
            tick = max(tick, 0.0)

            if _flight.enabled():
                _flight.emit("tick", t=self.stopwatch.time(), dt=tick,
                             n_running=len(self.jobs_running))
            self._accumulate_tick_stats(tick)
            self.stopwatch.tick(tick)

            # scenario failure windows: emit each window's crossing event
            # once when the clock first passes its t0. The pointer always
            # advances (recorder on or off), and the emitted ``t`` is the
            # window's own t0 — a pure function of (seed, spec) — so
            # traces stay bit-identical across lookahead backends.
            sr = self.scenario_runtime
            if sr is not None and self._scenario_emit_ptr < len(sr.windows):
                now = self.stopwatch.time()
                while (self._scenario_emit_ptr < len(sr.windows)
                       and sr.windows[self._scenario_emit_ptr]["t0"] <= now):
                    w = sr.windows[self._scenario_emit_ptr]
                    self._scenario_emit_ptr += 1
                    if _flight.enabled():
                        from ddls_tpu.scenarios.failures import \
                            FAILURE_WORKER_PREEMPT
                        if w["kind"] == FAILURE_WORKER_PREEMPT:
                            _flight.emit("worker_preempted", t=w["t0"],
                                         server=w["resource"], t0=w["t0"],
                                         t1=w["t1"], rate=w["rate"])
                        else:
                            _flight.emit("channel_degraded", t=w["t0"],
                                         channel=w["resource"], t0=w["t0"],
                                         t1=w["t1"], rate=w["rate"])

            completed = []
            for job in self.jobs_running.values():
                elapsed = self.stopwatch.time() - job.details["time_started"]
                remaining = (job.details["lookahead_job_completion_time"]
                             - elapsed - self.machine_epsilon)
                if remaining <= 0:
                    completed.append(job)
                    step_done = True
            for job in completed:
                self._register_completed_job(job)

            if len(self.jobs_generator) > 0:
                if (self.stopwatch.time() + self.machine_epsilon
                        >= self.time_next_job_to_arrive):
                    nxt = self._get_next_job()
                    self.step_stats["num_jobs_arrived"] += 1
                    if self.job_queue.can_fit(nxt):
                        self.job_queue.add(nxt)
                    else:
                        self._register_blocked_job(
                            nxt, cause="job_queue_full")
                    step_done = True
            else:
                self.time_next_job_to_arrive = float("inf")

            if self.is_done():
                step_done = True

        self._finalise_step_stats()
        self.step_counter += 1
        if self.is_done():
            self._finalise_episode_stats()
        if self.path_to_save is not None and (
                self.step_counter % self.save_freq == 0 or self.is_done()):
            self.save()
            if self.is_done() and self._save_thread is not None:
                self._save_thread.join()
        return None, None, None, self.is_done(), None

    # ------------------------------------------------------------ sub-actions
    def _partition_ops(self, op_partition) -> None:
        self.op_partition = op_partition
        for job_id in op_partition.action:
            self.job_queue.jobs[job_id] = op_partition.partitioned_jobs[job_id]

    def _place_ops(self, op_placement) -> None:
        for job_id, op_to_worker in op_placement.action.items():
            job = self.job_queue.jobs[job_id]
            job_idx = job.details["job_idx"]
            by_worker: Dict[str, list] = {}
            for op_id, worker_id in op_to_worker.items():
                by_worker.setdefault(worker_id, []).append(op_id)
            mounted_workers = job.details["mounted_workers"]
            for worker_id, op_ids in by_worker.items():
                worker = self.topology.workers[worker_id]
                # RAMP rule 1: at most one job per worker
                if any(idx != job_idx
                       for idx in worker.mounted_job_idx_to_ops):
                    raise RuntimeError(
                        f"RAMP rule violation: worker {worker_id} already "
                        f"holds job idx(s) "
                        f"{set(worker.mounted_job_idx_to_ops) - {job_idx}}, "
                        f"cannot mount job idx {job_idx}")
                worker.mount_ops(job, op_ids)
                mounted_workers.add(worker_id)
            self.job_op_to_worker.setdefault(job_idx, {}).update(
                op_to_worker)
            sc = op_placement.job_server_codes.get(job_id)
            if sc is not None:
                self.job_server_codes[job_idx] = sc
            if _flight.enabled():
                _flight.emit("placed", t=self.stopwatch.time(),
                             job_idx=job_idx, job_id=job_id,
                             workers=sorted(by_worker),
                             n_ops=len(op_to_worker))
            self._register_running_job(job)
            self.job_op_placement[job_id] = dict(op_to_worker)

    def _register_running_job(self, job: Job) -> None:
        job.register_running(time_started=self.stopwatch.time())
        self.jobs_running[job.details["job_idx"]] = job
        self.job_queue.remove(job)
        # zero out non-flow dep run times now that placement is known
        job_idx = job.details["job_idx"]
        arrays = job.graph.finalize()
        if getattr(job, "dep_init_run_time_arr", None) is not None:
            worker_to_server = self.topology.worker_to_server
            op_to_worker = self.job_op_to_worker[job_idx]
            _, is_flow = job.graph.flow_mask(
                [worker_to_server[op_to_worker[op_id]]
                 for op_id in arrays["op_ids"]])
            job.set_dep_init_run_times_bulk(
                np.where(is_flow, job.dep_init_run_time_arr, 0.0))
            return
        for u, v in job.graph.edge_ids:
            if job.graph.edge_size(u, v) == 0:
                job.set_dep_init_run_time((u, v), 0.0)
            else:
                src_w = self.job_op_to_worker[job_idx][u]
                dst_w = self.job_op_to_worker[job_idx][v]
                if (self.topology.worker_to_server[src_w]
                        == self.topology.worker_to_server[dst_w]):
                    job.set_dep_init_run_time((u, v), 0.0)
                else:
                    job.set_dep_init_run_time(
                        (u, v), job.dep_init_run_time.get((u, v), 0.0))

    def _schedule_ops(self, op_schedule) -> None:
        for worker_id, job_to_ops in op_schedule.action.items():
            worker = self.topology.workers[worker_id]
            for job_id, op_to_pri in job_to_ops.items():
                job_idx = self.job_id_to_job_idx[job_id]
                worker.op_priority.setdefault(job_idx, {}).update(op_to_pri)

    def _place_deps(self, dep_placement) -> None:
        from ddls_tpu.sim.actions import DepArrays

        if any(isinstance(v, DepArrays)
               for v in dep_placement.action.values()):
            for job_id, payload in dep_placement.action.items():
                job_idx = self.job_id_to_job_idx[job_id]
                job = self.jobs_running[job_idx]
                occ_vals = self.channel_occ[payload.channels]
                bad = (occ_vals != -1) & (occ_vals != job_idx)
                if bad.any():
                    # RAMP rule 2: at most one job per channel
                    raise RuntimeError(
                        f"RAMP rule violation: channels "
                        f"{payload.channels[bad][:8].tolist()} already hold "
                        f"other job idxs "
                        f"{self.channel_occ[payload.channels[bad]][:8].tolist()}")
                self.channel_occ[payload.channels] = job_idx
                self.job_dep_arrays[job_idx] = payload
                job.details["mounted_channels"].update(
                    payload.channels.tolist())
                self.job_dep_placement[job_id] = payload
                if _flight.enabled():
                    _flight.emit(
                        "mounted", t=self.stopwatch.time(),
                        job_idx=job_idx, job_id=job_id,
                        channels=sorted(payload.channels.tolist()),
                        occ_used=int((self.channel_occ != -1).sum()))
            return
        channel_lookup = self.topology.channel_id_to_channel
        # keep channel_occ the single occupancy truth on dense topologies
        # even when a dict-style placement mounts (e.g. hand-crafted test
        # actions): the array placer reads only channel_occ for validity
        chan_index = self.topology.dense_tables()["channel_index"]
        jobdep_views = dep_placement.jobdep_to_channels
        for job_id, dep_to_channels in dep_placement.action.items():
            job_idx = self.job_id_to_job_idx[job_id]
            job = self.jobs_running[job_idx]
            # one pass grouping deps per channel, then bulk channel mounts:
            # same outcome as per-dep Channel.mount at a fraction of the cost
            ch_to_deps: Dict[str, list] = {}
            for dep_id in dep_to_channels:
                real = jobdep_views[(job_id, dep_id)]
                if not real:
                    continue
                self.job_dep_to_channels.setdefault(
                    job_idx, {})[dep_id] = real
                for ch_id in real:
                    lst = ch_to_deps.get(ch_id)
                    if lst is None:
                        lst = ch_to_deps.setdefault(ch_id, [])
                    lst.append(dep_id)
            mounted_channels = job.details["mounted_channels"]
            for ch_id, deps in ch_to_deps.items():
                channel = channel_lookup[ch_id]
                # RAMP rule 2: at most one job per channel — checked
                # against BOTH stores (an array-path job marks only
                # channel_occ, a dict-path job only the channel dicts)
                ci = chan_index.get(ch_id)
                occ = (self.channel_occ[ci] if ci is not None else -1)
                holders = (set(channel.mounted_job_idx_to_deps)
                           | {int(occ)}) - {-1, job_idx}
                if holders:
                    raise RuntimeError(
                        f"RAMP rule violation: channel {ch_id} already "
                        f"holds job idx(s) {holders}")
                channel.mounted_job_idx_to_deps.setdefault(
                    job_idx, set()).update(deps)
                mounted_channels.add(ch_id)
                ci = chan_index.get(ch_id)
                if ci is not None:
                    self.channel_occ[ci] = job_idx
            self.job_dep_placement[job_id] = dep_to_channels
            if _flight.enabled():
                _flight.emit("mounted", t=self.stopwatch.time(),
                             job_idx=job_idx, job_id=job_id,
                             channels=sorted(ch_to_deps),
                             occ_used=int((self.channel_occ != -1).sum()))

    def _schedule_deps(self, dep_schedule) -> None:
        for ch_id, job_to_deps in dep_schedule.action.items():
            if ch_id is None:
                continue
            if ch_id == "__arrays__":
                # array pipeline: priorities already live inside each job's
                # DepArrays payload (written by the scheduler, mounted by
                # _place_deps); nothing to copy into channel dicts
                continue
            channel = self.topology.channel_id_to_channel[ch_id]
            for job_id, dep_to_pri in job_to_deps.items():
                job_idx = self.job_id_to_job_idx[job_id]
                channel.dep_priority.setdefault(job_idx, {}).update(
                    dep_to_pri)

    # -------------------------------------------------------------- lifecycle
    def _remove_job_from_cluster(self, job: Job) -> None:
        job_idx = job.details["job_idx"]
        if job.job_id in self.job_queue.jobs:
            self.job_queue.remove(job)
        self.jobs_running.pop(job_idx, None)
        # bulk unmount: drop the whole job from each device it touched in
        # one call per device instead of per op / per dep
        if self.job_op_to_worker.pop(job_idx, None) is not None:
            workers = self.topology.workers
            for worker_id in job.details["mounted_workers"]:
                workers[worker_id].unmount_job(job)
        self.job_server_codes.pop(job_idx, None)
        payload = self.job_dep_arrays.pop(job_idx, None)
        if payload is not None:
            self.channel_occ[payload.channels] = -1
        elif self.job_dep_to_channels.pop(job_idx, None) is not None:
            channel_lookup = self.topology.channel_id_to_channel
            chan_index = self.topology.dense_tables()["channel_index"]
            for ch_id in job.details["mounted_channels"]:
                channel_lookup[ch_id].unmount_job(job_idx)
                ci = chan_index.get(ch_id)
                if ci is not None:
                    self.channel_occ[ci] = -1
        self.job_op_placement.pop(job.job_id, None)
        self.job_dep_placement.pop(job.job_id, None)

    def _register_completed_job(self, job: Job) -> None:
        job.register_completed(time_completed=self.stopwatch.time())
        job_idx = job.details["job_idx"]
        self.jobs_completed[job_idx] = job
        self.step_stats["num_jobs_completed"] += 1
        self.episode_stats["num_jobs_completed"] += 1

        jct = job.details["time_completed"] - job.details["time_arrived"]
        if _flight.enabled():
            _flight.emit("job_completed", t=self.stopwatch.time(),
                         job_idx=job_idx, job_id=job.job_id, jct=jct)
        e = self.episode_stats
        e["job_completion_time"].append(jct)
        e["job_completion_time_speedup"].append(
            job.seq_completion_time / jct if jct > 0 else 0.0)
        e["job_communication_overhead_time"].append(
            job.details["communication_overhead_time"])
        e["job_computation_overhead_time"].append(
            job.details["computation_overhead_time"])
        e["jobs_completed_num_nodes"].append(job.graph.n_ops)
        e["jobs_completed_num_edges"].append(job.graph.n_deps)
        e["jobs_completed_total_operation_memory_cost"].append(
            job.immutable["job_total_op_memory_cost"])
        e["jobs_completed_total_dependency_size"].append(
            job.immutable["job_total_dep_size"])
        e["jobs_completed_max_partitions_per_op"].append(
            job.details.get("max_partitions_per_op", 1))
        e["jobs_completed_job_sequential_completion_time"].append(
            job.seq_completion_time)
        e["jobs_completed_max_acceptable_job_completion_time_frac"].append(
            job.max_acceptable_jct_frac)
        e["jobs_completed_max_acceptable_job_completion_time"].append(
            job.max_acceptable_jct)
        e["jobs_completed_num_mounted_workers"].append(
            len(job.details["mounted_workers"]))
        e["jobs_completed_num_mounted_channels"].append(
            len(job.details["mounted_channels"]))
        e["jobs_completed_mean_mounted_worker_utilisation_frac"].append(
            job.details.get("mean_mounted_worker_utilisation_frac", 0.0))
        orig = job.original_job
        e["jobs_completed_original_demand_num_nodes"].append(orig.graph.n_ops)
        e["jobs_completed_original_demand_num_edges"].append(orig.graph.n_deps)
        e["jobs_completed_original_demand_total_operation_memory_cost"].append(
            orig.immutable["job_total_op_memory_cost"])
        e["jobs_completed_original_demand_total_dependency_size"].append(
            orig.immutable["job_total_dep_size"])

        self._remove_job_from_cluster(job)

    def _register_blocked_job(self, job: Job,
                              cause: str = "not_handled") -> None:
        job_idx = job.details["job_idx"]
        if job.job_id in self.job_queue.jobs:
            self.job_queue.remove(job)
        self.jobs_running.pop(job_idx, None)
        if job_idx in self.jobs_blocked:
            return
        if _flight.enabled():
            _flight.emit("job_blocked", t=self.stopwatch.time(),
                         job_idx=job_idx, job_id=job.job_id, cause=cause)
        self.jobs_blocked[job_idx] = job
        self.step_stats["num_jobs_blocked"] += 1
        self.episode_stats["num_jobs_blocked"] += 1
        e = self.episode_stats
        e["jobs_blocked_cause_of_unsuccessful_handling"].append(cause)
        e["jobs_blocked_num_nodes"].append(job.graph.n_ops)
        e["jobs_blocked_num_edges"].append(job.graph.n_deps)
        e["jobs_blocked_total_operation_memory_cost"].append(
            job.immutable["job_total_op_memory_cost"])
        e["jobs_blocked_total_dependency_size"].append(
            job.immutable["job_total_dep_size"])
        e["jobs_blocked_job_sequential_completion_time"].append(
            job.seq_completion_time)
        e["jobs_blocked_max_acceptable_job_completion_time_frac"].append(
            job.max_acceptable_jct_frac)
        e["jobs_blocked_max_acceptable_job_completion_time"].append(
            job.max_acceptable_jct)
        orig = job.original_job
        e["jobs_blocked_original_demand_num_nodes"].append(orig.graph.n_ops)
        e["jobs_blocked_original_demand_num_edges"].append(orig.graph.n_deps)
        e["jobs_blocked_original_demand_total_operation_memory_cost"].append(
            orig.immutable["job_total_op_memory_cost"])
        e["jobs_blocked_original_demand_total_dependency_size"].append(
            orig.immutable["job_total_dep_size"])

    # ------------------------------------------------------------------ stats
    def _accumulate_tick_stats(self, tick: float) -> None:
        s = self.step_stats
        self.mounted_workers, self.mounted_channels = set(), set()
        utilisations = []
        for job in self.jobs_running.values():
            jct = job.details["lookahead_job_completion_time"]
            frac = tick / jct if jct > 0 else 0.0
            s["compute_info_processed"] += (
                job.immutable["job_total_op_memory_cost"] * frac)
            s["dep_info_processed"] += (
                job.immutable["job_total_dep_size"] * frac)
            s["flow_info_processed"] += (
                job.details.get("job_total_flow_size", 0.0) * frac)
            s["cluster_info_processed"] += (
                (job.immutable["job_total_op_memory_cost"]
                 + job.immutable["job_total_dep_size"]) * frac)
            orig = job.original_job
            s["demand_compute_info_processed"] += (
                orig.immutable["job_total_op_memory_cost"] * frac)
            s["demand_dep_info_processed"] += (
                orig.immutable["job_total_dep_size"] * frac)
            s["demand_total_info_processed"] += (
                (orig.immutable["job_total_op_memory_cost"]
                 + orig.immutable["job_total_dep_size"]) * frac)
            if jct > 0:
                s["mean_compute_overhead_frac"].append(
                    job.details["computation_overhead_time"] / jct)
                s["mean_communication_overhead_frac"].append(
                    job.details["communication_overhead_time"] / jct)
            self.mounted_workers.update(job.details["mounted_workers"])
            self.mounted_channels.update(job.details["mounted_channels"])
            utilisations.append(
                job.details.get("mean_mounted_worker_utilisation_frac", 0.0))
        s["mean_num_jobs_running"].append(len(self.jobs_running))
        s["mean_num_mounted_workers"].append(len(self.mounted_workers))
        s["mean_num_mounted_channels"].append(len(self.mounted_channels))
        if utilisations:
            s["mean_mounted_worker_utilisation_frac"].append(
                float(np.mean(utilisations)))
            s["mean_cluster_worker_utilisation_frac"].append(
                (len(self.mounted_workers) / self.topology.num_workers)
                * float(np.mean(utilisations)))
        else:
            s["mean_mounted_worker_utilisation_frac"].append(0.0)
            s["mean_cluster_worker_utilisation_frac"].append(0.0)

    def _finalise_step_stats(self) -> None:
        s = self.step_stats
        s["step_end_time"] = self.stopwatch.time()
        s["step_time"] = s["step_end_time"] - s["step_start_time"]
        for key in ("mean_num_jobs_running", "mean_num_mounted_workers",
                    "mean_num_mounted_channels", "mean_compute_overhead_frac",
                    "mean_communication_overhead_frac",
                    "mean_mounted_worker_utilisation_frac",
                    "mean_cluster_worker_utilisation_frac"):
            s[key] = float(np.mean(s[key])) if len(s[key]) else 0.0
        for tput, info in (
                ("mean_compute_throughput", "compute_info_processed"),
                ("mean_dep_throughput", "dep_info_processed"),
                ("mean_flow_throughput", "flow_info_processed"),
                ("mean_cluster_throughput", "cluster_info_processed"),
                ("mean_demand_compute_throughput", "demand_compute_info_processed"),
                ("mean_demand_dep_throughput", "demand_dep_info_processed"),
                ("mean_demand_total_throughput", "demand_total_info_processed")):
            s[tput] = (s[info] / s["step_time"]
                       if s[info] != 0 and s["step_time"] != 0 else 0.0)
        s["job_queue_length"] = len(self.job_queue)
        for key, val in s.items():
            self.steps_log[key].append(val)
        for key in ("compute_info_processed", "dep_info_processed",
                    "flow_info_processed", "cluster_info_processed",
                    "demand_compute_info_processed", "demand_dep_info_processed",
                    "demand_total_info_processed", "mean_compute_overhead_frac",
                    "mean_communication_overhead_frac", "mean_num_jobs_running",
                    "mean_num_mounted_workers",
                    "mean_mounted_worker_utilisation_frac",
                    "mean_cluster_worker_utilisation_frac"):
            self.episode_stats[key].append(s[key])

    def _finalise_episode_stats(self) -> None:
        # block anything still running at simulation end
        for job in list(self.jobs_running.values()):
            self._register_blocked_job(job.original_job,
                                       cause="simulation_ended")
            self._remove_job_from_cluster(job)
        e = self.episode_stats
        e["episode_end_time"] = self.stopwatch.time()
        e["episode_time"] = e["episode_end_time"] - e["episode_start_time"]
        e["mean_load_rate"] = (float(np.mean(self.load_rates))
                               if self.load_rates else 0.0)
        arrived = e["num_jobs_arrived"]
        e["blocking_rate"] = e["num_jobs_blocked"] / arrived if arrived else 0.0
        e["acceptance_rate"] = (e["num_jobs_completed"] / arrived
                                if arrived else 0.0)
        for tput, info in (
                ("mean_compute_throughput", "compute_info_processed"),
                ("mean_dep_throughput", "dep_info_processed"),
                ("mean_flow_throughput", "flow_info_processed"),
                ("mean_cluster_throughput", "cluster_info_processed"),
                ("mean_demand_compute_throughput", "demand_compute_info_processed"),
                ("mean_demand_dep_throughput", "demand_dep_info_processed"),
                ("mean_demand_total_throughput", "demand_total_info_processed")):
            total = float(np.sum(e[info])) if isinstance(e[info], list) else e[info]
            e[info] = total
            e[tput] = (total / e["episode_time"]
                       if total != 0 and e["episode_time"] != 0 else 0.0)
        for key in ("mean_compute_overhead_frac",
                    "mean_communication_overhead_frac", "mean_num_jobs_running",
                    "mean_num_mounted_workers",
                    "mean_mounted_worker_utilisation_frac",
                    "mean_cluster_worker_utilisation_frac"):
            e[key] = float(np.mean(e[key])) if len(e[key]) else 0.0

    def is_done(self, verbose: bool = False) -> bool:
        if (self.max_simulation_run_time is not None
                and self.stopwatch.time() >= self.max_simulation_run_time):
            return True
        return (len(self.jobs_generator) == 0 and not self.jobs_running
                and len(self.job_queue) == 0)

    # ------------------------------------------------------------------- save
    def _save_logs(self, logs: dict) -> None:
        # keys are overwritten with the latest accumulated state
        # (reference: ramp_cluster_environment.py:1570)
        save_logs_to_dir(
            pathlib.Path(self.path_to_save) / f"reset_{self.reset_counter}",
            logs, use_sqlite=self.use_sqlite_database)

    def save(self) -> None:
        if self._save_thread is not None:
            self._save_thread.join()
        snapshot = snapshot_logs({"steps_log": self.steps_log,
                                  "episode_stats": self.episode_stats})
        self._save_thread = threading.Thread(target=self._save_logs,
                                             args=(snapshot,))
        self._save_thread.start()

    # static metric catalogues (reference: :1181-1280), used by loaders/loggers
    @staticmethod
    def episode_metrics() -> set:
        return {
            "episode_start_time", "episode_end_time", "episode_time",
            "num_jobs_arrived", "num_jobs_completed", "num_jobs_blocked",
            "compute_info_processed", "dep_info_processed",
            "flow_info_processed", "cluster_info_processed",
            "demand_compute_info_processed", "demand_dep_info_processed",
            "demand_total_info_processed", "mean_compute_throughput",
            "mean_dep_throughput", "mean_cluster_throughput",
            "mean_load_rate", "blocking_rate", "acceptance_rate",
            "mean_flow_throughput", "mean_demand_compute_throughput",
            "mean_demand_dep_throughput", "mean_demand_total_throughput",
            "mean_compute_overhead_frac", "mean_communication_overhead_frac",
            "mean_num_jobs_running", "mean_num_mounted_workers",
            "mean_mounted_worker_utilisation_frac",
            "mean_cluster_worker_utilisation_frac",
            "return", "episode_reward", "run_time", "epoch_counter",
            "episode_counter", "actor_step_counter",
        }

    @staticmethod
    def step_metrics() -> set:
        return {"mean_num_mounted_workers", "mean_num_mounted_channels"}

    @staticmethod
    def episode_completion_metrics() -> set:
        return {
            "job_completion_time", "job_communication_overhead_time",
            "job_computation_overhead_time", "jobs_completed_num_nodes",
            "jobs_completed_num_edges",
            "jobs_completed_total_operation_memory_cost",
            "jobs_completed_total_dependency_size",
            "job_completion_time_speedup",
            "jobs_completed_max_partitions_per_op",
            "jobs_completed_job_sequential_completion_time",
            "jobs_completed_max_acceptable_job_completion_time_frac",
            "jobs_completed_max_acceptable_job_completion_time",
            "jobs_completed_num_mounted_workers",
            "jobs_completed_num_mounted_channels",
            "jobs_completed_mean_mounted_worker_utilisation_frac",
            "jobs_completed_original_demand_num_nodes",
            "jobs_completed_original_demand_num_edges",
            "jobs_completed_original_demand_total_operation_memory_cost",
            "jobs_completed_original_demand_total_dependency_size",
        }

    @staticmethod
    def episode_blocked_metrics() -> set:
        return {
            "jobs_blocked_num_nodes", "jobs_blocked_num_edges",
            "jobs_blocked_total_operation_memory_cost",
            "jobs_blocked_total_dependency_size",
            "jobs_blocked_job_sequential_completion_time",
            "jobs_blocked_max_acceptable_job_completion_time_frac",
            "jobs_blocked_max_acceptable_job_completion_time",
            "jobs_blocked_original_demand_num_nodes",
            "jobs_blocked_original_demand_num_edges",
            "jobs_blocked_original_demand_total_operation_memory_cost",
            "jobs_blocked_original_demand_total_dependency_size",
            "jobs_blocked_cause_of_unsuccessful_handling",
        }
