"""Graph partitioning transforms: data-parallel replication and model/tensor
op splitting.

Reproduces the reference's two rewrite passes
(ddls/environments/ramp_cluster/agents/partitioners/utils.py:5-110) with the
same observable semantics, because partitioned-graph costs feed directly into
simulated JCTs:

``data_split`` (dp_splits=0 in the PAC-ML path): relabels ops to string ids
and **rewrites every edge's size to the memory cost of its producer op**
(activation+parameter) -- partitioned graphs measure dependencies in resident
bytes, unlike raw profile graphs which use activation sizes
(partitioners/utils.py:33-38).

``model_split``: each split forward op ``f`` (and, simultaneously, its
backward counterpart) is replaced by ``n`` sub-ops ``f"a", f"b", ...`` with
compute/memory divided by ``n``; in/out edges are rewired to every sub-op with
size = (neighbour's current memory cost)/n; the backward sub-ops additionally
get a bidirectional all-to-all clique of weight-sync edges, each sized at the
sub-op's memory cost (partitioners/utils.py:54-105). Edge sizes are assigned
at creation time from the neighbour's memory at that moment; when a neighbour
is split later the edge is destroyed and recreated, which reproduces the
reference's last-writer-wins attribute application.

Sub-op id scheme: ``str(int(op)) + chr(97 + i)``
(reference: agents/placers/utils.py:324).
"""
from __future__ import annotations

from typing import Dict, List, Sequence

from ddls_tpu.graphs.op_graph import OpGraph
from ddls_tpu.graphs.readers import backward_op_id


def partitioned_op_id(op_id, split_idx: int) -> str:
    return f"{int(op_id)}{chr(97 + split_idx)}"


def data_split(graph: OpGraph) -> OpGraph:
    """Relabel ops to canonical string ids and re-base edge sizes on producer
    memory cost (the reference's data_split_node with dp_splits=0)."""
    out = OpGraph(graph.device_type)
    for op in graph.op_ids:
        out.add_op(str(int(op)),
                   compute=graph.compute_cost(op),
                   memory=graph.memory_cost(op),
                   is_forward=graph.is_forward(op),
                   counterpart=graph.counterpart(op))
    for u, v in graph.edge_ids:
        out.add_edge(str(int(u)), str(int(v)), size=graph.memory_cost(u))
    out.meta = dict(graph.meta)
    return out


def model_split(graph: OpGraph,
                split_forward_op_ids: Sequence[str],
                splits: Sequence[int]) -> OpGraph:
    """Split the given forward ops (and their backward counterparts) in order.

    ``graph`` must already be data_split output. Returns a new OpGraph.
    """
    g = graph.copy()
    n_forward = len(graph.forward_op_ids())

    for f_op, n in zip(split_forward_op_ids, splits):
        f_op = str(f_op)
        if not g.has_op(f_op) or not graph.is_forward(f_op):
            continue
        b_op = backward_op_id(f_op, n_forward)
        for node_id, is_backward_pass in ((f_op, False), (b_op, True)):
            in_nbrs = g.predecessors(node_id)
            out_nbrs = g.successors(node_id)
            compute = g.compute_cost(node_id) / n
            memory = g.memory_cost(node_id) / n
            is_fwd = g.is_forward(node_id)
            in_sizes = {p: g.memory_cost(p) / n for p in in_nbrs}
            out_sizes = {c: g.memory_cost(c) / n for c in out_nbrs}

            g.remove_op(node_id)
            sub_ids = [partitioned_op_id(node_id, i) for i in range(n)]
            for i, sub in enumerate(sub_ids):
                other = partitioned_op_id(b_op if not is_backward_pass else f_op, i)
                g.add_op(sub, compute=compute, memory=memory,
                         is_forward=is_fwd, counterpart=other)
            for sub in sub_ids:
                for p in in_nbrs:
                    g.add_edge(p, sub, size=in_sizes[p])
                for c in out_nbrs:
                    g.add_edge(sub, c, size=out_sizes[c])
            if is_backward_pass:
                # all-to-all weight-sync clique between backward sub-ops,
                # each direction sized at the sub-op memory cost
                for a in sub_ids:
                    for b in sub_ids:
                        if a != b:
                            g.add_edge(a, b, size=memory)
    return g


def partition_graph(graph: OpGraph,
                    op_to_num_partitions: Dict[str, int]) -> OpGraph:
    """Full partition pipeline: data_split then model_split.

    ``op_to_num_partitions`` maps op ids (forward and/or backward; backward
    entries are ignored -- splitting is driven from the forward op and applied
    to its counterpart) to an even partition count (or 1 for no split).
    """
    base = data_split(graph)
    split_ids: List[str] = []
    splits: List[int] = []
    for op in graph.forward_op_ids():
        n = int(op_to_num_partitions.get(str(int(op)), 1))
        if n == 1:
            continue
        if n % 2 != 0:
            raise ValueError(
                f"num_partitions for op {op} must be 1 or even, got {n} "
                "(RAMP symmetry requirement)")
        split_ids.append(str(int(op)))
        splits.append(n)
    return model_split(base, split_ids, splits)
