"""Device-resident lookahead memo for the jitted environment (ISSUE 13).

The host simulator memoises the SRPT lookahead under an exact signature
(`cluster.py:452-520` ``_lookahead_cache_key``: the split/degree map, the
canonical first-appearance worker grouping, and the placed per-dep times)
and hits >80% past the ~300-step transient — the single biggest reason
the warmed host sim out-steps the in-kernel env at the canonical
degree-16 pads (docs/perf_round8.md). This module mirrors that memo into
a fixed-capacity, set-associative table of jax arrays carried through
the episode/segment scan, so the in-kernel env stops recomputing the
lookahead from scratch on every decision.

Key contract (the host signature, in-kernel form):

* ``cfg`` — the (model type, partition degree) config-row index. The
  split map is a pure function of (model, degree) (`config_tables_for`
  builds one table row per pair), so this one i32 subsumes the host
  key's ``(model, split)`` components.
* ``groups`` — the canonical first-appearance renumbering of the per-op
  server codes (:func:`canonical_groups`), the traced mirror of the
  host's vectorised ``np.unique``/argsort canonicalisation
  (cluster.py:468-476). Collapses physical server identity exactly like
  the host: all workers are identical and servers symmetric.
* ``times`` — the MOUNTED per-dep times (non-flow deps zeroed), byte-for
  -byte what the host keys on: ``_assemble_lookahead_key`` reads
  ``dep_init_run_time_arr`` AFTER ``_register_running_job`` (and
  candidate pricing after its own ``set_dep_init_run_times_bulk``)
  zeroed the non-flows.

Exactness: the jitted lookahead consumes, beyond cfg-static tables,
(op_worker, op_score, dep_remaining, is_flow, dep_score, dep_channel).
Given the key triple these are determined up to relabelings the engine
is invariant under: worker/channel ids enter only as occupancy indices
(one-hot rows / scatter-max buckets — permutation invariant), op scores
are a pure function of (cfg, grouping), and dep scores are compared only
BETWEEN flow deps, whose relative SRPT order is the descending order of
their own (mounted == raw) times — non-flow raw times shift all flow
ranks monotonically and cancel in every comparison the engine makes.
Hash collisions cannot break any of this: the probe compares the FULL
key residual bitwise (u32 bit patterns, so ``-0.0``/NaN can only miss,
never alias), so a collision is a miss, never a wrong entry.

Bitwise-hit guarantee: a hit serves a value previously computed by the
SAME compiled ``jax_lookahead`` on bit-identical inputs, so memo-on and
memo-off episodes are indistinguishable in any precision mode — the x64
full-episode parity suites run with the memo enabled unchanged.

Wide-vmap probe (ISSUE 17): the probe is BATCHED, not branched. Each
lane gathers its hit value from its own table, then the lookahead runs
with the hit flag masked into its ``while_loop`` cond
(``jax_lookahead(..., skip=hit)``) and the result is where-selected
against the stored value. jax batches ``lax.while_loop`` to run while
ANY lane's cond holds (select-freezing finished lanes), so under a
multi-lane ``vmap`` the loop trips exactly to the max count over MISS
lanes — zero when every lane hits — and the per-lane ``.at[].set``
insertions scatter back through vmap's batching rule. The lanes=1
canonical 13x therefore generalises to every width, and
``resolve_memo_cfg``'s ``"auto"`` enables the memo at ALL widths
(es_device, bench vmap8, multi-lane fused/collector lanes). Miss lanes
iterate under their own cond regardless of neighbours, so memo-on and
memo-off stay bit-identical at every width.

Persistence: the table rides the scan carry OUTSIDE the in-kernel
episode reset (`make_segment_fn` resets the env state to ``fresh`` but
never the memo), mirroring the host contract that
``cluster.lookahead_cache`` persists across ``reset()`` while the
workload signature is unchanged — the jitted env replays one fixed bank
per lane, so its workload signature never changes between resets.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple, Union

import numpy as np

#: host key builders this module mirrors — the lint engine's
#: backend-surface-parity rule checks each still exists in
#: ``sim/cluster.py``, so a host key-builder rename fails at lint time
#: instead of silently diverging the in-kernel key contract.
HOST_KEY_SURFACE = ("lookahead_key_for", "_assemble_lookahead_key")

#: cumulative counter keys the memo-enabled segment kernel traces per
#: step alongside the ``ep_*`` episode counters (drained with them at
#: sync boundaries, never fetched per step).
MEMO_TRACE_KEYS = ("memo_hits", "memo_misses", "memo_evicts")

#: the wide-probe surface: the batched probe is only effective under
#: vmap while the hit flag keeps reaching the lookahead while_loop's
#: cond — ``memo_lookahead`` hands ``hit`` to ``compute(hit)`` and the
#: env's ``run_lookahead`` forwards it as the named keyword of the
#: named ``sim/jax_lookahead.py`` function. The lint engine's
#: backend-surface-parity rule pins both ends (a rename or a dropped
#: mask fails at lint time instead of silently reverting every
#: multi-lane caller to inert-memo behaviour).
WIDE_PROBE_SURFACE = ("jax_lookahead", "skip")


@dataclasses.dataclass(frozen=True)
class MemoConfig:
    """Table geometry: ``n_sets`` x ``n_ways`` entries, round-robin way
    eviction per set. The default 64x2 holds 128 keys — comfortably
    above the distinct (model, degree, grouping, times) population of a
    steady-state canonical episode, at ~13 MB of key residuals for the
    degree-16 pads in f64 (N=480 groups + M=13072 times per entry)."""
    n_sets: int = 64
    n_ways: int = 2


def resolve_memo_cfg(memo_cfg: Union[str, MemoConfig, None],
                     n_lanes: int) -> Optional[MemoConfig]:
    """The ONE resolution home for the ``use_jax_lookahead_memo`` knob:
    ``"auto"`` enables the memo at EVERY lane count — the batched probe
    masks hit lanes out of the lookahead while_loop, so wide-vmap lanes
    hit the cache too (ISSUE 17; the historical lanes=1-only auto
    predates the mask, when the cond probe was select-inert under
    vmap). An explicit MemoConfig/None still forces it on/off;
    ``n_lanes`` stays in the signature as the callers' resolution
    context (geometry may key on it later)."""
    if memo_cfg == "auto":
        if n_lanes < 1:
            raise ValueError(f"n_lanes must be >= 1, got {n_lanes}")
        return MemoConfig()
    if memo_cfg is None or isinstance(memo_cfg, MemoConfig):
        return memo_cfg
    raise ValueError(f"memo_cfg must be 'auto', None or a MemoConfig, "
                     f"got {memo_cfg!r}")


def _hash_weights(n_words: int) -> np.ndarray:
    """Deterministic odd u32 multipliers for the key hash (embedded as
    program constants; counted by the fused autotuner's size model via
    ``rl/fused.py:memo_table_cells``). The hash only picks the set — the
    bitwise residual compare makes its quality a perf knob, not a
    correctness one."""
    r = np.random.RandomState(0x5EED)
    w = r.randint(0, 1 << 31, size=n_words, dtype=np.int64).astype(
        np.uint32)
    return (w << np.uint32(1)) | np.uint32(1)


def memo_init(et, cfg: MemoConfig):
    """A fresh (empty) device-resident memo table sized to ``et``'s pads.

    Keys are stored as their raw components (cfg row, canonical groups,
    mounted times); values are exactly what the decision kernel consumes
    from ``jax_lookahead`` — the per-step time and the convergence flag.
    Counters are i32 scalars traced alongside the episode counters."""
    import jax.numpy as jnp

    N, M = et.pads.n_ops, et.pads.n_deps
    dt = et.tables["dep_size"].dtype
    S, W = cfg.n_sets, cfg.n_ways
    return {
        "key_cfg": jnp.full((S, W), -1, jnp.int32),
        "key_groups": jnp.zeros((S, W, N), jnp.int32),
        "key_times": jnp.zeros((S, W, M), dt),
        "val_t": jnp.zeros((S, W), dt),
        "val_ok": jnp.zeros((S, W), bool),
        "rr": jnp.zeros((S,), jnp.int32),
        "hits": jnp.zeros((), jnp.int32),
        "misses": jnp.zeros((), jnp.int32),
        "evicts": jnp.zeros((), jnp.int32),
    }


def canonical_groups(ots, valid):
    """First-appearance renumbering of the per-op server codes — the
    traced mirror of the host's canonicalisation (cluster.py:468-476:
    ``np.unique(return_index, return_inverse)`` + double argsort).
    ``ots`` [N] i32 server codes; ``valid`` [N] bool. Invalid slots map
    to -1 (their count and positions are cfg-static, so they can never
    distinguish two placements of the same cfg)."""
    import jax.numpy as jnp

    n = ots.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    same = ((ots[None, :] == ots[:, None])
            & valid[None, :] & valid[:, None])
    # first[i] = smallest j with the same server as op i (== i when op i
    # is its server's first appearance)
    first = jnp.min(jnp.where(same, idx[None, :], jnp.int32(n)), axis=1)
    is_first = valid & (first == idx)
    rank_at = jnp.cumsum(is_first.astype(jnp.int32)) - 1
    return jnp.where(valid, rank_at[jnp.clip(first, 0, n - 1)],
                     jnp.int32(-1)).astype(jnp.int32)


def _bits(x):
    """Raw u32 bit pattern of a float array, flattened over the trailing
    word axis bitcast introduces for 64-bit dtypes — the ONLY equality
    the probe uses (bitwise: ``-0.0 != 0.0``, NaN never matches, exactly
    the host's ``arr.tobytes()`` key semantics)."""
    import jax
    import jax.numpy as jnp

    b = jax.lax.bitcast_convert_type(x, jnp.uint32)
    return b.reshape(x.shape[:-1] + (-1,)) if b.ndim > x.ndim else b


def memo_lookahead(memo: dict, cfg, groups, times,
                   compute: Callable[..., Tuple]):
    """Probe-or-compute one lookahead under the memo key (cfg, groups,
    times); returns ``((t, ok), memo')``.

    Probe (batched — the wide-vmap form, ISSUE 17): hash the key onto a
    set, compare the FULL residual bitwise against every way, gather the
    matching way's stored value, then call ``compute(hit)`` — the
    caller must thread the flag into the lookahead while_loop's cond
    (``jax_lookahead(..., skip=hit)``; :data:`WIDE_PROBE_SURFACE`) so a
    hit lane exits before its first iteration — and where-select the
    stored value over the (garbage) masked-out result. At lanes=1 a hit
    costs one cond evaluation; under a multi-lane vmap the loop trips
    to the max count over MISS lanes only. Miss: the computed (key,
    value) is inserted at the set's round-robin way (deterministic
    eviction — same decision stream, same table, every run; per-lane
    ``.at[].set`` writes scatter back through vmap batching)."""
    import jax
    import jax.numpy as jnp

    S, W = memo["key_cfg"].shape
    n_groups = memo["key_groups"].shape[-1]

    cfg = jnp.asarray(cfg, jnp.int32)
    tbits = _bits(times).reshape(-1)
    payload = jnp.concatenate([
        cfg.astype(jnp.uint32).reshape(1),
        groups.astype(jnp.uint32),
        tbits,
    ])
    weights = jnp.asarray(_hash_weights(1 + n_groups + tbits.shape[0]))
    h = jnp.sum(payload * weights, dtype=jnp.uint32)
    set_idx = (h % jnp.uint32(S)).astype(jnp.int32)

    way_cfg = memo["key_cfg"][set_idx]          # [W]
    way_groups = memo["key_groups"][set_idx]    # [W, N]
    way_times = memo["key_times"][set_idx]      # [W, M]
    eq = ((way_cfg == cfg)
          & jnp.all(way_groups == groups[None], axis=-1)
          & jnp.all(_bits(way_times) == _bits(times)[None],
                    axis=tuple(range(1, _bits(way_times).ndim))))
    hit = eq.any()
    way_hit = jnp.argmax(eq).astype(jnp.int32)

    # batched gather/mask/select: serve the hit value from the table,
    # run the (skip-masked) lookahead for the miss case, keep whichever
    # the hit flag says. Bitwise-hit guarantee is preserved at every
    # width — hits serve previously computed bits verbatim, misses run
    # the loop under their own cond exactly as unbatched.
    t_c, ok_c = compute(hit)
    t = jnp.where(hit, memo["val_t"][set_idx, way_hit], t_c)
    ok = jnp.where(hit, memo["val_ok"][set_idx, way_hit], ok_c)

    # miss insert: round-robin way per set; the write is a pair of
    # where-gated dynamic-update-slices, cheap either way (and dead on
    # the hit path only in the sense that it rewrites identical state)
    way_ins = memo["rr"][set_idx] % jnp.int32(W)
    miss = ~hit
    evict = miss & (memo["key_cfg"][set_idx, way_ins] >= 0)

    def upd(arr, val):
        old = arr[set_idx, way_ins]
        return arr.at[set_idx, way_ins].set(jnp.where(miss, val, old))

    memo = {
        "key_cfg": upd(memo["key_cfg"], cfg),
        "key_groups": upd(memo["key_groups"], groups),
        "key_times": upd(memo["key_times"], times),
        "val_t": upd(memo["val_t"], t),
        "val_ok": upd(memo["val_ok"], ok),
        "rr": memo["rr"].at[set_idx].add(miss.astype(jnp.int32)),
        "hits": memo["hits"] + hit.astype(jnp.int32),
        "misses": memo["misses"] + miss.astype(jnp.int32),
        "evicts": memo["evicts"] + evict.astype(jnp.int32),
    }
    return (t, ok), memo


def memo_trace_counters(memo: dict) -> dict:
    """The per-step cumulative counter snapshot the segment/episode
    kernels trace under :data:`MEMO_TRACE_KEYS` order."""
    return {"memo_hits": memo["hits"], "memo_misses": memo["misses"],
            "memo_evicts": memo["evicts"]}


def summarize_counters(memo: dict) -> dict:
    """{hits, misses, evicts, hit_rate} from a carried (possibly
    lane-stacked) memo state — the ONE summary home shared by
    `DevicePPOCollector.memo_counters` and
    `FusedEpochDriver.memo_counters`. One explicit device fetch of three
    small arrays; call at drain/reporting boundaries only (bench JSON,
    logging), never on a per-collect/per-epoch hot path."""
    import jax

    vals = jax.device_get({k: memo[k]
                           for k in ("hits", "misses", "evicts")})
    out = {k: int(np.sum(v)) for k, v in vals.items()}
    total = out["hits"] + out["misses"]
    out["hit_rate"] = out["hits"] / total if total else 0.0
    return out
