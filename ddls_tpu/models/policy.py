"""GNN policy: per-node embeddings -> pooled graph embedding -> masked
action logits + value.

Parity with the reference RLlib policy (ddls/ml_models/policies/
gnn_policy.py:53): node embeddings from the GNN are masked-mean-pooled; the
graph features (which already include the action mask, obs.py) are embedded
by a LayerNorm MLP; both embeddings are concatenated and read out by an MLP
into action logits and, via a separate branch, a state-value estimate
(RLlib's FullyConnectedNetwork with vf_share_layers=False). Invalid actions
get log(0)-masked logits so they can never be sampled
(gnn_policy.py:265-271).

The forward is written for a single observation; ``batched_policy_apply``
runs a batch as one flattened "mega-graph" (every sample's nodes/edges
concatenated, edge indices offset by ``sample * n_nodes``) — this replaces
the reference's Python loop building one DGL graph per batch element
(gnn_policy.py:226-253), and is exactly DGL's own ``dgl.batch`` trick. The
flattening matters for speed, not just elegance: every LayerNorm/Dense in
the model is row-wise, and XLA's backward for Dense on rank-3 ``[B, N, F]``
inputs (what ``vmap`` produces) lowers the dW reduction ~6x slower on CPU
than the ``[B*N, F]`` matmul, which computes the same sums. Outputs match
``vmap``-ing the single-sample ``__call__`` to f32-reassociation
tolerance — XLA may tile the row-wise matmuls differently per shape
(tests/test_models.py pins this).
"""
from __future__ import annotations

from typing import Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ddls_tpu.models.gnn import GNN, FeatureModule, get_activation
from ddls_tpu.ops.segment import masked_mean


class MLPHead(nn.Module):
    """Plain Dense stack used for the logit and value readouts (the
    reference uses RLlib's FullyConnectedNetwork here)."""

    hiddens: Sequence[int]
    out_features: int
    activation: str = "relu"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        act = get_activation(self.activation)
        for h in self.hiddens:
            x = act(nn.Dense(h)(x))
        return nn.Dense(self.out_features)(x)


class GNNPolicy(nn.Module):
    """Actor-critic over one padded-graph observation.

    Returns (logits [n_actions], value []). Defaults follow the tuned
    reference config (scripts/ramp_job_partitioning_configs/model/gnn.yaml).
    """

    n_actions: int
    out_features_msg: int = 32
    out_features_hidden: int = 64
    out_features_node: int = 16
    out_features_graph: int = 8
    num_rounds: int = 2
    module_depth: int = 1
    activation: str = "relu"
    fcnet_hiddens: Sequence[int] = (256, 256)
    fcnet_activation: str = "relu"
    apply_action_mask: bool = True

    def setup(self):
        # attribute names fix the param-tree paths; they match what the
        # original nn.compact version produced, so existing checkpoints
        # restore unchanged
        self.gnn = GNN(self.out_features_msg, self.out_features_hidden,
                       self.out_features_node, self.num_rounds,
                       self.module_depth, self.activation)
        self.graph_module = FeatureModule(self.out_features_graph,
                                          self.module_depth, self.activation)
        self.logit_head = MLPHead(self.fcnet_hiddens, self.n_actions,
                                  self.fcnet_activation)
        self.value_head = MLPHead(self.fcnet_hiddens, 1,
                                  self.fcnet_activation)

    def _mask_logits(self, logits, action_mask):
        if not self.apply_action_mask:
            return logits
        inf_mask = jnp.maximum(jnp.log(action_mask.astype(jnp.float32)),
                               jnp.finfo(jnp.float32).min)
        return logits + inf_mask

    def __call__(self, obs: Dict[str, jnp.ndarray]
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        node_feats = obs["node_features"]
        edge_feats = obs["edge_features"]
        n_nodes = obs["node_split"][0]
        n_edges = obs["edge_split"][0]
        node_mask = (jnp.arange(node_feats.shape[0]) < n_nodes)
        edge_mask = (jnp.arange(edge_feats.shape[0]) < n_edges)

        node_emb = self.gnn(node_feats, edge_feats, obs["edges_src"],
                            obs["edges_dst"], node_mask, edge_mask)
        pooled = masked_mean(node_emb, node_mask)

        graph_emb = self.graph_module(obs["graph_features"])
        final_emb = jnp.concatenate([pooled, graph_emb], axis=-1)
        logits = self.logit_head(final_emb)
        value = self.value_head(final_emb)[0]
        return self._mask_logits(logits, obs["action_mask"]), value

    def flat_batched(self, obs: Dict[str, jnp.ndarray]
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Batch of B observations as ONE flattened graph of B*N nodes and
        B*E edges (edge indices offset per sample). Every parameterised op
        (LayerNorm/Dense) is row-wise and the segment reduction sums each
        node's mailbox in the same edge order, so this computes the same
        sums as ``vmap(__call__)`` (equal to f32 reassociation; XLA may
        tile matmuls differently per shape) — while the Dense backward
        runs on rank-2 inputs, the layout XLA CPU handles ~6x faster than
        the vmapped rank-3 one.
        """
        nf = obs["node_features"]
        ef = obs["edge_features"]
        B, N, Fn = nf.shape
        E = ef.shape[1]
        n_nodes = obs["node_split"][:, 0]
        n_edges = obs["edge_split"][:, 0]
        node_mask = jnp.arange(N) < n_nodes[:, None]   # [B, N]
        edge_mask = jnp.arange(E) < n_edges[:, None]   # [B, E]
        offsets = (jnp.arange(B, dtype=obs["edges_src"].dtype) * N)[:, None]
        src = (obs["edges_src"] + offsets).reshape(B * E)
        dst = (obs["edges_dst"] + offsets).reshape(B * E)

        node_emb = self.gnn(nf.reshape(B * N, Fn),
                            ef.reshape(B * E, ef.shape[-1]), src, dst,
                            node_mask.reshape(B * N),
                            edge_mask.reshape(B * E))
        pooled = jax.vmap(masked_mean)(
            node_emb.reshape(B, N, node_emb.shape[-1]), node_mask)

        graph_emb = self.graph_module(obs["graph_features"])
        final_emb = jnp.concatenate([pooled, graph_emb], axis=-1)
        logits = self.logit_head(final_emb)
        value = self.value_head(final_emb)[:, 0]
        return self._mask_logits(logits, obs["action_mask"]), value


def batched_policy_apply(model: GNNPolicy, params,
                         obs: Dict[str, jnp.ndarray]):
    """Apply the policy over a batch: dict of [B, ...] arrays ->
    (logits [B, n_actions], values [B]). Runs the flattened mega-graph
    forward (see ``GNNPolicy.flat_batched``)."""
    return model.apply(params, obs, method=GNNPolicy.flat_batched)


def vmapped_policy_apply(model: GNNPolicy, params,
                         obs: Dict[str, jnp.ndarray]):
    """Reference implementation: vmap the single-sample forward. Slower
    backward on CPU (rank-3 Dense dW); kept as the parity oracle for
    ``batched_policy_apply`` (tests/test_models.py)."""
    return jax.vmap(lambda o: model.apply(params, o))(obs)
