"""GNN policy: per-node embeddings -> pooled graph embedding -> masked
action logits + value.

Parity with the reference RLlib policy (ddls/ml_models/policies/
gnn_policy.py:53): node embeddings from the GNN are masked-mean-pooled; the
graph features (which already include the action mask, obs.py) are embedded
by a LayerNorm MLP; both embeddings are concatenated and read out by an MLP
into action logits and, via a separate branch, a state-value estimate
(RLlib's FullyConnectedNetwork with vf_share_layers=False). Invalid actions
get log(0)-masked logits so they can never be sampled
(gnn_policy.py:265-271).

The forward is written for a single observation; ``batched_policy_apply``
vmaps it over the leading batch axis — this replaces the reference's Python
loop building one DGL graph per batch element (gnn_policy.py:226-253).
"""
from __future__ import annotations

from typing import Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ddls_tpu.models.gnn import GNN, FeatureModule, get_activation
from ddls_tpu.ops.segment import masked_mean


class MLPHead(nn.Module):
    """Plain Dense stack used for the logit and value readouts (the
    reference uses RLlib's FullyConnectedNetwork here)."""

    hiddens: Sequence[int]
    out_features: int
    activation: str = "relu"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        act = get_activation(self.activation)
        for h in self.hiddens:
            x = act(nn.Dense(h)(x))
        return nn.Dense(self.out_features)(x)


class GNNPolicy(nn.Module):
    """Actor-critic over one padded-graph observation.

    Returns (logits [n_actions], value []). Defaults follow the tuned
    reference config (scripts/ramp_job_partitioning_configs/model/gnn.yaml).
    """

    n_actions: int
    out_features_msg: int = 32
    out_features_hidden: int = 64
    out_features_node: int = 16
    out_features_graph: int = 8
    num_rounds: int = 2
    module_depth: int = 1
    activation: str = "relu"
    fcnet_hiddens: Sequence[int] = (256, 256)
    fcnet_activation: str = "relu"
    apply_action_mask: bool = True

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray]
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        node_feats = obs["node_features"]
        edge_feats = obs["edge_features"]
        edges_src = obs["edges_src"]
        edges_dst = obs["edges_dst"]
        n_nodes = obs["node_split"][0]
        n_edges = obs["edge_split"][0]
        node_mask = (jnp.arange(node_feats.shape[0]) < n_nodes)
        edge_mask = (jnp.arange(edge_feats.shape[0]) < n_edges)

        gnn = GNN(self.out_features_msg, self.out_features_hidden,
                  self.out_features_node, self.num_rounds, self.module_depth,
                  self.activation, name="gnn")
        node_emb = gnn(node_feats, edge_feats, edges_src, edges_dst,
                       node_mask, edge_mask)
        pooled = masked_mean(node_emb, node_mask)

        graph_emb = FeatureModule(self.out_features_graph, self.module_depth,
                                  self.activation, name="graph_module")(
            obs["graph_features"])
        final_emb = jnp.concatenate([pooled, graph_emb], axis=-1)

        logits = MLPHead(self.fcnet_hiddens, self.n_actions,
                         self.fcnet_activation, name="logit_head")(final_emb)
        value = MLPHead(self.fcnet_hiddens, 1, self.fcnet_activation,
                        name="value_head")(final_emb)[0]

        if self.apply_action_mask:
            mask = obs["action_mask"].astype(jnp.float32)
            inf_mask = jnp.maximum(jnp.log(mask),
                                   jnp.finfo(jnp.float32).min)
            logits = logits + inf_mask
        return logits, value


def batched_policy_apply(model: GNNPolicy, params,
                         obs: Dict[str, jnp.ndarray]):
    """Apply the policy over a batch: dict of [B, ...] arrays ->
    (logits [B, n_actions], values [B])."""
    return jax.vmap(lambda o: model.apply(params, o))(obs)
