"""Message-passing GNN over padded op graphs.

Architecture parity with the reference (ddls/ml_models/models/mean_pool.py,
gnn.py), tuned hyperparameters from
scripts/ramp_job_partitioning_configs/model/gnn.yaml:

* ``MeanPoolLayer``: node and edge features pass through small
  LayerNorm→Dense→act modules; the message on edge (u→v) is
  concat(node_module(h_u), edge_module(e_uv)); every node also forms a
  self-message concat(node_module(h_v), 0); each message is embedded by a
  reduce module and a node's new embedding is the mean of its embedded
  self-message and embedded incoming messages.
* ``GNN``: num_rounds >= 2 stacked layers (in -> hidden^(r-2) -> out), the
  original edge features re-used at every round.

All ops are fixed-shape w.r.t. the padded node/edge counts; padding is
removed by masks, so the module is jit/vmap/pjit-safe.
"""
from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from ddls_tpu.ops.segment import masked_segment_mean

ACTIVATIONS = {
    "relu": nn.relu,
    "leaky_relu": nn.leaky_relu,
    "tanh": nn.tanh,
    "swish": nn.swish,
    "gelu": nn.gelu,
}


def get_activation(name: str) -> Callable:
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise ValueError(f"unrecognised activation {name!r}; "
                         f"choose from {sorted(ACTIVATIONS)}")


class FeatureModule(nn.Module):
    """LayerNorm -> Dense -> act, repeated ``depth`` times (the reference's
    node/edge/reduce module shape, mean_pool.py:55-97)."""

    features: int
    depth: int = 1
    activation: str = "relu"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        act = get_activation(self.activation)
        x = nn.LayerNorm()(x)
        x = act(nn.Dense(self.features)(x))
        for _ in range(self.depth - 1):
            x = act(nn.Dense(self.features)(x))
        return x


class MeanPoolLayer(nn.Module):
    """One round of message passing + mean aggregation (single sample)."""

    out_features_msg: int
    out_features_reduce: int
    module_depth: int = 1
    activation: str = "relu"

    @nn.compact
    def __call__(self,
                 node_feats: jnp.ndarray,
                 edge_feats: jnp.ndarray,
                 edges_src: jnp.ndarray,
                 edges_dst: jnp.ndarray,
                 node_mask: jnp.ndarray,
                 edge_mask: jnp.ndarray) -> jnp.ndarray:
        half = self.out_features_msg // 2
        node_int = FeatureModule(half, self.module_depth, self.activation,
                                 name="node_module")(node_feats)
        edge_int = FeatureModule(half, self.module_depth, self.activation,
                                 name="edge_module")(edge_feats)
        reduce_module = FeatureModule(self.out_features_reduce,
                                      self.module_depth, self.activation,
                                      name="reduce_module")

        # message along each edge + a zero-edge self-message per node
        messages = jnp.concatenate([node_int[edges_src], edge_int], axis=-1)
        self_state = jnp.concatenate(
            [node_int, jnp.zeros_like(node_int)], axis=-1)

        embedded_msgs = reduce_module(messages)
        embedded_self = reduce_module(self_state)
        out = masked_segment_mean(embedded_msgs, edges_dst, edge_mask,
                                  num_segments=node_feats.shape[0],
                                  extra=embedded_self)
        return out * node_mask[:, None]


class GNN(nn.Module):
    """Stack of ``num_rounds`` MeanPool layers (reference gnn.py:40-81)."""

    out_features_msg: int = 32
    out_features_hidden: int = 64
    out_features_node: int = 16
    num_rounds: int = 2
    module_depth: int = 1
    activation: str = "relu"

    @nn.compact
    def __call__(self, node_feats, edge_feats, edges_src, edges_dst,
                 node_mask, edge_mask) -> jnp.ndarray:
        if self.num_rounds < 2:
            raise ValueError("num_rounds must be >= 2")
        dims: Sequence[int] = (
            [self.out_features_hidden] * (self.num_rounds - 1)
            + [self.out_features_node])
        h = node_feats
        for i, dim in enumerate(dims):
            h = MeanPoolLayer(self.out_features_msg, dim, self.module_depth,
                              self.activation, name=f"round_{i}")(
                h, edge_feats, edges_src, edges_dst, node_mask, edge_mask)
        return h
