"""TPU-native policy networks (flax).

The reference's L6 is a DGL + RLlib ``TorchModelV2`` GNN policy
(ddls/ml_models/). Here the same architecture is expressed as flax modules
over fixed-shape padded arrays: message passing is ``segment_sum`` scatter
(ddls_tpu.ops) instead of DGL's C++ kernels, and the whole
forward is vmapped over the batch — no per-sample Python graph construction
(the reference's known hot-loop sink, ddls/ml_models/policies/
gnn_policy.py:226-253 loops over the batch building DGL graphs).
"""
from ddls_tpu.models.gnn import GNN, MeanPoolLayer
from ddls_tpu.models.policy import GNNPolicy, batched_policy_apply

__all__ = ["MeanPoolLayer", "GNN", "GNNPolicy", "batched_policy_apply"]
