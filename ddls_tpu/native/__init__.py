"""Native (C++) hot-path kernels for the cluster simulator.

The reference delegates its accelerated work to torch/DGL/Ray
(SURVEY.md §2.9); its simulator hot loop is pure Python. This package is
the TPU-framework counterpart for the *host* side of that loop: the
per-step kernels that dominate env.step wall-clock (the lookahead tick
engine first — cluster.py:_run_lookahead) implemented in C++ with flat
array interfaces, loaded via ctypes (no pybind11 in the image).

The library is compiled lazily with g++ on first use and cached under
``_build/``; every entry point degrades gracefully (returns None /
``native_available() is False``) when no toolchain is present, so the
Python engines remain the source of truth and the fallback.

Contract: kernels are bit-exact with the host engines (f64, identical
operation order) — golden stats tests must pass unchanged with the native
path enabled.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "engine.cpp")
_BUILD_DIR = os.path.join(_DIR, "_build")
_LIB = os.path.join(_BUILD_DIR, "libddls_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False

_f64 = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
_i32 = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
_u8 = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if (os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
        return True
    # per-pid temp + atomic replace: concurrent first-use across processes
    # (parallel env workers, multi-host tests) must not interleave output
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            return False
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


_i64 = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.ddls_lookahead.restype = None
    lib.ddls_lookahead.argtypes = [
        ctypes.c_int64, _f64, _i32, _f64, _i32,        # ops
        ctypes.c_int64, _f64, _i32, _i32, _u8, _u8, _f64,  # deps
        ctypes.c_int64, _i32,                          # links, dep_channel
        ctypes.c_int64, ctypes.c_int64,                # workers, channels
        _f64,                                          # out[5]
    ]
    lib.ddls_first_fit_block.restype = ctypes.c_int64
    lib.ddls_first_fit_block.argtypes = [
        _i64, ctypes.c_int64,                          # shapes [n,3]
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # meta shape
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # ramp shape
        _f64, _u8,                                     # mem, blocked
        ctypes.c_double, ctypes.c_int32,               # op_size, check_mem
        ctypes.c_int32,                                # meta_scan
        _i64, _i32,                                    # out_origin, out
    ]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The compiled kernel library, or None when unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if _compile():
                _lib = _bind(ctypes.CDLL(_LIB))
            else:
                _load_failed = True
        except OSError:
            _load_failed = True
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def run_first_fit_block(shapes, meta_shape, ramp_shape, mem, blocked,
                        op_size, meta_scan: bool):
    """First-fit block search on the C++ kernel.

    ``shapes``: [n, 3] int64 candidate shapes (search order preserved;
    -1 in the last slot selects the diagonal layout). ``mem``/``blocked``:
    C-order [C*R*S] views of the ramp snapshot. Returns
    (list of (c, r, s) coords in enumeration order, origin) or None when
    nothing fits, or the string "unavailable" when the library is absent
    (caller falls back to the Python search)."""
    lib = get_lib()
    if lib is None:
        return "unavailable"
    shapes = np.ascontiguousarray(shapes, np.int64)
    if shapes.size == 0:
        return None
    rC, rR, rS = ramp_shape
    if meta_scan and (meta_shape[0] > rC or meta_shape[1] > rR
                      or meta_shape[2] > rS):
        # a meta block larger than the ramp can never fit (find_meta_block's
        # span guard); bailing here also keeps the out buffer bound valid
        return None
    # worst-case servers a candidate block can cover: the kernel writes
    # C*R*S cells per attempt (diagonal shapes cover |C| cells; abs also
    # turns the -1 marker into a safe overestimate)
    max_block = int(np.abs(shapes).prod(axis=1).max())
    out = np.empty((max(rC * rR * rS, max_block), 3), np.int32)
    origin = np.zeros(3, np.int64)
    n = lib.ddls_first_fit_block(
        shapes, shapes.shape[0], meta_shape[0], meta_shape[1],
        meta_shape[2], rC, rR, rS,
        np.ascontiguousarray(mem, np.float64),
        np.ascontiguousarray(blocked, np.uint8),
        float(op_size) if op_size is not None else 0.0,
        1 if op_size is not None else 0,
        1 if meta_scan else 0, origin, out)
    if n == 0:
        return None
    block = [tuple(int(x) for x in row) for row in out[:n]]
    return block, (int(origin[0]), int(origin[1]), int(origin[2]))


def run_lookahead(arrays) -> Optional[Tuple[float, float, float, float]]:
    """Run the C++ lookahead on a ``LookaheadArrays`` built with
    ``dtype=np.float64`` and exact (unpadded) sizes. Returns
    (t, comm_overhead, comp_overhead, busy) for ONE training step, or
    None when the library is unavailable or the engine could not finish
    (caller falls back to the host engine, which raises with
    diagnostics)."""
    lib = get_lib()
    if lib is None:
        return None
    a = arrays
    out = np.zeros(5, dtype=np.float64)
    lib.ddls_lookahead(
        a.op_remaining.shape[0],
        np.ascontiguousarray(a.op_remaining, np.float64),
        np.ascontiguousarray(a.op_worker, np.int32),
        np.ascontiguousarray(a.op_score, np.float64),
        np.ascontiguousarray(a.num_parents, np.int32),
        a.dep_remaining.shape[0],
        np.ascontiguousarray(a.dep_remaining, np.float64),
        np.ascontiguousarray(a.dep_src, np.int32),
        np.ascontiguousarray(a.dep_dst, np.int32),
        np.ascontiguousarray(a.dep_mutual, np.uint8),
        np.ascontiguousarray(a.dep_is_flow, np.uint8),
        np.ascontiguousarray(a.dep_score, np.float64),
        a.dep_channel.shape[1],
        np.ascontiguousarray(a.dep_channel, np.int32),
        a.num_workers, a.num_channels, out)
    if out[4] != 1.0:
        return None
    return float(out[0]), float(out[1]), float(out[2]), float(out[3])
