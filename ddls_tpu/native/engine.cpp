// Native hot-path kernels for the RAMP cluster simulator.
//
// The Python host engine (ddls_tpu/sim/cluster.py:_run_lookahead) and the
// jitted array engine (ddls_tpu/sim/jax_lookahead.py) pin the lookahead
// semantics; this C++ engine reproduces them bit-for-bit in f64 so it can
// substitute for the host engine without perturbing golden stats tests
// (tests/test_stats_parity.py). Reference provenance: the tick loop models
// ddls ramp_cluster_environment.py:686-800 (see SURVEY.md §3.5).
//
// Semantics (must match cluster.py:_run_lookahead exactly):
//  * per worker, the highest-score ready op is selected (score encodes
//    priority then smallest-op-id tie-break); op bound = min remaining
//    among selected ops;
//  * ready non-flow deps (zero size or same server) force a zero tick and
//    only they advance that tick;
//  * otherwise each channel nominates its highest-score ready flow dep;
//    comm bound = min remaining among nominated deps; ALL ready flow deps
//    advance (the reference's parallel-flow-tick hack);
//  * deps readied by op completions within a tick do not advance until the
//    next tick (readiness is snapshotted before ticking);
//  * mutual (backward-sync) deps never gate their destination op;
//  * tick_x(rem, tick) = rem - min(tick, rem); completion at exactly 0.0
//    (ddls_tpu/demands/job.py:113-128);
//  * comp overhead += tick when >=1 op advanced; comm overhead += tick when
//    flow deps advanced; busy += (#selected ops) * tick.
//
// Build: g++ -O2 -shared -fPIC (no -ffast-math: accumulation order and
// IEEE semantics are part of the contract).

#include <cstdint>
#include <cstring>
#include <queue>
#include <utility>
#include <vector>

namespace {

using ScoreIdx = std::pair<double, int64_t>;
// max-heap on (score, -index); scores are distinct per valid slot by
// construction, the index term only makes ordering fully deterministic
struct HeapLess {
  bool operator()(const ScoreIdx& a, const ScoreIdx& b) const {
    if (a.first != b.first) return a.first < b.first;
    return a.second < b.second;
  }
};
using MaxHeap = std::priority_queue<ScoreIdx, std::vector<ScoreIdx>, HeapLess>;

inline double tick_down(double rem, double tick) {
  // job.py:116 — rem - min(tick, rem); exact 0.0 on completion
  return rem - (tick < rem ? tick : rem);
}

}  // namespace

extern "C" {

// One-training-step lookahead of a mounted job.
//
// Inputs are the exact (unpadded) arrays of
// ddls_tpu.sim.jax_lookahead.build_lookahead_arrays in f64.
// dep_channel is [n_deps, n_links] with -1 padding.
// out = {t, comm_overhead, comp_overhead, busy, ok}; ok=0 means the engine
// could not finish (no progress possible or guard exceeded) and the caller
// must fall back to the host engine (which raises with diagnostics).
void ddls_lookahead(
    int64_t n_ops, const double* op_remaining, const int32_t* op_worker,
    const double* op_score, const int32_t* num_parents, int64_t n_deps,
    const double* dep_remaining, const int32_t* dep_src,
    const int32_t* dep_dst, const uint8_t* dep_mutual,
    const uint8_t* dep_is_flow, const double* dep_score, int64_t n_links,
    const int32_t* dep_channel, int64_t num_workers, int64_t num_channels,
    double* out) {
  const double BIG = 1.7e308;

  std::vector<double> rem_op(op_remaining, op_remaining + n_ops);
  std::vector<double> rem_dep(dep_remaining, dep_remaining + n_deps);
  std::vector<uint8_t> op_done(n_ops, 0), dep_done(n_deps, 0);
  std::vector<int32_t> parent_done(n_ops, 0);

  // CSR adjacency: op -> out deps (by dep_src)
  std::vector<int64_t> out_start(n_ops + 1, 0);
  for (int64_t e = 0; e < n_deps; ++e) out_start[dep_src[e] + 1]++;
  for (int64_t i = 0; i < n_ops; ++i) out_start[i + 1] += out_start[i];
  std::vector<int64_t> out_deps(n_deps);
  {
    std::vector<int64_t> cursor(out_start.begin(), out_start.end() - 1);
    for (int64_t e = 0; e < n_deps; ++e) out_deps[cursor[dep_src[e]]++] = e;
  }

  std::vector<MaxHeap> worker_ready(num_workers);     // ready ops per worker
  std::vector<MaxHeap> channel_ready(num_channels);   // ready flow deps
  std::vector<int64_t> nonflow_ready;   // ready non-flow deps (compacted)
  std::vector<int64_t> flow_active;     // ready, not-done flow deps

  for (int64_t i = 0; i < n_ops; ++i)
    if (num_parents[i] == 0 && op_worker[i] >= 0)
      worker_ready[op_worker[i]].push({op_score[i], -i});

  // staging area: deps readied by op completions this tick join the ready
  // structures only after dep advancement (host snapshots readiness)
  std::vector<int64_t> staged_deps;

  auto dep_completed = [&](int64_t e) {
    dep_done[e] = 1;
    if (!dep_mutual[e]) {
      int64_t child = dep_dst[e];
      if (++parent_done[child] == num_parents[child] && !op_done[child])
        worker_ready[op_worker[child]].push({op_score[child], -child});
    }
  };

  int64_t n_ops_done = 0, n_deps_done = 0;
  double t = 0.0, comm_oh = 0.0, comp_oh = 0.0, busy = 0.0;
  const int64_t guard = 2 * (n_ops + n_deps) + 16;
  int64_t it = 0;
  bool ok = false;

  std::vector<int64_t> selected;
  selected.reserve(num_workers);

  while (true) {
    if (n_ops_done == n_ops && n_deps_done == n_deps) { ok = true; break; }
    if (++it > guard) break;  // livelock (host raises); fall back

    // 1. per-worker best ready op
    selected.clear();
    double shortest_op = BIG;
    for (int64_t w = 0; w < num_workers; ++w) {
      MaxHeap& h = worker_ready[w];
      while (!h.empty() && op_done[-h.top().second]) h.pop();
      if (!h.empty()) {
        int64_t oi = -h.top().second;
        selected.push_back(oi);
        if (rem_op[oi] < shortest_op) shortest_op = rem_op[oi];
      }
    }

    // compact nonflow_ready (entries complete only at exactly-0 remaining)
    size_t keep = 0;
    for (size_t k = 0; k < nonflow_ready.size(); ++k)
      if (!dep_done[nonflow_ready[k]]) nonflow_ready[keep++] = nonflow_ready[k];
    nonflow_ready.resize(keep);
    const bool any_nonflow = !nonflow_ready.empty();

    // 2. comm bound: zero if any ready non-flow dep, else min remaining
    // over per-channel nominated flow deps
    double shortest_comm;
    if (any_nonflow) {
      shortest_comm = 0.0;
    } else {
      shortest_comm = BIG;
      for (int64_t c = 0; c < num_channels; ++c) {
        MaxHeap& h = channel_ready[c];
        while (!h.empty() && dep_done[-h.top().second]) h.pop();
        if (!h.empty()) {
          int64_t e = -h.top().second;
          if (rem_dep[e] < shortest_comm) shortest_comm = rem_dep[e];
        }
      }
    }

    double tick = shortest_op < shortest_comm ? shortest_op : shortest_comm;
    if (tick >= BIG) break;  // nothing can progress (host raises)

    // 3. advance selected ops; completions stage their out-deps
    staged_deps.clear();
    for (int64_t oi : selected) {
      rem_op[oi] = tick_down(rem_op[oi], tick);
      if (rem_op[oi] == 0.0 && !op_done[oi]) {
        op_done[oi] = 1;
        ++n_ops_done;
        for (int64_t k = out_start[oi]; k < out_start[oi + 1]; ++k)
          if (!dep_done[out_deps[k]]) staged_deps.push_back(out_deps[k]);
      }
    }

    // 4. advance deps from the pre-tick snapshot
    bool ticked_flows = false;
    if (any_nonflow) {
      for (int64_t e : nonflow_ready) {
        rem_dep[e] = tick_down(rem_dep[e], tick);
        if (rem_dep[e] == 0.0 && !dep_done[e]) {
          dep_completed(e);
          ++n_deps_done;
        }
      }
    } else {
      ticked_flows = !flow_active.empty();
      size_t fkeep = 0;
      for (size_t k = 0; k < flow_active.size(); ++k) {
        int64_t e = flow_active[k];
        rem_dep[e] = tick_down(rem_dep[e], tick);
        if (rem_dep[e] == 0.0 && !dep_done[e]) {
          dep_completed(e);
          ++n_deps_done;
        } else {
          flow_active[fkeep++] = e;
        }
      }
      flow_active.resize(fkeep);
    }

    // 5. newly readied deps join the ready structures for the next tick
    for (int64_t e : staged_deps) {
      if (dep_is_flow[e]) {
        flow_active.push_back(e);
        for (int64_t l = 0; l < n_links; ++l) {
          int32_t c = dep_channel[e * n_links + l];
          if (c >= 0) channel_ready[c].push({dep_score[e], -e});
        }
      } else {
        nonflow_ready.push_back(e);
      }
    }

    // 6. overheads (accumulation order matches the host loop)
    if (!selected.empty() && ticked_flows) {
      comm_oh += tick;
      comp_oh += tick;
    } else if (ticked_flows) {
      comm_oh += tick;
    } else if (!selected.empty()) {
      comp_oh += tick;
    }
    busy += static_cast<double>(selected.size()) * tick;
    t += tick;
  }

  out[0] = t;
  out[1] = comm_oh;
  out[2] = comp_oh;
  out[3] = busy;
  out[4] = ok ? 1.0 : 0.0;
}

// First-fit block search over the RAMP server grid.
//
// Exact-order mirror of ddls_tpu/agents/block_search.py
// (first_fit_block + enumerate_block + block_ok; reference:
// placers/utils.py:394-443 ff_block): shapes in order, origins in
// (i, j, k) C-order, cells in enumeration order. shape[2] == -1 selects
// the diagonal layout whose coordinates wrap modulo (dim + 1) — the
// reference's quirk — so out-of-range cells invalidate the block.
// meta_scan == 1 reproduces find_meta_block's whole-extent origin scan
// (used with a single shape and no memory check).
//
// Returns the number of servers written to out ([n][3] coords, in
// enumeration order), or 0 when no block fits. out_origin receives the
// winning origin.
extern "C" int64_t ddls_first_fit_block(
    const int64_t* shapes, int64_t n_shapes, int64_t mC, int64_t mR,
    int64_t mS, int64_t rC, int64_t rR, int64_t rS, const double* mem,
    const uint8_t* blocked, double op_size, int32_t check_mem,
    int32_t meta_scan, int64_t* out_origin, int32_t* out) {
  auto cell_ok = [&](int64_t c, int64_t r, int64_t s) -> bool {
    if (c < 0 || c >= rC || r < 0 || r >= rR || s < 0 || s >= rS)
      return false;  // host: "server not in ramp"
    const int64_t idx = (c * rR + r) * rS + s;
    if (blocked[idx]) return false;
    if (check_mem && mem[idx] < op_size) return false;
    return true;
  };

  for (int64_t si = 0; si < n_shapes; ++si) {
    const int64_t C = shapes[si * 3], R = shapes[si * 3 + 1],
                  S = shapes[si * 3 + 2];
    int64_t i1, j1, k1;
    if (meta_scan) {
      i1 = rC;
      j1 = rR;
      k1 = rS;
    } else {
      i1 = mC - C + 1;
      j1 = mR - R + 1;
      k1 = mS - S + 1;
      if (i1 <= 0 || j1 <= 0 || k1 <= 0) continue;
    }
    for (int64_t i = 0; i < i1; ++i)
      for (int64_t j = 0; j < j1; ++j)
        for (int64_t k = 0; k < k1; ++k) {
          int64_t n_out = 0;
          bool ok = true;
          if (S == -1) {
            ok = C > 0;
            for (int64_t n = 0; ok && n < C; ++n) {
              const int64_t c = (i + n) % (rC + 1);
              const int64_t r = (j + n) % (rR + 1);
              const int64_t s = ((k % rS) + rS) % rS;
              if (!cell_ok(c, r, s)) {
                ok = false;
                break;
              }
              out[n_out * 3] = static_cast<int32_t>(c);
              out[n_out * 3 + 1] = static_cast<int32_t>(r);
              out[n_out * 3 + 2] = static_cast<int32_t>(s);
              ++n_out;
            }
          } else {
            ok = C > 0 && R > 0 && S > 0;
            for (int64_t c = 0; ok && c < C; ++c)
              for (int64_t r = 0; ok && r < R; ++r)
                for (int64_t s = 0; s < S; ++s) {
                  const int64_t cc = (i + c) % rC;
                  const int64_t rr = (j + r) % rR;
                  const int64_t ss = (k + s) % rS;
                  if (!cell_ok(cc, rr, ss)) {
                    ok = false;
                    break;
                  }
                  out[n_out * 3] = static_cast<int32_t>(cc);
                  out[n_out * 3 + 1] = static_cast<int32_t>(rr);
                  out[n_out * 3 + 2] = static_cast<int32_t>(ss);
                  ++n_out;
                }
          }
          if (ok && n_out > 0) {
            out_origin[0] = i;
            out_origin[1] = j;
            out_origin[2] = k;
            return n_out;
          }
        }
  }
  return 0;
}

}  // extern "C"
