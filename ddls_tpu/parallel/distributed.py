"""Multi-host runtime: ``jax.distributed`` wiring for pod-slice scale-out.

The reference scales across processes with Ray (module-level ``ray.init``,
ramp_cluster_environment.py:29-36) and RLlib's worker actors; the TPU-native
replacement is one JAX process per host joined into a single SPMD program
(SURVEY.md §5.8). After :func:`initialize_distributed`, ``jax.devices()``
returns the *global* device set, every mesh built by
:func:`ddls_tpu.parallel.mesh.make_mesh` spans it, and XLA emits the
cross-host collectives (ICI within a slice, DCN across slices) from sharding
annotations alone.

On a TPU pod slice ``jax.distributed.initialize()`` auto-discovers the
coordinator from the TPU environment; elsewhere (multi-host CPU tests, GPU
clusters) pass coordinator/process counts explicitly or via the
``DDLS_TPU_COORDINATOR`` / ``DDLS_TPU_NUM_PROCESSES`` /
``DDLS_TPU_PROCESS_ID`` environment variables so the same command line can
be launched on every host.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           platform: Optional[str] = None,
                           **kwargs) -> Dict[str, Any]:
    """Join this process into the global JAX runtime; returns topology info.

    Args resolve from explicit values first, then the ``DDLS_TPU_*``
    environment, then JAX's own auto-detection (the TPU pod path, where no
    arguments are needed). ``platform='cpu'`` pins the CPU backend and
    selects gloo cross-process collectives -- the CI substitute for a pod
    slice, mirroring the test strategy in SURVEY.md §4.
    """
    global _initialized
    import jax

    if platform == "cpu":
        # must run before backend init; gloo provides the cross-process
        # CPU collectives used by the virtual-pod tests
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    coordinator_address = (coordinator_address
                           or os.environ.get("DDLS_TPU_COORDINATOR"))
    if num_processes is None and os.environ.get("DDLS_TPU_NUM_PROCESSES"):
        num_processes = int(os.environ["DDLS_TPU_NUM_PROCESSES"])
    if process_id is None and os.environ.get("DDLS_TPU_PROCESS_ID"):
        process_id = int(os.environ["DDLS_TPU_PROCESS_ID"])

    if not _initialized:
        init_kwargs = dict(kwargs)
        if coordinator_address is not None:
            init_kwargs.update(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
        jax.distributed.initialize(**init_kwargs)
        _initialized = True
        if jax.process_count() > 1:
            _warmup_collectives()
    return distributed_info()


def _warmup_collectives() -> None:
    """Run one tiny all-device reduction immediately after init.

    Cross-process collective contexts (gloo on CPU) are established lazily
    on first use with a short handshake timeout; if hosts reach their first
    real collective at different times (e.g. the primary writes an initial
    checkpoint first), the handshake can expire. Doing it here, while every
    process is in lockstep, makes later collectives timing-insensitive.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = np.asarray(jax.devices())
    mesh = Mesh(devices.reshape(-1), ("all",))
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("all")),
        np.ones((jax.local_device_count(),), np.float32),
        (devices.size,))
    y = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
    jax.block_until_ready(y)


def distributed_info() -> Dict[str, Any]:
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "num_local_devices": jax.local_device_count(),
        "num_global_devices": jax.device_count(),
        "platform": jax.devices()[0].platform if jax.devices() else None,
    }


def is_primary() -> bool:
    """True on the process that should own logging/checkpointing."""
    import jax

    return jax.process_index() == 0


def shutdown_distributed() -> None:
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False
