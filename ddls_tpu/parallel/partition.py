"""Partition rules: one declarative sharding vocabulary for the learner.

The Podracer recipe this stack follows (PAPERS.md, arXiv 2104.06272)
gets model scale from a pjit'd learner whose params are SHARDED over
the mesh rather than replicated.  This module is the single home of
that placement decision: a ``match_partition_rules``-style engine
(regex over ``/``-joined param-tree paths -> ``PartitionSpec``) plus a
declarative rule table for the GNN param tree with three NAMED LAYOUTS:

``replicated``
    Today's behaviour, the default.  ``state_shardings`` returns the
    exact ``replicated_sharding(mesh)`` object the learners always
    used, so the compiled program, the jit-cache key and every bit of
    the update are IDENTICAL to the pre-partition code path.

``fsdp``
    ZeRO-3 over the data axis: the Dense kernels are sharded along
    their INPUT-feature (first) dim over the existing ``dp`` axis —
    the same devices that shard the batch also shard the params and
    the adam moments, so per-device state bytes drop by the dp width
    and GSPMD emits the all-gather (forward) / reduce-scatter (grads)
    pairs from the annotations alone.  No new mesh geometry: on the
    1-axis training mesh the dp axis IS the fsdp axis (a dedicated
    axis name would change geometry, not semantics).

``tp``
    Tensor parallelism per SNIPPETS [3]: kernels sharded along their
    OUTPUT-feature (last) dim over a second mesh axis ``mp`` (the
    ``mp_tree_shardings`` axis vocabulary), biases and LayerNorms
    replicated.  Needs a 2-axis mesh — ``mesh_for_layout`` builds
    ``("dp", "mp")``; a mesh without the axis raises with the fix.

Matching is ``re.search`` over the ``/``-joined tree path, so ONE rule
table covers a bare params dict and a whole TrainState alike: the adam
``mu``/``nu`` moments mirror the params tree and their paths END with
the same ``.../Dense_i/kernel`` suffix the rule names.  Scalar leaves
(``step``, ``count``, ``kl_coeff`` — ndim 0 or size 1) are ALWAYS
replicated before any rule is consulted; a non-scalar leaf no rule
matches is a LOUD error, never a silent replicate.

Leaves whose named dim does not divide the mesh axis fall back to
replicated per leaf (deterministic in shapes — multi-host safe); the
canonical checkpoint family therefore loads into ``fsdp``/``tp`` with
its small kernels replicated and only the eligible ones sharded, while
the frozen ``gnn/graph_module/logit_head/value_head`` names keep every
shipped checkpoint loading into ``replicated`` unchanged.

The lint engine's ``frozen-param-tree`` rule cross-validates the table
below against ``CANONICAL_PARAM_PATHS`` (every rule matches >= 1 real
path; every path is covered; every ``LARGE_KERNEL_PATHS`` entry
first-matches a SHARDING rule in fsdp/tp) — a stale or typo'd regex
fails lint before it can fail at init_state time.
"""
from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddls_tpu.parallel.mesh import make_mesh, replicated_sharding

#: the three named layouts of the train-config ``param_sharding`` knob
LAYOUTS = ("replicated", "fsdp", "tp")

#: fsdp shards over the data axis (ZeRO-3); tp over the second mesh axis
FSDP_AXIS = "dp"
TP_AXIS = "mp"

#: canonical GNNPolicy param-tree paths (n_actions=17 checkpoint family;
#: models/policy.py + models/gnn.py — the frozen setup() names).  The
#: lint frozen-param-tree rule validates PARTITION_RULES against this
#: list, so it must stay in sync with the canonical model: regenerate
#: with ``tree_paths(model.init(...))`` when the architecture changes.
CANONICAL_PARAM_PATHS = (
    "gnn/round_0/edge_module/Dense_0/bias",
    "gnn/round_0/edge_module/Dense_0/kernel",
    "gnn/round_0/edge_module/LayerNorm_0/bias",
    "gnn/round_0/edge_module/LayerNorm_0/scale",
    "gnn/round_0/node_module/Dense_0/bias",
    "gnn/round_0/node_module/Dense_0/kernel",
    "gnn/round_0/node_module/LayerNorm_0/bias",
    "gnn/round_0/node_module/LayerNorm_0/scale",
    "gnn/round_0/reduce_module/Dense_0/bias",
    "gnn/round_0/reduce_module/Dense_0/kernel",
    "gnn/round_0/reduce_module/LayerNorm_0/bias",
    "gnn/round_0/reduce_module/LayerNorm_0/scale",
    "gnn/round_1/edge_module/Dense_0/bias",
    "gnn/round_1/edge_module/Dense_0/kernel",
    "gnn/round_1/edge_module/LayerNorm_0/bias",
    "gnn/round_1/edge_module/LayerNorm_0/scale",
    "gnn/round_1/node_module/Dense_0/bias",
    "gnn/round_1/node_module/Dense_0/kernel",
    "gnn/round_1/node_module/LayerNorm_0/bias",
    "gnn/round_1/node_module/LayerNorm_0/scale",
    "gnn/round_1/reduce_module/Dense_0/bias",
    "gnn/round_1/reduce_module/Dense_0/kernel",
    "gnn/round_1/reduce_module/LayerNorm_0/bias",
    "gnn/round_1/reduce_module/LayerNorm_0/scale",
    "graph_module/Dense_0/bias",
    "graph_module/Dense_0/kernel",
    "graph_module/LayerNorm_0/bias",
    "graph_module/LayerNorm_0/scale",
    "logit_head/Dense_0/bias",
    "logit_head/Dense_0/kernel",
    "logit_head/Dense_1/bias",
    "logit_head/Dense_1/kernel",
    "logit_head/Dense_2/bias",
    "logit_head/Dense_2/kernel",
    "value_head/Dense_0/bias",
    "value_head/Dense_0/kernel",
    "value_head/Dense_1/bias",
    "value_head/Dense_1/kernel",
    "value_head/Dense_2/bias",
    "value_head/Dense_2/kernel",
)

#: the kernels that dominate state bytes (the MLP heads: 24x256 and
#: 256x256 at canonical width, wider under --model-scale) — the lint
#: rule requires each to first-match a rule with a REAL axis in the
#: fsdp and tp tables (an "uncovered large leaf" is a lint error)
LARGE_KERNEL_PATHS = (
    "logit_head/Dense_0/kernel",
    "logit_head/Dense_1/kernel",
    "value_head/Dense_0/kernel",
    "value_head/Dense_1/kernel",
)

#: the declarative layout tables: ordered (regex, PartitionSpec) pairs,
#: FIRST re.search match wins.  Keep every entry a literal — the lint
#: frozen-param-tree rule reads this table from the AST.
PARTITION_RULES: Dict[str, Tuple[Tuple[str, P], ...]] = {
    "replicated": (
        (r".*", P()),
    ),
    "fsdp": (
        # all Dense kernels: shard the input-feature (first) dim over
        # dp; ineligible dims (canonical small kernels) fall back to
        # replicated per leaf in specs_to_shardings
        (r"Dense_\d+/kernel$", P(FSDP_AXIS, None)),
        (r"LayerNorm_\d+/(scale|bias)$", P()),
        (r"Dense_\d+/bias$", P()),
    ),
    "tp": (
        # GNN + logit/value heads: shard the output-feature (last) dim
        # over mp (SNIPPETS [3] layout); biases/LayerNorms replicated
        (r"(logit_head|value_head)/Dense_\d+/kernel$", P(None, TP_AXIS)),
        (r"(gnn|graph_module).*/Dense_\d+/kernel$", P(None, TP_AXIS)),
        (r"LayerNorm_\d+/(scale|bias)$", P()),
        (r"Dense_\d+/bias$", P()),
    ),
}


# ----------------------------------------------------------- path utils
def _path_str(key_path) -> str:
    """One tree-path entry -> its ``/``-joined name: dict keys and
    attribute names verbatim, sequence indices as their position (so an
    optax chain's tuple levels read ``opt_state/1/0/mu/...``)."""
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:  # pragma: no cover - future key kinds
            parts.append(str(k))
    return "/".join(parts)


def tree_paths(tree) -> Tuple[str, ...]:
    """The ``/``-joined path of every leaf, in flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return tuple(_path_str(p) for p, _ in flat)


def _is_scalar(leaf) -> bool:
    shp = getattr(leaf, "shape", ())
    return len(shp) == 0 or int(np.prod(shp)) <= 1


# --------------------------------------------------------- rule matching
def match_partition_rules(rules: Sequence[Tuple[str, P]], tree):
    """Assign a ``PartitionSpec`` to every leaf of ``tree``.

    ``rules`` is an ordered sequence of ``(regex, PartitionSpec)``; the
    FIRST rule whose ``re.search`` hits the leaf's ``/``-joined path
    wins (the SNIPPETS [1] contract).  Scalar leaves (ndim 0 or size
    <= 1) are always ``P()`` without consulting the rules; a non-scalar
    leaf that no rule matches raises — placement must be exhaustive,
    never an accidental replicate.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for key_path, leaf in flat:
        name = _path_str(key_path)
        if _is_scalar(leaf):
            specs.append(P())
            continue
        for pat, spec in compiled:
            if pat.search(name):
                specs.append(spec)
                break
        else:
            raise ValueError(
                f"partition rule not found for param {name!r} "
                f"(shape {tuple(getattr(leaf, 'shape', ()))}): every "
                "non-scalar leaf must match a rule — extend the layout "
                "table in ddls_tpu/parallel/partition.py")
    return jax.tree_util.tree_unflatten(treedef, specs)


def layout_axis(layout: str) -> Optional[str]:
    """The mesh axis a layout shards over (None for replicated)."""
    return {"replicated": None, "fsdp": FSDP_AXIS, "tp": TP_AXIS}[layout]


def validate_layout(layout: str) -> str:
    if layout not in LAYOUTS:
        raise ValueError(
            f"param_sharding must be one of {LAYOUTS}, got {layout!r}")
    return layout


def validate_mesh_for_layout(mesh: Mesh, layout: str) -> None:
    """Loud contract edge: a layout naming an axis the mesh lacks is a
    config error, not a silent replicate."""
    axis = layout_axis(validate_layout(layout))
    if axis is not None and axis not in mesh.shape:
        raise ValueError(
            f"param_sharding={layout!r} shards over mesh axis {axis!r}, "
            f"but the mesh has axes {tuple(mesh.shape)} — build the "
            f"mesh with partition.mesh_for_layout(n_devices, {layout!r})"
            " (train/loops.py does this from the param_sharding knob)")


def mesh_for_layout(n_devices: Optional[int], layout: str,
                    tp_size: Optional[int] = None) -> Mesh:
    """The training mesh a layout wants: the 1-D dp mesh for
    ``replicated``/``fsdp`` (bit-identical to today's ``make_mesh``),
    a ``("dp", "mp")`` mesh for ``tp`` with ``tp_size`` devices on the
    tensor axis (default 2)."""
    validate_layout(layout)
    if layout != "tp":
        return make_mesh(n_devices)
    n = n_devices if n_devices is not None else len(jax.devices())
    tp = int(tp_size or 2)
    if tp < 2 or n % tp:
        raise ValueError(
            f"param_sharding='tp' needs tp_size >= 2 dividing the "
            f"device count ({n}), got tp_size={tp}")
    return make_mesh(n, ("dp", TP_AXIS), shape=(n // tp, tp))


# ------------------------------------------------------ sharding trees
def specs_to_shardings(mesh: Mesh, tree, specs):
    """Spec tree -> NamedSharding tree over ``mesh``, with the per-leaf
    divisibility fallback: a leaf whose named dim does not divide its
    mesh axis (or whose rank is below the spec) is replicated.  Pure in
    (shapes, specs) — identical on every process, multi-host safe."""

    def to_sharding(leaf, spec):
        shp = tuple(getattr(leaf, "shape", ()))
        if not isinstance(spec, P):
            return spec  # already a Sharding
        if len(spec) > len(shp):
            return NamedSharding(mesh, P())
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            names = axis if isinstance(axis, tuple) else (axis,)
            width = int(np.prod([mesh.shape[a] for a in names]))
            if shp[dim] % width:
                return NamedSharding(mesh, P())
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map(to_sharding, tree, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def state_shardings(mesh: Mesh, state, layout: str):
    """The ONE learner entry point: sharding (tree) for a whole
    TrainState under a named layout.  ``replicated`` returns the single
    ``replicated_sharding(mesh)`` object — the exact pre-partition
    value, so default-layout jit keys, programs and bits are unchanged;
    other layouts run the rule table over the state (params and adam
    moments match the same suffix rules) with the divisibility
    fallback applied."""
    validate_mesh_for_layout(mesh, layout)
    if layout == "replicated":
        return replicated_sharding(mesh)
    specs = match_partition_rules(PARTITION_RULES[layout], state)
    return specs_to_shardings(mesh, state, specs)


def params_shardings_of(state_sh, state=None):
    """The params subtree of a state-shardings value: a single Sharding
    passes through (replicated layouts), a state-shaped tree yields its
    ``.params`` field — what collectors feed their jit in_shardings so
    sharded params enter the forward WITHOUT an implicit reshard."""
    from jax.sharding import Sharding

    if isinstance(state_sh, Sharding):
        return state_sh
    return state_sh.params


# ------------------------------------------------------- accounting
def live_bytes_per_device(tree) -> int:
    """Peak resident bytes any one device holds for ``tree``: the sum
    over leaves of that device's SHARD bytes (aval metadata only — no
    device sync, works on virtual CPU meshes where allocator telemetry
    does not).  Replicated leaves count full size on every device;
    sharded leaves 1/width — exactly the number the fsdp layout exists
    to shrink (docs/perf_round13.md "peak live bytes method")."""
    per_device: Dict[object, int] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        for shard in leaf.addressable_shards:
            per_device[shard.device] = (per_device.get(shard.device, 0)
                                        + int(shard.data.nbytes))
    return max(per_device.values(), default=0)
