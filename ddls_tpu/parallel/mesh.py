"""Device-mesh helpers: the distributed backend of the framework.

The reference scales RL training with Ray/RLlib worker processes and keeps
its learner on one GPU (SURVEY.md §5.8); the TPU-native replacement is a
single SPMD program over a ``jax.sharding.Mesh``. Data (trajectory batches)
is sharded over the ``dp`` axis with ``NamedSharding``; parameters are
replicated; XLA then emits the gradient all-reduce (``psum`` over ICI) from
the sharding annotations alone — there is no NCCL/MPI code to write.

On a real pod slice, call ``jax.distributed.initialize()`` first (one process
per host) and these helpers operate on the global device set; on a laptop or
in tests, ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` provides a
virtual N-device mesh.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = ("dp",),
              devices=None,
              shape: Optional[Sequence[int]] = None) -> Mesh:
    """Build a mesh over the first ``n_devices`` devices.

    With one axis name the mesh is a 1-D data-parallel mesh; more axis names
    split the device count into factors, largest-last (e.g. ``("dp", "tp")``
    with 8 devices -> dp=2, tp=4). Pass ``shape`` (one int per axis name,
    product = device count) to pick the factorisation explicitly, e.g.
    ``make_mesh(8, ("dp", "mp"), shape=(4, 2))``.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} "
                "are available")
        devices = devices[:n_devices]
    n = len(devices)
    if shape is not None:
        shape = list(shape)
        if len(shape) != len(axis_names) or int(np.prod(shape)) != n:
            raise ValueError(
                f"mesh shape {shape} does not factor {n} devices over "
                f"axes {tuple(axis_names)}")
    else:
        shape = []
        remaining = n
        for _ in axis_names[:-1]:
            f = _largest_factor_leq(remaining, int(np.sqrt(remaining)))
            shape.append(f)
            remaining //= f
        shape.append(remaining)
    mesh_devices = np.asarray(devices).reshape(shape)
    return Mesh(mesh_devices, axis_names)


def _largest_factor_leq(n: int, k: int) -> int:
    for f in range(max(k, 1), 0, -1):
        if n % f == 0:
            return f
    return 1


def batch_sharding(mesh: Mesh, batch_axis: int = 0,
                   axis_name: str = "dp") -> NamedSharding:
    """Sharding that splits ``batch_axis`` over ``axis_name``."""
    spec = [None] * batch_axis + [axis_name]
    return NamedSharding(mesh, P(*spec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mp_tree_shardings(mesh: Mesh, tree, axis_name: str = "mp",
                      min_size: int = 0):
    """Tensor-parallel shardings for a parameter (or train-state) pytree.

    Shape-based rule, applied per leaf: a dense kernel (ndim >= 2) whose
    last (output-feature) dimension divides the ``axis_name`` mesh axis and
    whose size reaches ``min_size`` is sharded over that dimension; every
    other leaf (biases, scalars, counters) is replicated. Because the rule
    depends only on leaf shape, optimiser moments (adam mu/nu mirror the
    params tree) pick up exactly the params' layout, so one ``tree_map``
    covers a whole TrainState. XLA's GSPMD partitioner then emits the
    activation all-gathers / gradient reduce-scatters over ``axis_name``
    from these annotations alone — the TPU-native counterpart of
    hand-written tensor-parallel NCCL collectives.
    """
    size = mesh.shape[axis_name]

    def rule(x):
        shp = getattr(x, "shape", ())
        if (len(shp) >= 2 and shp[-1] % size == 0
                and int(np.prod(shp)) >= min_size):
            return NamedSharding(
                mesh, P(*([None] * (len(shp) - 1) + [axis_name])))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, tree)


def shard_batch(mesh: Mesh, tree, batch_axis: int = 0,
                axis_name: str = "dp"):
    """Place every leaf of ``tree`` on the mesh, sharded over its batch axis.

    Single-process: a plain ``device_put`` with the batch sharding. Multi-
    process (after ``jax.distributed.initialize``): each process holds only
    its locally collected rollouts, so leaves are treated as this process's
    shard of the global batch and assembled with
    ``jax.make_array_from_process_local_data`` -- the global batch is the
    concatenation of every host's contribution along ``batch_axis``.

    Leaves whose batch dimension is not divisible by the local mesh axis
    size are rejected (callers pad rollout batches to a multiple of the dp
    size).
    """
    sharding = batch_sharding(mesh, batch_axis, axis_name)
    multiprocess = jax.process_count() > 1
    axis_size = mesh.shape[axis_name]
    if multiprocess and axis_size % jax.process_count():
        raise ValueError(
            f"mesh axis {axis_name!r} of size {axis_size} cannot be evenly "
            f"divided across {jax.process_count()} processes; size the "
            "mesh as a multiple of the process count")
    local_axis_size = (axis_size // jax.process_count()
                       if multiprocess else axis_size)

    def put(x):
        x = np.asarray(x) if not isinstance(x, jax.Array) else x
        if x.ndim <= batch_axis or x.shape[batch_axis] % local_axis_size:
            raise ValueError(
                f"leaf shape {getattr(x, 'shape', None)} not shardable over "
                f"{local_axis_size} local devices on axis {batch_axis}")
        if multiprocess:
            global_shape = list(x.shape)
            global_shape[batch_axis] *= jax.process_count()
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x), tuple(global_shape))
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(put, tree)


def place_state_tree(tree, shardings, mesh: Optional[Mesh] = None):
    """Place a process-identical host pytree (train state) onto its
    shardings — the multi-host-safe ``device_put``.

    ``shardings`` may also be a ``PartitionSpec`` tree (the partition-rule
    engine's vocabulary, ``parallel/partition.py``) when ``mesh`` is given:
    each spec leaf is wrapped into a ``NamedSharding`` on that mesh before
    placement, so callers can hand the declarative spec table straight to
    the placement layer.

    Single-process this IS ``jax.device_put`` (same aliasing/donation
    semantics, bit-identical path). Multi-process, ``device_put`` onto a
    non-fully-addressable sharding routes every host/uncommitted leaf
    through ``multihost_utils.assert_equal``, which broadcasts the WHOLE
    value per leaf — a per-leaf collective stream that current jax/gloo
    can collide with neighbouring collectives under process skew
    (measured on the 1-core CPU box: ``gloo ... op.preamble.length <=
    op.nbytes`` aborts in the distributed workers' ``init_state``). The
    framework's multi-host rules already guarantee the state is
    IDENTICAL on every process by construction (deterministic seeds —
    CLAUDE.md), so the check is redundant: each process contributes its
    local copy through ``jax.make_array_from_process_local_data``
    exactly like :func:`shard_batch`, collective-free. The logical
    (global) shape of every leaf equals its local shape — replicated
    leaves are whole copies, and tensor-parallel leaves
    (``mp_tree_shardings``) have each process slice ITS shards out of
    its full local copy.
    """
    if mesh is not None:
        if isinstance(shardings, P):
            shardings = NamedSharding(mesh, shardings)
        else:
            shardings = jax.tree_util.tree_map(
                lambda s: (NamedSharding(mesh, s)
                           if isinstance(s, P) else s),
                shardings, is_leaf=lambda s: isinstance(s, P))
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)
    from jax.sharding import Sharding

    if isinstance(shardings, Sharding):
        shardings = jax.tree_util.tree_map(lambda _: shardings, tree)

    def put(x, s):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(s, x, x.shape)

    return jax.tree_util.tree_map(put, tree, shardings)
