from ddls_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                    replicated_sharding, shard_batch)

__all__ = ["make_mesh", "batch_sharding", "replicated_sharding",
           "shard_batch"]
