from ddls_tpu.parallel.distributed import (distributed_info,
                                           initialize_distributed,
                                           is_primary,
                                           shutdown_distributed)
from ddls_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                    replicated_sharding, shard_batch)

__all__ = ["make_mesh", "batch_sharding", "replicated_sharding",
           "shard_batch", "initialize_distributed", "distributed_info",
           "is_primary", "shutdown_distributed"]
