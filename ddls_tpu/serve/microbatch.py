"""Deadline microbatching: queue per bucket, flush on fill or deadline.

The amortisation argument from the TPU tunnel measurements (CLAUDE.md:
~116 ms per dispatch) and from Podracer/MSRL-style decoupling (ISSUE 1,
arXiv 2104.06272 / 2210.00882): individual requests must never each pay a
device round-trip. Requests wait in a per-bucket queue until either the
batch fills (``max_batch``) or the *oldest* request's latency budget
(``deadline_s``) expires; the flush hands one same-bucket batch to the
forward. The engine is clock-parameterised (callers pass ``now``) so tests
and the bench drive it deterministically without sleeping.

The engine never drops a request: saturation is signalled to the caller at
``submit`` time (``would_saturate``), and the caller answers those from the
heuristic fallback instead of enqueueing — the server stays responsive when
the device backend stalls (e.g. a wedged axon tunnel).
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple


@dataclass
class PendingRequest:
    """One queued decision request, already bucket-padded."""
    request_id: int
    bucket_idx: int
    obs: Dict[str, Any]
    enqueue_time: float
    meta: Optional[dict] = field(default=None)


class MicrobatchEngine:
    def __init__(self, n_buckets: int, max_batch: int = 8,
                 deadline_s: float = 0.01, max_queue: int = 64):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.n_buckets = int(n_buckets)
        self.max_batch = int(max_batch)
        self.deadline_s = float(deadline_s)
        self.max_queue = int(max_queue)
        self._queues: List[Deque[PendingRequest]] = [
            deque() for _ in range(self.n_buckets)]

    # ------------------------------------------------------------------ state
    def queued(self) -> int:
        return sum(len(q) for q in self._queues)

    def would_saturate(self) -> bool:
        """True when one more enqueue would exceed the queue budget; the
        caller should answer that request from the fallback instead."""
        return self.queued() >= self.max_queue

    def next_deadline(self) -> Optional[float]:
        """Earliest wall-clock time any queued batch becomes due (None when
        idle) — lets a serving loop sleep exactly until work exists. A
        queue already holding a full batch is due NOW (its head's enqueue
        time, always in the past), never deadline_s out — a caller that
        sleeps to this value must not delay a flush-on-fill."""
        full = [q[0].enqueue_time for q in self._queues
                if len(q) >= self.max_batch]
        if full:
            return min(full)
        heads = [q[0].enqueue_time for q in self._queues if q]
        if not heads:
            return None
        return min(heads) + self.deadline_s

    # ------------------------------------------------------------------ queue
    def submit(self, req: PendingRequest) -> None:
        if not 0 <= req.bucket_idx < self.n_buckets:
            raise IndexError(f"bucket_idx {req.bucket_idx} out of range "
                             f"[0, {self.n_buckets})")
        self._queues[req.bucket_idx].append(req)

    def due_batches(self, now: float,
                    force: bool = False
                    ) -> List[Tuple[int, List[PendingRequest]]]:
        """Pop every batch that is due at ``now``: full batches always, and
        partial batches whose head has waited ``deadline_s``. ``force``
        drains everything regardless of deadline (shutdown / EOF flush).
        Batches never mix buckets and never exceed ``max_batch``."""
        out: List[Tuple[int, List[PendingRequest]]] = []
        for idx, q in enumerate(self._queues):
            while len(q) >= self.max_batch:
                out.append((idx, [q.popleft()
                                  for _ in range(self.max_batch)]))
            if q and (force
                      or now - q[0].enqueue_time >= self.deadline_s):
                out.append((idx, [q.popleft() for _ in range(len(q))]))
        return out
