"""In-process online policy server: bucket -> microbatch -> one jitted
flat-batched forward -> partition-degree decision.

The inference half of the stack (ISSUE 1): turns a shipped checkpoint into
an online "partition this arriving job" service. Three design rules carried
over from the training-side measurements:

* **Fixed compile shapes.** Every bucket runs ONE XLA program: the
  flattened mega-graph forward (``GNNPolicy.flat_batched`` — never a vmapped
  apply, round-5 invariant) at a fixed batch size ``max_batch``. Partial
  flushes are padded by replicating the first request's rows; at a fixed
  program a request's output rows are bit-identical whatever rides in the
  other slots (XLA CPU tiles by shape, not by data — pinned in
  tests/test_serve.py), so batching can never change an answer, and each
  bucket compiles exactly once.
* **Deadline microbatching.** Requests queue per bucket and flush on fill
  or when the oldest has waited ``deadline_s`` (serve/microbatch.py) — the
  ~116 ms tunnel RTT is amortised across the batch instead of paid per
  request.
* **Heuristic degraded mode.** When the queue saturates, a request fits no
  bucket, or the device forward fails (wedged axon tunnel), the answer
  comes from the rule-extracted ``FixedDegreePacking`` heuristic
  (envs/baselines.py) — the decision rule the shipped checkpoints
  themselves implement (docs/results_round5/rule_extraction.md), so
  degraded-mode answers agree with the policy at the extracted degree. The
  server never blocks on the device and never drops a request.

The server is single-threaded and clock-parameterised: ``submit``/``poll``
take an optional ``now`` so tests and the bench drive time deterministically;
production callers just let it default to ``time.perf_counter``.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ddls_tpu import telemetry
from ddls_tpu.envs.baselines import FixedDegreePacking
from ddls_tpu.envs.obs import EDGE_FEATURE_DIM, NODE_FEATURE_DIM
from ddls_tpu.serve.bucketing import (BucketOverflowError, BucketSpec,
                                      ObsBucketer, default_buckets)
from ddls_tpu.serve.microbatch import MicrobatchEngine, PendingRequest

# the canonical 32-server extraction (rule_extraction.md): what the shipped
# ppo_device_trained / ppo_price_mixed policies implement
DEFAULT_FALLBACK_DEGREE = 8

# every encoded-obs key the batched forward stacks (envs/obs.py contract)
# PLUS action_set, which every heuristic-fallback path reads
# (envs/baselines.py _valid_actions); validated at submit so one malformed
# request errors to ITS caller instead of poisoning a batch (or latching
# degraded mode)
_REQUIRED_OBS_KEYS = ("node_features", "edge_features", "graph_features",
                      "edges_src", "edges_dst", "node_split", "edge_split",
                      "action_set", "action_mask")


def _validate_obs(obs: Dict[str, Any], widths: Dict[str, int]) -> None:
    """Reject a malformed obs at submit, before it can reach a batch: the
    fixed per-row feature widths come from the ``envs/obs.py`` encode
    contract; the config-dependent ``graph_features``/``action_mask``
    widths come from the server's model/config where known
    (``PolicyServer`` seeds them) and are otherwise pinned to the first
    accepted request — pins commit only after the WHOLE obs passes, so a
    rejected request can never poison the contract. Without the width
    checks a single bad request passes submit and fails at the device
    call — downgrading innocent co-batched requests to the heuristic (or
    wrongly latching degraded mode for a healthy backend)."""
    missing = [k for k in _REQUIRED_OBS_KEYS if k not in obs]
    if missing:
        raise ValueError(f"request obs missing keys {missing}")
    for key, dim in (("node_features", NODE_FEATURE_DIM),
                     ("edge_features", EDGE_FEATURE_DIM)):
        arr = np.asarray(obs[key])
        if arr.ndim != 2 or arr.shape[1] != dim:
            raise ValueError(f"obs[{key!r}] must be 2-D [rows, {dim}], "
                             f"got shape {arr.shape}")
    # split counts must be consistent with the rows actually present: an
    # inflated split would make pad_obs_to zero-fill phantom "real" rows
    # (served as a garbage policy decision), a negative one silently
    # truncates real rows — both are data errors owed to the caller
    for split_key, rows_key, row_count in (
            ("node_split", "node_features",
             int(np.asarray(obs["node_features"]).shape[0])),
            ("edge_split", "edge_features",
             int(np.asarray(obs["edge_features"]).shape[0]))):
        split = np.asarray(obs[split_key]).reshape(-1)
        if split.size != 1:
            raise ValueError(f"obs[{split_key!r}] must hold one count, "
                             f"got {split.size} values")
        count = int(split[0])
        if not 0 <= count <= row_count:
            raise ValueError(f"obs[{split_key!r}]={count} out of range "
                             f"for {row_count} {rows_key} rows")
    m = int(np.asarray(obs["edge_split"]).reshape(-1)[0])
    n = int(np.asarray(obs["node_split"]).reshape(-1)[0])
    for key in ("edges_src", "edges_dst"):
        arr = np.asarray(obs[key])
        if arr.ndim != 1 or arr.shape[0] < m:
            raise ValueError(f"obs[{key!r}] must be 1-D with >= "
                             f"edge_split={m} entries, got shape "
                             f"{arr.shape}")
        # REAL edges must point at REAL nodes of THIS graph: in the
        # flat-batched mega-graph an out-of-range endpoint escapes its
        # slot (dst + k*N lands in a neighbour's node rows) and the
        # scatter silently changes a CO-BATCHED client's embedding —
        # the one way a request could break "batching never changes an
        # answer". Padded edges beyond edge_split are masked; no
        # constraint on them.
        real = arr[:m]
        if m and (int(real.min()) < 0 or int(real.max()) >= n):
            raise ValueError(
                f"obs[{key!r}] endpoints must lie in [0, "
                f"node_split={n}) for the first edge_split={m} edges; "
                f"got range [{int(real.min())}, {int(real.max())}]")
    pins: Dict[str, int] = {}
    for key in ("graph_features", "action_mask"):
        arr = np.asarray(obs[key])
        if arr.ndim != 1:
            raise ValueError(f"obs[{key!r}] must be 1-D, "
                             f"got shape {arr.shape}")
        expected = widths.get(key)
        if expected is None:
            pins[key] = int(arr.shape[0])
        elif int(arr.shape[0]) != expected:
            raise ValueError(f"obs[{key!r}] width {arr.shape[0]} != "
                             f"{expected} (this server's model)")
    n_mask = int(np.asarray(obs["action_mask"]).shape[0])
    if np.asarray(obs["action_set"]).shape != (n_mask,):
        raise ValueError(
            f"obs['action_set'] shape "
            f"{np.asarray(obs['action_set']).shape} != action_mask's "
            f"({n_mask},)")
    widths.update(pins)


@dataclass
class ServeResponse:
    request_id: int
    action: int
    source: str           # "policy" | "fallback"
    reason: str           # "batched" | "saturated" | "overflow"
                          # | "invalid" | "degraded"
    bucket_idx: Optional[int]
    latency_s: float
    batch_fill: Optional[int] = None   # real requests in the flushed batch


# trailing-window size for the percentile/occupancy samples: a long-lived
# server must not hold one float per request ever served (the counters
# above the window stay exact forever)
STATS_WINDOW = 8192

# batch-fill fractions land in (0, 1]: an eighth-ladder matches the
# default max_batch=8 (one bucket per possible fill count)
_OCCUPANCY_BUCKETS = tuple((i + 1) / 8 for i in range(8))


class ServeStats:
    """Serving accounting on the shared telemetry primitives (ISSUE 3):
    counters + fixed-bucket latency/occupancy histograms in a PRIVATE
    always-on ``telemetry.Registry`` — per-server isolation (concurrent
    servers must never share counters) and independence from the global
    telemetry enable switch (serve's counters are part of its contract,
    pinned bit-equal by tests/test_serve.py). ``summary()`` keeps its
    JSON shape; percentiles/occupancy read the histograms' trailing
    ``STATS_WINDOW`` windows — the exact semantics the hand-rolled deques
    had. ``registry.snapshot()`` is the bench/report surface.
    """

    def __init__(self, registry: Optional[telemetry.Registry] = None):
        self.registry = (registry if registry is not None
                         else telemetry.Registry(enabled=True))
        r = self.registry
        self._requests = r.counter("serve.requests")
        self._policy = r.counter("serve.policy")
        self._fallback = r.counter("serve.fallback")
        self._flushes = r.counter("serve.flushes")
        self._degraded = r.counter("serve.degraded_transitions")
        self._compiles = r.gauge("serve.compiles")
        self._latency = r.histogram("serve.latency_s",
                                    window=STATS_WINDOW)
        self._occupancy = r.histogram("serve.batch_occupancy",
                                      buckets=_OCCUPANCY_BUCKETS,
                                      window=STATS_WINDOW)

    # --------------------------------------------------------------- intake
    def record_request(self) -> None:
        self._requests.inc()

    def record_bucket_hit(self, bucket_idx: int) -> None:
        self.registry.counter(f"serve.bucket_hits.{bucket_idx}").inc()

    def record_response(self, resp: ServeResponse) -> None:
        self._latency.observe(resp.latency_s)
        if resp.source == "policy":
            self._policy.inc()
        else:
            self._fallback.inc()
            self.registry.counter(
                f"serve.fallback_reason.{resp.reason}").inc()

    def record_flush(self, fill: int, capacity: int,
                     bucket_idx: Optional[int] = None,
                     cause: Optional[str] = None) -> None:
        self._flushes.inc()
        occ = fill / capacity
        self._occupancy.observe(occ)
        if bucket_idx is not None:
            self.registry.histogram(
                f"serve.batch_occupancy.bucket{bucket_idx}",
                buckets=_OCCUPANCY_BUCKETS,
                window=STATS_WINDOW).observe(occ)
        if cause is not None:
            self.registry.counter(f"serve.flush_cause.{cause}").inc()

    def record_degraded_transition(self) -> None:
        self._degraded.inc()

    # ------------------------------------------------------------ readbacks
    def _prefixed_counts(self, prefix: str) -> Dict[str, int]:
        return {name[len(prefix):]: value
                for name, value in self.registry.counter_items()
                if name.startswith(prefix)}

    @property
    def n_requests(self) -> int:
        return self._requests.value

    @property
    def n_policy(self) -> int:
        return self._policy.value

    @property
    def n_fallback(self) -> int:
        return self._fallback.value

    @property
    def n_flushes(self) -> int:
        return self._flushes.value

    @property
    def degraded_transitions(self) -> int:
        return self._degraded.value

    @property
    def n_compiles(self) -> int:
        return int(self._compiles.value or 0)

    @n_compiles.setter
    def n_compiles(self, value: int) -> None:
        self._compiles.set(int(value))

    @property
    def fallback_reasons(self) -> Dict[str, int]:
        return self._prefixed_counts("serve.fallback_reason.")

    @property
    def flush_causes(self) -> Dict[str, int]:
        return self._prefixed_counts("serve.flush_cause.")

    @property
    def bucket_hits(self) -> Dict[int, int]:
        return {int(k): v
                for k, v in self._prefixed_counts(
                    "serve.bucket_hits.").items()}

    @property
    def latencies_s(self):
        return self._latency.window

    @property
    def occupancies(self):
        return self._occupancy.window

    def per_bucket_occupancy(self) -> Dict[int, float]:
        """Mean batch-fill fraction per bucket ladder rung (over the
        trailing window) — the --stats-interval line's occupancy field."""
        out = {}
        for name, h in self.registry.histogram_items():
            if name.startswith("serve.batch_occupancy.bucket"):
                vals = h.window_values()
                if vals:
                    idx = int(name[len("serve.batch_occupancy.bucket"):])
                    out[idx] = float(np.mean(
                        np.asarray(vals, dtype=np.float64)))
        return out

    def summary(self) -> Dict[str, Any]:
        n_requests = self.n_requests
        n_fallback = self.n_fallback
        lat = self._latency
        return {
            "n_requests": n_requests,
            "n_policy": self.n_policy,
            "n_fallback": n_fallback,
            "fallback_rate": (n_fallback / n_requests
                              if n_requests else 0.0),
            "fallback_reasons": self.fallback_reasons,
            "bucket_hits": {str(k): v
                            for k, v in sorted(self.bucket_hits.items())},
            "n_flushes": self.n_flushes,
            "n_compiles": self.n_compiles,
            "p50_latency_ms": (lat.percentile(50) * 1e3
                               if lat.count else None),
            "p99_latency_ms": (lat.percentile(99) * 1e3
                               if lat.count else None),
            "batch_occupancy": (float(np.mean(np.asarray(
                self._occupancy.window_values(), dtype=np.float64)))
                                if self._occupancy.count else None),
            "flush_causes": self.flush_causes,
            "degraded_transitions": self.degraded_transitions,
        }


class BucketForward:
    """The fixed-shape batched forward for one bucket ladder.

    ``forward(obs_list)`` stacks up to ``max_batch`` same-bucket
    observations (padding free slots with replicas of the first — masked
    rows and replica rows change no real output bits at a fixed program
    shape) and runs ``GNNPolicy.flat_batched`` through one jitted call,
    returning per-request (logits, values) as numpy. One XLA program per
    bucket, compiled on that bucket's first flush.
    """

    def __init__(self, model, params, max_batch: int,
                 apply_fn: Optional[Callable] = None):
        import jax

        from ddls_tpu.models.policy import batched_policy_apply

        self.model = model
        self.params = params
        self.max_batch = int(max_batch)
        raw = apply_fn or (lambda p, o: batched_policy_apply(model, p, o))
        self._jit = jax.jit(raw)
        self._compiled_shapes: set = set()
        self._stack_bufs: Dict[tuple, Dict[str, np.ndarray]] = {}

    @property
    def n_compiles(self) -> int:
        return len(self._compiled_shapes)

    def stack(self, obs_list: Sequence[Dict[str, np.ndarray]]
              ) -> Tuple[Dict[str, np.ndarray], int]:
        """Host-side batch assembly, separated from the device call so the
        server can tell malformed request DATA (stack fails here) apart
        from a dead device BACKEND (run fails below). The stacked batch
        is assembled into a per-shape REUSED buffer, so steady-state
        flushes allocate nothing. Reuse is safe because ``run`` DRAINS
        the forward (``jax.device_get``) before returning, and the next
        ``stack`` cannot happen until then — NOT because jax copies the
        input: its CPU client zero-copy ALIASES page-aligned host
        buffers (rl/rollout.py round-7 discovery), so making ``run``
        async would require a fresh buffer per flush."""
        if not obs_list:
            raise ValueError("empty batch")
        if len(obs_list) > self.max_batch:
            raise ValueError(f"batch of {len(obs_list)} exceeds max_batch "
                             f"{self.max_batch}")
        n_real = len(obs_list)
        filled = list(obs_list) + [obs_list[0]] * (self.max_batch - n_real)
        arrays = {k: [np.asarray(o[k]) for o in filled]
                  for k in ("node_features", "edge_features",
                            "graph_features", "edges_src", "edges_dst",
                            "node_split", "edge_split", "action_mask")}
        shape_key = tuple(sorted((k, v[0].shape, str(v[0].dtype))
                                 for k, v in arrays.items()))
        stacked = self._stack_bufs.get(shape_key)
        if stacked is None:
            stacked = {k: np.empty((self.max_batch,) + v[0].shape,
                                   v[0].dtype) for k, v in arrays.items()}
            self._stack_bufs[shape_key] = stacked
        for k, v in arrays.items():
            np.stack(v, out=stacked[k])
        return stacked, n_real

    def run(self, stacked: Dict[str, np.ndarray], n_real: int
            ) -> Tuple[np.ndarray, np.ndarray]:
        import jax

        self._compiled_shapes.add(
            tuple(sorted((k, v.shape) for k, v in stacked.items())))
        logits, values = jax.device_get(self._jit(self.params, stacked))
        return np.asarray(logits)[:n_real], np.asarray(values)[:n_real]

    def forward(self, obs_list: Sequence[Dict[str, np.ndarray]]
                ) -> Tuple[np.ndarray, np.ndarray]:
        stacked, n_real = self.stack(obs_list)
        return self.run(stacked, n_real)


class PolicyServer:
    """Batched online partition-degree serving from a policy's params.

    Parameters
    ----------
    model, params : the ``GNNPolicy`` and its (restored) variables.
    buckets : (max_nodes, max_edges) ladder; defaults to a 3-step halving
        ladder under ``max_nodes``/``max_edges``.
    max_batch : microbatch size = the fixed compile batch per bucket.
    deadline_s : latency budget before a partial batch flushes.
    max_queue : total queued requests before saturation fallback.
    fallback : heuristic actor for degraded mode (default
        ``FixedDegreePacking(8)``, the checkpoint-extracted rule).
    graph_feature_dim : the obs encoder's graph-vector width under the
        model's training config (``build_model_from_config`` returns it).
        When given, a request from a client built against a DIFFERENT env
        config (e.g. without candidate-price features) is rejected at
        submit instead of failing inside the device call — which would
        wrongly latch degraded mode on a healthy backend. When omitted,
        the width is pinned to the first accepted request.
    apply_fn : test hook — replaces the batched forward (e.g. with one
        that raises, to simulate a dead device backend).
    clock : test hook — the time source for deadlines/latency.
    """

    def __init__(self, model, params,
                 buckets: Optional[Sequence[BucketSpec]] = None,
                 max_nodes: int = 32, max_edges: Optional[int] = None,
                 max_batch: int = 8, deadline_s: float = 0.01,
                 max_queue: int = 64,
                 fallback=None,
                 graph_feature_dim: Optional[int] = None,
                 apply_fn: Optional[Callable] = None,
                 clock: Callable[[], float] = time.perf_counter):
        # arena reuse: bucketed obs land in recycled per-bucket arrays
        # (pad_obs_to out=); leases are released at the end of each
        # flush in _run_batch, after the batch (or its fallback) is
        # fully resolved — the pool bound tracks the queue budget
        self.bucketer = ObsBucketer(
            buckets if buckets is not None
            else default_buckets(max_nodes, max_edges),
            reuse_arenas=True, max_pool_per_bucket=max(int(max_queue), 1))
        self.engine = MicrobatchEngine(len(self.bucketer.buckets),
                                       max_batch=max_batch,
                                       deadline_s=deadline_s,
                                       max_queue=max_queue)
        self._forward = BucketForward(model, params, max_batch,
                                      apply_fn=apply_fn)
        self.fallback = (fallback if fallback is not None
                         else FixedDegreePacking(
                             degree=DEFAULT_FALLBACK_DEGREE))
        self.clock = clock
        self.stats = ServeStats()
        self.degraded = False
        # fleet lifecycle flags (ISSUE 8): ``draining`` tells a Router to
        # stop routing here while queued work finishes normally (policy
        # answers, never a mid-swap degraded latch); ``closed`` rejects
        # new submits after close()
        self.draining = False
        self.closed = False
        self._next_id = 0
        self._ready: List[ServeResponse] = []
        self._submit_time: Dict[int, float] = {}
        # config-dependent obs widths (see _validate_obs): action width
        # always comes from the model itself; graph width from the
        # training config when the caller knows it, else pinned at the
        # first accepted request
        self._obs_widths: Dict[str, int] = {}
        n_actions = getattr(model, "n_actions", None)
        if n_actions is not None:
            self._obs_widths["action_mask"] = int(n_actions)
        if graph_feature_dim is not None:
            self._obs_widths["graph_features"] = int(graph_feature_dim)

    # ---------------------------------------------------------------- intake
    def submit(self, obs: Dict[str, np.ndarray],
               now: Optional[float] = None,
               meta: Optional[dict] = None) -> int:
        """Accept one request; returns its request_id. The decision arrives
        via ``poll``/``drain`` — immediately (fallback paths) or once its
        microbatch flushes. Raises ``ValueError`` (before any state
        changes) for an obs missing required keys or mis-shaped — data
        errors belong to the submitting caller, never to the batch."""
        if self.closed:
            raise RuntimeError("PolicyServer is closed")
        _validate_obs(obs, self._obs_widths)
        now = self.clock() if now is None else now
        rid = self._next_id
        self._next_id += 1
        self.stats.record_request()
        self._submit_time[rid] = now

        # fallback answers complete at the clock's now, not the (possibly
        # backdated) arrival instant `now` — a caller submitting arrivals
        # late (bench.py's real-time loop reaching a request after a
        # blocking forward) must still see that wait in latency
        if self.degraded:
            self._resolve_fallback(rid, obs, self.clock(), reason="degraded")
            return rid
        if self.engine.would_saturate():
            # answer NOW from the heuristic rather than queue beyond the
            # latency budget — saturation must degrade quality, not
            # availability
            self._resolve_fallback(rid, obs, self.clock(),
                                   reason="saturated")
            return rid
        try:
            idx, bucketed = self.bucketer.bucket_obs(obs)
        except BucketOverflowError:
            self._resolve_fallback(rid, obs, self.clock(), reason="overflow")
            return rid
        self.stats.record_bucket_hit(idx)
        self.engine.submit(PendingRequest(
            request_id=rid, bucket_idx=idx, obs=bucketed,
            enqueue_time=now, meta=meta))
        return rid

    # ---------------------------------------------------------------- serving
    def poll(self, now: Optional[float] = None,
             force: bool = False) -> List[ServeResponse]:
        """Flush every due microbatch and return all completed responses
        (including fallback answers resolved at submit time)."""
        real_time = now is None
        now = self.clock() if real_time else now
        for idx, reqs in self.engine.due_batches(now, force=force):
            self._run_batch(idx, reqs, now, reread_clock=real_time,
                            force=force)
        out, self._ready = self._ready, []
        return out

    def drain(self, now: Optional[float] = None) -> List[ServeResponse]:
        """Force-flush everything still queued (shutdown / end of input)."""
        return self.poll(now=now, force=True)

    def serve_one(self, obs: Dict[str, np.ndarray]) -> ServeResponse:
        """Synchronous single-request convenience: submit + immediate
        drain, matched by request id — responses the forced drain resolves
        for OTHER queued requests stay pending for the caller's next
        ``poll``. Runs the same fixed-shape program as full batches, so
        the answer is bit-identical to the batched path."""
        rid = self.submit(obs)
        resolved = self.drain()
        mine = next(r for r in resolved if r.request_id == rid)
        self._ready.extend(r for r in resolved if r.request_id != rid)
        return mine

    def next_deadline(self) -> Optional[float]:
        return self.engine.next_deadline()

    def queued(self) -> int:
        return self.engine.queued()

    # ------------------------------------------------------- fleet lifecycle
    def begin_drain(self) -> None:
        """Stop being a routing target (the fleet Router consults
        ``draining``); queued work keeps flushing normally via ``poll``.
        Already-admitted requests MUST still be answered on the normal
        path — a draining replica never latches degraded and never
        drops (ISSUE 8 satellite)."""
        self.draining = True

    def end_drain(self) -> None:
        self.draining = False

    def swap_params(self, params, now: Optional[float] = None) -> None:
        """Checkpoint hot-swap, drain-then-swap: everything already
        admitted is force-flushed and answered by the OLD params (policy
        answers — a swap must never produce dropped or degraded-mode
        decisions), the answers stay queued for the caller's next
        ``poll``, then the forward's params are replaced in place. The
        compiled bucket programs are shape-keyed, so the swap costs no
        recompile."""
        # drain FIRST, then re-park: ``poll`` rebinds ``_ready`` to a
        # fresh list, so extending the pre-drain binding would strand
        # the answers in an orphaned object
        pending = self.drain(now=now)
        self._ready.extend(pending)
        self._forward.params = params

    def reconfigure_buckets(self, buckets: Sequence[BucketSpec],
                            now: Optional[float] = None) -> None:
        """Bucket-ladder re-fit: drain (old ladder answers everything
        already admitted), then rebuild the bucketer + microbatch queues
        on the new ladder. New buckets compile on their first flush;
        stats/degraded state carry over untouched."""
        pending = self.drain(now=now)  # see swap_params: drain rebinds
        self._ready.extend(pending)
        eng = self.engine
        self.bucketer = ObsBucketer(
            buckets, reuse_arenas=True,
            max_pool_per_bucket=max(int(eng.max_queue), 1))
        self.engine = MicrobatchEngine(len(self.bucketer.buckets),
                                       max_batch=eng.max_batch,
                                       deadline_s=eng.deadline_s,
                                       max_queue=eng.max_queue)

    def close(self, now: Optional[float] = None) -> List[ServeResponse]:
        """Drain-aware, idempotent shutdown: the first call answers every
        already-admitted request (forced flush — policy answers, plus
        anything already resolved and unfetched) and returns those
        responses; later calls return ``[]`` and change nothing. New
        submits raise after close. Safe under the fleet's concurrent
        lifecycle (autoscaler retire racing a router close: whichever
        runs first does the drain, the other is a no-op)."""
        if self.closed:
            return []
        self.draining = True
        responses = self.drain(now=now)
        self.closed = True
        return responses

    # --------------------------------------------------------------- internal
    def _run_batch(self, bucket_idx: int, reqs: List[PendingRequest],
                   now: float, reread_clock: bool = True,
                   force: bool = False) -> None:
        try:
            self._run_batch_inner(bucket_idx, reqs, now, reread_clock,
                                  force)
        finally:
            # every path below is done with the bucketed obs (policy
            # answers read only logits; fallback answers resolve
            # synchronously inside), so the arenas recycle here
            for r in reqs:
                self.bucketer.release(bucket_idx, r.obs)

    def _run_batch_inner(self, bucket_idx: int, reqs: List[PendingRequest],
                         now: float, reread_clock: bool = True,
                         force: bool = False) -> None:
        # flush-cause attribution: a full batch always means fill (the
        # engine pops full batches before deadline/force partials)
        cause = ("fill" if len(reqs) >= self.engine.max_batch
                 else ("drain" if force else "deadline"))
        self.stats.record_flush(len(reqs), self.engine.max_batch,
                                bucket_idx=bucket_idx, cause=cause)
        try:
            stacked, n_real = self._forward.stack([r.obs for r in reqs])
        except Exception:
            # host-side batch assembly failed: malformed request DATA
            # (wrong dtype/feature width slipping past submit validation),
            # not a device failure — answer this batch from the heuristic
            # but do NOT latch degraded, the backend is healthy
            done = self.clock() if reread_clock else now
            for r in reqs:
                self._resolve_fallback(r.request_id, r.obs, done,
                                       reason="invalid")
            return
        try:
            logits, _values = self._forward.run(stacked, n_real)
            self.stats.n_compiles = self._forward.n_compiles
        except Exception:
            # device backend died mid-flight (the wedged-tunnel scenario):
            # answer this batch from the heuristic and stop offering the
            # device path to later requests. Real-time mode re-reads the
            # clock so the (possibly seconds-long) failed forward is
            # charged to these requests' latency, same as the policy path.
            if not self.degraded:
                self.stats.record_degraded_transition()
                telemetry.record_event("serve_degraded",
                                       bucket_idx=bucket_idx,
                                       batch_fill=len(reqs))
            self.degraded = True
            done = self.clock() if reread_clock else now
            for r in reqs:
                self._resolve_fallback(r.request_id, r.obs, done,
                                       reason="degraded")
            return
        # real-time mode charges the forward itself to latency; explicit
        # ``now`` (tests, virtual clocks) stays deterministic
        done = self.clock() if reread_clock else now
        for r, lg in zip(reqs, logits):
            # logits are already log(0)-masked by the model; argmax can
            # never pick an invalid action
            action = int(np.argmax(lg))
            self._emit(ServeResponse(
                request_id=r.request_id, action=action, source="policy",
                reason="batched", bucket_idx=bucket_idx,
                latency_s=done - self._submit_time.pop(r.request_id),
                batch_fill=len(reqs)))

    def _resolve_fallback(self, rid: int, obs, done: float,
                          reason: str) -> None:
        """``done`` is the completion timestamp (the fallback answers
        synchronously, so completion = when the caller reached us, not
        the request's arrival instant)."""
        action = int(self.fallback.compute_action(obs))
        self._emit(ServeResponse(
            request_id=rid, action=action, source="fallback", reason=reason,
            bucket_idx=None,
            latency_s=done - self._submit_time.pop(rid)))

    def _emit(self, resp: ServeResponse) -> None:
        self.stats.record_response(resp)
        self._ready.append(resp)


def build_model_from_config(config_path: str, config_name: str,
                            overrides: Sequence[str] = ()) -> Tuple:
    """(model, n_actions, graph_feature_dim) from the training config tree
    — same model merge as train_from_config.build_epoch_loop_kwargs (model
    group + algo-level model overrides), so a checkpoint restores onto the
    exact architecture it was trained with (the shipped PPO checkpoints
    override fcnet_hiddens at the algo level; a default-architecture
    ``GNNPolicy`` cannot load them). ``graph_feature_dim`` is the obs
    encoder's graph-vector width under this config (envs/obs.py: base
    features + action mask + candidate prices when
    ``obs_include_candidate_prices``) — what a template obs for param init
    must use."""
    import copy

    from ddls_tpu.config import load_config
    from ddls_tpu.envs.obs import graph_feature_width
    from ddls_tpu.train.loops import build_policy_from_model_config
    from ddls_tpu.utils.common import recursive_update

    cfg = load_config(config_path, config_name, list(overrides or []))
    model_cfg = copy.deepcopy(cfg.get("model") or {})
    algo_model = (cfg.get("algo") or {}).get("model")
    if algo_model:
        model_cfg = recursive_update(model_cfg, copy.deepcopy(algo_model))
    env_cfg = cfg["env_config"]
    n_actions = int(env_cfg["max_partitions_per_op"]) + 1
    graph_feature_dim = graph_feature_width(
        n_actions, bool(env_cfg.get("obs_include_candidate_prices")))
    return (build_policy_from_model_config(n_actions, model_cfg),
            n_actions, graph_feature_dim)


def checkpoint_graph_feature_dim(params) -> Optional[int]:
    """The graph-vector input width a restored checkpoint's params were
    trained with — ``graph_module/Dense_0/kernel``'s input dimension (the
    attribute names are frozen by the shipped checkpoints, CLAUDE.md).
    Lets a caller reject a checkpoint/config pairing at startup (e.g.
    the plain-obs 34-wide ``ppo_device_trained`` under a price-features
    51-wide config) instead of crashing inside the first forward — which
    the server would misread as a dead device backend and latch degraded
    mode. Returns None for an unrecognised param-tree shape."""
    try:
        kernel = params["params"]["graph_module"]["Dense_0"]["kernel"]
        return int(kernel.shape[0])
    except (KeyError, TypeError, IndexError, AttributeError):
        return None


def load_checkpoint_params(checkpoint_path: str):
    """Restore a shipped checkpoint's policy variables without building a
    training loop: raw (target-free) restore of the saved TrainState,
    returning its ``params`` subtree (the flax variables dict
    ``{"params": ...}`` that ``model.apply`` takes)."""
    from ddls_tpu.train.checkpointer import restore_train_state

    raw = restore_train_state(checkpoint_path)
    if not isinstance(raw, dict) or "params" not in raw:
        raise ValueError(
            f"checkpoint at {checkpoint_path} has no 'params' subtree "
            f"(keys: {list(raw) if isinstance(raw, dict) else type(raw)})")
    return raw["params"]
