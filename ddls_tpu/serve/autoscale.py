"""Telemetry-driven autoscaling for the serving fleet (ISSUE 8).

The control loop closes over the SAME counters the bench reports: the
Router's ``autoscale_snapshot()`` reads each replica's private
``ServeStats`` registry (rolling windowed p99, batch-occupancy window)
plus the live microbatch queue depths, and :class:`Autoscaler.decide`
maps that snapshot to a target replica count. Nothing else feeds the
decision — if the bench JSON says the fleet was slow, the autoscaler saw
the same numbers.

Design rules:

* **Deterministic.** ``decide`` is a pure function of (config, the
  decision counter state, the snapshot) — no wall clock, no randomness.
  A recorded snapshot sequence replays to the identical decision
  sequence (pinned in tests/test_fleet.py), which is what makes a
  production scaling incident reconstructable from a telemetry dump.
* **Hysteresis.** Scale-up triggers on breach (p99 over target OR mean
  queue depth over the high watermark); scale-down needs ALL of: queue
  below the low watermark, p99 under half the target, occupancy under
  the low watermark — and every change arms a cooldown of ``cooldown``
  decide() calls so the fleet never flaps on one noisy window.
* **The autoscaler only picks targets.** Applying them —
  ``Router.scale_to`` — drains retiring replicas (no dropped answers)
  and builds fresh ones through the replica factory; the controller
  records every applied decision in the router's private registry
  (``fleet.autoscale.{up,down}`` + the ``fleet.replicas`` gauge), so
  scaling history rides the same snapshot surface.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, NamedTuple, Optional


@dataclass
class AutoscaleConfig:
    """Watermarks are in the bench's own units: ``target_p99_ms`` wall
    milliseconds (the SLO-adjacent latency budget), queue depths in
    requests per replica, occupancy as batch-fill fraction."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_p99_ms: float = 50.0
    queue_high: float = 8.0    # mean queued/replica that forces growth
    queue_low: float = 1.0     # mean queued/replica idle enough to shrink
    occupancy_low: float = 0.5  # batches this empty mean spare capacity
    cooldown: int = 3          # decide() calls held after any change

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")
        if self.queue_low > self.queue_high:
            raise ValueError("queue_low must not exceed queue_high")


class AutoscaleDecision(NamedTuple):
    target: int
    reason: str


class Autoscaler:
    """Snapshot -> target replica count, with cooldown hysteresis.

    ``decide`` mutates only the internal cooldown counter; feed it the
    same snapshot sequence from the same initial state and the decision
    sequence is identical.
    """

    def __init__(self, config: Optional[AutoscaleConfig] = None):
        self.config = config or AutoscaleConfig()
        self._cooldown = 0

    def decide(self, snapshot: Dict[str, Any]) -> AutoscaleDecision:
        cfg = self.config
        n = int(snapshot["replicas"])
        clamped = min(max(n, cfg.min_replicas), cfg.max_replicas)
        if clamped != n:
            # out-of-band fleet size (manual scale, config change):
            # snap back inside the configured range first
            return AutoscaleDecision(clamped, "clamp")
        if self._cooldown > 0:
            self._cooldown -= 1
            return AutoscaleDecision(n, "cooldown")
        p99 = snapshot.get("p99_latency_ms")
        occ = snapshot.get("batch_occupancy")
        queue_mean = snapshot["queued_total"] / max(n, 1)
        if n < cfg.max_replicas and (
                (p99 is not None and p99 > cfg.target_p99_ms)
                or queue_mean > cfg.queue_high):
            self._cooldown = cfg.cooldown
            why = ("p99" if (p99 is not None and p99 > cfg.target_p99_ms)
                   else "queue")
            return AutoscaleDecision(n + 1, f"up:{why}")
        if (n > cfg.min_replicas
                and queue_mean <= cfg.queue_low
                and (p99 is None or p99 < 0.5 * cfg.target_p99_ms)
                and (occ is None or occ < cfg.occupancy_low)):
            self._cooldown = cfg.cooldown
            return AutoscaleDecision(n - 1, "down:idle")
        return AutoscaleDecision(n, "hold")


class AutoscaleController:
    """Wires an :class:`Autoscaler` to a fleet ``Router``: each
    ``step()`` snapshots the fleet, decides, applies the change through
    ``Router.scale_to`` (drain-then-retire on the way down), and records
    the decision. ``decisions`` keeps the full (snapshot, decision,
    resolved) history — the bench embeds it so a scaling trajectory is
    part of the measurement artifact."""

    def __init__(self, router, autoscaler: Optional[Autoscaler] = None):
        self.router = router
        self.autoscaler = autoscaler or Autoscaler()
        self.decisions: List[Dict[str, Any]] = []

    def step(self, now: Optional[float] = None) -> AutoscaleDecision:
        snapshot = self.router.autoscale_snapshot()
        decision = self.autoscaler.decide(snapshot)
        resolved = snapshot["replicas"]
        if decision.target != snapshot["replicas"]:
            resolved = self.router.scale_to(decision.target, now=now)
        self.decisions.append({"snapshot": snapshot,
                               "target": decision.target,
                               "reason": decision.reason,
                               "resolved": resolved})
        return decision
