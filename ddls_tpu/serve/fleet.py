"""Serving fleet: N PolicyServer replicas behind an admission/routing
front end (ISSUE 8; ROADMAP open item 2).

The MSRL dataflow-fragment decomposition (PAPERS.md: arXiv 2210.00882)
applied to serving: capacity scales by adding independently compiled
replica *fragments* — each :class:`PolicyServer` keeps its own private
``ServeStats`` registry and its own compiled bucket ladder — behind a
thin :class:`Router` that owns only admission and placement. Three
design rules:

* **Shed before degrade.** The single-server stack answers overload from
  the ``FixedDegreePacking`` heuristic (the ``saturated`` fallback).
  With shedding enabled the Router refuses the request EXPLICITLY
  (``source="shed"``, no action) *before* the replica's saturation
  fallback can fire — overload becomes visible back-pressure the client
  can act on, instead of silently degraded answers. Data-error
  (``overflow``/``invalid``) and dead-backend (``degraded``) fallbacks
  are untouched: shedding is a load decision, availability on failure is
  the replica's.
* **Routing never changes an answer.** Every replica runs the same
  fixed-shape compiled programs over the same params, and at a fixed
  program a request's output rows depend only on its own data (the PR-1
  pin), so fleet answers are bit-equal to a single server whatever the
  routing policy or batch composition (pinned in tests/test_fleet.py).
* **Live reconfiguration is drain-then-swap.** Checkpoint hot-swap and
  bucket-ladder re-fit drain each replica (old params/ladder answer
  everything already admitted — policy answers, no drops, no mid-swap
  degraded latch) before installing the new state; the Router keeps at
  least one serviceable replica at all times.

Everything is single-threaded and clock-parameterised like the rest of
the serve stack (``submit``/``poll`` take an optional ``now``), so tests
and the bench drive time deterministically; quota and shed decisions are
pure functions of the submitted timestamps — a seeded trace replays to
identical decisions.
"""
from __future__ import annotations

import bisect
import hashlib
import time
from collections import deque
from dataclasses import dataclass
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple)

import numpy as np

from ddls_tpu import telemetry
from ddls_tpu.serve.bucketing import BucketSpec
from ddls_tpu.serve.server import PolicyServer, ServeResponse

# virtual nodes per replica on the consistent-hash ring: enough that
# adding/retiring one replica moves ~1/N of tenant keys, small enough
# that ring rebuilds are free at fleet sizes
HASH_RING_VNODES = 32

# observed request-size window for bucket-ladder re-fit: bounded so a
# long-lived router holds a recent-distribution sample, not every
# request ever routed
SIZE_WINDOW = 4096


@dataclass
class FleetResponse:
    """One routed decision (or an explicit shed). ``action is None``
    exactly when ``source == "shed"`` — a shed is a refusal, not a
    heuristic answer (shed-before-degrade: the client sees back-pressure
    instead of a silently degraded decision)."""
    request_id: int
    action: Optional[int]
    source: str            # "policy" | "fallback" | "shed"
    reason: str            # ServeResponse reasons | "quota" | "overload"
    replica: Optional[int]
    bucket_idx: Optional[int]
    latency_s: float
    tenant: Optional[str] = None
    batch_fill: Optional[int] = None


class TokenBucket:
    """Per-tenant admission quota: ``rate`` tokens/s refill up to
    ``burst``; one token per admitted request. Deterministic in the
    submitted ``now`` timestamps (out-of-order timestamps clamp to a
    zero refill rather than minting tokens from the past)."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.last = float(now)

    def admit(self, now: float) -> bool:
        dt = max(now - self.last, 0.0)
        self.tokens = min(self.burst, self.tokens + dt * self.rate)
        self.last = max(self.last, now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


def _stable_hash(key: str) -> int:
    """Process-stable 32-bit hash (python's ``hash`` is salted per
    process — routing must be reproducible across runs)."""
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:4], "big")


def fit_buckets(sizes: Sequence[Tuple[int, int]],
                n_buckets: int = 3) -> List[BucketSpec]:
    """A bucket ladder fitted to an observed (n_ops, n_deps) population:
    rung ``k`` of ``n`` sits at the ceil of the ``(k+1)/n`` quantile of
    each dimension independently, so the top rung covers the observed
    max and the lower rungs track where the mass actually is (vs the
    blind halving ladder of ``default_buckets``). Deterministic in the
    sample; duplicate rungs collapse."""
    if not sizes:
        raise ValueError("need at least one observed size to fit buckets")
    ns = np.sort(np.asarray([s[0] for s in sizes], dtype=np.int64))
    ms = np.sort(np.asarray([s[1] for s in sizes], dtype=np.int64))
    specs = []
    for k in range(max(1, int(n_buckets))):
        q = (k + 1) / max(1, int(n_buckets))
        i = min(len(ns) - 1, int(np.ceil(q * len(ns))) - 1)
        specs.append((max(1, int(ns[i])), max(1, int(ms[i]))))
    # monotone + unique: a lower rung may not exceed a higher one in
    # either dimension (selection requires BOTH dims to fit)
    out: List[BucketSpec] = []
    for n, m in sorted(set(specs)):
        while out and (out[-1][0] >= n or out[-1][1] >= m):
            n, m = max(n, out[-1][0]), max(m, out[-1][1])
            out.pop()
        out.append((n, m))
    return out


@dataclass
class _Replica:
    rid: int
    server: PolicyServer

    @property
    def routable(self) -> bool:
        return not (self.server.draining or self.server.closed)


class ReplicaSet:
    """The fleet's replica fragments: owns creation (``replica_factory``
    — each call builds a fresh PolicyServer with its OWN ServeStats and
    compiled ladder), retirement (drain-then-close), rolling hot-swap
    and ladder re-fit. Replica ids are monotonic — a retired id is
    never reused, so per-replica stats keys stay unambiguous across
    scale events."""

    def __init__(self, replica_factory: Callable[[], PolicyServer],
                 n_replicas: int = 1):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        self.replica_factory = replica_factory
        self._next_rid = 0
        self.replicas: List[_Replica] = []
        for _ in range(int(n_replicas)):
            self.add_replica()

    def add_replica(self) -> _Replica:
        rep = _Replica(rid=self._next_rid, server=self.replica_factory())
        self._next_rid += 1
        self.replicas.append(rep)
        return rep

    def retire_replica(self, now: Optional[float] = None
                       ) -> Tuple[_Replica, List[ServeResponse]]:
        """Drain and close the newest replica (LIFO keeps the hash ring
        maximally stable for the survivors); every admitted request is
        answered before the replica leaves. Returns the retired replica
        so the caller can keep its final stats snapshot — the private
        registry leaves the fleet with it."""
        if len(self.replicas) <= 1:
            raise RuntimeError("cannot retire the last replica")
        rep = self.replicas.pop()
        return rep, rep.server.close(now=now)

    def routable(self) -> List[_Replica]:
        return [r for r in self.replicas if r.routable]

    def swap_all(self, params, now: Optional[float] = None) -> None:
        """Rolling drain-then-swap across the fleet: one replica at a
        time leaves the routing set, answers everything it already
        admitted with the OLD params, gets the new params, and rejoins —
        the fleet never serves a mid-swap degraded answer and never has
        zero routable replicas (single-threaded, so "rolling" here
        bounds *drain batching*: each replica's queue flushes as one
        forced drain under old params)."""
        for rep in list(self.replicas):
            rep.server.begin_drain()
            rep.server.swap_params(params, now=now)
            rep.server.end_drain()

    def refit_all(self, buckets: Sequence[BucketSpec],
                  now: Optional[float] = None) -> None:
        for rep in list(self.replicas):
            rep.server.begin_drain()
            rep.server.reconfigure_buckets(buckets, now=now)
            rep.server.end_drain()


class Router:
    """Admission + placement front end over a :class:`ReplicaSet`.

    Parameters
    ----------
    replica_factory : builds one PolicyServer (own stats, own compiled
        ladder); also used by the autoscaler's scale-up path.
    n_replicas : initial fleet size.
    routing : ``"affinity"`` (default — consistent-hash by tenant,
        least-loaded for untenanted requests), ``"least_loaded"``,
        ``"round_robin"``, or ``"hash"`` (consistent-hash by tenant,
        falling back to the request id — fully deterministic spread).
    shed_enabled : refuse (``source="shed"``) instead of letting a
        saturated replica answer from the heuristic; ``max_fleet_queue``
        optionally sheds on TOTAL queued depth before any single replica
        saturates.
    quota_rps / quota_burst : per-tenant token-bucket admission
        (requests without a tenant are exempt); quota shedding implies
        nothing about untenanted traffic.
    clock : shared time source (tests inject a fake; replicas built by
        the default factories share it).
    warm_replica : optional hook run on every replica the Router builds
        (initial fleet AND autoscale scale-ups) BEFORE it joins the
        routing set — the bench passes its per-bucket compile warmer so
        a scale-up never serves its first batches cold (first-flush XLA
        compile would otherwise land inside the measured serving
        window; true pre-built warm pools are ROADMAP next-tier).
    """

    def __init__(self, replica_factory: Callable[[], PolicyServer],
                 n_replicas: int = 1, routing: str = "affinity",
                 shed_enabled: bool = False,
                 max_fleet_queue: Optional[int] = None,
                 quota_rps: Optional[float] = None,
                 quota_burst: Optional[float] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 warm_replica: Optional[
                     Callable[[PolicyServer], None]] = None):
        if routing not in ("affinity", "least_loaded", "round_robin",
                           "hash"):
            raise ValueError(f"unknown routing policy {routing!r}")
        self.replica_set = ReplicaSet(replica_factory, n_replicas)
        self.routing = routing
        self.shed_enabled = bool(shed_enabled)
        self.max_fleet_queue = (int(max_fleet_queue)
                                if max_fleet_queue is not None else None)
        self.quota_rps = quota_rps
        self.quota_burst = (quota_burst if quota_burst is not None
                            else (quota_rps if quota_rps else None))
        self.clock = clock
        self.warm_replica = warm_replica
        if warm_replica is not None:
            for rep in self.replica_set.replicas:
                warm_replica(rep.server)
        # router accounting on a PRIVATE always-on registry, same
        # contract as ServeStats: fleets never share counters and the
        # global telemetry switch does not gate them (guard-tested in
        # tests/test_telemetry.py's fleet burst)
        self.registry = telemetry.Registry(enabled=True)
        self._next_id = 0
        self._rr = 0  # round-robin cursor
        self.closed = False
        self._ready: List[FleetResponse] = []
        # (replica_rid, server_request_id) -> (router_rid, tenant)
        self._pending: Dict[Tuple[int, int], Tuple[int, Optional[str]]] = {}
        self._quotas: Dict[str, TokenBucket] = {}
        # final registry snapshots of autoscale-retired replicas: the
        # bench aggregate must keep counting traffic a replica served
        # before a scale-down event (rids never reuse, keys are stable)
        self._retired_snapshots: Dict[str, Dict[str, Any]] = {}
        self._sizes: deque = deque(maxlen=SIZE_WINDOW)
        self._ring: List[Tuple[int, int]] = []
        self._rebuild_ring()
        self.registry.gauge("fleet.replicas").set(
            len(self.replica_set.replicas))

    # ------------------------------------------------------------- routing
    def _rebuild_ring(self) -> None:
        ring = []
        for rep in self.replica_set.replicas:
            for v in range(HASH_RING_VNODES):
                ring.append((_stable_hash(f"replica-{rep.rid}#{v}"),
                             rep.rid))
        self._ring = sorted(ring)
        # rid->replica cache for the per-request ring lookup; the
        # replica SET only changes where the ring is rebuilt (routable
        # flags stay dynamic — checked per lookup)
        self._by_rid = {r.rid: r for r in self.replica_set.replicas}

    def _ring_lookup(self, key: str) -> Optional[_Replica]:
        if not self._ring:
            return None
        by_rid = self._by_rid
        h = _stable_hash(key)
        i = bisect.bisect_left(self._ring, (h, -1))
        for k in range(len(self._ring)):
            _, rid = self._ring[(i + k) % len(self._ring)]
            rep = by_rid.get(rid)
            if rep is not None and rep.routable:
                return rep
        return None

    def _least_loaded(self) -> Optional[_Replica]:
        live = self.replica_set.routable()
        if not live:
            return None
        # deterministic tie-break: lowest replica id wins
        return min(live, key=lambda r: (r.server.queued(), r.rid))

    def _route(self, tenant: Optional[str], rid: int) -> Optional[_Replica]:
        if self.routing == "round_robin":
            live = self.replica_set.routable()
            if not live:
                return None
            rep = live[self._rr % len(live)]
            self._rr += 1
            return rep
        if self.routing == "least_loaded":
            return self._least_loaded()
        if self.routing == "hash":
            return self._ring_lookup(tenant if tenant is not None
                                     else f"req-{rid}")
        # affinity: tenant requests stick to their hash-ring replica,
        # untenanted traffic fills the least-loaded one
        if tenant is not None:
            return self._ring_lookup(tenant)
        return self._least_loaded()

    # -------------------------------------------------------------- intake
    def submit(self, obs: Dict[str, Any], now: Optional[float] = None,
               tenant: Optional[str] = None) -> int:
        """Admit/route one request; returns the fleet request id.
        Quota and overload sheds resolve immediately (the refusal is
        part of the response stream, fetched via ``poll``); admitted
        requests ride the chosen replica's microbatcher. Obs validation
        stays with the replica — a data error raises to THIS caller
        before any admission state changes."""
        if self.closed:
            # same contract as PolicyServer.submit — a lifecycle bug in
            # the caller must error, not pollute shed stats
            raise RuntimeError("Router is closed")
        now = self.clock() if now is None else now
        fid = self._next_id
        bucket = None
        if tenant is not None and self.quota_rps:
            bucket = self._quotas.get(tenant)
            if bucket is None:
                bucket = self._quotas[tenant] = TokenBucket(
                    self.quota_rps, self.quota_burst, now)
            if not bucket.admit(now):
                self._next_id += 1
                self._shed(fid, tenant, reason="quota")
                return fid
        def refund() -> None:
            # a request the fleet refused (overload shed) or rejected
            # (data error) must not burn the tenant's admission budget —
            # only SERVED requests spend quota
            if bucket is not None:
                bucket.tokens = min(bucket.burst, bucket.tokens + 1.0)

        if self.shed_enabled and self.max_fleet_queue is not None \
                and self.queued() >= self.max_fleet_queue:
            self._next_id += 1
            refund()
            self._shed(fid, tenant, reason="overload")
            return fid
        rep = self._route(tenant, fid)
        if rep is None:
            self._next_id += 1
            refund()
            self._shed(fid, tenant, reason="overload")
            return fid
        if self.shed_enabled and rep.server.engine.would_saturate():
            # shed-before-degrade (THE ordering this module exists for):
            # the replica would answer this from the heuristic
            # ("saturated" fallback); the fleet refuses explicitly first
            self._next_id += 1
            refund()
            self._shed(fid, tenant, reason="overload")
            return fid
        try:
            sid = rep.server.submit(obs, now=now)
        except Exception:
            # data error raised to ITS caller before any replica state
            # changed
            refund()
            raise
        self._next_id += 1
        self._pending[(rep.rid, sid)] = (fid, tenant)
        n = int(np.asarray(obs["node_split"]).reshape(-1)[0])
        m = int(np.asarray(obs["edge_split"]).reshape(-1)[0])
        self._sizes.append((n, m))
        self.registry.counter("fleet.requests").inc()
        self.registry.counter(f"fleet.routed.r{rep.rid}").inc()
        return fid

    def _shed(self, fid: int, tenant: Optional[str], reason: str) -> None:
        self.registry.counter("fleet.requests").inc()
        self.registry.counter("fleet.shed").inc()
        self.registry.counter(f"fleet.shed_reason.{reason}").inc()
        self._ready.append(FleetResponse(
            request_id=fid, action=None, source="shed", reason=reason,
            replica=None, bucket_idx=None, latency_s=0.0, tenant=tenant))

    # ------------------------------------------------------------- serving
    def _wrap(self, rid: int, resp: ServeResponse) -> FleetResponse:
        fid, tenant = self._pending.pop((rid, resp.request_id),
                                        (resp.request_id, None))
        return FleetResponse(
            request_id=fid, action=resp.action, source=resp.source,
            reason=resp.reason, replica=rid, bucket_idx=resp.bucket_idx,
            latency_s=resp.latency_s, tenant=tenant,
            batch_fill=resp.batch_fill)

    def poll(self, now: Optional[float] = None,
             force: bool = False) -> List[FleetResponse]:
        now = self.clock() if now is None else now
        for rep in self.replica_set.replicas:
            for resp in rep.server.poll(now=now, force=force):
                self._ready.append(self._wrap(rep.rid, resp))
        out, self._ready = self._ready, []
        return out

    def drain(self, now: Optional[float] = None) -> List[FleetResponse]:
        return self.poll(now=now, force=True)

    def next_deadline(self) -> Optional[float]:
        deadlines = [r.server.next_deadline()
                     for r in self.replica_set.replicas]
        deadlines = [d for d in deadlines if d is not None]
        return min(deadlines) if deadlines else None

    def queued(self) -> int:
        return sum(r.server.queued() for r in self.replica_set.replicas)

    def close(self, now: Optional[float] = None) -> List[FleetResponse]:
        """Drain-and-close every replica (idempotent — closed replicas
        answer ``[]``); all still-admitted requests come back answered.
        Later ``submit`` calls raise, matching ``PolicyServer``."""
        self.closed = True
        for rep in self.replica_set.replicas:
            for resp in rep.server.close(now=now):
                self._ready.append(self._wrap(rep.rid, resp))
        out, self._ready = self._ready, []
        return out

    # -------------------------------------------------- live reconfiguration
    def hot_swap(self, params, now: Optional[float] = None) -> int:
        """Rolling checkpoint hot-swap (drain-then-swap, see
        ``ReplicaSet.swap_all``); drained answers surface on the next
        ``poll``. Returns the number of replicas swapped."""
        now = self.clock() if now is None else now
        self.replica_set.swap_all(params, now=now)
        # swap_params parks drained answers on each server's ready list;
        # pull them through the fleet wrapper so ids/tenants resolve
        for rep in self.replica_set.replicas:
            for resp in rep.server.poll(now=now):
                self._ready.append(self._wrap(rep.rid, resp))
        self.registry.counter("fleet.swaps").inc()
        return len(self.replica_set.replicas)

    def observed_sizes(self) -> List[Tuple[int, int]]:
        return list(self._sizes)

    def refit_buckets(self, n_buckets: int = 3,
                      now: Optional[float] = None) -> List[BucketSpec]:
        """Re-fit every replica's bucket ladder to the observed request
        size distribution (``fit_buckets`` over the trailing size
        window). Drain-then-swap per replica like ``hot_swap``."""
        specs = fit_buckets(self.observed_sizes(), n_buckets=n_buckets)
        now = self.clock() if now is None else now
        self.replica_set.refit_all(specs, now=now)
        for rep in self.replica_set.replicas:
            for resp in rep.server.poll(now=now):
                self._ready.append(self._wrap(rep.rid, resp))
        self.registry.counter("fleet.refits").inc()
        return specs

    # ------------------------------------------------------------- scaling
    def scale_to(self, target: int, now: Optional[float] = None) -> int:
        """Add or retire replicas toward ``target`` (>= 1). Scale-down
        drains each retiring replica; its last answers surface on the
        next ``poll``. Returns the resolved replica count."""
        target = max(1, int(target))
        while len(self.replica_set.replicas) < target:
            rep = self.replica_set.add_replica()
            if self.warm_replica is not None:
                # compile the new replica's ladder BEFORE it becomes a
                # routing target — otherwise its first flush pays XLA
                # compile inside the serving window
                self.warm_replica(rep.server)
            self.registry.counter("fleet.autoscale.up").inc()
        while len(self.replica_set.replicas) > target:
            rep, responses = self.replica_set.retire_replica(now=now)
            for resp in responses:
                self._ready.append(self._wrap(rep.rid, resp))
            # the drained replica leaves with its private registry; keep
            # its final snapshot so the fleet aggregate stays exact
            self._retired_snapshots[f"r{rep.rid}"] = \
                rep.server.stats.registry.snapshot()
            self.registry.counter("fleet.autoscale.down").inc()
        self._rebuild_ring()
        n = len(self.replica_set.replicas)
        self.registry.gauge("fleet.replicas").set(n)
        return n

    # ------------------------------------------------------------ readbacks
    def autoscale_snapshot(self) -> Dict[str, Any]:
        """The autoscaler's input, built from the SAME per-replica
        registries the bench reports (ISSUE 8: the counters close the
        loop): live queue depths, rolling windowed p99 over the fleet's
        latency samples, mean batch occupancy. JSON-round-trippable so
        decisions are reproducible from a stored snapshot."""
        reps = self.replica_set.replicas
        lat: List[float] = []
        occ: List[float] = []
        queued = []
        for rep in reps:
            stats = rep.server.stats
            lat.extend(stats.latencies_s or [])
            occ.extend(stats.occupancies or [])
            q = rep.server.queued()
            queued.append(q)
            stats.registry.gauge("serve.queue_depth").set(q)
        return {
            "replicas": len(reps),
            "queued_total": int(sum(queued)),
            "queued_max": int(max(queued)) if queued else 0,
            "p99_latency_ms": (float(np.percentile(
                np.asarray(lat, dtype=np.float64), 99)) * 1e3
                if lat else None),
            "batch_occupancy": (float(np.mean(
                np.asarray(occ, dtype=np.float64))) if occ else None),
        }

    def per_replica_summary(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        for rep in self.replica_set.replicas:
            s = rep.server.stats.summary()
            out[f"r{rep.rid}"] = {
                "n_requests": s["n_requests"],
                "queued": rep.server.queued(),
                "p99_latency_ms": s["p99_latency_ms"],
                "batch_occupancy": s["batch_occupancy"],
                "fallback_rate": s["fallback_rate"],
                "degraded": rep.server.degraded,
                "draining": rep.server.draining,
            }
        return out

    def summary(self) -> Dict[str, Any]:
        counters = dict(self.registry.counter_items())
        n_requests = counters.get("fleet.requests", 0)
        n_shed = counters.get("fleet.shed", 0)
        return {
            "n_requests": n_requests,
            "n_shed": n_shed,
            "shed_rate": (n_shed / n_requests) if n_requests else 0.0,
            "shed_reasons": {
                name[len("fleet.shed_reason."):]: v
                for name, v in counters.items()
                if name.startswith("fleet.shed_reason.")},
            "replicas": len(self.replica_set.replicas),
            "routing": self.routing,
            "per_replica": self.per_replica_summary(),
        }

    def registry_snapshots(self) -> Dict[str, Any]:
        """Per-registry snapshots keyed for the bench/report surface:
        ``fleet`` (router admission counters), one ``r<id>`` per replica
        (its private ServeStats registry — retired replicas contribute
        their final pre-retirement snapshot, so a scale-down never
        loses served traffic), and ``aggregate`` (the exact
        multi-registry merge — ``telemetry.aggregate_snapshots``)."""
        per = dict(self._retired_snapshots)
        per.update({f"r{rep.rid}": rep.server.stats.registry.snapshot()
                    for rep in self.replica_set.replicas})
        return {
            "fleet": self.registry.snapshot(),
            "aggregate": telemetry.aggregate_snapshots(list(per.values())),
            **per,
        }

    def reset_stats(self) -> None:
        """Fresh measurement window (bench warmup discipline): new
        ServeStats per replica, retired-replica snapshots dropped,
        fresh router registry counters."""
        for rep in self.replica_set.replicas:
            rep.server.stats = type(rep.server.stats)()
        self._retired_snapshots = {}
        self.registry = telemetry.Registry(enabled=True)
        self.registry.gauge("fleet.replicas").set(
            len(self.replica_set.replicas))


def build_fleet(model, params, n_replicas: int = 1,
                routing: str = "affinity",
                shed_enabled: bool = False,
                max_fleet_queue: Optional[int] = None,
                quota_rps: Optional[float] = None,
                quota_burst: Optional[float] = None,
                clock: Callable[[], float] = time.perf_counter,
                warm_replica: Optional[
                    Callable[[PolicyServer], None]] = None,
                **server_kwargs) -> Router:
    """A Router over ``n_replicas`` PolicyServers sharing (model, params)
    and the server config but nothing else — each replica compiles its
    own bucket ladder and keeps its own stats. ``server_kwargs`` pass
    through to :class:`PolicyServer` (buckets, max_batch, deadline_s,
    max_queue, graph_feature_dim, fallback, apply_fn...)."""

    def factory() -> PolicyServer:
        return PolicyServer(model, params, clock=clock, **server_kwargs)

    return Router(factory, n_replicas=n_replicas, routing=routing,
                  shed_enabled=shed_enabled,
                  max_fleet_queue=max_fleet_queue,
                  quota_rps=quota_rps, quota_burst=quota_burst,
                  clock=clock, warm_replica=warm_replica)
