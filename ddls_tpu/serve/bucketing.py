"""Observation bucketing for online serving.

The jitted forward compiles once per input shape, and the axon-tunnelled
TPU pays ~116 ms per dispatch (CLAUDE.md), so the server cannot afford one
compile per distinct graph size — nor one giant pad bound that drags ~20x
dead masked rows through every forward (docs/perf_round2.md). The middle
ground is a small fixed ladder of (max_nodes, max_edges) **buckets**: each
incoming observation is re-padded (``envs.obs.pad_obs_to`` — the masked-pad
policy, real rows untouched) into the smallest bucket that fits, so the
whole request population compiles exactly ``len(buckets)`` programs.

Bucket choice is deterministic in the request's true (n_ops, n_deps), so a
given request always runs the same program — reproducible decisions.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ddls_tpu.envs.obs import pad_obs_to

BucketSpec = Tuple[int, int]  # (max_nodes, max_edges)


def default_buckets(max_nodes: int, max_edges: Optional[int] = None,
                    n_buckets: int = 3) -> List[BucketSpec]:
    """A halving ladder ending at the dataset bound: e.g. 32 nodes ->
    [(8, e/4), (16, e/2), (32, e)]. ``max_edges`` defaults to the
    fully-connected bound (the reference's own pad policy; pass the
    dataset's true dep bound for tight buckets, as bench.py does)."""
    if max_nodes < 1:
        raise ValueError(f"max_nodes must be >= 1, got {max_nodes}")
    if max_edges is None:
        max_edges = (max_nodes * (max_nodes - 1)) // 2
    buckets: List[BucketSpec] = []
    n, e = int(max_nodes), int(max_edges)
    for _ in range(max(1, n_buckets)):
        buckets.append((n, max(e, 1)))
        if n <= 2:
            break
        n = (n + 1) // 2
        e = (e + 1) // 2
    return sorted(set(buckets))


class ObsBucketer:
    """Maps encoded observations onto a fixed bucket ladder.

    ``buckets`` is a sequence of (max_nodes, max_edges) pairs; selection is
    smallest-first by (nodes, edges) with both dimensions required to fit.
    Requests larger than every bucket raise ``BucketOverflowError`` — the
    server answers those from the heuristic fallback rather than compiling
    an unbounded program on demand.

    ``reuse_arenas``: recycle per-bucket destination arrays (the
    ``pad_obs_to(out=...)`` encode-into-destination API) instead of
    allocating a fresh padded obs per request — bit-identical output
    (pinned with the per-bucket equality tests in tests/test_serve.py).
    The caller then OWNS the lease discipline: each ``bucket_obs`` result
    aliases one arena until ``release(idx, obs)`` returns it to the pool,
    so release only after the request leaves the microbatch queue and its
    batch is resolved (PolicyServer does this at the end of each flush).
    """

    def __init__(self, buckets: Sequence[BucketSpec],
                 reuse_arenas: bool = False,
                 max_pool_per_bucket: int = 64):
        if not buckets:
            raise ValueError("need at least one bucket")
        self.buckets: List[BucketSpec] = sorted(
            (int(n), int(e)) for n, e in buckets)
        for n, e in self.buckets:
            if n < 1 or e < 1:
                raise ValueError(f"bucket ({n}, {e}) must be positive")
        self.reuse_arenas = bool(reuse_arenas)
        self.max_pool_per_bucket = int(max_pool_per_bucket)
        self._pools: List[List[Dict[str, np.ndarray]]] = [
            [] for _ in self.buckets]

    def bucket_index(self, n_nodes: int, n_edges: int) -> int:
        for i, (bn, be) in enumerate(self.buckets):
            if n_nodes <= bn and n_edges <= be:
                return i
        raise BucketOverflowError(
            f"graph with {n_nodes} ops / {n_edges} deps exceeds every "
            f"bucket {self.buckets}")

    def _new_arena(self, idx: int,
                   obs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Destination arrays for one request in bucket ``idx``: padded
        fields at the bucket bounds, passthrough fields (graph_features,
        action_mask, action_set, ...) shaped/typed from this obs."""
        bn, be = self.buckets[idx]
        arena: Dict[str, np.ndarray] = {
            "node_features": np.zeros((bn, np.asarray(
                obs["node_features"]).shape[1]), np.float32),
            "edge_features": np.zeros((be, np.asarray(
                obs["edge_features"]).shape[1]), np.float32),
            "edges_src": np.zeros(be, np.int32),
            "edges_dst": np.zeros(be, np.int32),
            "node_split": np.zeros(1, np.int32),
            "edge_split": np.zeros(1, np.int32),
        }
        for key, val in obs.items():
            if key not in arena:
                val = np.asarray(val)
                arena[key] = np.empty(val.shape, val.dtype)
        return arena

    def _arena_fits(self, arena: Dict[str, np.ndarray],
                    obs: Dict[str, np.ndarray]) -> bool:
        """Passthrough fields must match this obs exactly — BOTH ways:
        every obs extra must have a matching arena array, and the arena
        must carry no key this obs lacks (``pad_obs_to(out=)`` copies
        every ``out`` entry from the obs, so a stale extra key from a
        previous occupant would KeyError mid-request). A mismatched
        client simply gets a fresh arena rather than a crash or a
        silent cast; widths are config-constant in practice."""
        if set(arena) != set(obs):
            return False
        for key in ("node_features", "edge_features"):
            # feature WIDTH rides the client obs (the server pins it at
            # submit; standalone callers may vary) — row counts are the
            # bucket's own and always match within a pool
            if arena[key].shape[1] != np.asarray(obs[key]).shape[1]:
                return False
        for key, val in obs.items():
            if key in ("node_features", "edge_features", "edges_src",
                       "edges_dst", "node_split", "edge_split"):
                continue
            dst = arena[key]
            val = np.asarray(val)
            if dst.shape != val.shape or dst.dtype != val.dtype:
                return False
        return True

    def bucket_obs(self, obs: Dict[str, np.ndarray]
                   ) -> Tuple[int, Dict[str, np.ndarray]]:
        """Pick the smallest fitting bucket and re-pad the obs into it."""
        n = int(np.asarray(obs["node_split"]).reshape(-1)[0])
        m = int(np.asarray(obs["edge_split"]).reshape(-1)[0])
        idx = self.bucket_index(n, m)
        bn, be = self.buckets[idx]
        if not self.reuse_arenas:
            return idx, pad_obs_to(obs, bn, be)
        pool = self._pools[idx]
        arena = pool.pop() if pool else self._new_arena(idx, obs)
        if not self._arena_fits(arena, obs):
            arena = self._new_arena(idx, obs)
        return idx, pad_obs_to(obs, bn, be, out=arena)

    def release(self, idx: int, obs: Dict[str, np.ndarray]) -> None:
        """Return a ``bucket_obs`` result's arena to bucket ``idx``'s
        pool once nothing references its arrays any more. No-op unless
        ``reuse_arenas``; the pool is bounded so a queue burst can never
        pin unbounded memory."""
        if not self.reuse_arenas or obs is None:
            return
        pool = self._pools[idx]
        if len(pool) < self.max_pool_per_bucket:
            pool.append(obs)


class BucketOverflowError(ValueError):
    """Raised when a request graph fits no configured bucket."""
