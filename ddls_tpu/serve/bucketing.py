"""Observation bucketing for online serving.

The jitted forward compiles once per input shape, and the axon-tunnelled
TPU pays ~116 ms per dispatch (CLAUDE.md), so the server cannot afford one
compile per distinct graph size — nor one giant pad bound that drags ~20x
dead masked rows through every forward (docs/perf_round2.md). The middle
ground is a small fixed ladder of (max_nodes, max_edges) **buckets**: each
incoming observation is re-padded (``envs.obs.pad_obs_to`` — the masked-pad
policy, real rows untouched) into the smallest bucket that fits, so the
whole request population compiles exactly ``len(buckets)`` programs.

Bucket choice is deterministic in the request's true (n_ops, n_deps), so a
given request always runs the same program — reproducible decisions.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ddls_tpu.envs.obs import pad_obs_to

BucketSpec = Tuple[int, int]  # (max_nodes, max_edges)


def default_buckets(max_nodes: int, max_edges: Optional[int] = None,
                    n_buckets: int = 3) -> List[BucketSpec]:
    """A halving ladder ending at the dataset bound: e.g. 32 nodes ->
    [(8, e/4), (16, e/2), (32, e)]. ``max_edges`` defaults to the
    fully-connected bound (the reference's own pad policy; pass the
    dataset's true dep bound for tight buckets, as bench.py does)."""
    if max_nodes < 1:
        raise ValueError(f"max_nodes must be >= 1, got {max_nodes}")
    if max_edges is None:
        max_edges = (max_nodes * (max_nodes - 1)) // 2
    buckets: List[BucketSpec] = []
    n, e = int(max_nodes), int(max_edges)
    for _ in range(max(1, n_buckets)):
        buckets.append((n, max(e, 1)))
        if n <= 2:
            break
        n = (n + 1) // 2
        e = (e + 1) // 2
    return sorted(set(buckets))


class ObsBucketer:
    """Maps encoded observations onto a fixed bucket ladder.

    ``buckets`` is a sequence of (max_nodes, max_edges) pairs; selection is
    smallest-first by (nodes, edges) with both dimensions required to fit.
    Requests larger than every bucket raise ``BucketOverflowError`` — the
    server answers those from the heuristic fallback rather than compiling
    an unbounded program on demand.
    """

    def __init__(self, buckets: Sequence[BucketSpec]):
        if not buckets:
            raise ValueError("need at least one bucket")
        self.buckets: List[BucketSpec] = sorted(
            (int(n), int(e)) for n, e in buckets)
        for n, e in self.buckets:
            if n < 1 or e < 1:
                raise ValueError(f"bucket ({n}, {e}) must be positive")

    def bucket_index(self, n_nodes: int, n_edges: int) -> int:
        for i, (bn, be) in enumerate(self.buckets):
            if n_nodes <= bn and n_edges <= be:
                return i
        raise BucketOverflowError(
            f"graph with {n_nodes} ops / {n_edges} deps exceeds every "
            f"bucket {self.buckets}")

    def bucket_obs(self, obs: Dict[str, np.ndarray]
                   ) -> Tuple[int, Dict[str, np.ndarray]]:
        """Pick the smallest fitting bucket and re-pad the obs into it."""
        n = int(np.asarray(obs["node_split"]).reshape(-1)[0])
        m = int(np.asarray(obs["edge_split"]).reshape(-1)[0])
        idx = self.bucket_index(n, m)
        bn, be = self.buckets[idx]
        return idx, pad_obs_to(obs, bn, be)


class BucketOverflowError(ValueError):
    """Raised when a request graph fits no configured bucket."""
