"""Online policy serving: bucketed padding + deadline microbatching +
one fixed-shape jitted forward per bucket + heuristic degraded mode.

See docs/serving.md for the design and its invariants; the entry points:

* :class:`PolicyServer` — in-process request/response server;
* :class:`ObsBucketer` / :func:`default_buckets` — (max_nodes, max_edges)
  bucket ladder;
* :class:`MicrobatchEngine` — flush-on-fill-or-deadline queueing;
* :func:`load_checkpoint_params` — checkpoint -> policy variables without
  a training loop;
* ``scripts/serve_policy.py`` — stdin/JSON front end;
* ``bench.py --mode serve`` — offered-load throughput/latency measurement.
"""
from ddls_tpu.serve.bucketing import (BucketOverflowError, BucketSpec,
                                      ObsBucketer, default_buckets)
from ddls_tpu.serve.microbatch import MicrobatchEngine, PendingRequest
from ddls_tpu.serve.server import (DEFAULT_FALLBACK_DEGREE, BucketForward,
                                   PolicyServer, ServeResponse, ServeStats,
                                   build_model_from_config,
                                   checkpoint_graph_feature_dim,
                                   load_checkpoint_params)

__all__ = [
    "BucketForward",
    "BucketOverflowError",
    "BucketSpec",
    "DEFAULT_FALLBACK_DEGREE",
    "MicrobatchEngine",
    "ObsBucketer",
    "PendingRequest",
    "PolicyServer",
    "ServeResponse",
    "ServeStats",
    "build_model_from_config",
    "checkpoint_graph_feature_dim",
    "default_buckets",
    "load_checkpoint_params",
]
