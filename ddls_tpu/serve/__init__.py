"""Online policy serving: bucketed padding + deadline microbatching +
one fixed-shape jitted forward per bucket + heuristic degraded mode,
scaled out as a multi-replica fleet with routing, quotas, trace-driven
load, and telemetry-driven autoscaling.

See docs/serving.md for the design and its invariants; the entry points:

* :class:`PolicyServer` — in-process request/response server;
* :class:`Router` / :class:`ReplicaSet` / :func:`build_fleet` —
  multi-replica fleet: admission control, least-loaded + consistent-hash
  tenant routing, token-bucket quotas, shed-before-degrade, checkpoint
  hot-swap and bucket-ladder re-fit (serve/fleet.py);
* :class:`Autoscaler` / :class:`AutoscaleController` — replica-count
  control loop over the per-replica telemetry registries
  (serve/autoscale.py);
* ``ddls_tpu.serve.loadgen`` — seeded, fingerprinted open-loop traces
  (diurnal + bursts + heavy-tailed sizes) and the SLO/goodput rollup;
* :class:`ObsBucketer` / :func:`default_buckets` / :func:`fit_buckets`
  — (max_nodes, max_edges) bucket ladders;
* :class:`MicrobatchEngine` — flush-on-fill-or-deadline queueing;
* :func:`load_checkpoint_params` — checkpoint -> policy variables without
  a training loop;
* ``scripts/serve_policy.py`` — stdin/JSON front end (``--replicas N``
  routes through the fleet Router);
* ``bench.py --mode serve`` — offered-load throughput/latency
  measurement (``--load trace --replicas N`` drives the fleet under the
  open-loop trace with coordinated-omission-correct p99/p999 and
  SLO/goodput accounting).
"""
from ddls_tpu.serve.autoscale import (AutoscaleConfig, AutoscaleController,
                                      AutoscaleDecision, Autoscaler)
from ddls_tpu.serve.bucketing import (BucketOverflowError, BucketSpec,
                                      ObsBucketer, default_buckets)
from ddls_tpu.serve.fleet import (FleetResponse, ReplicaSet, Router,
                                  TokenBucket, build_fleet, fit_buckets)
from ddls_tpu.serve.microbatch import MicrobatchEngine, PendingRequest
from ddls_tpu.serve.server import (DEFAULT_FALLBACK_DEGREE, BucketForward,
                                   PolicyServer, ServeResponse, ServeStats,
                                   build_model_from_config,
                                   checkpoint_graph_feature_dim,
                                   load_checkpoint_params)

__all__ = [
    "AutoscaleConfig",
    "AutoscaleController",
    "AutoscaleDecision",
    "Autoscaler",
    "BucketForward",
    "BucketOverflowError",
    "BucketSpec",
    "DEFAULT_FALLBACK_DEGREE",
    "FleetResponse",
    "MicrobatchEngine",
    "ObsBucketer",
    "PendingRequest",
    "PolicyServer",
    "ReplicaSet",
    "Router",
    "ServeResponse",
    "ServeStats",
    "TokenBucket",
    "build_fleet",
    "build_model_from_config",
    "checkpoint_graph_feature_dim",
    "default_buckets",
    "fit_buckets",
    "load_checkpoint_params",
]
