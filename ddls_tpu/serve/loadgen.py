"""Trace-driven open-loop load for the serving bench (ISSUE 8).

A closed Poisson process at a constant rate is the friendliest load a
server ever sees. Production traffic is not that: rates follow a diurnal
cycle, bursts arrive on top of it, job sizes are heavy-tailed, and
tenants are skewed. This module generates such a trace — **seeded and
fingerprinted**, so every bench line names exactly the load it measured
and two rounds are comparable — and defines the latency/SLO accounting
the bench reports over it:

* **Open-loop**: request *i* is scheduled at ``arrival_s[i]``
  regardless of how the server is doing — arrivals never wait for
  responses (the closed-loop trap that hides overload).
* **Coordinated-omission-correct**: latency is measured against the
  SCHEDULED arrival timestamp, not the instant the driving loop got
  around to submitting (the server stack supports backdated ``now=`` at
  submit precisely for this). A stalled server therefore charges its
  stall to every request that arrived during it — p99/p999 stay honest
  exactly in overload, where the naive measurement is most wrong.
* **SLO/goodput**: a request *attains* the SLO when it got an actual
  decision (policy or heuristic fallback — sheds are explicit refusals
  and never count) within the budget, measured from scheduled arrival.
  ``goodput_rps`` is attaining requests per second of trace time.

The arrival process is a non-homogeneous Poisson approximation
(interarrival ``Exp(1)/rate(t)`` at the current instant's rate) with
``rate(t) = base_rps * diurnal(t) * burst(t)``; sizes draw a Pareto tail
mapped into ``[0, 1)`` ranks (the bench maps ranks onto its obs pool
sorted by graph size); tenants draw from a 1/(k+1) zipf-ish weighting.
Everything is a pure function of the seed + knobs: same seed, same
fingerprint, bit-same trace.

``python -m ddls_tpu.serve.loadgen --selftest`` validates the schema
machinery itself (tier-1, numpy-only — no jax import).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

TRACE_SCHEMA = "ddls_tpu.serve.trace/v1"

# knobs recorded in trace["meta"] and folded into the fingerprint; a new
# generator knob MUST be added here or two differently-shaped traces
# could fingerprint identically
_META_KEYS = ("seed", "n_requests", "base_rps", "diurnal_period_s",
              "diurnal_amplitude", "burst_factor", "burst_period_s",
              "burst_duty", "size_tail_alpha", "n_tenants")


def rate_at(t: float, base_rps: float, diurnal_period_s: float,
            diurnal_amplitude: float, burst_factor: float,
            burst_period_s: float, burst_duty: float) -> float:
    """Instantaneous offered rate: diurnal sinusoid times a periodic
    burst window (the first ``burst_duty`` fraction of every
    ``burst_period_s`` runs at ``burst_factor`` x)."""
    rate = base_rps
    if diurnal_amplitude and diurnal_period_s > 0:
        rate *= 1.0 + diurnal_amplitude * math.sin(
            2.0 * math.pi * t / diurnal_period_s)
    if burst_factor != 1.0 and burst_period_s > 0 and burst_duty > 0:
        if (t % burst_period_s) < burst_duty * burst_period_s:
            rate *= burst_factor
    return max(rate, 1e-9)


def generate_trace(n_requests: int, base_rps: float, seed: int = 0,
                   diurnal_period_s: float = 30.0,
                   diurnal_amplitude: float = 0.5,
                   burst_factor: float = 3.0,
                   burst_period_s: float = 10.0,
                   burst_duty: float = 0.2,
                   size_tail_alpha: float = 1.5,
                   n_tenants: int = 4) -> Dict[str, Any]:
    """One seeded open-loop trace. ``diurnal_amplitude=0`` and
    ``burst_factor=1`` degrade to a plain Poisson process at
    ``base_rps`` (what the bench's ``--load poisson`` fleet path uses,
    so poisson runs are fingerprinted through the same machinery)."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if base_rps <= 0:
        raise ValueError(f"base_rps must be > 0, got {base_rps}")
    if not 0.0 <= diurnal_amplitude < 1.0:
        raise ValueError("diurnal_amplitude must be in [0, 1) (a full "
                         "amplitude would zero the rate)")
    rng = np.random.RandomState(int(seed))
    arrivals = np.empty(n_requests, dtype=np.float64)
    t = 0.0
    for i in range(n_requests):
        lam = rate_at(t, base_rps, diurnal_period_s, diurnal_amplitude,
                      burst_factor, burst_period_s, burst_duty)
        t += rng.exponential(1.0 / lam)
        arrivals[i] = t
    # heavy-tailed size rank in [0, 1): Pareto(alpha) mapped through
    # 1 - 1/x — most requests small, a fat tail of near-max graphs
    u = rng.uniform(0.0, 1.0, size=n_requests)
    x = np.power(1.0 - u, -1.0 / float(size_tail_alpha))
    size_frac = 1.0 - 1.0 / x
    # zipf-ish tenant skew: w_k ∝ 1/(k+1)
    weights = 1.0 / (np.arange(int(n_tenants)) + 1.0)
    weights /= weights.sum()
    tenant_idx = rng.choice(int(n_tenants), size=n_requests, p=weights)
    meta = {"seed": int(seed), "n_requests": int(n_requests),
            "base_rps": float(base_rps),
            "diurnal_period_s": float(diurnal_period_s),
            "diurnal_amplitude": float(diurnal_amplitude),
            "burst_factor": float(burst_factor),
            "burst_period_s": float(burst_period_s),
            "burst_duty": float(burst_duty),
            "size_tail_alpha": float(size_tail_alpha),
            "n_tenants": int(n_tenants)}
    return {
        "schema": TRACE_SCHEMA,
        "meta": meta,
        "arrival_s": arrivals,
        "size_frac": size_frac,
        "tenant": [f"tenant-{int(k)}" for k in tenant_idx],
    }


def trace_fingerprint(trace: Dict[str, Any]) -> str:
    """Stable 16-hex-digit content fingerprint: meta knobs + the arrival
    / size arrays (rounded to ns / 1e-12 so the fingerprint survives
    JSON round-trips) + tenants. Two bench lines with equal fingerprints
    measured the identical offered load."""
    h = hashlib.sha256()
    meta = trace.get("meta") or {}
    h.update(json.dumps({k: meta.get(k) for k in _META_KEYS},
                        sort_keys=True).encode())
    h.update(np.round(np.asarray(trace["arrival_s"], dtype=np.float64),
                      9).tobytes())
    h.update(np.round(np.asarray(trace["size_frac"], dtype=np.float64),
                      12).tobytes())
    h.update("\x00".join(trace["tenant"]).encode())
    return h.hexdigest()[:16]


def validate_trace(trace: Dict[str, Any]) -> None:
    """Schema validator (the ``--selftest`` surface, also run by the
    bench before driving a trace): raises ``ValueError`` naming the
    first violated invariant."""
    if not isinstance(trace, dict):
        raise ValueError(f"trace must be a dict, got {type(trace)}")
    if trace.get("schema") != TRACE_SCHEMA:
        raise ValueError(f"unknown trace schema {trace.get('schema')!r} "
                         f"(expected {TRACE_SCHEMA!r})")
    meta = trace.get("meta")
    if not isinstance(meta, dict):
        raise ValueError("trace missing 'meta' dict")
    missing = [k for k in _META_KEYS if k not in meta]
    if missing:
        raise ValueError(f"trace meta missing keys {missing}")
    for key in ("arrival_s", "size_frac", "tenant"):
        if key not in trace:
            raise ValueError(f"trace missing {key!r}")
    arr = np.asarray(trace["arrival_s"], dtype=np.float64)
    size = np.asarray(trace["size_frac"], dtype=np.float64)
    tenants = trace["tenant"]
    n = int(meta["n_requests"])
    if not (arr.shape == size.shape == (n,)) or len(tenants) != n:
        raise ValueError(
            f"trace length mismatch: meta says {n}, arrays are "
            f"{arr.shape}/{size.shape}/{len(tenants)}")
    if not np.all(np.isfinite(arr)) or (n and arr[0] < 0):
        raise ValueError("arrival_s must be finite and non-negative")
    if np.any(np.diff(arr) < 0):
        raise ValueError("arrival_s must be non-decreasing (open-loop "
                         "schedule)")
    if not np.all(np.isfinite(size)) or np.any((size < 0) | (size >= 1)):
        raise ValueError("size_frac must lie in [0, 1)")
    if not all(isinstance(t, str) and t for t in tenants):
        raise ValueError("tenant entries must be non-empty strings")


def trace_to_jsonable(trace: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "schema": trace["schema"],
        "meta": trace["meta"],
        "arrival_s": [round(float(x), 9) for x in trace["arrival_s"]],
        "size_frac": [round(float(x), 12) for x in trace["size_frac"]],
        "tenant": list(trace["tenant"]),
    }


def trace_from_jsonable(obj: Dict[str, Any]) -> Dict[str, Any]:
    trace = {
        "schema": obj.get("schema"),
        "meta": obj.get("meta"),
        "arrival_s": np.asarray(obj.get("arrival_s", []), np.float64),
        "size_frac": np.asarray(obj.get("size_frac", []), np.float64),
        "tenant": list(obj.get("tenant", [])),
    }
    validate_trace(trace)
    return trace


# ------------------------------------------------------------ SLO accounting
def slo_summary(responses: Sequence[Any], slo_s: float,
                duration_s: float) -> Dict[str, Any]:
    """Coordinated-omission-correct latency + SLO rollup over a bench
    run's responses (anything with ``.action``/``.source``/
    ``.latency_s``, latencies measured from SCHEDULED arrivals).

    Percentiles (p50/p99/p999) are over DECIDED requests only; sheds
    are explicit refusals reported via ``shed_rate`` (folding their
    ~0 s refusal latency into the percentiles would bias them low
    exactly when shedding is protecting the tail). ``slo_attainment``
    and ``goodput_rps`` charge sheds as misses: attainment is
    ``decided within budget / offered``."""
    n_offered = len(responses)
    decided = [r for r in responses if r.source != "shed"]
    shed = n_offered - len(decided)
    fallback = sum(1 for r in decided if r.source == "fallback")
    lats = np.asarray([r.latency_s for r in decided], dtype=np.float64)
    attained = int(np.sum(lats <= float(slo_s))) if len(lats) else 0

    def pct(q):
        return (float(np.percentile(lats, q)) * 1e3 if len(lats)
                else None)

    return {
        "n_offered": n_offered,
        "n_decided": len(decided),
        "p50_latency_ms": pct(50),
        "p99_latency_ms": pct(99),
        "p999_latency_ms": pct(99.9),
        "slo_ms": float(slo_s) * 1e3,
        "slo_attainment": (attained / n_offered) if n_offered else 0.0,
        "goodput_rps": (attained / duration_s) if duration_s > 0 else 0.0,
        "shed_rate": (shed / n_offered) if n_offered else 0.0,
        "degraded_rate": (fallback / n_offered) if n_offered else 0.0,
    }


# ------------------------------------------------------------------ selftest
def run_selftest() -> Dict[str, Any]:
    """Exercise the generator + validator + fingerprint invariants
    without touching jax (tier-1): determinism, seed sensitivity,
    modulation sanity, and that the validator actually rejects each
    class of malformed trace."""
    # periods scaled well inside the ~2.5 s the trace spans, so the
    # burst-share check below sees several full cycles (with the
    # defaults' 10 s burst period the whole trace would sit inside one
    # burst window and the check would pass vacuously)
    kwargs = dict(n_requests=512, base_rps=200.0, seed=7,
                  diurnal_period_s=1.6, burst_period_s=0.8)
    a = generate_trace(**kwargs)
    b = generate_trace(**kwargs)
    validate_trace(a)
    validate_trace(b)
    ok = trace_fingerprint(a) == trace_fingerprint(b)
    ok &= (trace_fingerprint(generate_trace(n_requests=512,
                                            base_rps=200.0, seed=8))
           != trace_fingerprint(a))
    # knob changes must change the fingerprint even when arrivals would
    # collide by luck (meta is folded in)
    ok &= (trace_fingerprint({**a, "meta": {**a["meta"],
                                            "size_tail_alpha": 9.9}})
           != trace_fingerprint(a))
    # JSON round trip preserves schema + fingerprint
    rt = trace_from_jsonable(json.loads(json.dumps(trace_to_jsonable(a))))
    ok &= trace_fingerprint(rt) == trace_fingerprint(a)
    # burst sanity: the burst windows hold a super-proportional share of
    # arrivals (rate modulation is real, not cosmetic)
    m = a["meta"]
    arr = np.asarray(a["arrival_s"])
    in_burst = (arr % m["burst_period_s"]) < (m["burst_duty"]
                                              * m["burst_period_s"])
    burst_share = float(np.mean(in_burst))
    # super-proportional but not degenerate: a share of ~1.0 would mean
    # the whole trace sat inside one burst window (periods mis-scaled)
    ok &= m["burst_duty"] * 1.5 < burst_share < 0.9
    # heavy tail sanity: the size distribution is skewed small with a
    # real tail
    size = np.asarray(a["size_frac"])
    ok &= float(np.median(size)) < 0.5 and float(size.max()) > 0.8
    # the validator rejects each malformation class
    rejected = 0
    bad_arr = dict(a, arrival_s=np.asarray(a["arrival_s"])[::-1].copy())
    bad_size = dict(a, size_frac=np.asarray(a["size_frac"]) + 1.5)
    bad_schema = dict(a, schema="bogus/v0")
    bad_meta = dict(a, meta={k: v for k, v in a["meta"].items()
                             if k != "seed"})
    for bad in (bad_arr, bad_size, bad_schema, bad_meta):
        try:
            validate_trace(bad)
        except ValueError:
            rejected += 1
    ok &= rejected == 4
    return {"selftest": "ok" if ok else "FAILED",
            "n_requests": int(m["n_requests"]),
            "fingerprint": trace_fingerprint(a),
            "burst_share": round(burst_share, 4),
            "rejected_malformed": rejected}


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Seeded, fingerprinted open-loop serving traces")
    parser.add_argument("--selftest", action="store_true",
                        help="validate the trace schema machinery "
                             "(numpy-only, tier-1)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--requests", type=int, default=256)
    parser.add_argument("--base-rps", type=float, default=200.0)
    parser.add_argument("--out", default=None,
                        help="write the generated trace as JSON here "
                             "(default: print meta + fingerprint only)")
    args = parser.parse_args(argv)
    if args.selftest:
        result = run_selftest()
        print(json.dumps(result), flush=True)
        return 0 if result["selftest"] == "ok" else 1
    trace = generate_trace(n_requests=args.requests,
                           base_rps=args.base_rps, seed=args.seed)
    validate_trace(trace)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(trace_to_jsonable(trace), f)
    print(json.dumps({"schema": trace["schema"], "meta": trace["meta"],
                      "fingerprint": trace_fingerprint(trace),
                      "out": args.out}), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
