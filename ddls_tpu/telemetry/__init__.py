"""Unified telemetry layer: spans, counters, gauges, latency histograms
(ISSUE 3) — one vocabulary for timing/attribution evidence across the
simulator, the train loops, the serve stack, and bench.py.

The process-global registry here is **disabled by default** and the
module-level API is a near-no-op while it stays disabled: one bool check,
a shared singleton span, no metric creation, no allocation. That is the
hot-path contract (CLAUDE.md): sim/env/train code may only touch
telemetry through these gated functions, so golden tests and the env
step loop are byte- and speed-identical with telemetry off
(tests/test_telemetry.py pins both).

Usage::

    from ddls_tpu import telemetry

    telemetry.enable(sink_path="run.jsonl")      # CLI entry points
    with telemetry.span("train.collect"):
        ...
    telemetry.inc("sim.lookahead_cache.hit")
    telemetry.record_event("tpu_probe", phase="timeout",
                           wedge_suspected=True)
    print(telemetry.snapshot())                  # JSON-friendly rollup

Opt-in ``jax.profiler`` capture: ``enable(jax_trace_dir=...,
jax_trace_spans=("train.train_step",))`` makes the first matching span
per process wrap a ``jax.profiler`` trace (TensorBoard/Perfetto), tying
device timelines to the same span names the histograms use.

Subsystems that need isolated, always-on metrics (serve's per-server
stats) instantiate a private ``Registry(enabled=True)`` instead of the
global one — multiple servers must never share counters, and their stats
must keep working with global telemetry disabled.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence

from ddls_tpu.telemetry.metrics import (DEFAULT_LATENCY_BUCKETS_S,
                                        DEFAULT_WINDOW, NULL_SPAN, Counter,
                                        Gauge, Histogram, NullSpan,
                                        Registry, Span, TransferSpan,
                                        aggregate_snapshots,
                                        overlap_summary,
                                        percentile_from_bucket_counts,
                                        tree_nbytes)
from ddls_tpu.telemetry.sink import JsonlSink

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Span", "NullSpan",
    "NULL_SPAN", "TransferSpan", "JsonlSink", "DEFAULT_LATENCY_BUCKETS_S",
    "DEFAULT_WINDOW", "percentile_from_bucket_counts", "overlap_summary",
    "aggregate_snapshots", "tree_nbytes",
    "registry", "enabled", "enable", "disable", "span", "transfer", "inc",
    "observe", "set_gauge", "record_event", "snapshot", "span_summaries",
    "reset", "dump_snapshot", "clock_now", "record_span", "span_intervals",
]

_GLOBAL = Registry(enabled=False)

# environment override for processes whose CLI has no telemetry flag
# (subprocess env workers, the bench's sim-mode rider): a path enables
# the global registry with a JSONL sink at import of the entry point
# that consults it (bench.py, scripts/serve_policy.py)
SINK_ENV_VAR = "DDLS_TELEMETRY_JSONL"


def registry() -> Registry:
    """The process-global registry (for snapshot plumbing and tests —
    hot paths go through the gated module functions below)."""
    return _GLOBAL


def enabled() -> bool:
    return _GLOBAL.enabled


def enable(sink_path: Optional[str] = None,
           clock=None,
           jax_trace_dir: Optional[str] = None,
           jax_trace_spans: Sequence[str] = (),
           record_intervals: Optional[bool] = None) -> Registry:
    """Turn the global registry on (idempotent; existing metrics are
    kept — call ``reset()`` first for a fresh measurement window).
    ``sink_path`` attaches a JSONL sink; ``jax_trace_dir`` +
    ``jax_trace_spans`` arm the opt-in jax.profiler capture;
    ``record_intervals=True`` keeps per-span (start, end) pairs in a
    bounded ring for ``overlap_summary`` concurrency accounting."""
    if sink_path:
        _GLOBAL.sink = JsonlSink(sink_path)
    if clock is not None:
        _GLOBAL.clock = clock
    if jax_trace_dir:
        _GLOBAL.jax_trace_dir = str(jax_trace_dir)
        _GLOBAL._jax_trace_done = False  # arm a fresh one-shot capture
    if jax_trace_spans:
        _GLOBAL.jax_trace_spans = frozenset(jax_trace_spans)
    if record_intervals is not None:
        _GLOBAL.record_intervals = bool(record_intervals)
    _GLOBAL.enabled = True
    return _GLOBAL


def disable() -> None:
    """Flip telemetry off; recorded metrics survive until ``reset()``."""
    _GLOBAL.enabled = False


def env_sink_path() -> Optional[str]:
    return os.environ.get(SINK_ENV_VAR) or None


# ----------------------------------------------------------- gated hot API
def span(name: str):
    """A timed block; the shared no-op singleton when disabled (so a hot
    loop allocates nothing — identity-tested by the guard test)."""
    if not _GLOBAL.enabled:
        return NULL_SPAN
    return Span(_GLOBAL, name)


def transfer(name: str, direction: str):
    """A timed, byte-attributed block around an EXISTING explicit
    device_put/device_get/drain site (the transfer ledger, ISSUE 18):
    ``with telemetry.transfer("sebulba.params", "h2d") as tr: ...;
    tr.add(tree)``. The shared no-op singleton when disabled — zero
    allocation, and ``add`` never reads device data either way
    (``.nbytes`` metadata only), so transfer-guard pins stay valid."""
    if not _GLOBAL.enabled:
        return NULL_SPAN
    return TransferSpan(_GLOBAL, name, direction)


def inc(name: str, n: int = 1) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.counter(name).inc(n)


def observe(name: str, value: float, **histogram_kwargs) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.histogram(name, **histogram_kwargs).observe(value)


def set_gauge(name: str, value: float) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.gauge(name).set(value)


def record_event(kind: str, **fields) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.event(kind, **fields)


def clock_now() -> float:
    """The registry clock's current reading — the t0 source for
    ``record_span`` (injectable-clock discipline: never pair a raw
    wall-clock read with a registry-recorded end)."""
    return _GLOBAL.clock()


def record_span(name: str, t0: float, t1: Optional[float] = None) -> None:
    """Record an explicitly-timed span (see ``Registry.record_span``);
    no-op while disabled, like the context-manager form."""
    if _GLOBAL.enabled:
        _GLOBAL.record_span(name, t0, t1)


# --------------------------------------------------------------- readbacks
def snapshot() -> Dict[str, Any]:
    return _GLOBAL.snapshot()


def span_summaries() -> Dict[str, Dict[str, float]]:
    return _GLOBAL.span_summaries()


def span_intervals() -> list:
    return _GLOBAL.span_intervals()


def reset() -> None:
    _GLOBAL.reset()


def dump_snapshot(extra: Optional[Dict[str, Any]] = None) -> None:
    _GLOBAL.dump_snapshot(extra=extra)
