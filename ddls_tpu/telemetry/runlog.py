"""Run ledger (ISSUE 18): every bench/train/conformance/serve run leaves
one fingerprinted, diffable directory.

A :class:`RunLedger` owns a run directory holding:

* ``manifest.json`` — who/what/where: argv, the resolved config, scenario
  fingerprint, device/mesh topology (recorded ONLY if a jax backend is
  already initialized — the ledger must never force backend init and
  wake the axon tunnel), process index/count, git sha, probe/lock state,
  and a ``clock`` block (paired ``unix``/``perf`` readings) that lets
  ``telemetry.timeline`` correlate multi-process runs by clock offset.
* ``telemetry.jsonl`` — the JSONL sink for the run's window: spans,
  events, transfer-ledger records, snapshots (see telemetry/sink.py).
* ``result.json`` — every result payload the run emitted (bench's JSON
  line, the train loop's final results, conformance's report doc).
* ``snapshot.json`` — the final registry snapshot plus named counter
  blocks (ring ledger stats, memo counters, fleet rollups).

The ledger is OPT-IN and composes with the existing telemetry window
discipline: ``open()`` saves the global registry's (enabled, sink) pair,
points the sink at the run directory, and ``finalize()`` restores both —
so bench.main's save/reset/restore window wraps it cleanly. Metrics are
NOT reset here; the caller owns the measurement window. When both a
``--telemetry-jsonl`` path and a run dir are given, the run dir's sink
wins for the window (documented in docs/telemetry.md).

Hot-path contract: nothing here is ever called per step — ``open`` /
``record_result`` / ``add_block`` / ``finalize`` run at run boundaries
only.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Any, Dict, Optional, Sequence

from ddls_tpu import telemetry
from ddls_tpu.telemetry.sink import JsonlSink

MANIFEST_NAME = "manifest.json"
SINK_NAME = "telemetry.jsonl"
RESULT_NAME = "result.json"
SNAPSHOT_NAME = "snapshot.json"


def _git_sha(repo_dir: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Best-effort git identity; never raises (a run outside a checkout
    still gets a manifest)."""
    try:
        cwd = repo_dir or os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd, capture_output=True,
            text=True, timeout=10)
        if sha.returncode != 0:
            return None
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=cwd,
            capture_output=True, text=True, timeout=10)
        return {"sha": sha.stdout.strip(),
                "dirty": bool(dirty.stdout.strip())
                if dirty.returncode == 0 else None}
    except Exception:
        return None


def _device_summary() -> Optional[Dict[str, Any]]:
    """Topology of an ALREADY-initialized jax backend; None otherwise.
    Never triggers backend init: ``jax.devices()`` on a cold process
    would open the axon tunnel client (CLAUDE.md wedge hazard)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        xb = jax._src.xla_bridge
        if not getattr(xb, "_backends", None):
            return None
        devs = jax.devices()
        return {
            "count": len(devs),
            "local_count": jax.local_device_count(),
            "platform": devs[0].platform if devs else None,
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "kinds": sorted({getattr(d, "device_kind", "?")
                             for d in devs}),
        }
    except Exception:
        return None


def _probe_state(probe_dir: Optional[str]) -> Optional[Dict[str, Any]]:
    if not probe_dir:
        return None
    out: Dict[str, Any] = {}
    try:
        state_path = os.path.join(probe_dir, "probe_state.json")
        if os.path.exists(state_path):
            with open(state_path) as f:
                out["probe_state"] = json.load(f)
        out["lock_held"] = os.path.exists(
            os.path.join(probe_dir, "tpu.lock"))
        out["lock_owner_env"] = os.environ.get(
            "DDLS_TPU_LOCK_OWNER") or None
    except Exception:
        return out or None
    return out


def _jsonable(obj):
    if hasattr(obj, "tolist"):
        return obj.tolist()
    try:
        return float(obj)
    except (TypeError, ValueError):
        return repr(obj)


def _write_json(path: str, doc: Any) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=_jsonable)
        f.write("\n")
    os.replace(tmp, path)


class RunLedger:
    """One run's correlated artifact directory (module docstring has the
    file layout). Lifecycle: construct → ``open()`` (mkdir + manifest +
    telemetry sink swap) → work → ``record_result``/``add_block`` →
    ``finalize()`` (snapshot + restore). ``open``/``finalize`` are
    idempotent; a ledger that is never opened is inert."""

    def __init__(self, run_dir: str, kind: str,
                 argv: Optional[Sequence[str]] = None,
                 config: Optional[Dict[str, Any]] = None,
                 scenario_fingerprint: Optional[str] = None,
                 process_index: int = 0, process_count: int = 1,
                 probe_dir: Optional[str] = None,
                 extra: Optional[Dict[str, Any]] = None,
                 enable_telemetry: bool = True):
        self.run_dir = str(run_dir)
        self.kind = str(kind)
        self.argv = list(argv if argv is not None else sys.argv)
        self.config = dict(config or {})
        self.scenario_fingerprint = scenario_fingerprint
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.probe_dir = probe_dir
        self.extra = dict(extra or {})
        self.enable_telemetry = bool(enable_telemetry)
        self._opened = False
        self._finalized = False
        self._results: list = []
        self._blocks: Dict[str, Any] = {}
        self._own_sink: Optional[JsonlSink] = None
        self._prior: Optional[tuple] = None  # (enabled, sink)

    # ------------------------------------------------------------- paths
    def path(self, name: str) -> str:
        return os.path.join(self.run_dir, name)

    @property
    def manifest_path(self) -> str:
        return self.path(MANIFEST_NAME)

    @property
    def sink_path(self) -> str:
        return self.path(SINK_NAME)

    # --------------------------------------------------------- lifecycle
    def open(self) -> "RunLedger":
        if self._opened:
            return self
        os.makedirs(self.run_dir, exist_ok=True)
        manifest = {
            "kind": self.kind,
            "argv": self.argv,
            "config": self.config,
            "scenario_fingerprint": self.scenario_fingerprint,
            "process": {"index": self.process_index,
                        "count": self.process_count},
            # paired clock readings: sink ``ts`` stamps are unix
            # wall-clock; registry spans/intervals use the perf clock —
            # the offset (unix - perf) aligns both per process, and
            # unix itself aligns processes on one host
            "clock": {"unix": time.time(),
                      "perf": time.perf_counter()},
            "host": {"hostname": socket.gethostname(),
                     "pid": os.getpid(),
                     "platform": sys.platform,
                     "python": sys.version.split()[0]},
            "git": _git_sha(),
            "devices": _device_summary(),
            "probe": _probe_state(self.probe_dir),
        }
        if self.extra:
            manifest["extra"] = self.extra
        _write_json(self.manifest_path, manifest)
        if self.enable_telemetry:
            reg = telemetry.registry()
            self._prior = (reg.enabled, reg.sink)
            self._own_sink = JsonlSink(self.sink_path)
            reg.sink = self._own_sink
            telemetry.enable(record_intervals=True)
        self._opened = True
        return self

    def update_config(self, fields: Dict[str, Any]) -> None:
        """Merge resolved-config fields in; if the manifest is already
        on disk (the caller opened early to capture the whole telemetry
        window) it is rewritten with the merged config."""
        self.config.update(fields)
        if self._opened and os.path.exists(self.manifest_path):
            try:
                with open(self.manifest_path) as f:
                    manifest = json.load(f)
            except Exception:
                return
            manifest["config"] = self.config
            _write_json(self.manifest_path, manifest)

    def record_result(self, payload: Dict[str, Any]) -> None:
        """Append one result payload (the same dict bench's ``emit``
        prints) and rewrite ``result.json`` — called at reporting
        boundaries only."""
        if not self._opened:
            return
        self._results.append(payload)
        _write_json(self.path(RESULT_NAME), {"results": self._results})

    def add_block(self, name: str, data: Any) -> None:
        """Attach a named counter block (ring ``stats()``, memo
        counters, fleet rollup) for ``snapshot.json``."""
        if data is not None:
            self._blocks[str(name)] = data

    def finalize(self, blocks: Optional[Dict[str, Any]] = None) -> None:
        """Write ``snapshot.json`` (final registry snapshot + blocks),
        close the run's sink, and restore the prior telemetry state."""
        if not self._opened or self._finalized:
            return
        self._finalized = True
        for k, v in (blocks or {}).items():
            self.add_block(k, v)
        reg = telemetry.registry()
        doc = {"snapshot": reg.snapshot()}
        if self._blocks:
            doc["blocks"] = self._blocks
        intervals = reg.span_intervals()
        if intervals:
            # perf-clock intervals; timeline aligns them via the
            # manifest clock offset (sink records are already unix)
            doc["span_intervals"] = [
                [n, t0, t1] for n, t0, t1 in intervals]
        _write_json(self.path(SNAPSHOT_NAME), doc)
        if self.enable_telemetry and self._prior is not None:
            prior_enabled, prior_sink = self._prior
            reg.sink = prior_sink
            reg.enabled = prior_enabled
            self._prior = None
        if self._own_sink is not None:
            self._own_sink.close()
            self._own_sink = None


def load_run_dir(run_dir: str) -> Dict[str, Any]:
    """Read a ledger directory back: manifest + sink records + snapshot
    + results (missing pieces → absent keys; a half-written run must
    still load for the timeline/report tools)."""
    out: Dict[str, Any] = {"run_dir": str(run_dir)}
    man = os.path.join(run_dir, MANIFEST_NAME)
    if os.path.exists(man):
        with open(man) as f:
            out["manifest"] = json.load(f)
    snap = os.path.join(run_dir, SNAPSHOT_NAME)
    if os.path.exists(snap):
        with open(snap) as f:
            out["snapshot"] = json.load(f)
    res = os.path.join(run_dir, RESULT_NAME)
    if os.path.exists(res):
        with open(res) as f:
            out["results"] = json.load(f).get("results", [])
    sink = os.path.join(run_dir, SINK_NAME)
    records = []
    if os.path.exists(sink):
        with open(sink) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn final line of a crashed run
    out["records"] = records
    return out
