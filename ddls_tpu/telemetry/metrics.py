"""Dependency-free metrics primitives: counters, gauges, fixed-bucket
latency histograms, span tracing, and a registry with snapshot/reset.

The substrate Podracer (arXiv 2104.06272) and MSRL (arXiv 2210.00882)
attribute their scaling wins to: per-stage instrumentation of the
actor/learner dataflow, here shared by the simulator, the train loops,
the serve stack, and bench.py so every perf claim speaks one vocabulary.

Design rules (ISSUE 3):

* **Near-no-op when disabled.** The module-level API in
  ``ddls_tpu.telemetry`` early-outs on a single bool and returns one
  shared singleton span object, so a disabled hot loop performs no
  allocation and creates no metrics (guard-tested in
  tests/test_telemetry.py). Hot-path modules must only ever go through
  that gated API — never instantiate metrics per step.
* **Thread-safe aggregation.** Every mutation takes the metric's own
  lock (serve batches, background save threads, and the multi-host
  launcher all touch metrics off the main thread); registry
  create-or-get takes the registry lock.
* **Injectable clock.** ``Registry(clock=...)`` parameterises every
  span/duration measurement, so tests drive time deterministically —
  the same discipline as ``PolicyServer(clock=...)``.
* **Histograms carry fixed buckets AND a trailing sample window.** The
  bucket counts are exact over the metric's lifetime (what a JSONL sink
  or a cross-process aggregator can merge); the window gives exact
  ``np.percentile`` p50/p95/p99 over the last ``window`` samples — the
  same windowed-percentile semantics serve's stats always had, so
  histogram-derived latency figures agree bit-for-bit with them.
"""
from __future__ import annotations

import bisect
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

# geometric ~1-2.5-5 ladder from 10 us to 30 s: spans range from a
# sub-ms host env step to a multi-second tunnelled-TPU compile
DEFAULT_LATENCY_BUCKETS_S: Tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 1e-1, 2.5e-1, 5e-1, 1.0, 2.5, 5.0, 10.0, 30.0)

# trailing-window size for exact percentiles: a long-lived process must
# not hold one float per observation ever made (matches serve's
# STATS_WINDOW; the bucket counts above the window stay exact forever)
DEFAULT_WINDOW = 8192


class Counter:
    """Monotonic event counter."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-value-wins instantaneous measurement."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    @property
    def value(self) -> Optional[float]:
        return self._value


class Histogram:
    """Fixed-bucket histogram + trailing raw-sample window.

    ``buckets`` are ascending upper bounds (``le`` convention: a sample
    lands in the first bucket whose bound it does not exceed; one
    implicit overflow bucket catches the rest). Bucket counts, count,
    sum, min and max are exact over the histogram's lifetime; the
    percentiles are exact (``np.percentile``, linear interpolation) over
    the trailing ``window`` samples, falling back to bucket
    interpolation when the window is disabled (``window=0``).
    """

    __slots__ = ("name", "bounds", "_counts", "_count", "_sum", "_min",
                 "_max", "window", "_lock")

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
                 window: int = DEFAULT_WINDOW):
        self.name = name
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # + overflow
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self.window: Optional[deque] = (deque(maxlen=int(window))
                                        if window else None)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._counts[bisect.bisect_left(self.bounds, value)] += 1
            self._count += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value
            if self.window is not None:
                self.window.append(value)

    # ------------------------------------------------------------- readbacks
    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def min(self) -> Optional[float]:
        return self._min

    @property
    def max(self) -> Optional[float]:
        return self._max

    def window_values(self) -> list:
        """Copy of the trailing window taken under the lock — the only
        safe way to iterate it while another thread may be observing
        (a deque append during iteration raises RuntimeError)."""
        if self.window is None:
            return []
        with self._lock:
            return list(self.window)

    def percentile(self, q: float) -> Optional[float]:
        """Exact percentile over the trailing window (the semantics serve
        stats always used); bucket-interpolated when no window exists."""
        vals = self.window_values()
        if vals:
            return float(np.percentile(
                np.asarray(vals, dtype=np.float64), q))
        if self._count:
            return self.percentile_from_buckets(q)
        return None

    def percentile_from_buckets(self, q: float) -> Optional[float]:
        """Approximate percentile by linear interpolation inside the
        bucket holding the target rank (the only percentile available to
        an aggregator that sees bucket counts alone, e.g.
        scripts/telemetry_report.py over merged sink snapshots)."""
        return percentile_from_bucket_counts(
            self.bounds, self._counts, q, lo=self._min, hi=self._max)

    def bucket_counts(self) -> Dict[str, int]:
        """Nonzero buckets only, keyed by upper bound ('+inf' overflow)."""
        out = {}
        for bound, n in zip(self.bounds, self._counts):
            if n:
                out[repr(bound)] = n
        if self._counts[-1]:
            out["+inf"] = self._counts[-1]
        return out

    def summary(self) -> Dict[str, Any]:
        if not self._count:
            return {"count": 0}
        return {
            "count": self._count,
            "sum": self._sum,
            "mean": self._sum / self._count,
            "min": self._min,
            "max": self._max,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
            "buckets": self.bucket_counts(),
        }


def percentile_from_bucket_counts(bounds: Sequence[float],
                                  counts: Sequence[int], q: float,
                                  lo: Optional[float] = None,
                                  hi: Optional[float] = None
                                  ) -> Optional[float]:
    """Shared bucket-interpolation percentile (Histogram +
    telemetry_report.py): walk the cumulative counts to the bucket
    containing rank ``q/100 * count`` and interpolate linearly between
    its bounds, clamped to the observed [lo, hi] when known."""
    total = int(sum(counts))
    if not total:
        return None
    target = (q / 100.0) * total
    cum = 0
    for i, n in enumerate(counts):
        if not n:
            continue
        if cum + n >= target:
            b_lo = bounds[i - 1] if i > 0 else (lo if lo is not None
                                                else 0.0)
            b_hi = (bounds[i] if i < len(bounds)
                    else (hi if hi is not None else bounds[-1]))
            if lo is not None:
                b_lo = max(b_lo, lo) if i == 0 else b_lo
            if hi is not None:
                b_hi = min(b_hi, hi)
            frac = (target - cum) / n
            return float(b_lo + (b_hi - b_lo) * min(max(frac, 0.0), 1.0))
        cum += n
    return float(bounds[-1] if hi is None else hi)


class NullSpan:
    """The shared disabled-path span: a do-nothing context manager
    returned by ``telemetry.span`` (and ``telemetry.transfer``) when
    telemetry is off, so hot loops pay one bool check and zero
    allocations per call."""

    __slots__ = ()

    duration_s = 0.0
    bytes = 0

    def __enter__(self) -> "NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def elapsed(self) -> float:
        return 0.0

    def add(self, tree) -> None:
        """No-op byte attribution (TransferSpan interface)."""


NULL_SPAN = NullSpan()


class Span:
    """One timed block: ``with registry.span("collect"): ...`` records
    the duration into the registry's span histogram (and the JSONL sink
    when one is attached). ``duration_s`` is set on exit; ``elapsed()``
    reads the running clock mid-span."""

    __slots__ = ("_registry", "name", "_t0", "duration_s",
                 "_owns_jax_trace")

    def __init__(self, registry: "Registry", name: str):
        self._registry = registry
        self.name = name
        self._t0 = 0.0
        self.duration_s = 0.0
        self._owns_jax_trace = False

    def __enter__(self) -> "Span":
        reg = self._registry
        # opt-in jax.profiler capture: ONE trace per process — the first
        # configured span to enter owns it (jax supports a single active
        # trace), stops it on ITS exit (instance ownership, so a nested
        # or repeated same-name span can neither stop the outer trace
        # early nor re-arm a second capture)
        if (reg.jax_trace_dir and not reg._jax_tracing
                and not reg._jax_trace_done
                and self.name in reg.jax_trace_spans):
            try:
                import jax

                jax.profiler.start_trace(str(reg.jax_trace_dir))
                reg._jax_tracing = self.name
                self._owns_jax_trace = True
            except Exception:
                pass  # profiling must never break the measured code
        self._t0 = reg.clock()
        return self

    def __exit__(self, *exc) -> bool:
        reg = self._registry
        self.duration_s = reg.clock() - self._t0
        reg._record_span(self.name, self.duration_s, t0=self._t0)
        if self._owns_jax_trace:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            reg._jax_tracing = None
            reg._jax_trace_done = True
            self._owns_jax_trace = False
        return False

    def elapsed(self) -> float:
        return self._registry.clock() - self._t0


def tree_nbytes(tree) -> int:
    """Payload size of an array (py)tree from ``.nbytes`` METADATA only
    (shape x dtype — never a device read or sync, so a wrapped
    ``device_put`` stays legal under ``jax.transfer_guard``). Uses jax's
    tree flattener only if jax is already imported; leaves without
    ``.nbytes`` (scalars, None) count zero."""
    jax = sys.modules.get("jax")
    if jax is not None:
        leaves = jax.tree_util.tree_leaves(tree)
    else:  # minimal container walk so jax-less callers still attribute
        leaves, stack = [], [tree]
        while stack:
            node = stack.pop()
            if isinstance(node, dict):
                stack.extend(node.values())
            elif isinstance(node, (list, tuple)):
                stack.extend(node)
            else:
                leaves.append(node)
    total = 0
    for leaf in leaves:
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            try:
                total += int(nb)
            except TypeError:
                pass
    return total


class TransferSpan:
    """One explicit host<->device or mesh<->mesh hop (the transfer
    ledger, ISSUE 18): wraps an EXISTING explicit ``device_put`` /
    ``device_get`` / drain call site, timing it into the
    ``transfer.<name>`` span histogram and counting payload bytes the
    caller attributes via ``add(tree)``. Tunnel-RTT amortization
    (~116 ms per dispatch) falls straight out of
    ``transfer.<name>.calls`` vs ``.bytes`` per run."""

    __slots__ = ("_registry", "name", "direction", "bytes", "_t0",
                 "duration_s")

    def __init__(self, registry: "Registry", name: str, direction: str):
        self._registry = registry
        self.name = name
        self.direction = direction
        self.bytes = 0
        self._t0 = 0.0
        self.duration_s = 0.0

    def __enter__(self) -> "TransferSpan":
        self._t0 = self._registry.clock()
        return self

    def add(self, tree) -> None:
        """Attribute a payload (metadata-only byte count, see
        ``tree_nbytes``); call after the transfer dispatch with either
        the input or the output tree."""
        self.bytes += tree_nbytes(tree)

    def __exit__(self, *exc) -> bool:
        reg = self._registry
        self.duration_s = reg.clock() - self._t0
        reg.record_transfer(self.name, self.direction, self.bytes,
                            self.duration_s, t0=self._t0)
        return False


# bounded span-interval ring: overlap accounting needs (start, end) pairs,
# which the duration histograms deliberately do not keep; the ring caps the
# cost of leaving interval recording on for a long run
DEFAULT_INTERVAL_RING = 65536


class Registry:
    """A named collection of metrics + span tracer + optional sink.

    The process-global instance lives in ``ddls_tpu.telemetry`` (disabled
    by default; hot paths reach it only through the gated module API).
    Private instances are cheap and always-on — serve's per-server stats
    use one so concurrent servers never share counters and stats work
    with global telemetry disabled.
    """

    def __init__(self, enabled: bool = True,
                 clock: Callable[[], float] = time.perf_counter,
                 sink=None):
        self.enabled = bool(enabled)
        self.clock = clock
        self.sink = sink
        self.jax_trace_dir: Optional[str] = None
        self.jax_trace_spans: frozenset = frozenset()
        self._jax_tracing: Optional[str] = None
        self._jax_trace_done = False  # one capture per process/registry
        # opt-in (enable(record_intervals=True)): keep (name, t0, t1) for
        # every completed span so overlap/gap accounting can PROVE claimed
        # concurrency (e.g. train.update_device running under
        # train.collect) instead of asserting it
        self.record_intervals = False
        self._intervals: deque = deque(maxlen=DEFAULT_INTERVAL_RING)
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._spans: Dict[str, Histogram] = {}

    # ------------------------------------------------------------- metrics
    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
                  window: int = DEFAULT_WINDOW) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(
                    name, buckets=buckets, window=window)
            return h

    def histogram_items(self):
        """Live (name, Histogram) pairs — read-side iteration for rollups
        (e.g. serve's per-bucket occupancy line)."""
        with self._lock:
            return list(self._histograms.items())

    def counter_items(self):
        """Live (name, value) counter pairs — a cheap read (dict copy
        under the lock) for callers that only need counters; a full
        ``snapshot()`` would also summarise every histogram."""
        with self._lock:
            return [(n, c.value) for n, c in self._counters.items()]

    # ---------------------------------------------------- state swapping
    def metrics_state(self) -> tuple:
        """Opaque handle to the CURRENT metric dicts. ``reset()`` swaps
        in fresh dicts rather than mutating, so a caller that needs a
        private measurement window (bench.main) can save this, reset,
        measure, and hand the handle back to ``restore_metrics_state`` —
        the previous owner's metrics come back untouched."""
        with self._lock:
            return (self._counters, self._gauges, self._histograms,
                    self._spans)

    def restore_metrics_state(self, state: tuple) -> None:
        with self._lock:
            (self._counters, self._gauges, self._histograms,
             self._spans) = state

    # --------------------------------------------------------------- spans
    def span(self, name: str) -> Span:
        return Span(self, name)

    def _record_span(self, name: str, duration_s: float,
                     t0: Optional[float] = None) -> None:
        with self._lock:
            h = self._spans.get(name)
            if h is None:
                h = self._spans[name] = Histogram(name)
        h.observe(duration_s)
        if self.record_intervals and t0 is not None:
            # deque.append is itself thread-safe; bounded by maxlen
            self._intervals.append((name, t0, t0 + duration_s))
        sink = self.sink
        if sink is not None:
            sink.write({"type": "span", "name": name,
                        "dur_s": duration_s})

    def record_span(self, name: str, t0: float,
                    t1: Optional[float] = None) -> None:
        """Record an explicitly-timed span (same histogram/sink/interval
        plumbing as the context manager). For work whose start and end
        live on different threads — e.g. the pipelined train loop's
        device-update watcher, which captures t0 at dispatch on the main
        thread and closes the span from the thread that blocked on the
        device result."""
        if t1 is None:
            t1 = self.clock()
        self._record_span(name, t1 - t0, t0=t0)

    def record_transfer(self, name: str, direction: str, nbytes: int,
                        duration_s: float,
                        t0: Optional[float] = None) -> None:
        """Transfer-ledger record (see ``TransferSpan``): duration rides
        the span plumbing under ``transfer.<name>`` (histogram +
        interval ring + summaries), bytes/calls ride counters
        (``transfer.<name>.bytes`` / ``.calls`` plus the per-direction
        total ``transfer.<direction>.bytes``), and the sink gets one
        ``{"type": "transfer", ...}`` record the timeline renders as a
        flow arrow."""
        span_name = f"transfer.{name}"
        with self._lock:
            h = self._spans.get(span_name)
            if h is None:
                h = self._spans[span_name] = Histogram(span_name)
        h.observe(duration_s)
        if self.record_intervals and t0 is not None:
            self._intervals.append((span_name, t0, t0 + duration_s))
        self.counter(f"{span_name}.calls").inc()
        self.counter(f"{span_name}.bytes").inc(int(nbytes))
        self.counter(f"transfer.{direction}.bytes").inc(int(nbytes))
        sink = self.sink
        if sink is not None:
            sink.write({"type": "transfer", "name": name,
                        "direction": direction, "bytes": int(nbytes),
                        "dur_s": duration_s})

    def span_intervals(self) -> list:
        """Copy of the recorded (name, t0, t1) interval ring (empty unless
        ``record_intervals`` was set); feed to ``overlap_summary``."""
        return list(self._intervals)

    def span_summaries(self) -> Dict[str, Dict[str, float]]:
        """Per-span rollup in the units humans read spans in (ms), the
        shape both ``snapshot()['spans']`` and the W&B flatten emit."""
        out = {}
        with self._lock:
            spans = dict(self._spans)
        for name, h in spans.items():
            if not h.count:
                continue
            out[name] = {
                "count": h.count,
                "total_s": h.sum,
                "mean_ms": h.sum / h.count * 1e3,
                "p50_ms": h.percentile(50) * 1e3,
                "p95_ms": h.percentile(95) * 1e3,
                "p99_ms": h.percentile(99) * 1e3,
                "max_ms": (h.max or 0.0) * 1e3,
            }
        return out

    # -------------------------------------------------------------- events
    def event(self, kind: str, **fields) -> None:
        """A discrete occurrence (e.g. a TPU probe outcome): tallied as a
        counter (``event.<kind>``, plus ``event.<kind>.<phase>`` when a
        ``phase`` field is given) and written verbatim to the sink so the
        trail survives the process."""
        name = f"event.{kind}"
        self.counter(name).inc()
        phase = fields.get("phase")
        if phase is not None:
            self.counter(f"{name}.{phase}").inc()
        sink = self.sink
        if sink is not None:
            sink.write({"type": "event", "kind": kind, **fields})

    # ----------------------------------------------------- snapshot / reset
    def snapshot(self) -> Dict[str, Any]:
        """JSON-friendly dump of every live metric; empty sections are
        omitted (a registry that recorded nothing snapshots to ``{}``)."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            gauges = {n: g.value for n, g in self._gauges.items()
                      if g.value is not None}
            hists = dict(self._histograms)
        out: Dict[str, Any] = {}
        if counters:
            out["counters"] = counters
        if gauges:
            out["gauges"] = gauges
        hist_section = {n: h.summary() for n, h in hists.items() if h.count}
        if hist_section:
            out["histograms"] = hist_section
        spans = self.span_summaries()
        if spans:
            out["spans"] = spans
        return out

    def reset(self) -> None:
        """Drop every metric and span (fresh dicts — outstanding handles
        keep counting into orphaned objects, which is the safe failure
        mode for a racing thread)."""
        with self._lock:
            self._counters = {}
            self._gauges = {}
            self._histograms = {}
            self._spans = {}
            self._intervals = deque(maxlen=DEFAULT_INTERVAL_RING)

    def dump_snapshot(self, extra: Optional[Dict[str, Any]] = None) -> None:
        """Write the current snapshot to the sink (no-op without one)."""
        sink = self.sink
        if sink is not None:
            data = self.snapshot()
            if extra:
                data = {**data, **extra}
            sink.write({"type": "snapshot", "data": data})


def aggregate_snapshots(snaps: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge several ``Registry.snapshot()`` dicts into one fleet-level
    rollup (ISSUE 8: N serving replicas each keep a PRIVATE always-on
    registry; the bench/report surface needs the fleet total without the
    replicas ever sharing live metric objects).

    Exact merges only: counters and gauges sum, histogram ``count`` /
    ``sum`` / ``min`` / ``max`` and the fixed bucket counts add (the
    bucket counts are lifetime-exact by design — docs/telemetry.md), and
    the merged percentiles are reconstructed by bucket interpolation
    (``percentile_from_bucket_counts``) because trailing sample windows
    cannot be merged order-faithfully across registries. Span summaries
    merge count/total/mean/max the same way; their percentiles are
    dropped (window-only). Empty sections are omitted, mirroring
    ``snapshot()``.
    """
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    spans: Dict[str, Dict[str, float]] = {}
    for snap in snaps:
        if not snap:
            continue
        for name, value in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + int(value)
        for name, value in (snap.get("gauges") or {}).items():
            if value is not None:
                gauges[name] = gauges.get(name, 0.0) + float(value)
        for name, summ in (snap.get("histograms") or {}).items():
            if not summ.get("count"):
                continue
            agg = hists.setdefault(name, {
                "count": 0, "sum": 0.0, "min": None, "max": None,
                "buckets": {}})
            agg["count"] += int(summ["count"])
            agg["sum"] += float(summ.get("sum", 0.0))
            for bound, n in (summ.get("buckets") or {}).items():
                agg["buckets"][bound] = (agg["buckets"].get(bound, 0)
                                         + int(n))
            for key, pick in (("min", min), ("max", max)):
                v = summ.get(key)
                if v is not None:
                    agg[key] = (v if agg[key] is None
                                else pick(agg[key], v))
        for name, summ in (snap.get("spans") or {}).items():
            agg = spans.setdefault(name, {"count": 0, "total_s": 0.0,
                                          "max_ms": 0.0})
            agg["count"] += int(summ.get("count", 0))
            agg["total_s"] += float(summ.get("total_s", 0.0))
            agg["max_ms"] = max(agg["max_ms"],
                                float(summ.get("max_ms", 0.0)))
    for agg in hists.values():
        agg["mean"] = agg["sum"] / agg["count"]
        bounds = sorted(float(b) for b in agg["buckets"] if b != "+inf")
        cnts = [agg["buckets"].get(repr(b), agg["buckets"].get(str(b), 0))
                for b in bounds]
        cnts.append(agg["buckets"].get("+inf", 0))
        for q in (50, 95, 99):
            agg[f"p{q}"] = percentile_from_bucket_counts(
                bounds, cnts, q, lo=agg["min"], hi=agg["max"])
    for agg in spans.values():
        if agg["count"]:
            agg["mean_ms"] = agg["total_s"] / agg["count"] * 1e3
    out: Dict[str, Any] = {}
    if counters:
        out["counters"] = counters
    if gauges:
        out["gauges"] = gauges
    if hists:
        out["histograms"] = hists
    if spans:
        out["spans"] = spans
    return out


def overlap_summary(intervals: Sequence[Tuple[str, float, float]],
                    prefix: Optional[str] = None,
                    top_gaps: int = 3) -> Dict[str, Any]:
    """Concurrency accounting over span (name, t0, t1) intervals.

    The check Podracer-style pipelining claims need: over the window
    [min t0, max t1] of the (optionally ``prefix``-filtered) spans,
    report the wall-clock covered by >= 1 span (``covered_1_s``), by
    >= 2 concurrent spans (``covered_2_s`` — time when two instrumented
    phases genuinely ran at once), the uncovered gap total, and the
    ``top_gaps`` largest individual gaps. ``overlap_fraction`` =
    covered_2 / covered_1: 0 for a strictly sequential loop, > 0 only
    when phases actually overlap. Sources: a Registry's interval ring
    (``enable(record_intervals=True)``) or a JSONL sink's span records
    via ``(ts - dur_s, ts)`` (scripts/telemetry_report.py).
    """
    ivs = [(t0, t1) for name, t0, t1 in intervals
           if t1 > t0 and (prefix is None or name.startswith(prefix))]
    if not ivs:
        return {"n_spans": 0}
    events = []
    for t0, t1 in ivs:
        events.append((t0, 1))
        events.append((t1, -1))
    events.sort()
    window_t0, window_t1 = events[0][0], max(t1 for _, t1 in ivs)
    covered_1 = covered_2 = 0.0
    gaps = []  # (length, start, end) of zero-coverage stretches
    depth = 0
    prev_t = window_t0
    gap_start = None
    for t, delta in events:
        if t > prev_t:
            if depth >= 1:
                covered_1 += t - prev_t
            if depth >= 2:
                covered_2 += t - prev_t
        if depth == 0 and delta > 0 and gap_start is not None:
            if t > gap_start:
                gaps.append((t - gap_start, gap_start, t))
            gap_start = None
        prev_t = t
        depth += delta
        if depth == 0:
            gap_start = t
    gaps.sort(reverse=True)
    wall = window_t1 - window_t0
    return {
        "n_spans": len(ivs),
        "window_s": wall,
        "covered_1_s": covered_1,
        "covered_2_s": covered_2,
        "gap_s": max(wall - covered_1, 0.0),
        "overlap_fraction": (covered_2 / covered_1) if covered_1 else 0.0,
        "largest_gaps": [
            {"dur_s": g, "start": s, "end": e}
            for g, s, e in gaps[:max(top_gaps, 0)]],
    }
