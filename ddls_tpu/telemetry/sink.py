"""JSONL event sink: one JSON object per line, append-only.

Three record types land here (all stamped with a wall-clock ``ts``):

* ``{"type": "span", "name": ..., "dur_s": ...}`` — one per completed
  span (written by ``Registry._record_span``);
* ``{"type": "event", "kind": ..., ...fields}`` — discrete occurrences
  (TPU probe outcomes, degraded-mode transitions);
* ``{"type": "snapshot", "data": {...}}`` — a full registry dump
  (``Registry.dump_snapshot``), the record ``scripts/telemetry_report.py``
  reads counters/histograms from.

Writes are line-buffered and lock-guarded so spans recorded off the main
thread (serve batches, background savers) interleave whole lines, and a
crash loses at most the current line.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict


class JsonlSink:
    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self._f = open(self.path, "a", buffering=1)

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps({"ts": time.time(), **record},
                          default=_jsonable)
        with self._lock:
            self._f.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            self._f.close()


def _jsonable(obj):
    """Last-resort coercion: telemetry must never crash the code it
    observes over an exotic field type (numpy scalars etc.)."""
    try:
        return float(obj)
    except (TypeError, ValueError):
        return repr(obj)
